"""dispatch-budget: every jitted kernel in ops/ must have warm-up coverage.

The planner's ``precompile()`` walks every compile key production rounds
can request, so the first real round never pays multi-second XLA
compiles through the TPU tunnel (PR 3: two silent fresh compiles were
the bulk of a "solver-bound" 15.2 s gang round).  That guarantee only
holds while every jitted definition in ``poseidon_tpu/ops/`` stays
*reachable* from the precompile path — a new kernel wired into a round
path but not into precompile ships exactly the failure mode PR 3 dug
out by hand.

This is the suite's first *project-scoped* rule: ``check()`` collects
per-file facts (function definitions, name references, jitted defs) for
every scanned file, and ``finalize()`` — called once after the walk —
computes a name-based transitive closure from every ``precompile``
function/method seen, then flags jitted defs under ``poseidon_tpu/ops/``
outside the closure.

The closure is deliberately an over-approximation (any Load of a name,
any attribute tail, joins the graph): a false "covered" verdict is
possible, a false finding on genuinely-wired code is not — the gate
stays quiet on the live tree and only fires on kernels nothing
references.  Three escape hatches:

- scanning a path set that contains no ``precompile`` definition (e.g.
  ``--rule dispatch-budget`` on one kernel file) disables the rule —
  reachability cannot be judged on a partial graph;
- explicit file-list scans (``--changed``, ``check a.py b.py``) never
  judge: only files under a DIRECTORY scan root are flagged, because a
  file list that happens to include ``precompile`` can still miss the
  intermediate file that wires a kernel in (``begin()`` records the
  roots);
- a deliberately dispatch-time-compiled kernel carries the standard
  line suppression ``# posecheck: ignore[dispatch-budget]`` on its
  ``def`` line, which is the explicit opt-out the review trail can see.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Set

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    suppressions,
)
from poseidon_tpu.check.jit_purity import (
    _is_jit_expr,
    _jit_names,
    _partial_names,
)


@dataclass
class _FileFacts:
    path: str
    # function/method name -> referenced names (Loads + attribute tails)
    refs: Dict[str, Set[str]] = field(default_factory=dict)
    # jitted defs in this file: name -> def lineno
    jitted: Dict[str, int] = field(default_factory=dict)
    # names this file defines (functions and methods, unqualified)
    defs: Set[str] = field(default_factory=set)
    # lines with a posecheck suppression covering this rule
    suppressed_lines: Set[int] = field(default_factory=set)


def _referenced_names(fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            # self._dispatch_solve / transport.solve_transport: the tail
            # is the edge.  Over-approximate: any same-named function in
            # the scanned set joins the closure.
            names.add(node.attr)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                names.add(a.name)
    return names


class DispatchBudgetRule(Rule):
    name = "dispatch-budget"
    # Empty scopes: facts are collected from EVERY scanned file (the
    # precompile seeds live in graph/ and replay/); only jitted defs
    # under _FLAG_FRAGMENT are ever flagged.
    scopes: tuple = ()

    # ensure_precompiled joined in PR 11: the service's eager warm-up
    # entry point (server.py) is a first-class seed — a kernel wired
    # only through it is covered, not orphaned.
    _SEED_NAMES = ("precompile", "ensure_precompiled")

    def __init__(self, flag_fragments=("poseidon_tpu/ops/",)) -> None:
        # Jitted defs are only FLAGGED in files matching these fragments
        # (facts still collect everywhere); the selfcheck tests narrow
        # this to the fixtures directory.
        self._flag_fragments = tuple(flag_fragments)
        self._files: List[_FileFacts] = []
        # Directory scan roots from begin(): None = no restriction (the
        # check_file/finalize path the selfcheck tests drive directly).
        self._dir_roots = None

    def begin(self, paths) -> None:
        # A reachability verdict is only sound over a COMPLETE reference
        # graph.  Explicit file lists (--changed, `check a.py b.py`) see
        # a partial graph — a kernel wired via an un-listed file would
        # false-flag — so only files under directory scan roots are ever
        # judged; a pure file-list scan judges nothing.  (The seed guard
        # below is not enough on its own: {instance.py, transport_fused.py}
        # contains precompile yet misses the wiring in transport.py.)
        from pathlib import Path

        self._dir_roots = [
            Path(p).resolve() for p in paths if Path(p).is_dir()
        ]

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        assert isinstance(tree, ast.Module)
        jit = _jit_names(tree)
        partials = _partial_names(tree)
        facts = _FileFacts(path=path)

        supp = suppressions(source)
        for lineno, rules in supp.items():
            if rules is None or self.name in rules:
                facts.suppressed_lines.add(lineno)

        def visit_function(fn: ast.FunctionDef) -> None:
            facts.defs.add(fn.name)
            facts.refs.setdefault(fn.name, set()).update(
                _referenced_names(fn)
            )
            if any(
                _is_jit_expr(d, jit, partials) for d in fn.decorator_list
            ):
                facts.jitted[fn.name] = fn.lineno

        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                visit_function(node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        visit_function(sub)
            elif isinstance(node, ast.Assign):
                # g = jax.jit(f) / g = partial(jax.jit, ...)(f): the
                # wrapper name is the jitted def; the wrapped function
                # is reachable whenever the wrapper is.
                v = node.value
                if (
                    isinstance(v, ast.Call)
                    and _is_jit_expr(v.func, jit, partials)
                    and v.args
                ):
                    inner = dotted_name(v.args[0])
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            facts.defs.add(t.id)
                            facts.jitted[t.id] = node.lineno
                            if inner and "." not in inner:
                                facts.refs.setdefault(
                                    t.id, set()
                                ).add(inner)
        self._files.append(facts)
        return []

    def _judgeable(self, path: str) -> bool:
        if self._dir_roots is None:
            return True
        from pathlib import Path

        try:
            resolved = Path(path).resolve()
        except OSError:
            return False
        return any(
            root == resolved or root in resolved.parents
            for root in self._dir_roots
        )

    def finalize(self) -> List[Finding]:
        files, self._files = self._files, []
        all_refs: Dict[str, Set[str]] = {}
        defined: Set[str] = set()
        for f in files:
            defined.update(f.defs)
            for name, refs in f.refs.items():
                all_refs.setdefault(name, set()).update(refs)

        seeds = [
            s for s in self._SEED_NAMES
            if any(s in f.defs for f in files)
        ]
        if not seeds:
            # Partial graph (single-file / kernel-only invocation):
            # reachability is not judgeable, stay silent.
            return []

        reached: Set[str] = set()
        frontier = list(seeds)
        while frontier:
            name = frontier.pop()
            if name in reached:
                continue
            reached.add(name)
            for ref in all_refs.get(name, ()):
                if ref in defined and ref not in reached:
                    frontier.append(ref)

        findings: List[Finding] = []
        for f in files:
            if not any(frag in f.path for frag in self._flag_fragments):
                continue
            if not self._judgeable(f.path):
                continue
            for name, lineno in sorted(f.jitted.items()):
                if name in reached or lineno in f.suppressed_lines:
                    continue
                findings.append(Finding(
                    f.path, lineno, self.name,
                    f"jitted `{name}` is not reachable from the "
                    "precompile path: its first production dispatch "
                    "pays a fresh XLA compile (wire it into "
                    "precompile(), or opt out with "
                    "`# posecheck: ignore[dispatch-budget]` plus a "
                    "justification)",
                ))
        findings.sort(key=lambda x: (x.path, x.line))
        self._dir_roots = None
        return findings
