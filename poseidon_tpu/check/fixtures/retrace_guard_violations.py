"""retrace-guard violation fixture: every retrace-hazard class, seeded.

Expected findings (tests/test_check_selfcheck.py asserts these):
  - jit constructed inside a function / loop / nested def /
    class method / module-level loop (bare + if-gated)        (6)
  - static_argnames argument derived from len()               (1)
  - str constant at a traced position                         (1)
  - bool constant at a traced position                        (1)
  - unpadded len()-shaped array at the jit boundary           (1)
  - Python float literal at a traced position                 (1)
  - suppressed float literal does NOT count
"""

import functools
from functools import partial

import jax
import numpy as np


@functools.partial(jax.jit, static_argnames=("scale",))
def kernel(x, eps, *, scale):
    return x * scale + eps


def _inner(x, mode):
    return x


loose = jax.jit(_inner)

_WARMED = []
for _size in (8, 16):
    _WARMED.append(jax.jit(_inner))       # VIOLATION: jit in module loop

if len(_WARMED) < 4:
    for _size in (32, 64):
        # VIOLATION: gating the warm-up loop behind an `if` is still a
        # per-iteration wrapper mint.
        _WARMED.append(jax.jit(_inner))


class RoundDriver:
    def drive(self, xs):
        return jax.jit(_inner)(xs, 0)     # VIOLATION: per-call jit, method


def fresh_cache_per_call(xs):
    f = jax.jit(_inner)                   # VIOLATION: per-call jit cache
    return f(xs, 0)


def fresh_cache_in_loop(xs):
    out = []
    for x in xs:
        g = partial(jax.jit, static_argnames=())(_inner)  # VIOLATION
        out.append(g(x, 0))
    return out


def nested_jit(xs):
    @jax.jit                              # VIOLATION: nested-def cache
    def h(x):
        return x + 1

    return h(xs)


def varying_static(xs):
    return kernel(xs, 0, scale=len(xs))   # VIOLATION: retrace per value


def str_at_traced(xs):
    return loose(xs, "fast")              # VIOLATION: dropped static entry


def bool_at_traced(xs):
    return loose(xs, mode=True)           # VIOLATION: dropped static entry


def unpadded_shape(xs):
    return loose(np.zeros(len(xs)), 0)    # VIOLATION: shape-varying array


def weak_float(xs):
    return kernel(xs, 0.5, scale=2)       # VIOLATION: weak-type promotion


def suppressed_float(xs):
    return kernel(xs, 1.5, scale=2)  # posecheck: ignore[retrace-guard]


@functools.partial(jax.jit, static_argnames=("max_iter",))
def ladder_kernel(x, eps_sched, global_every, adaptive, *, max_iter):
    return x * eps_sched[0] + global_every + adaptive


def ladder_schedule_as_python_value(xs):
    # VIOLATION: the epsilon-ladder / adaptive-cadence knobs are TRACED
    # int32 operands in the production solve (transport._solve_device);
    # a bool constant at the adaptive position mints a fresh executable
    # per distinct value — the ladder-schedule-as-Python-value
    # regression the wave smoke's budget-0 gate catches at runtime,
    # linted red here statically.
    return ladder_kernel(xs, xs, 4, True, max_iter=8192)
