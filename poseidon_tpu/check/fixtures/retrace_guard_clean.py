"""retrace-guard clean fixture: the sanctioned jit-boundary patterns.

Module-level jit definitions (process-lived compile cache), varying
counts normalized through a padding-bucket helper before they become
shapes, strings/bools bound only to ``static_argnames`` parameters, and
plain ints at traced positions.  Zero findings expected.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def bucket_size(n: int, lo: int = 32) -> int:
    """Stand-in for the transport padding helper: quantized extents."""
    if n <= lo:
        return lo
    return 1 << (n - 1).bit_length()


@functools.partial(jax.jit, static_argnames=("scale", "mode"))
def kernel(x, eps, *, scale, mode="dense"):
    del mode
    return x * scale + eps


@functools.partial(jax.jit, static_argnums=(1,))
def kernel_nums(x, mode):
    del mode
    return x


def nums_call(xs):
    # Position 1 is static via static_argnums (resolved through the
    # signature): a str constant here is the sanctioned pattern.
    return kernel_nums(jnp.asarray(xs), "fast")


# Module-level wrapper: the cache lives as long as the process.
warm = jax.jit(lambda x: x * 2)

# Wrapper around a function DEFINED ELSEWHERE with static names: the
# positional binding happens through a signature this module cannot
# see, so the rule must not guess static-vs-traced for positionals.
wrapped_ext = jax.jit(np.argsort, static_argnames=("kind",))


def ext_positional(xs):
    return wrapped_ext(xs, "stable")


def padded_call(xs):
    # len() is fine when it feeds the padding helper: the bucketed
    # extent is the compile key, not the raw count.
    m_pad = bucket_size(len(xs))
    buf = np.zeros(m_pad, dtype=np.int32)
    buf[: len(xs)] = xs
    # A str bound to a static_argnames parameter is the sanctioned way
    # to select a code path per compile key.
    return kernel(buf, 0, scale=4, mode="dense")


def traced_scalars(xs, budget):
    # Python ints trace as int32 operands without minting compile keys.
    return kernel(jnp.asarray(xs), budget, scale=8)
