"""dispatch-budget violation fixture: jitted defs without warm-up.

Expected findings (tests/test_check_selfcheck.py asserts these):
  - ``uncovered_kernel``: decorated jit precompile never reaches   (1)
  - ``wrapper_orphan``: module-level jit wrapper nothing references (1)
  - ``covered_kernel`` is reached through precompile: no finding
  - ``opted_out`` carries the explicit suppression: no finding
"""

import functools

import jax


@jax.jit
def covered_kernel(x):
    return x + 1


@functools.partial(jax.jit, static_argnames=("n",))
def uncovered_kernel(x, *, n):
    # VIOLATION: no path from precompile() reaches this kernel — its
    # first production dispatch pays a fresh XLA compile.
    return x * n


def _plain(x):
    return x


wrapper_orphan = jax.jit(_plain)  # VIOLATION: orphaned jit wrapper


@jax.jit
def opted_out(x):  # posecheck: ignore[dispatch-budget]
    return x - 1


def precompile():
    return covered_kernel(0)
