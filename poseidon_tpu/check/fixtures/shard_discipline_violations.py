"""shard-discipline violation fixture: seeded mesh-hygiene breaks.

Expected findings (tests/test_check_selfcheck.py asserts these):
  - collective naming an axis no mesh declares                    (1)
  - collective outside any shard_map/mesh scope                   (1)
  - PartitionSpec axis not drawn from a declared mesh             (1)
  - NamedSharding + device_put with no pad-to-mesh-multiple       (1)
  - sharded jitted def unreachable from precompile                (1)
  - ``covered_sharded`` is precompile-reachable: no finding
  - ``opted_out_sharded`` carries ignore[dispatch-budget]: no finding
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MACHINE_AXIS = "machines"


@jax.jit
def covered_sharded(cols):
    return cols + 1


@jax.jit
def orphan_sharded(cols):
    # VIOLATION: sharded jitted def precompile never reaches.
    return cols - 1


@jax.jit
def opted_out_sharded(cols):  # posecheck: ignore[dispatch-budget]
    return cols * 3


def make_mesh():
    return Mesh(np.asarray(jax.devices()), (MACHINE_AXIS,))


def wrapped_wrong_axis(mesh):
    def body(x):
        # VIOLATION: "rows" is not a declared mesh axis.
        return lax.psum(jnp.sum(x), "rows")

    return shard_map(
        body, mesh=mesh, in_specs=P(MACHINE_AXIS), out_specs=P()
    )


def stray_collective(x):
    # VIOLATION: a collective outside any shard_map-scoped function.
    return lax.psum(x, MACHINE_AXIS)


def bad_spec(mesh):
    # VIOLATION: PartitionSpec names an axis no mesh declares.
    return NamedSharding(mesh, P("bogus_axis"))


def unpadded_upload(costs, mesh):
    # VIOLATION: NamedSharding + device_put with no visible
    # pad-to-mesh-multiple computation or divisibility guard.
    col = NamedSharding(mesh, P(None, MACHINE_AXIS))
    return jax.device_put(jnp.asarray(costs), col)


def precompile():
    return covered_sharded(jnp.zeros(4))
