"""hatch-registry violation fixture: bypasses and undeclared hatches.

Expected findings (tests/test_check_selfcheck.py asserts these):
  - direct env reads of REGISTERED hatches (bypass)               (3)
  - direct env read of an UNDECLARED POSEIDON_* name              (1)
  - accessor read of an UNDECLARED name                           (1)
  - the suppressed bypass and the env WRITE do not count
"""

import os

from poseidon_tpu.utils.hatches import hatch_bool


def bypasses():
    a = os.environ.get("POSEIDON_TRACE")          # VIOLATION: bypass
    b = os.getenv("POSEIDON_FUSED")               # VIOLATION: bypass
    c = os.environ["POSEIDON_TILED"]              # VIOLATION: bypass
    ok = os.environ.get("POSEIDON_CHAINED")  # posecheck: ignore[hatch-registry]
    return a, b, c, ok


def undeclared():
    # VIOLATION: a POSEIDON_* name the registry does not declare.
    x = os.environ.get("POSEIDON_NOT_A_DECLARED_HATCH")
    # VIOLATION: the accessor would raise KeyError at call time.
    y = hatch_bool("POSEIDON_ALSO_NOT_DECLARED")
    return x, y


def legal_write():
    os.environ["POSEIDON_TRACE"] = "1"  # write: a harness latch, legal
