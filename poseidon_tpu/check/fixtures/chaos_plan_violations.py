"""determinism-rule VIOLATION fixture, chaos flavor: every way a fault
plan stops being seed-reproducible.  Expected findings (one per marked
line): 2 wall-clock, 2 unseeded-RNG, 1 seedless default_rng, 2 set
iteration — 7 total."""

import random
import time

import numpy as np


def wall_clock_schedule(rounds: int):
    """Fault timing off the wall clock: two runs disagree."""
    now = time.time()                       # finding: wall-clock
    return [int(now) % rounds, int(time.time()) % rounds]  # finding


def entropy_schedule(rounds: int):
    """OS-entropy draws: unseeded global streams."""
    r = random.randrange(rounds)            # finding: unseeded global RNG
    rng = np.random.default_rng()           # finding: default_rng no seed
    k = np.random.randint(rounds)           # finding: unseeded global RNG
    return [r, int(rng.integers(rounds)), int(k)]


def family_order(faults):
    """Set iteration order feeds the plan's output order."""
    families = {"watch", "events", "rpc"}
    out = []
    for fam in families:                    # finding: set iteration
        out.append(fam)
    return out + list({f.family for f in faults})   # finding: set iteration
