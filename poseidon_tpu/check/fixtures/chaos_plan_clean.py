"""determinism-rule CLEAN fixture, chaos flavor: a seed-reproducible
fault plan.  Everything here is the pattern chaos/ code must follow —
seeded RNG streams, sorted iteration over unordered collections, no wall
clock — and must produce ZERO findings."""

import random

import numpy as np

FAMILIES = ("watch", "events", "rpc")


def seeded_schedule(seed: int, rounds: int):
    """Fault rounds drawn from an explicit seeded stream."""
    rng = np.random.default_rng(seed)
    return sorted(int(rng.integers(rounds)) for _ in FAMILIES)


def seeded_jitter(seed: int) -> float:
    """Backoff jitter threads a seeded random.Random, never the global."""
    stream = random.Random(seed)
    return stream.random()


def covered_families(faults) -> tuple:
    """Set contents reach output only through sorted()."""
    families = {f.family for f in faults}
    return tuple(sorted(families))


def virtual_time(round_index: int, interval_s: float) -> float:
    """Round index is the only time axis a replayable plan may carry."""
    return round_index * interval_s
