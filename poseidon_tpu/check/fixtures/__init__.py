# Fixture files for the posecheck self-tests (tests/test_check_selfcheck.py).
# The *_violations.py files contain seeded findings ON PURPOSE; the default
# repo walk skips this directory (core._SKIP_FRAGMENTS), and ruff excludes it.
# These modules are parsed, never imported.
