"""Seeded violations for posecheck `numerics` (never imported, only parsed).

Expected findings (12):
- i32-overflow (5): int32 `np.sum` reduction, int32 `.cumsum()` method
  reduction, `*` between two int32-tagged arrays, narrowing
  `astype(int32)` of a float-ish tracked name, narrowing
  `astype(int32)` directly on a `np.floor(...)` chain.
- inf-sentinel (4): `+` through a locally seeded INF_COST plane,
  `np.sum` over that plane, `-` through a plane returned by a jitted
  producer (cross-function lattice), `np.sum` over that returned plane.
- promotion (3): f32/i32 Name-vs-Name mix inside a jitted def, Python
  float literal against an int32-tagged operand inside a jitted def,
  Python float literal passed positionally at a jit call boundary.

Two seeded hazards carry `# posecheck: ignore[numerics]` (one per-file
i32 reduction, one finalize-path sentinel binop) and must NOT count.
"""

import jax
import jax.numpy as jnp
import numpy as np

INF_COST = 1 << 28


def overflowing_counts(counts2):
    counts = np.zeros((4, 8), dtype=np.int32)
    total = np.sum(counts)                  # VIOLATION: i32 sum
    running = counts.cumsum()               # VIOLATION: i32 cumsum
    other = np.ones((4, 8), dtype=np.int32)
    pairs = counts * other                  # VIOLATION: i32 * i32 product
    # Documented bound: the fixture matrix is 4x8 of zeros.
    bounded = np.sum(counts)  # posecheck: ignore[numerics]
    return total, running, pairs, bounded


def narrowing_casts(free, req):
    n = np.floor(free / np.maximum(req, 1e-9))
    cap = n.astype(np.int32)                # VIOLATION: unclamped narrow
    cap2 = np.floor(free / req).astype(np.int32)   # VIOLATION: same, inline
    return cap, cap2


def hot_total(base, forbidden, penalty):
    plane = np.where(forbidden, INF_COST, base)
    tot = plane + penalty                   # VIOLATION: + through sentinels
    s = np.sum(plane)                       # VIOLATION: sum mixes sentinels
    # Justified: the fixture pretends a downstream isfinite guard.
    t2 = plane + penalty  # posecheck: ignore[numerics]
    safe = np.where(plane >= INF_COST, 0, plane)
    ok = np.sum(safe)                       # clean: integer-guarded
    return tot, s, t2, ok


@jax.jit
def _seed_plane(c):
    p = jnp.where(c > 9, INF_COST, c)
    return p


def consume(c, drift):
    out = _seed_plane(c)
    bad = out - drift                       # VIOLATION: via jitted producer
    tot = np.sum(out)                       # VIOLATION: via jitted producer
    return bad, tot


@jax.jit
def mix(a, b):
    x = a.astype(jnp.float32)
    y = b.astype(jnp.int32)
    xy = x * y                              # VIOLATION: f32 * i32 mix
    z = y * 0.5                             # VIOLATION: weak float vs i32
    return xy + z


def boundary_caller(a):
    return mix(a, 2.5)                      # VIOLATION: weak literal at jit
