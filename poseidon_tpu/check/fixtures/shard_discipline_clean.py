"""shard-discipline clean fixture: the transport_sharded idiom.

A declared mesh axis constant, collectives under shard_map with the
declared axis, PartitionSpec drawn from it, pad-to-mesh-multiple at the
sharded boundary, and the sharded jitted kernel reachable from
precompile.  Zero findings.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MACHINE_AXIS = "machines"


@jax.jit
def _sharded_kernel(cols):
    return cols * 2


def _block_reduce(x):
    # Referenced by a shard_map-wrapped fn: joins the mesh scope.
    return lax.psum(x, MACHINE_AXIS)


def make_mesh():
    return Mesh(np.asarray(jax.devices()), (MACHINE_AXIS,))


def wrapped(mesh):
    def body(x):
        return _block_reduce(jnp.sum(x))

    return shard_map(
        body, mesh=mesh, in_specs=P(MACHINE_AXIS), out_specs=P()
    )


def solve_sharded(costs, mesh):
    n_dev = len(mesh.devices)
    m = costs.shape[1]
    m_pad = ((m + n_dev - 1) // n_dev) * n_dev   # pad to mesh multiple
    padded = np.zeros((costs.shape[0], m_pad), costs.dtype)
    padded[:, :m] = costs
    col = NamedSharding(mesh, P(None, MACHINE_AXIS))
    dev = jax.device_put(jnp.asarray(padded), col)
    return _sharded_kernel(dev)


def precompile():
    mesh = make_mesh()
    return solve_sharded(np.zeros((2, 4), np.int32), mesh)
