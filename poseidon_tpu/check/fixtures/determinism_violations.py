"""determinism violation fixture: wall clock, unseeded RNG, set iteration.

Expected findings:
  - time.time() wall clock                       (2: dotted + from-import)
  - unseeded global random.* / np.random.*       (3)
  - default_rng() with no seed                   (1)
  - iteration over bare sets                     (5: for / comprehension /
                                                  list() / tracked var /
                                                  var grown via |=)
  - import-time environment reads                (4: .get / subscript /
                                                  class body / def default)
  - suppressed time.time() does NOT count
"""

import os
import random
import time
from time import time as now

import numpy as np

UNROLL = int(os.environ.get("FIXTURE_UNROLL", "4"))   # VIOLATION: import-time
MODE = os.environ["FIXTURE_MODE"]                     # VIOLATION: import-time


class Tunables:
    budget = int(os.getenv("FIXTURE_BUDGET", "8"))    # VIOLATION: class body

    def call_time(self):
        return os.environ.get("FIXTURE_BUDGET", "8")  # call time: fine


def pinned_default(                                   # default evaluates at
    n=int(os.environ.get("FIXTURE_N", "4")),          # VIOLATION: import
):
    return n


def stamp_events(events):
    t = time.time()                         # VIOLATION: wall clock
    t2 = now()                              # VIOLATION: wall clock (alias)
    ok = time.time()                        # posecheck: ignore[determinism]
    return [(t, t2, ok, e) for e in events]


def jitter(n):
    a = random.random()                     # VIOLATION: global RNG
    b = np.random.uniform(0, 1, size=n)     # VIOLATION: global np RNG
    c = random.shuffle(list(range(n)))      # VIOLATION: global RNG
    rng = np.random.default_rng()           # VIOLATION: unseeded default_rng
    return a, b, c, rng.integers(0, n)


def leak_order(uuids):
    pending = set(uuids)
    out = []
    for u in pending:                       # VIOLATION: tracked set var
        out.append(u)
    for u in {x for x in uuids}:            # VIOLATION: set comprehension
        out.append(u)
    out.extend(list(set(uuids)))            # VIOLATION: list(set(...))
    out.extend(x for x in set(uuids))       # VIOLATION: genexp over set
    grown = set(uuids)
    grown |= {"extra"}                      # set algebra keeps it a set
    for u in grown:                         # VIOLATION: still unordered
        out.append(u)
    return out
