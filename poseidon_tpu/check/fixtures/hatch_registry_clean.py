"""hatch-registry clean fixture: registered call-time accessor reads.

Accessor reads of declared hatches, environment WRITES (harness
latches), and dynamic accessor names are all legal.  Zero findings.
"""

import os

from poseidon_tpu.utils.hatches import hatch_bool, hatch_int, hatch_raw

GATE = "POSEIDON_COST_DELTA"


def gates():
    if not hatch_bool("POSEIDON_PRUNE_WAVE"):
        return 0
    return hatch_int("POSEIDON_PRUNE_MIN_ROWS", 192)


def policy(env_var: str):
    # Dynamic name: validated by the accessor at call time.
    return hatch_raw(env_var)


def latch_for_children():
    # Environment WRITES are harness latches, not reads: legal.
    os.environ["POSEIDON_BENCH_NO_PROBE"] = "1"
    os.environ.setdefault("POSEIDON_REPLAY_PROGRESS", "1")


def named_gate():
    # A module constant carrying the name keeps the hatch live for the
    # dead-flag check AND reads through the accessor.
    return hatch_bool(GATE)


def non_hatch_env():
    # Non-POSEIDON environment reads are out of this rule's scope.
    return os.environ.get("JAX_PLATFORMS", "")
