"""transfer-discipline clean fixture: the declared-boundary idiom.

Jitted results are fetched ONCE, explicitly, at a host-boundary
function (``_host_*`` / ``host_fetch``); scalars ride the same fetch;
donating kernels' operands are rebound, never reused.  Zero findings.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _kernel(x):
    return x * 2, x.sum()


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter(buf, val):
    # Donation declared: the in-place update reuses the operand's HBM.
    return buf.at[0].set(val)


def host_fetch(*vals):
    # The declared boundary: explicit transfer, transient-retry home.
    return jax.device_get(vals)


def _host_decode(F, s):
    # _host_* prefix: a declared boundary — materialization is its job.
    return np.asarray(F), jax.device_get(s)


def solve(x):
    F, s = _kernel(x)
    F, s = host_fetch(F, s)       # one explicit boundary fetch
    total = float(s)              # host scalar now: no sync
    return F[:2], total


def donate_properly(x):
    buf = jnp.zeros(4)
    buf = _scatter(buf, x)        # rebound: the donated name dies here
    return buf


def pure_host(costs):
    # numpy-only host work never flags.
    padded = np.asarray(costs, dtype=np.int32)
    return int(padded.sum())
