"""lock-discipline clean fixture: every guarded write holds the lock,
including the locked-helper pattern (private method only entered under
the lock) and recursion."""

import threading


class GuardedRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}          # construction-time writes are exempt
        self._index = {}
        self._threads = []        # never touched under the lock: unguarded

    def put(self, key, value):
        with self._lock:
            self._items[key] = value
            self._reindex(key, value)

    def _reindex(self, key, value):
        # Lock-held helper: every intra-class call site holds the lock.
        self._index[value] = key
        for child in getattr(value, "children", ()):
            self._reindex(key, child)

    def get(self, key):
        with self._lock:
            return self._items.get(key)

    def track(self, thread):
        # _threads is not lock-guarded (single-threaded setup path).
        self._threads.append(thread)


class CondQueue:
    def __init__(self):
        self._cond = threading.Condition()
        self._queue = []
        self._shutdown = False

    def add(self, item):
        with self._cond:
            if self._shutdown:
                return
            self._queue.append(item)
            self._cond.notify()

    def shut_down(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()


class NoLocksHere:
    """Classes without a lock are out of the rule's jurisdiction."""

    def __init__(self):
        self._state = 0

    def bump(self):
        self._state += 1
