"""Clean counterpart for posecheck `numerics` (never imported).

Every hazard class from numerics_violations.py, written the sanctioned
way: widened accumulators, certified widen/narrow helpers, clamp-before-
cast, sentinel planes consumed through guards or min/max reductions,
dtype-consistent jitted arithmetic, and one documented bound riding a
justified suppression.
"""

import jax
import jax.numpy as jnp
import numpy as np

from poseidon_tpu.utils.numerics import checked_narrow_i32, widen_counts

INF_COST = 1 << 28


def widened_totals():
    counts = np.zeros((4, 8), dtype=np.int32)
    total = np.sum(counts, dtype=np.int64)          # widened accumulator
    wide = widen_counts(counts, site="fixture.counts")
    grand = wide.sum()                              # int64 input
    return total, grand


def bounded_narrows(free, req):
    big = np.iinfo(np.int32).max // 4
    n = np.floor(free / np.maximum(req, 1e-9))
    n = np.minimum(n, big)                          # clamp before the cast
    cap = n.astype(np.int32)
    clipped = np.clip(np.floor(free / req), 0, big).astype(np.int32)
    certified = checked_narrow_i32(free, site="fixture.free", hi=big)
    return cap, clipped, certified


def guarded_sentinels(base, forbidden):
    plane = np.where(forbidden, INF_COST, base)     # construction is legal
    worst = plane.max()                             # min/max stay legal
    finite = np.where(plane >= INF_COST, 0, plane)  # integer guard
    tot = np.sum(finite)
    fin2 = np.where(np.isfinite(base), base, 0)     # float guard
    tot2 = np.sum(fin2)
    return worst, tot, tot2


@jax.jit
def consistent_kernel(a, b):
    x = a.astype(jnp.float32)
    y = b.astype(jnp.float32)
    return x * y + 0.5                              # same family: fine


def documented_bound():
    counts = np.zeros(8, dtype=np.int32)
    # Bounded by construction: eight zero cells cannot accumulate.
    t = np.sum(counts)  # posecheck: ignore[numerics]
    return t
