"""transfer-discipline violation fixture: seeded implicit syncs.

Expected findings (tests/test_check_selfcheck.py asserts these):
  - scalar syncs on jitted-call results: float / item / int / tolist (4)
  - np materialization of a jitted result outside a boundary       (1)
  - jax.device_get outside a declared boundary                     (1)
  - in-place ``.at`` update without donate_argnums                 (1)
  - use-after-donation of a donated operand                        (1)
  - the suppressed np.asarray does NOT count
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _kernel(x):
    return x * 2, x.sum()


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter(buf, val):
    return buf.at[0].set(val)


@jax.jit
def _inplace_no_donate(buf, val):
    # VIOLATION: .at update of an operand with no donate_argnums.
    return buf.at[0].set(val)


def leaky_wrapper(x):
    F, s = _kernel(x)
    a = float(s)                  # VIOLATION: implicit scalar sync
    b = s.item()                  # VIOLATION: implicit scalar sync
    c = int(F[0, 0])              # VIOLATION: implicit scalar sync
    lst = F.tolist()              # VIOLATION: implicit scalar sync
    host = np.asarray(F)          # VIOLATION: implicit materialization
    got = jax.device_get(s)       # VIOLATION: device_get off-boundary
    ok = np.asarray(F)            # posecheck: ignore[transfer-discipline]
    return a, b, c, lst, host, got, ok


def reuse_after_donate(x):
    buf = jnp.zeros(4)
    out = _scatter(buf, x)
    return buf.sum() + out.sum()  # VIOLATION: buf was donated
