"""Seeded concurrency violations (lock-order / blocking-under-lock /
unsafe-publication) for the posecheck self-tests.  Counts are asserted
exactly in tests/test_check_selfcheck.py — keep them in sync.

Expected: 2 lock-order cycles, 5 blocking-under-lock, 2
unsafe-publication.
"""

import queue
import threading
import time


class TwoLocks:
    """In-class cycle: ``forward`` nests _a -> _b, ``backward`` nests
    _b -> _a — the textbook AB/BA deadlock."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.count = 0

    def forward(self):
        with self._a:
            with self._b:
                self.count += 1

    def backward(self):
        with self._b:
            with self._a:
                self.count -= 1


class Outer:
    """Cross-class cycle with :class:`Inner`: ``poke`` calls into
    Inner.submit while holding _mu; Inner.callback calls back into
    ``refresh`` while holding _gate."""

    def __init__(self):
        self._mu = threading.Lock()
        self.seen = 0

    def poke(self, inner):
        with self._mu:
            inner.submit()

    def refresh(self):
        with self._mu:
            self.seen += 1


class Inner:
    def __init__(self):
        self._gate = threading.Lock()
        self.pending = 0

    def submit(self):
        with self._gate:
            self.pending += 1

    def callback(self, outer):
        with self._gate:
            outer.refresh()


class Blocker:
    """Five distinct park-under-lock shapes, one legal Condition.wait,
    one suppressed sleep."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._q = queue.Queue()
        self.ready = False

    def sleepy(self):
        with self._lock:
            time.sleep(0.1)

    def joiny(self, worker):
        with self._lock:
            worker.join()

    def getty(self):
        with self._lock:
            return self._q.get()

    def resulty(self, fut):
        with self._lock:
            return fut.result()

    def waity(self, event):
        with self._lock:
            event.wait()

    def legal_condition_wait(self):
        # Condition.wait on the HELD lock releases it — the one legal
        # wait inside a critical section; must not be flagged.
        with self._cond:
            while not self.ready:
                self._cond.wait()

    def suppressed_sleep(self):
        with self._lock:
            time.sleep(0.0)  # posecheck: ignore[blocking-under-lock]


class Publisher:
    """Spawns a thread, then republishes mutable state without a lock
    (two findings); the locked and handoff-annotated swaps are clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}
        self._snapshots = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        pass

    def reset(self):
        self._state = {}

    def snapshot(self, items):
        self._snapshots = [i for i in items]

    def rebuild_under_lock(self):
        with self._lock:
            self._state = {}

    def swap_documented(self):
        self._state = {}  # handoff: worker joined before the swap


class QuietPublisher:
    """No thread ever spawned: republication is single-threaded state,
    out of unsafe-publication's jurisdiction."""

    def __init__(self):
        self._cache = {}

    def reset(self):
        self._cache = {}
