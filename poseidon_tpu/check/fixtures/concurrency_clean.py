"""Clean concurrency fixture: consistent lock order, waits outside
critical sections, publication under the lock or via a documented
handoff.  Must produce ZERO findings for all three concurrency rules
(tests/test_check_selfcheck.py)."""

import threading
import time

from poseidon_tpu.utils.locks import TrackedLock, tracked_condition


class OrderedPair:
    """One global order — _coarse before _fine — on every path."""

    def __init__(self):
        self._coarse = TrackedLock("fixture.OrderedPair._coarse")
        self._fine = TrackedLock("fixture.OrderedPair._fine")
        self._items = []

    def update(self, x):
        with self._coarse:
            with self._fine:
                self._items.append(x)

    def refresh(self):
        with self._coarse:
            with self._fine:
                self._items.clear()


class PatientWorker:
    """Waits happen on the condition's OWN lock; sleeps happen outside
    any critical section; republication is locked or handed off."""

    def __init__(self):
        self._cond = tracked_condition("fixture.PatientWorker._cond")
        self._queue = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        with self._cond:
            while not self._queue:
                self._cond.wait()

    def put(self, item):
        with self._cond:
            self._queue.append(item)
            self._cond.notify()

    def rebuild(self):
        with self._cond:
            self._queue = []

    def reset_before_start(self):
        self._queue = []  # handoff: called before the worker starts

    def backoff(self):
        time.sleep(0.0)
