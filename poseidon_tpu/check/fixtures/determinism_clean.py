"""determinism clean fixture: seeded RNG streams, virtual time, sorted
iteration over sets, and call-time environment reads."""

import os
import time

import numpy as np


def unroll_factor() -> int:
    # Call-time accessor: tests/bench can vary the env var per call.
    return int(os.environ.get("FIXTURE_UNROLL", "4"))


def seeded_trace(seed: int):
    rng = np.random.default_rng(seed)          # seeded stream: fine
    return rng.uniform(0.0, 1.0, size=8)


def measure(fn):
    # perf_counter feeds telemetry, not decisions: not flagged.
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def stable_order(uuids):
    pending = set(uuids)
    # sorted() normalizes set order before it can leak into output.
    report = [u.upper() for u in sorted(pending)]
    for u in sorted({x for x in uuids if x}):
        report.append(u)
    if "m0" in pending:                         # membership tests are fine
        report.append("m0")
    return report
