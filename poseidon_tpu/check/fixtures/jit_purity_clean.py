"""jit-purity clean fixture: jitted kernels that stay on device, plus
host-side wrapper code that may use numpy freely (out of jit scope)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _helper(x):
    # Same-module callee of a jitted function: must stay pure too.
    return jnp.maximum(x, 0)


@jax.jit
def kernel(x):
    y = _helper(x)
    jax.debug.print("y={y}", y=y)  # the sanctioned print
    return y.astype(jnp.int32) * 2


@functools.partial(jax.jit, static_argnames=("n",))
def kernel_static(x, *, n):
    # int() on a literal is a host-time constant, not a tracer sync.
    return x + int("4") + n


def host_wrapper(arr):
    # NOT in jit scope: numpy materialization and .item() are fine here.
    a = np.asarray(arr, dtype=np.int32)
    out = kernel(jnp.asarray(a))
    total = float(np.asarray(out).sum())
    print("host-side report:", total)
    return int(out[0].item())
