"""dispatch-budget clean fixture: every jitted def has warm-up coverage.

``precompile`` reaches both kernels — one through a host wrapper (the
``solve_transport`` shape), one directly.  Zero findings expected.
"""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("scale",))
def kernel(x, *, scale):
    return x * scale


def _plain(x):
    return x + 1


wrapped = jax.jit(_plain)


def solve(x):
    """Host wrapper around the dispatch (the solve_transport shape)."""
    return kernel(x, scale=4)


def precompile():
    """Warm every compile key the round paths can request."""
    solve(0)
    return wrapped(0)
