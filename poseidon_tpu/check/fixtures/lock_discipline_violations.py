"""lock-discipline violation fixture: unlocked writes to guarded state.

Expected findings:
  - plain assignment outside the lock           (1: racy_set)
  - subscript store outside the lock            (1: racy_put)
  - mutating method call outside the lock       (1: racy_append)
  - augmented assignment outside the lock       (1: racy_bump)
  - helper with one unlocked call site is NOT lock-held; its write flags (1)
  - thread-target escape defeats lock-held inference                     (1)
  - suppressed unlocked write does NOT count
"""

import threading
from threading import Condition


class RacyRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._count = 0
        self._log = []

    def put(self, key, value):
        with self._lock:
            self._items[key] = value
            self._count += 1
            self._log.append(key)

    def racy_set(self):
        self._items = {}                    # VIOLATION: assignment

    def racy_put(self, key, value):
        self._items[key] = value            # VIOLATION: subscript store

    def racy_append(self, key):
        self._log.append(key)               # VIOLATION: mutation call

    def racy_bump(self):
        self._count += 1                    # VIOLATION: augmented assign

    def locked_then_not(self, key):
        with self._lock:
            self._helper(key)
        self._helper(key)                   # unlocked call site...

    def _helper(self, key):
        self._items[key] = 1                # VIOLATION: not lock-held

    def intentional(self):
        self._count = 0                     # posecheck: ignore[lock-discipline]


class ThreadTargetEscape:
    """A locked call site must not exempt a method that also escapes as a
    thread target — it runs unlocked on its own thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._state = {}

    def start(self):
        t = threading.Thread(target=self._worker)   # escapes _worker
        t.start()

    def sync_path(self, key):
        with self._lock:
            self._state[key] = 0
            self._worker()                  # the (only) lexical call site

    def _worker(self):
        self._state["tick"] = 1             # VIOLATION: runs on the thread


class RacyCond:
    def __init__(self):
        self._cond = Condition()
        self._queue = []

    def add(self, item):
        with self._cond:
            self._queue.append(item)
            self._cond.notify()

    def drop_all(self):
        self._queue.clear()                 # VIOLATION: mutation call
