"""jit-purity violation fixture: every host-sync escape class, seeded.

Expected findings (tests/test_check_selfcheck.py asserts these):
  - np.asarray / np.array inside jit scope        (2)
  - .item() inside jit scope                      (1)
  - float()/int() tracer casts inside jit scope   (2)
  - jax.device_get inside jit scope               (1)
  - bare print inside jit scope                   (2: direct + callee)
  - suppressed np.asarray does NOT count
"""

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _leaky_callee(x):
    # Joins jit scope through direct_call below: flagged through closure.
    print("inside the kernel", x)
    return x


@jax.jit
def direct_call(x):
    y = np.asarray(x)                     # VIOLATION: host materialization
    z = np.array([1, 2, 3])               # VIOLATION: host materialization
    w = jax.device_get(x)                 # VIOLATION: explicit device->host
    s = x.sum().item()                    # VIOLATION: .item() sync
    f = float(x[0])                       # VIOLATION: tracer cast
    i = int(y.sum())                      # VIOLATION: tracer cast
    print("shape", x.shape)               # VIOLATION: bare print
    ok = np.asarray(x)                    # posecheck: ignore[jit-purity]
    return _leaky_callee(jnp.asarray(y) + z.sum() + w + s + f + i + ok[0])


@functools.partial(jax.jit, donate_argnums=(0,))
def partial_decorated(x):
    return x * 2


scanned_alias = partial(jax.jit, static_argnames=())(partial_decorated)
