"""numerics: int32 overflow, inf-sentinel hygiene, promotion hazards.

Scope: ``poseidon_tpu/ops/``, ``poseidon_tpu/costmodel/``,
``poseidon_tpu/graph/`` — the int32 solver substrate (overridable via
the ``POSEIDON_NUMERICS_SCOPES`` hatch, comma-separated fragments).  The
solver is int32 end to end because that is what the accelerator kernels
run natively, and int32 arithmetic wraps silently in numpy AND in XLA
(x64 is disabled; there is no trap).  PR 2 ate a real one: a
slot-capacity product crossed 2^31 at cluster scale and the flow network
happily routed through a *negative* capacity — invisible at test scale,
wrong at 100k machines.  The runtime twin is
``check.ledger.NumericsLedger`` (budget-0 windows around warm
bench/soak rounds, validating at the ``host_fetch`` boundary) plus the
certified helpers in ``utils/numerics.py``.

Three sub-checks (message prefixes ``i32-overflow:``, ``inf-sentinel:``,
``promotion:``; suppress with ``# posecheck: ignore[numerics]`` plus a
justification for the bound that makes the line safe):

- **i32-overflow**: ``sum``/``cumsum``/``prod``/``dot``/``matmul``
  reductions over arrays dataflow-tagged int32 (dtype= kwargs, astype
  casts, propagated through where/minimum/arithmetic) without widening
  (``dtype=np.int64`` / a float accumulator / the
  ``utils.numerics.widen_counts`` certificate); ``*`` between two
  int32-tagged arrays (a count product is exactly the PR 2 wrap);
  and narrowing ``astype(int32)`` casts of unbounded float-ish values
  (floor/rint/division chains, tracked through ``np.where``) without a
  clip — ``np.clip``/``np.minimum(x, BOUND)``/
  ``utils.numerics.checked_narrow_i32`` all count as declared bounds.
- **inf-sentinel**: the cost planes carry ``INF_COST`` (2^28, an int32
  *sentinel*, not a number) on forbidden arcs.  Additive arithmetic
  through such a plane silently compounds sentinels into garbage that
  still *looks* like a big cost (``INF_COST + INF_COST`` is fine in
  int32 but no longer means "forbidden"; summing a row mixes sentinels
  into totals).  The lattice seeds at construction sites (expressions
  mentioning a sentinel constant), propagates through arithmetic,
  subscripts, aliases, and — cross-file, resolved in ``finalize()`` —
  through calls to functions that return a tainted plane.  Cleansed by
  a finiteness-guarded ``where`` (condition mentions
  ``isfinite``/``isinf``), by ``minimum``/``clip`` against a non-tainted
  bound, or by masked comparison (``>=``-style tests are how sentinels
  are *meant* to be consumed).  ``min``/``max`` reductions stay legal
  (they preserve sentinel semantics); ``sum``/``mean``/``dot``/
  ``cumsum``/``prod`` through a tainted plane are findings.
- **promotion**: jax's weak-type promotion decides silently at jit
  boundaries.  Inside a jitted def, mixing operands explicitly tagged
  with different dtype families (f32 vs i32, bf16 vs f32) in bare
  arithmetic promotes by table, not by intent — widen explicitly.  A
  Python float literal against an int32-tagged operand turns counts
  into weak f32 mid-kernel; a float literal passed positionally at a
  jitted call boundary ships an untyped weak scalar into the trace.

Dataflow is per-function, name-based, and LINE-ORDERED (unlike
transfer-discipline's fixpoint): rebinding through a clamp
(``n = np.minimum(n, big)``) genuinely cleanses the name from then on,
which is exactly the sanctioned fix shape.  Over-approximation is
possible through aliasing; every finding names the operand so a
justified ``ignore[numerics]`` documents the bound.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    import_aliases,
    suppressions,
)
from poseidon_tpu.check.jit_purity import (
    _is_jit_expr,
    _jit_names,
    _partial_names,
)

_DEFAULT_SCOPES = (
    "poseidon_tpu/ops/", "poseidon_tpu/costmodel/", "poseidon_tpu/graph/",
)

# Reductions that accumulate (overflow risk / sentinel mixing).  min/max
# family is deliberately absent: it neither accumulates nor mixes.
_ACC_REDUCTIONS = ("sum", "cumsum", "prod", "cumprod", "dot", "matmul")
_SENTINEL_REDUCTIONS = (
    "sum", "cumsum", "prod", "cumprod", "dot", "matmul", "mean", "average",
)
_FLOOR_FNS = ("floor", "rint", "ceil", "round", "around", "trunc", "fix")
_CERTIFIED_NARROWS = ("checked_narrow_i32",)
_CERTIFIED_WIDENS = ("widen_counts", "certify_i32")

_DTYPE_TAGS = {
    "int8": "i8", "int16": "i16", "int32": "i32", "int64": "i64",
    "uint8": "u8", "uint16": "u16", "uint32": "u32", "uint64": "u64",
    "float16": "f16", "bfloat16": "bf16", "float32": "f32",
    "float64": "f64", "bool_": "bool", "bool": "bool",
}
_NARROW_INT_TAGS = {"i8", "i16", "i32", "u8", "u16", "u32"}
_WIDE_ACC_TAGS = {"i64", "u64", "f32", "f64", "bf16", "f16"}


def _family(tag: str) -> str:
    if tag in ("bool",):
        return "bool"
    return "int" if tag.startswith(("i", "u")) else "float"


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dtype_tag(node: Optional[ast.AST]) -> Optional[str]:
    """'i32'/'f32'/... for np.int32 / jnp.float32 / "int32" nodes."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_TAGS.get(node.value)
    d = dotted_name(node)
    if d:
        return _DTYPE_TAGS.get(d.rpartition(".")[2])
    return None


def _dtype_kwarg(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "dtype":
            return _dtype_tag(kw.value)
    return None


def _call_tail(call: ast.Call) -> Optional[str]:
    d = dotted_name(call.func)
    return d.rpartition(".")[2] if d else None


def _call_head(call: ast.Call) -> Optional[str]:
    d = dotted_name(call.func)
    return d.partition(".")[0] if d else None


def _mentions_name(node: ast.AST, names: Set[str]) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id in names for n in ast.walk(node)
    )


def _mentions_outside_compare(node: ast.AST, names: Set[str]) -> bool:
    """Sentinel mention that is NOT inside a comparison: ``x >= INF_COST``
    is the sanctioned way to consume a sentinel, never a seed."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Compare):
            continue
        if isinstance(n, ast.Name) and n.id in names:
            return True
        stack.extend(ast.iter_child_nodes(n))
    return False


def _site_root(node: ast.AST) -> Optional[str]:
    """Bare-Name root of a Name/Subscript chain; Attribute chains return
    None — taint is plane-granular, and ``sol.objective`` on a tainted
    ``sol`` is a different value than the tainted plane itself."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _mentions_call(node: ast.AST, tails: Sequence[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            t = _call_tail(n)
            if t in tails:
                return True
    return False


def _ordered_simple_stmts(scope: ast.AST):
    """Simple statements of ``scope`` in source order, descending into
    compound bodies but never into nested defs/lambdas/classes."""
    def rec(stmts):
        for s in stmts:
            if isinstance(
                s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            if isinstance(
                s, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr,
                    ast.Return, ast.Assert)
            ):
                yield s
                continue
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if sub:
                    yield from rec(sub)
            for h in getattr(s, "handlers", []) or []:
                yield from rec(h.body)
    yield from rec(getattr(scope, "body", []))


def _walk_no_lambda(node: ast.AST):
    """ast.walk that does not descend into lambdas (their bodies run in
    another activation; name tracking does not transfer)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.Lambda):
                continue
            stack.append(child)


def _assign_targets(node: ast.stmt) -> Tuple[str, ...]:
    targets: List[str] = []
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(
                    e.id for e in t.elts if isinstance(e, ast.Name)
                )
    elif isinstance(node, ast.AnnAssign) and isinstance(
        node.target, ast.Name
    ) and node.value is not None:
        targets.append(node.target.id)
    return tuple(targets)


# -------------------------------------------------- sentinel lattice facts

# assign specs, replayed in finalize: ("seed",) / ("cleanse",) /
# ("taint_if", roots) / ("call", callee_tail)
_AssignSpec = Tuple


@dataclass
class _SentinelFn:
    fn: str
    # line-ordered events: ("assign", line, targets, spec) |
    # ("site_binop", line, op, roots, always) |
    # ("site_reduce", line, opname, root) | ("return", line, roots)
    events: List[Tuple] = field(default_factory=list)


@dataclass
class _FileFacts:
    path: str
    jitted: Set[str] = field(default_factory=set)
    sentinel_fns: List[_SentinelFn] = field(default_factory=list)
    # (line, callee_tail, literal) — float literals at call boundaries,
    # resolved against the scan-wide jitted union in finalize.
    jit_literal_sites: List[Tuple[int, str, str]] = field(
        default_factory=list
    )
    suppressed: Set[int] = field(default_factory=set)


class NumericsDisciplineRule(Rule):
    name = "numerics"
    scopes = _DEFAULT_SCOPES

    def __init__(self) -> None:
        self._files: List[_FileFacts] = []
        raw = ""
        try:
            from poseidon_tpu.utils.hatches import hatch_str
            raw = hatch_str("POSEIDON_NUMERICS_SCOPES")
        except Exception:  # noqa: BLE001 - registry unavailable mid-bootstrap
            raw = ""
        if raw:
            self.scopes = tuple(
                s.strip() for s in raw.split(",") if s.strip()
            )

    # ---------------------------------------------------------------- check

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        assert isinstance(tree, ast.Module)
        num_aliases = (
            import_aliases(tree, "numpy")
            | import_aliases(tree, "jax.numpy")
            | {"np", "jnp"}
        )
        jit = _jit_names(tree)
        partials = _partial_names(tree)

        facts = _FileFacts(path=path)
        for lineno, rules in suppressions(source).items():
            if rules is None or self.name in rules:
                facts.suppressed.add(lineno)

        sentinel_consts = self._sentinel_consts(tree)

        jitted_defs: Set[str] = set()

        def note_jit_def(node: ast.FunctionDef) -> None:
            for d in node.decorator_list:
                if _is_jit_expr(d, jit, partials):
                    facts.jitted.add(node.name)
                    jitted_defs.add(node.name)
                    break

        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                note_jit_def(node)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        note_jit_def(sub)
            elif isinstance(node, ast.Assign):
                v = node.value
                if (
                    isinstance(v, ast.Call)
                    and _is_jit_expr(v.func, jit, partials)
                    and v.args
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            facts.jitted.add(t.id)

        findings: List[Finding] = []
        scopes: List[Tuple[str, ast.AST]] = [("<module>", tree)] + [
            (n.name, n) for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for fn_name, scope in scopes:
            self._overflow_pass(
                scope, fn_name, path, num_aliases, findings
            )
            facts.sentinel_fns.append(self._sentinel_facts(
                scope, fn_name, num_aliases, sentinel_consts
            ))
            if fn_name in jitted_defs:
                self._promotion_pass(
                    scope, fn_name, path, num_aliases, findings
                )
        self._collect_literal_sites(tree, facts)

        self._files.append(facts)
        return findings

    # ------------------------------------------------------- i32 overflow

    def _overflow_pass(
        self, scope, fn_name, path, num_aliases, findings
    ) -> None:
        i32: Set[str] = set()
        floaty: Set[str] = set()

        def expr_i32(v: ast.AST) -> bool:
            if isinstance(v, ast.Name):
                return v.id in i32
            if isinstance(v, (ast.Attribute, ast.Subscript)):
                r = _root_name(v)
                return r is not None and r in i32
            if isinstance(v, ast.BinOp) and isinstance(
                v.op, (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv, ast.Mod)
            ):
                return expr_i32(v.left) or expr_i32(v.right)
            if isinstance(v, ast.Call):
                tail = _call_tail(v)
                if tail == "astype":
                    base = v.func.value if isinstance(
                        v.func, ast.Attribute
                    ) else None
                    tag = _dtype_tag(v.args[0]) if v.args else None
                    if tag == "i32" and not isinstance(base, ast.Compare):
                        return True
                    return False
                if tail in _CERTIFIED_NARROWS:
                    return False  # certified: bounded by construction
                if _dtype_kwarg(v) == "i32":
                    return True
                if tail in ("where", "minimum", "maximum", "abs",
                            "absolute") and _call_head(v) in num_aliases:
                    return any(expr_i32(a) for a in v.args)
            return False

        def expr_floaty(v: ast.AST) -> bool:
            if isinstance(v, ast.Name):
                return v.id in floaty
            if isinstance(v, (ast.Attribute, ast.Subscript)):
                r = _root_name(v)
                return r is not None and r in floaty
            if isinstance(v, ast.BinOp):
                if isinstance(v.op, ast.Div):
                    return True
                return expr_floaty(v.left) or expr_floaty(v.right)
            if isinstance(v, ast.Call):
                tail = _call_tail(v)
                head = _call_head(v)
                if head in num_aliases and tail in _FLOOR_FNS:
                    # floor(x): unbounded float-ish unless x already
                    # carries a bound — floor itself adds none.
                    return True
                if head in num_aliases and tail == "where":
                    return any(expr_floaty(a) for a in v.args)
                if head in num_aliases and tail == "minimum":
                    # minimum bounds above ONLY when the other operand
                    # is itself bounded; min of two unbounded floats is
                    # still unbounded.
                    fl = [expr_floaty(a) for a in v.args]
                    return all(fl) if fl else False
                if head in num_aliases and tail == "maximum":
                    return any(expr_floaty(a) for a in v.args)
                if head in num_aliases and tail == "clip":
                    return False  # both bounds declared
                if tail in _CERTIFIED_NARROWS + _CERTIFIED_WIDENS:
                    return False
            return False

        for stmt in _ordered_simple_stmts(scope):
            # Sites first (RHS evaluates before the binding lands).
            for node in _walk_no_lambda(stmt):
                if isinstance(node, ast.Call):
                    self._overflow_call_site(
                        node, fn_name, path, num_aliases, i32, floaty,
                        findings,
                    )
                elif isinstance(node, ast.BinOp) and isinstance(
                    node.op, ast.Mult
                ):
                    lr = _root_name(node.left)
                    rr = _root_name(node.right)
                    if (
                        lr is not None and rr is not None
                        and lr in i32 and rr in i32
                    ):
                        findings.append(Finding(
                            path, node.lineno, self.name,
                            f"i32-overflow: `{lr} * {rr}` multiplies two "
                            "int32-tagged arrays — a count product is "
                            "exactly the PR 2 cluster-scale wrap; widen "
                            "one side to int64 (or document the bound "
                            "with # posecheck: ignore[numerics])",
                        ))
            targets = _assign_targets(stmt)
            if targets and getattr(stmt, "value", None) is not None:
                v = stmt.value
                is_i32 = expr_i32(v)
                is_fl = expr_floaty(v)
                for t in targets:
                    i32.add(t) if is_i32 else i32.discard(t)
                    floaty.add(t) if is_fl else floaty.discard(t)

    def _overflow_call_site(
        self, node, fn_name, path, num_aliases, i32, floaty, findings
    ) -> None:
        tail = _call_tail(node)
        head = _call_head(node)
        if tail in _ACC_REDUCTIONS:
            operand: Optional[ast.AST] = None
            if head in num_aliases and node.args:
                operand = node.args[0]
            elif isinstance(node.func, ast.Attribute) and head not in (
                num_aliases
            ):
                operand = node.func.value
            if operand is not None:
                root = _root_name(operand)
                acc = _dtype_kwarg(node)
                widened = acc in _WIDE_ACC_TAGS
                if root is not None and root in i32 and not widened:
                    findings.append(Finding(
                        path, node.lineno, self.name,
                        f"i32-overflow: `{tail}` over int32-tagged "
                        f"`{root}` accumulates in int32 and wraps "
                        "silently at scale — pass dtype=np.int64, "
                        "widen through utils.numerics.widen_counts, or "
                        "document the saturation bound "
                        "(# posecheck: ignore[numerics])",
                    ))
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "astype":
            # NOT via _call_tail: `np.floor(x).astype(i32)` roots the
            # attribute chain in a Call, where dotted_name returns None.
            tag = _dtype_tag(node.args[0]) if node.args else None
            if tag not in _NARROW_INT_TAGS:
                return
            base = node.func.value
            if isinstance(base, ast.Compare):
                return  # bool mask -> 0/1: no magnitude to wrap
            hazard = False
            if isinstance(base, ast.BinOp) and isinstance(
                base.op, ast.Div
            ):
                hazard = True
            elif isinstance(base, ast.Call):
                btail = _call_tail(base)
                bhead = _call_head(base)
                if bhead in num_aliases and btail in _FLOOR_FNS:
                    hazard = True
            else:
                root = _root_name(base)
                hazard = root is not None and root in floaty
            if hazard:
                subj = _root_name(base) or ast.unparse(base)
                findings.append(Finding(
                    path, node.lineno, self.name,
                    f"i32-overflow: narrowing `astype({tag})` of "
                    f"unbounded float-ish `{subj}` truncates through "
                    "the int32 rails silently — clamp first (np.clip / "
                    "np.minimum against a declared bound / "
                    "utils.numerics.checked_narrow_i32)",
                ))
            return
        if (
            tail in ("asarray", "array") and head in num_aliases
            and node.args and _dtype_kwarg(node) in _NARROW_INT_TAGS
        ):
            root = _root_name(node.args[0])
            if root is not None and root in floaty:
                findings.append(Finding(
                    path, node.lineno, self.name,
                    f"i32-overflow: `{tail}(..., dtype=int32)` of "
                    f"unbounded float-ish `{root}` truncates through "
                    "the int32 rails silently — clamp first (np.clip / "
                    "utils.numerics.checked_narrow_i32)",
                ))

    # ---------------------------------------------------- sentinel lattice

    def _sentinel_consts(self, tree: ast.Module) -> Set[str]:
        consts: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    local = a.asname or a.name
                    if "INF" in a.name and a.name.isupper():
                        consts.add(local)
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Name)
                        and "INF" in t.id
                        and t.id.isupper()
                        and not _mentions_call(node.value, ("float",))
                        and not any(
                            isinstance(n, ast.Name)
                            for n in ast.walk(node.value)
                        )
                    ):
                        consts.add(t.id)
        # float("inf") / np.inf sentinels are FLOAT planes — the
        # finiteness half of NumericsLedger owns those; this lattice is
        # the int32 sentinel (INF_COST-class) one.
        return consts

    def _sentinel_facts(
        self, scope, fn_name, num_aliases, consts
    ) -> _SentinelFn:
        sf = _SentinelFn(fn=fn_name)

        def guarded_where(call: ast.Call) -> bool:
            """A where whose condition tests finiteness — either float
            (isfinite/isinf) or integer (a comparison against a sentinel
            constant) — is the sanctioned guard, not a propagator."""
            if not call.args:
                return False
            cond = call.args[0]
            if _mentions_call(cond, ("isfinite", "isinf")):
                return True
            return any(
                isinstance(n, ast.Compare) and _mentions_name(n, consts)
                for n in ast.walk(cond)
            )

        def classify(v: ast.AST) -> _AssignSpec:
            if isinstance(v, ast.Call):
                tail = _call_tail(v)
                head = _call_head(v)
                if head in num_aliases and tail == "where":
                    value_args = v.args[1:]
                    if any(
                        _mentions_outside_compare(a, consts)
                        for a in value_args
                    ):
                        return ("seed",)  # rails written into the plane
                    if guarded_where(v):
                        return ("cleanse",)
                    roots = tuple(
                        r for a in v.args
                        for r in [_root_name(a)] if r
                    )
                    return ("taint_if", roots)
                if head in num_aliases and tail in (
                    "minimum", "clip"
                ):
                    # Bounded above by a non-tainted operand: the
                    # sentinel can no longer dominate arithmetic.
                    return ("cleanse",)
                if _mentions_outside_compare(v, consts):
                    return ("seed",)
                if tail is not None and "." not in (
                    dotted_name(v.func) or "."
                ):
                    return ("call", tail)
                # Method / dotted calls (cost.copy(), cost[ix].ravel()):
                # taint flows through the receiver and the arguments.
                roots = tuple(
                    r for src in ([v.func] + list(v.args))
                    for r in [_root_name(src)] if r
                )
                return ("taint_if", roots)
            if _mentions_outside_compare(v, consts):
                return ("seed",)
            roots = tuple(
                n.id for n in ast.walk(v) if isinstance(n, ast.Name)
            )
            return ("taint_if", roots)

        for stmt in _ordered_simple_stmts(scope):
            # Arithmetic lexically inside a guarded where's branches is
            # where-guarded by definition (the sentinel cells are
            # discarded by the select) — exclude those subtrees.
            guarded_nodes: Set[int] = set()
            for node in _walk_no_lambda(stmt):
                if (
                    isinstance(node, ast.Call)
                    and _call_tail(node) == "where"
                    and _call_head(node) in num_aliases
                    and guarded_where(node)
                ):
                    for arg in node.args[1:]:
                        guarded_nodes.update(
                            id(n) for n in ast.walk(arg)
                        )
            for node in _walk_no_lambda(stmt):
                if id(node) in guarded_nodes:
                    continue
                if isinstance(node, ast.BinOp) and isinstance(
                    node.op, (ast.Add, ast.Sub, ast.Mult)
                ):
                    if isinstance(
                        node.left, (ast.Tuple, ast.List)
                    ) or isinstance(node.right, (ast.Tuple, ast.List)):
                        continue  # tuple/list concat, not plane math
                    # Bare-Name/Subscript operands only; scalar rail
                    # math on the constant itself (INF_COST - 1) and
                    # attribute reads off tainted objects are sanctioned.
                    roots = tuple(
                        r for side in (node.left, node.right)
                        for r in [_site_root(side)]
                        if r and r not in consts
                    )
                    op = {
                        ast.Add: "+", ast.Sub: "-", ast.Mult: "*",
                    }[type(node.op)]
                    if roots:
                        sf.events.append((
                            "site_binop", node.lineno, op, roots, False,
                        ))
                elif isinstance(node, ast.Call):
                    tail = _call_tail(node)
                    head = _call_head(node)
                    operand: Optional[ast.AST] = None
                    if tail in _SENTINEL_REDUCTIONS:
                        if head in num_aliases and node.args:
                            operand = node.args[0]
                        elif isinstance(node.func, ast.Attribute):
                            operand = node.func.value
                    if operand is not None:
                        root = _root_name(operand)
                        if root:
                            sf.events.append((
                                "site_reduce", node.lineno, tail, root,
                            ))
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                elts = stmt.value.elts if isinstance(
                    stmt.value, (ast.Tuple, ast.List)
                ) else [stmt.value]
                roots = tuple(
                    r for e in elts for r in [_root_name(e)] if r
                )
                if roots:
                    sf.events.append(("return", stmt.lineno, roots))
            targets = _assign_targets(stmt)
            if targets and getattr(stmt, "value", None) is not None:
                sf.events.append((
                    "assign", stmt.lineno, targets,
                    classify(stmt.value),
                ))
        return sf

    def _replay_sentinel(
        self, sf: _SentinelFn, producers: Set[str],
    ) -> Tuple[bool, List[Tuple[int, str]]]:
        """(returns_tainted, [(line, message)]) for one function."""
        tainted: Set[str] = set()
        hits: List[Tuple[int, str]] = []
        returns_tainted = False
        for ev in sf.events:
            kind = ev[0]
            if kind == "assign":
                _k, _line, targets, spec = ev
                if spec[0] == "seed":
                    tainted.update(targets)
                elif spec[0] == "cleanse":
                    tainted.difference_update(targets)
                elif spec[0] == "taint_if":
                    if any(r in tainted for r in spec[1]):
                        tainted.update(targets)
                    else:
                        tainted.difference_update(targets)
                elif spec[0] == "call":
                    if spec[1] in producers:
                        tainted.update(targets)
                    else:
                        tainted.difference_update(targets)
            elif kind == "site_binop":
                _k, line, op, roots, always = ev
                bad = [r for r in roots if r in tainted]
                if always or bad:
                    subj = bad[0] if bad else "a sentinel constant"
                    hits.append((line, (
                        f"inf-sentinel: `{op}` through inf-carrying "
                        f"plane `{subj}` compounds the INF_COST "
                        "sentinel into ordinary-looking cost — guard "
                        "with np.where(np.isfinite(...)) / np.minimum "
                        "against a cap before arithmetic"
                    )))
            elif kind == "site_reduce":
                _k, line, opname, root = ev
                if root in tainted:
                    hits.append((line, (
                        f"inf-sentinel: `{opname}` over inf-carrying "
                        f"plane `{root}` mixes INF_COST sentinels into "
                        "the accumulated total — mask the forbidden "
                        "arcs first (min/max reductions stay legal)"
                    )))
            elif kind == "return":
                _k, _line, roots = ev
                if any(r in tainted for r in roots):
                    returns_tainted = True
        return returns_tainted, hits

    # ----------------------------------------------------------- promotion

    def _promotion_pass(
        self, scope, fn_name, path, num_aliases, findings
    ) -> None:
        tags: Dict[str, str] = {}

        def tag_of_expr(v: ast.AST) -> Optional[str]:
            if isinstance(v, ast.Call):
                tail = _call_tail(v)
                if tail == "astype" and v.args:
                    base = v.func.value if isinstance(
                        v.func, ast.Attribute
                    ) else None
                    if isinstance(base, ast.Compare):
                        return "bool"
                    return _dtype_tag(v.args[0])
                kw = _dtype_kwarg(v)
                if kw is not None:
                    return kw
                if _call_head(v) in num_aliases and tail in _DTYPE_TAGS:
                    return _DTYPE_TAGS[tail]  # jnp.float32(x) casts
            elif isinstance(v, ast.Name):
                return tags.get(v.id)
            return None

        for stmt in _ordered_simple_stmts(scope):
            for node in _walk_no_lambda(stmt):
                if not isinstance(node, ast.BinOp) or not isinstance(
                    node.op, (ast.Add, ast.Sub, ast.Mult, ast.Div)
                ):
                    continue
                lt = tags.get(node.left.id) if isinstance(
                    node.left, ast.Name
                ) else None
                rt = tags.get(node.right.id) if isinstance(
                    node.right, ast.Name
                ) else None
                if (
                    lt and rt and lt != rt
                    and "bool" not in (lt, rt)
                ):
                    ln = node.left.id     # type: ignore[union-attr]
                    rn = node.right.id    # type: ignore[union-attr]
                    findings.append(Finding(
                        path, node.lineno, self.name,
                        f"promotion: `{ln}` ({lt}) and `{rn}` ({rt}) "
                        f"mix dtypes in jitted `{fn_name}` — the "
                        "promotion table decides silently (weak-type "
                        "rules differ on accelerators); widen one "
                        "operand with an explicit astype",
                    ))
                    continue
                for side, other_tag in (
                    (node.left, rt), (node.right, lt),
                ):
                    if (
                        isinstance(side, ast.Constant)
                        and isinstance(side.value, float)
                        and other_tag is not None
                        and _family(other_tag) == "int"
                    ):
                        findings.append(Finding(
                            path, node.lineno, self.name,
                            f"promotion: Python float literal "
                            f"{side.value!r} against {other_tag} "
                            f"operand in jitted `{fn_name}` promotes "
                            "the whole array to weak float silently — "
                            "cast explicitly (jnp.float32(...)) or "
                            "keep the arithmetic integral",
                        ))
                        break
            targets = _assign_targets(stmt)
            if targets and getattr(stmt, "value", None) is not None:
                t = tag_of_expr(stmt.value)
                for name in targets:
                    if t is not None:
                        tags[name] = t
                    else:
                        tags.pop(name, None)

    def _collect_literal_sites(self, tree, facts) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if not callee or "." in callee:
                continue  # bare-name calls only: jitted defs/wrappers
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(
                    a.value, float
                ):
                    facts.jit_literal_sites.append(
                        (node.lineno, callee, repr(a.value))
                    )
                    break

    # ------------------------------------------------------------- finalize

    def finalize(self) -> List[Finding]:
        files, self._files = self._files, []
        findings: List[Finding] = []

        # Sentinel-lattice fixpoint: which functions return tainted
        # planes (cross-file by bare name, like the jitted-name union).
        producers: Set[str] = set()
        while True:
            nxt: Set[str] = set()
            for f in files:
                for sf in f.sentinel_fns:
                    rt, _hits = self._replay_sentinel(sf, producers)
                    if rt and sf.fn != "<module>":
                        nxt.add(sf.fn)
            if nxt == producers:
                break
            producers = nxt
        for f in files:
            for sf in f.sentinel_fns:
                _rt, hits = self._replay_sentinel(sf, producers)
                for line, msg in hits:
                    if line in f.suppressed:
                        continue
                    findings.append(Finding(f.path, line, self.name, msg))

        # Weak float literals at jit boundaries (scan-wide jitted union).
        jitted: Set[str] = set()
        for f in files:
            jitted.update(f.jitted)
        for f in files:
            for line, callee, lit in f.jit_literal_sites:
                if callee in jitted and line not in f.suppressed:
                    findings.append(Finding(
                        f.path, line, self.name,
                        f"promotion: Python float literal {lit} passed "
                        f"positionally at jit boundary `{callee}` is a "
                        "weak-typed scalar — the trace promotes by "
                        "table, not intent; bind an explicit dtype "
                        "(jnp.float32(...)) or pass it static",
                    ))

        findings.sort(key=lambda x: (x.path, x.line))
        # De-dup identical (path, line, message) triples: the same
        # arithmetic site can surface through several tainted aliases.
        seen: Set[Tuple[str, int, str]] = set()
        out: List[Finding] = []
        for fd in findings:
            key = (fd.path, fd.line, fd.message)
            if key not in seen:
                seen.add(key)
                out.append(fd)
        return out
