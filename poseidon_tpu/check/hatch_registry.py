"""hatch-registry: every POSEIDON_* env hatch reads through the registry.

``poseidon_tpu/utils/hatches.py`` is the single source of truth for the
~37 ``POSEIDON_*`` escape hatches (name, kind, default, one-line effect
— the generated ``docs/HATCHES.md`` table renders from it).  Before it,
hatch reads were ad-hoc ``os.environ.get`` calls with three different
boolean conventions and no registry, so a typo'd name read its default
forever, a renamed hatch left dead readers behind, and docs drifted
from code (the ``_try_chained_wave`` "default ON" docstring for an
opt-in flag).  This rule keeps the registry load-bearing:

- **bypass**: a direct ``os.environ`` / ``os.getenv`` READ of a
  ``POSEIDON_*`` string literal anywhere outside the registry module —
  registered or not — must go through the typed call-time accessors
  (``hatch_bool`` / ``hatch_int`` / ...), which also centralize the
  default and the parse-failure fallback.  Writes
  (``os.environ[...] = ...``, ``setdefault``) are fine: harnesses and
  probe latches legitimately *set* hatches for children.
- **undeclared**: an accessor call (or a bypassing read) naming a
  ``POSEIDON_*`` literal that the registry does not declare.  The
  accessors raise ``KeyError`` at runtime; this catches it at lint
  time, including in code paths no test executes.
- **dead flag** (project-scoped, judged in ``finalize``): a declared
  non-``external`` hatch whose name appears as a string literal in NO
  scanned file outside the registry.  Liveness is a whole-project
  property, so this sub-check stays silent unless the scan covered
  every liveness root (``poseidon_tpu/``, ``bench.py``, ``tools/`` —
  the scan set ``make lint`` walks); a partial scan must not flag a
  hatch whose one reader it simply didn't see.

Detection of "uses" for the dead-flag check is deliberately generous —
ANY string constant equal to the hatch name counts (accessor args,
``ENV_GATE``-style module constants later passed to an accessor,
``accel_policy("POSEIDON_FUSED")`` forwarding, environment writes in
tools) — so a false "dead" verdict requires the name to be truly
absent, while a false "live" verdict is possible and accepted (the
usual over-approximation posture of this suite: quiet on live code).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    from_imports,
    import_aliases,
    suppressions,
)

_PREFIX = "POSEIDON_"

# The typed accessors exported by the registry module; a str-literal
# first argument is statically checkable against the declarations.
_ACCESSORS = frozenset({
    "hatch", "hatch_raw", "hatch_set", "hatch_bool", "hatch_flag",
    "hatch_int", "hatch_float", "hatch_str",
})


def _parse_registry(path: Path) -> Tuple[Dict[str, int], Set[str], Set[int]]:
    """(name -> decl lineno, external-kind names, suppressed linenos)
    from the registry module source — parsed, never imported (the check
    CLI stays dependency-free)."""
    source = path.read_text()
    tree = ast.parse(source, filename=str(path))
    decls: Dict[str, int] = {}
    external: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func) in ("Hatch", "hatches.Hatch")):
            continue
        name = kind = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            kind = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                name = kw.value.value
            if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                kind = kw.value.value
        if name:
            decls[name] = node.lineno
            if kind == "external":
                external.add(name)
    suppressed = {
        lineno
        for lineno, rules in suppressions(source).items()
        if rules is None or HatchRegistryRule.name in rules
    }
    return decls, external, suppressed


class HatchRegistryRule(Rule):
    name = "hatch-registry"
    # Empty scopes: hatch reads live in poseidon_tpu/, bench.py, and
    # tools/ alike — every scanned file participates.
    scopes: tuple = ()

    _REGISTRY_FRAGMENT = "poseidon_tpu/utils/hatches.py"
    # Dead-flag liveness roots: the sub-check judges only when the scan
    # saw files under EVERY one of these (the `make lint` scan set).
    _LIVENESS_ROOTS = (
        "poseidon_tpu/", "bench.py", "tools/", "__graft_entry__.py",
    )

    def __init__(
        self,
        registry_path: Optional[Path] = None,
        liveness_roots: Optional[Sequence[str]] = None,
    ) -> None:
        # Default registry: resolved relative to this package so the
        # rule works from any cwd; fixtures inject their own.
        self._registry_path = registry_path or (
            Path(__file__).resolve().parent.parent / "utils" / "hatches.py"
        )
        if liveness_roots is not None:
            self._liveness_roots = tuple(liveness_roots)
        else:
            self._liveness_roots = self._LIVENESS_ROOTS
        self._decls: Optional[Dict[str, int]] = None
        self._external: Set[str] = set()
        self._reg_suppressed: Set[int] = set()
        self._seen_constants: Set[str] = set()
        self._scanned_paths: List[str] = []

    # ------------------------------------------------------------- registry

    def _registry(self) -> Dict[str, int]:
        if self._decls is None:
            try:
                self._decls, self._external, self._reg_suppressed = (
                    _parse_registry(self._registry_path)
                )
            except (OSError, SyntaxError):
                # No registry to check against (downstream vendoring the
                # checker without the registry): the rule stays silent
                # rather than flagging every hatch as undeclared.
                self._decls = {}
        return self._decls

    def _is_registry_module(self, path: str) -> bool:
        return path.replace("\\", "/").endswith("utils/hatches.py")

    # ---------------------------------------------------------------- check

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        self._scanned_paths.append(path)
        decls = self._registry()
        findings: List[Finding] = []

        # Liveness facts first: every POSEIDON_* string constant in a
        # non-registry file marks its hatch as referenced.
        in_registry = self._is_registry_module(path)
        if not in_registry:
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ) and node.value.startswith(_PREFIX):
                    self._seen_constants.add(node.value)
        if in_registry:
            return []

        os_aliases = import_aliases(tree, "os")
        env_fns = {
            local
            for local, orig in from_imports(tree, "os").items()
            if orig in ("getenv", "environ")
        }
        accessor_locals = {
            local: orig
            for local, orig in from_imports(
                tree, "poseidon_tpu.utils.hatches"
            ).items()
            if orig in _ACCESSORS
        }

        def literal_hatch(node: ast.AST) -> Optional[str]:
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ) and node.value.startswith(_PREFIX):
                return node.value
            return None

        def flag_read(node: ast.AST, name: str) -> None:
            if name in decls:
                findings.append(Finding(
                    path, node.lineno, self.name,
                    f"direct environment read of `{name}` bypasses the "
                    "hatch registry; use the typed accessor "
                    "(poseidon_tpu.utils.hatches) so default and parse "
                    "semantics stay centralized",
                ))
            else:
                findings.append(Finding(
                    path, node.lineno, self.name,
                    f"undeclared hatch `{name}`: declare it in "
                    "poseidon_tpu/utils/hatches.py (name, kind, "
                    "default, one-line effect) before reading it",
                ))

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname is None:
                    continue
                head, _, rest = fname.partition(".")
                # os.environ.get("POSEIDON_X") / os.getenv("POSEIDON_X")
                if (head in os_aliases and rest in (
                        "getenv", "environ.get")) or (
                        head in env_fns and rest in ("", "get")):
                    if node.args:
                        name = literal_hatch(node.args[0])
                        if name:
                            flag_read(node, name)
                    continue
                # accessor("POSEIDON_X"): undeclared names flag; the
                # registry module's own helpers are exempt above.
                orig = accessor_locals.get(fname) or (
                    rest if head == "hatches" and rest in _ACCESSORS
                    else None
                )
                if orig and node.args:
                    name = literal_hatch(node.args[0])
                    if name and name not in decls and decls:
                        findings.append(Finding(
                            path, node.lineno, self.name,
                            f"accessor read of undeclared hatch `{name}`"
                            ": the registry accessor will raise KeyError"
                            " at call time — declare it in "
                            "poseidon_tpu/utils/hatches.py",
                        ))
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                # os.environ["POSEIDON_X"] reads (stores/dels are
                # legitimate harness latches).
                vname = dotted_name(node.value)
                if vname is None:
                    continue
                head, _, rest = vname.partition(".")
                is_environ = (head in os_aliases and rest == "environ") \
                    or (head in env_fns and not rest)
                if is_environ:
                    name = literal_hatch(node.slice)
                    if name:
                        flag_read(node, name)
        return findings

    # ------------------------------------------------------------- finalize

    def finalize(self) -> List[Finding]:
        scanned, self._scanned_paths = self._scanned_paths, []
        seen, self._seen_constants = self._seen_constants, set()
        decls = self._registry()
        if not decls:
            return []
        registry_scanned = any(
            self._is_registry_module(p) for p in scanned
        )
        covered = all(
            any(root in p for p in scanned)
            for root in self._liveness_roots
        )
        if not (registry_scanned and covered):
            # Partial scan: a hatch's one reader may simply not have
            # been walked — liveness is not judgeable.
            return []
        reg_rel = self._registry_rel(scanned)
        findings: List[Finding] = []
        for name, lineno in sorted(decls.items()):
            if name in self._external or name in seen:
                continue
            if lineno in self._reg_suppressed:
                continue
            findings.append(Finding(
                reg_rel, lineno, self.name,
                f"declared hatch `{name}` is never read anywhere in the "
                "scanned tree (dead flag): delete the declaration or "
                "wire the reader through an accessor",
            ))
        return findings

    def _registry_rel(self, scanned: Sequence[str]) -> str:
        for p in scanned:
            if self._is_registry_module(p):
                return p
        return self._REGISTRY_FRAGMENT
