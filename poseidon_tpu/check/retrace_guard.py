"""retrace-guard: static recompile/dispatch-hazard analysis at jit boundaries.

Scope: ``poseidon_tpu/ops/`` and ``poseidon_tpu/graph/`` — the solver
kernels and the round planner that feeds them.  PR 3's headline finding
was that a "solver-bound" 15.2 s gang round was really two silent fresh
XLA compiles plus a poisoned warm start, found only by a manual
profiling session; this rule makes that bug class a lint failure.  Four
hazard patterns, all of which mint fresh compile keys (or silently
promote dtypes) without any visible code smell at the call site:

- **local jit construction**: ``jax.jit(...)`` / ``functools.partial(
  jax.jit, ...)`` evaluated inside a function or loop builds a *fresh
  compile cache per call* — every invocation retraces and recompiles,
  no matter how stable the shapes are.  Jitted callables must be
  module-level (decorator or module-level assignment), where the cache
  is process-lived.
- **non-array constant at a traced position**: a ``str``/``bool``
  literal passed to a jitted callable in a parameter *not* listed in
  ``static_argnames``.  This is exactly what dropping a
  ``static_argnames`` entry looks like from the call site: the value
  either fails to trace (str) or traces as a weak-typed array whose
  Python-level branch uses then crash — and on signatures that survive,
  each distinct value mints a fresh executable.
- **instance-varying static argument**: an argument bound to a
  ``static_argnames`` entry whose expression derives from ``len(...)``
  or ``.shape`` — a per-round-varying Python value used as a compile
  key retraces *per value* (the round-2 churn storm), where a padded
  bucket (``bucket_size`` / ``padded_shape``) holds the key fixed.
- **unpadded shape at the boundary**: an array constructed with a raw
  ``len(...)``-derived extent (``np.zeros(len(xs))``) passed straight
  to a jitted callable.  Shapes are compile keys; the padding-bucket
  helpers in ``ops/transport.py`` / ``graph/instance.py`` exist so
  per-round count churn lands on a small fixed set of padded sizes.
- **weak-type float at the boundary**: a Python float literal (or a
  ``float(...)``/``np.float64(...)`` cast) passed as a traced argument.
  jax types it as a weak float, which both mints a compile key distinct
  from the int32 planes everything else carries *and* silently promotes
  the arithmetic it touches (wrong dtype in the cost planes, then a
  second retrace when an int32 path reappears).

Detection reuses the jit-discovery machinery from ``jit_purity``:
module-level defs decorated with ``jax.jit`` / ``partial(jax.jit,
...)`` and module-level ``g = jax.jit(f)`` wrappers are the known jit
boundary; their ``static_argnames`` tuples are parsed from the
decorator/wrapper so call-site arguments can be classified
static-vs-traced through the actual signature.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from poseidon_tpu.check.core import Finding, Rule, dotted_name
from poseidon_tpu.check.jit_purity import (
    _is_jit_expr,
    _jit_names,
    _partial_names,
)

# Call names that normalize a varying count onto a fixed compile bucket;
# a len()/.shape occurrence under one of these is the sanctioned pattern,
# not a hazard.  Matched on the trailing identifier so both
# ``bucket_size`` and ``transport.bucket_size`` qualify.
_PADDING_HELPERS = ("bucket_size", "padded_shape")


def _is_padding_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if not name:
        return False
    tail = name.split(".")[-1]
    return tail in _PADDING_HELPERS or "pad" in tail


def _contains_varying(node: ast.AST) -> bool:
    """Does this expression derive from len(...) or .shape, outside any
    padding-helper call?"""
    if isinstance(node, ast.Call):
        if _is_padding_call(node):
            return False
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            return True
    if isinstance(node, ast.Attribute) and node.attr == "shape":
        return True
    return any(_contains_varying(c) for c in ast.iter_child_nodes(node))


# Array constructors whose first argument is a shape: a raw varying
# extent here puts a per-round shape on the compile key.
_SHAPE_CTORS = ("zeros", "ones", "full", "empty", "arange")


def _unpadded_shape_ctor(node: ast.AST) -> Optional[ast.Call]:
    """First array-constructor call in the expression whose shape
    argument varies unpadded, else None."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = dotted_name(sub.func)
        if not name or name.split(".")[-1] not in _SHAPE_CTORS:
            continue
        if sub.args and _contains_varying(sub.args[0]):
            return sub
    return None


def _weak_float_expr(node: ast.AST) -> bool:
    """Is this expression a Python-float-valued literal or cast?"""
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return True
    if isinstance(node, ast.UnaryOp):
        return _weak_float_expr(node.operand)
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name and name.split(".")[-1] in ("float", "float64", "float32"):
            return True
    return False


def _static_spec(call: ast.Call) -> Tuple[Set[str], Set[int], bool]:
    """``(names, positional indices, unparseable)`` from the
    ``static_argnames`` / ``static_argnums`` keywords of a
    ``jax.jit(...)`` / ``partial(jax.jit, ...)`` call.  A spec built
    from a variable or comprehension (not constants) is ``unparseable``
    — the def is then treated as opaque and its call sites are never
    judged, because guessing static-vs-traced there guarantees false
    positives one way or the other."""
    names: Set[str] = set()
    nums: Set[int] = set()
    unparseable = False
    for kw in call.keywords:
        if kw.arg not in ("static_argnames", "static_argnums"):
            continue
        v = kw.value
        elts = (
            list(v.elts) if isinstance(v, (ast.Tuple, ast.List, ast.Set))
            else [v]
        )
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                names.add(e.value)
            elif isinstance(e, ast.Constant) and isinstance(e.value, int):
                nums.add(e.value)
            else:
                unparseable = True
    return names, nums, unparseable


class _JitDef:
    """A module-level jitted callable: its signature (when the wrapped
    def is in this module) and its static parameter set."""

    def __init__(
        self,
        fn: Optional[ast.FunctionDef],
        static: Set[str],
        static_nums: Set[int] = frozenset(),
        opaque: bool = False,
    ):
        self.fn = fn
        self.static = set(static)
        self.opaque = opaque
        self.params: List[str] = []
        self.has_varargs = False
        if fn is not None:
            a = fn.args
            self.params = [p.arg for p in a.posonlyargs + a.args]
            self.has_varargs = a.vararg is not None
        # static_argnums resolve to names through the signature; an
        # index we cannot map (no signature, or out of range) makes the
        # whole def opaque rather than mis-classified.
        for i in static_nums:
            if fn is not None and 0 <= i < len(self.params):
                self.static.add(self.params[i])
            else:
                self.opaque = True

    def param_for_pos(self, i: int) -> Optional[str]:
        if i < len(self.params):
            return self.params[i]
        return None


class RetraceGuardRule(Rule):
    name = "retrace-guard"
    scopes = ("poseidon_tpu/ops/", "poseidon_tpu/graph/")

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        assert isinstance(tree, ast.Module)
        jit = _jit_names(tree)
        partials = _partial_names(tree)

        findings: List[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            findings.append(Finding(path, node.lineno, self.name, message))

        # ---- known jit boundary: module-level defs + wrappers ----------
        jit_defs: Dict[str, _JitDef] = {}
        table: Dict[str, ast.FunctionDef] = {
            n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
        }
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                for d in node.decorator_list:
                    if _is_jit_expr(d, jit, partials):
                        if isinstance(d, ast.Call):
                            names, nums, opaque = _static_spec(d)
                        else:
                            names, nums, opaque = set(), set(), False
                        jit_defs[node.name] = _JitDef(
                            node, names, nums, opaque
                        )
                        break
            elif isinstance(node, ast.Assign):
                v = node.value
                if (
                    isinstance(v, ast.Call)
                    and _is_jit_expr(v.func, jit, partials)
                    and v.args
                ):
                    inner = dotted_name(v.args[0])
                    names, nums, opaque = _static_spec(v)
                    if isinstance(v.func, ast.Call):
                        n2, m2, o2 = _static_spec(v.func)
                        names |= n2
                        nums |= m2
                        opaque = opaque or o2
                    fn = table.get(inner) if inner else None
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jit_defs[t.id] = _JitDef(
                                fn, names, nums, opaque
                            )

        # ---- hazard 1: jit constructed inside a function/loop ----------
        # Walk function BODIES only: a module-level def's own
        # `@partial(jax.jit, ...)` decorator is the sanctioned pattern,
        # not a hazard (decorator nodes are children of the FunctionDef).
        # Scan units: module-level functions, CLASS METHODS (the round
        # planner in graph/ is almost entirely methods), and module-
        # level loop bodies; nested defs are reached within their
        # enclosing unit's walk.
        units: List[ast.FunctionDef] = list(table.values())
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                units.extend(
                    n for n in node.body if isinstance(n, ast.FunctionDef)
                )
        def scan_module_loops(node: ast.AST, in_loop: bool) -> None:
            # Module-level statements outside any def/class, tracking
            # loop context at ANY depth (a backend-gated `if:` around a
            # warm-up loop is the realistic ops/ shape).  A conditional
            # one-shot `g = jax.jit(f)` stays sanctioned — only
            # constructions lexically inside a For/While flag.
            if isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                return
            if in_loop and isinstance(node, ast.Call) and _is_jit_expr(
                node, jit, partials
            ):
                flag(node, "jit wrapper constructed inside a module-"
                           "level loop mints a fresh compile cache "
                           "per iteration; hoist out of the loop")
                return
            child_in_loop = in_loop or isinstance(
                node, (ast.For, ast.While)
            )
            for child in ast.iter_child_nodes(node):
                scan_module_loops(child, child_in_loop)

        for stmt in tree.body:
            scan_module_loops(stmt, False)
        for fn in units:
            for stmt in fn.body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call) and _is_jit_expr(
                        node, jit, partials
                    ):
                        flag(node, "jit wrapper constructed inside "
                                   f"`{fn.name}()` mints a fresh compile "
                                   "cache per call (retrace + recompile "
                                   "every invocation); hoist to module "
                                   "level")
                    elif isinstance(node, ast.FunctionDef):
                        # Call-shaped decorators (partial(jax.jit, ...))
                        # are flagged by the Call branch above; this
                        # covers the bare `@jax.jit` attribute form.
                        for d in node.decorator_list:
                            if not isinstance(d, ast.Call) and \
                                    _is_jit_expr(d, jit, partials):
                                flag(d, f"`@jit` on nested `{node.name}()` "
                                        "builds a fresh compile cache per "
                                        f"`{fn.name}()` call; hoist to "
                                        "module level")

        # ---- hazards 2-5: call sites of the known jit boundary ---------
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            callee = (
                node.func.id if isinstance(node.func, ast.Name) else None
            )
            if callee not in jit_defs:
                continue
            jd = jit_defs[callee]
            if jd.opaque:
                continue  # static spec unresolvable: never guess
            bound: List[Tuple[Optional[str], ast.AST]] = []
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred):
                    continue
                if jd.fn is None and jd.static:
                    # Wrapper around a function defined elsewhere WITH
                    # static names: jax binds positionals to those
                    # names through the real signature, which we cannot
                    # see — classifying them static-vs-traced would be
                    # a guess, so positionals are skipped (keywords
                    # still classify exactly by name).
                    continue
                bound.append((jd.param_for_pos(i), a))
            for kw in node.keywords:
                if kw.arg is not None:
                    bound.append((kw.arg, kw.value))
            for pname, value in bound:
                is_static = pname is not None and pname in jd.static
                where = f"`{callee}(... {pname or '<pos>'}=)`"
                if is_static:
                    if _contains_varying(value):
                        flag(value, f"static argument {where} derives "
                                    "from len()/.shape: a per-instance "
                                    "value as a compile key retraces per "
                                    "value; bucket it (bucket_size/"
                                    "padded_shape) or make it traced")
                    continue
                if isinstance(value, ast.Constant) and isinstance(
                    value.value, (str, bool)
                ):
                    flag(value, f"{type(value.value).__name__} constant "
                                f"at traced position {where}: list the "
                                "parameter in static_argnames (a dropped "
                                "entry retraces or fails per value)")
                    continue
                if _weak_float_expr(value):
                    flag(value, f"Python float at traced position {where} "
                                "enters as a weak f32/f64: new compile "
                                "key vs the int32 planes plus silent "
                                "dtype promotion; use an int or an "
                                "explicitly-dtyped array")
                    continue
                ctor = _unpadded_shape_ctor(value)
                if ctor is not None:
                    flag(ctor, f"array with raw len()/.shape-derived "
                               f"extent reaches jit boundary {where}: "
                               "per-round counts are compile keys; pad "
                               "through bucket_size/padded_shape first")
        return findings
