"""Runtime compile ledger: assert an exact fresh-XLA-compile budget.

The static rules (``retrace-guard``, ``dispatch-budget``) catch the
*patterns* that mint compile keys; this module catches the *events*.
PR 3's "solver-bound" 15.2 s gang round was two silent fresh compiles
plus a poisoned warm start — invisible in every latency metric except
wall time, found only by a manual profiling session.  The ledger makes
"zero fresh compiles in a warm round" a cheap, permanent regression
gate instead of hard-won tribal knowledge.

Two layers:

- ``fresh_compile_count()``: a process-wide monotonic counter of
  backend (XLA) compiles, fed by a ``jax.monitoring`` duration-event
  listener.  Callers difference it around a window, exactly like
  ``transport.device_call_count()`` — ``RoundMetrics.fresh_compiles``
  and the bench sub-reports are wired this way.
- ``CompileLedger``: a context manager wrapping a window in an exact
  budget.  On exit, ``fresh_compiles > budget`` raises
  ``CompileBudgetExceeded`` naming the programs that compiled (captured
  from ``jax.log_compiles`` while the window is open), so the failure
  message says *what* retraced, not just that something did.

The listener counts ``/jax/core/compile/backend_compile_duration``
events: one per fresh XLA executable, helper programs included
(``jnp.ones`` and friends are their own tiny jit programs), and zero
for compile-cache hits — which is the correct strictness for a warm
window, where *nothing* should compile.  Tracing-only work (a jaxpr
re-trace that hits the executable cache) is surfaced separately via
``retraces`` for diagnostics but never counted against the budget.

Listener registration is lazy (first use) and permanent:
``jax.monitoring`` offers no single-listener deregistration, so one
module-level hook dispatches to whatever ledgers are active — cheap
enough (an int bump on a compile, which costs milliseconds anyway) to
leave installed.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import List, Optional

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

# "Compiling <name> with global shapes and types [...]" — the pxla log
# line emitted under jax.log_compiles(True); the payload that turns a
# budget failure into an actionable message.
_COMPILING_RE = re.compile(r"Compiling (\S+) with global shapes")

_lock = threading.Lock()
_installed = False
_compile_count = 0
_trace_count = 0
_active: List["CompileLedger"] = []


def _listener(event: str, duration: float, **kwargs) -> None:
    global _compile_count, _trace_count
    if event == _COMPILE_EVENT:
        with _lock:
            _compile_count += 1
            for led in _active:
                led._fresh += 1
    elif event == _TRACE_EVENT:
        with _lock:
            _trace_count += 1
            for led in _active:
                led._retraces += 1


def _ensure_listener() -> None:
    global _installed
    if _installed:
        return
    with _lock:
        if _installed:
            return
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def fresh_compile_count() -> int:
    """Process-wide count of fresh XLA backend compiles since the first
    ledger/counter use.  Difference around a window (a scheduling round,
    a bench config) the same way ``device_call_count`` is used."""
    _ensure_listener()
    return _compile_count


def retrace_count() -> int:
    """Process-wide count of jaxpr traces (diagnostic companion to
    ``fresh_compile_count``: a climbing trace count with a flat compile
    count means retracing into a warm executable cache)."""
    _ensure_listener()
    return _trace_count


class CompileBudgetExceeded(AssertionError):
    """A ledger window compiled more fresh XLA programs than budgeted."""


class _NameCapture(logging.Handler):
    def __init__(self, sink: List[str]):
        super().__init__(level=logging.DEBUG)
        self._sink = sink

    def emit(self, record: logging.LogRecord) -> None:
        m = _COMPILING_RE.search(record.getMessage())
        if m:
            self._sink.append(m.group(1))


class CompileLedger:
    """Context manager asserting an exact fresh-compile budget.

    >>> with CompileLedger(budget=0, label="warm gang round"):
    ...     planner.schedule_round()

    ``budget=None`` records without asserting (telemetry mode).  The
    assertion is raised from ``__exit__`` only when the body itself did
    not raise — a real failure inside the window must not be masked by
    the budget report.
    """

    # The logger whose "Compiling <name> ..." records identify fresh
    # programs under jax.log_compiles; the dispatch logger carries the
    # noisy per-stage "Finished ..." lines that must not leak to stderr
    # while the window holds log_compiles open.
    _PXLA_LOGGER = "jax._src.interpreters.pxla"
    _QUIET_LOGGERS = (_PXLA_LOGGER, "jax._src.dispatch")

    def __init__(self, budget: Optional[int] = 0, label: str = ""):
        self.budget = budget
        self.label = label
        self._fresh = 0
        self._retraces = 0
        self.compiled_names: List[str] = []
        self._log_ctx = None
        self._handler: Optional[_NameCapture] = None
        self._prev_propagate: dict = {}

    # -- telemetry ---------------------------------------------------------

    @property
    def fresh_compiles(self) -> int:
        return self._fresh

    @property
    def retraces(self) -> int:
        return self._retraces

    # -- context protocol --------------------------------------------------

    def __enter__(self) -> "CompileLedger":
        _ensure_listener()
        import jax

        # Capture compiled-program names while the window is open; the
        # pxla/dispatch loggers normally propagate to root at WARNING
        # under log_compiles, which would spam test output — disable
        # propagation for the window and restore on exit.
        # The handler goes on EVERY quieted logger (the regex only
        # matches pxla's "Compiling ..." lines): with propagation off, a
        # logger with no handler would fall through to logging's
        # lastResort stderr handler, defeating the quieting.
        self._handler = _NameCapture(self.compiled_names)
        for name in self._QUIET_LOGGERS:
            lg = logging.getLogger(name)
            lg.addHandler(self._handler)
            self._prev_propagate[name] = lg.propagate
            lg.propagate = False
        self._log_ctx = jax.log_compiles(True)
        self._log_ctx.__enter__()
        with _lock:
            _active.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        with _lock:
            if self in _active:
                _active.remove(self)
        if self._log_ctx is not None:
            self._log_ctx.__exit__(exc_type, exc, tb)
            self._log_ctx = None
        for name, prev in self._prev_propagate.items():
            lg = logging.getLogger(name)
            if self._handler is not None:
                lg.removeHandler(self._handler)
            lg.propagate = prev
        self._handler = None
        self._prev_propagate = {}
        if exc_type is None and self.budget is not None \
                and self._fresh > self.budget:
            where = f" in {self.label}" if self.label else ""
            names = ", ".join(self.compiled_names) or "<names not captured>"
            raise CompileBudgetExceeded(
                f"{self._fresh} fresh XLA compile(s){where}, budget "
                f"{self.budget}; compiled: {names}.  A warm path minted "
                "new compile keys — look for shape/dtype/static-arg "
                "drift at the jit boundary (posecheck retrace-guard "
                "names the static patterns)."
            )
        return False
