"""Runtime compile + transfer ledgers: exact budgets for the two silent
per-round costs — fresh XLA compiles and implicit device->host syncs.

The static rules (``retrace-guard``, ``dispatch-budget``) catch the
*patterns* that mint compile keys; this module catches the *events*.
PR 3's "solver-bound" 15.2 s gang round was two silent fresh compiles
plus a poisoned warm start — invisible in every latency metric except
wall time, found only by a manual profiling session.  The ledger makes
"zero fresh compiles in a warm round" a cheap, permanent regression
gate instead of hard-won tribal knowledge.

Two layers:

- ``fresh_compile_count()``: a process-wide monotonic counter of
  backend (XLA) compiles, fed by a ``jax.monitoring`` duration-event
  listener.  Callers difference it around a window, exactly like
  ``transport.device_call_count()`` — ``RoundMetrics.fresh_compiles``
  and the bench sub-reports are wired this way.
- ``CompileLedger``: a context manager wrapping a window in an exact
  budget.  On exit, ``fresh_compiles > budget`` raises
  ``CompileBudgetExceeded`` naming the programs that compiled (captured
  from ``jax.log_compiles`` while the window is open), so the failure
  message says *what* retraced, not just that something did.

The listener counts ``/jax/core/compile/backend_compile_duration``
events: one per fresh XLA executable, helper programs included
(``jnp.ones`` and friends are their own tiny jit programs), and zero
for compile-cache hits — which is the correct strictness for a warm
window, where *nothing* should compile.  Tracing-only work (a jaxpr
re-trace that hits the executable cache) is surfaced separately via
``retraces`` for diagnostics but never counted against the budget.

Listener registration is lazy (first use) and permanent:
``jax.monitoring`` offers no single-listener deregistration, so one
module-level hook dispatches to whatever ledgers are active — cheap
enough (an int bump on a compile, which costs milliseconds anyway) to
leave installed.

``TransferLedger`` is the transfer-side twin (the runtime complement of
the ``transfer-discipline`` static rule): it counts *implicit*
device->host synchronizations in a window and asserts a budget.  Two
detection layers, because no single mechanism covers every backend:

- ``jax.transfer_guard_device_to_host("disallow")`` held open for
  budget-0 windows — on accelerator backends any implicit d2h copy
  (``np.asarray`` on a device array, buffer-protocol reads) raises at
  the offending op with jax's own description.  On the CPU backend
  these conversions are zero-copy and the guard never consults — which
  is why a second layer exists;
- an install-once interposer over the scalar-sync methods jax itself
  attaches to its array class (``item``/``tolist``/``__float__``/
  ``__int__``/``__bool__``/``__index__``): each call is a blocking
  device->host sync on EVERY backend (a ~60-150 ms tunnel slot on the
  production TPU), counted process-wide exactly like
  ``fresh_compile_count`` and attributed to the offending call site in
  the budget report.  Explicit fetches (``jax.device_get``,
  ``transport.host_fetch``) return numpy and are never counted — that
  is the declared-boundary discipline the static rule enforces.
"""

from __future__ import annotations

import logging
import re
import threading
from typing import List, Optional

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

# "Compiling <name> with global shapes and types [...]" — the pxla log
# line emitted under jax.log_compiles(True); the payload that turns a
# budget failure into an actionable message.
_COMPILING_RE = re.compile(r"Compiling (\S+) with global shapes")

_lock = threading.Lock()
_installed = False
_compile_count = 0
_trace_count = 0
_active: List["CompileLedger"] = []


def _listener(event: str, duration: float, **kwargs) -> None:
    global _compile_count, _trace_count
    if event == _COMPILE_EVENT:
        with _lock:
            _compile_count += 1
            for led in _active:
                led._fresh += 1
    elif event == _TRACE_EVENT:
        with _lock:
            _trace_count += 1
            for led in _active:
                led._retraces += 1


def _ensure_listener() -> None:
    global _installed
    if _installed:
        return
    with _lock:
        if _installed:
            return
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def fresh_compile_count() -> int:
    """Process-wide count of fresh XLA backend compiles since the first
    ledger/counter use.  Difference around a window (a scheduling round,
    a bench config) the same way ``device_call_count`` is used."""
    _ensure_listener()
    return _compile_count


def retrace_count() -> int:
    """Process-wide count of jaxpr traces (diagnostic companion to
    ``fresh_compile_count``: a climbing trace count with a flat compile
    count means retracing into a warm executable cache)."""
    _ensure_listener()
    return _trace_count


class CompileBudgetExceeded(AssertionError):
    """A ledger window compiled more fresh XLA programs than budgeted."""


class _NameCapture(logging.Handler):
    def __init__(self, sink: List[str]):
        super().__init__(level=logging.DEBUG)
        self._sink = sink

    def emit(self, record: logging.LogRecord) -> None:
        m = _COMPILING_RE.search(record.getMessage())
        if m:
            self._sink.append(m.group(1))


class CompileLedger:
    """Context manager asserting an exact fresh-compile budget.

    >>> with CompileLedger(budget=0, label="warm gang round"):
    ...     planner.schedule_round()

    ``budget=None`` records without asserting (telemetry mode).  The
    assertion is raised from ``__exit__`` only when the body itself did
    not raise — a real failure inside the window must not be masked by
    the budget report.
    """

    # The logger whose "Compiling <name> ..." records identify fresh
    # programs under jax.log_compiles; the dispatch logger carries the
    # noisy per-stage "Finished ..." lines that must not leak to stderr
    # while the window holds log_compiles open.
    _PXLA_LOGGER = "jax._src.interpreters.pxla"
    _QUIET_LOGGERS = (_PXLA_LOGGER, "jax._src.dispatch")

    def __init__(self, budget: Optional[int] = 0, label: str = ""):
        self.budget = budget
        self.label = label
        self._fresh = 0
        self._retraces = 0
        self.compiled_names: List[str] = []
        self._log_ctx = None
        self._handler: Optional[_NameCapture] = None
        self._prev_propagate: dict = {}

    # -- telemetry ---------------------------------------------------------

    @property
    def fresh_compiles(self) -> int:
        return self._fresh

    @property
    def retraces(self) -> int:
        return self._retraces

    # -- context protocol --------------------------------------------------

    def __enter__(self) -> "CompileLedger":
        _ensure_listener()
        import jax

        # Capture compiled-program names while the window is open; the
        # pxla/dispatch loggers normally propagate to root at WARNING
        # under log_compiles, which would spam test output — disable
        # propagation for the window and restore on exit.
        # The handler goes on EVERY quieted logger (the regex only
        # matches pxla's "Compiling ..." lines): with propagation off, a
        # logger with no handler would fall through to logging's
        # lastResort stderr handler, defeating the quieting.
        self._handler = _NameCapture(self.compiled_names)
        for name in self._QUIET_LOGGERS:
            lg = logging.getLogger(name)
            lg.addHandler(self._handler)
            self._prev_propagate[name] = lg.propagate
            lg.propagate = False
        self._log_ctx = jax.log_compiles(True)
        self._log_ctx.__enter__()
        with _lock:
            _active.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        with _lock:
            if self in _active:
                _active.remove(self)
        if self._log_ctx is not None:
            self._log_ctx.__exit__(exc_type, exc, tb)
            self._log_ctx = None
        for name, prev in self._prev_propagate.items():
            lg = logging.getLogger(name)
            if self._handler is not None:
                lg.removeHandler(self._handler)
            lg.propagate = prev
        self._handler = None
        self._prev_propagate = {}
        if exc_type is None and self.budget is not None \
                and self._fresh > self.budget:
            where = f" in {self.label}" if self.label else ""
            names = ", ".join(self.compiled_names) or "<names not captured>"
            raise CompileBudgetExceeded(
                f"{self._fresh} fresh XLA compile(s){where}, budget "
                f"{self.budget}; compiled: {names}.  A warm path minted "
                "new compile keys — look for shape/dtype/static-arg "
                "drift at the jit boundary (posecheck retrace-guard "
                "names the static patterns)."
            )
        return False


# ------------------------------------------------------------ transfers

# Scalar-coercion methods jax attaches (in Python) to its array class;
# each call blocks the host on the device queue and ships the value —
# an implicit device->host sync on every backend.
_SYNC_METHODS = (
    "item", "tolist", "__float__", "__int__", "__bool__", "__index__",
)

_sync_installed = False
_transfer_count = 0
_transfer_active: List["TransferLedger"] = []


def _describe_sync(method: str, arr) -> str:
    """`float() on int32[] at instance.py:812` — the actionable half of
    a budget failure.  Stack walk only happens on a counted sync with a
    ledger open, so the cost sits on the already-expensive path."""
    import traceback

    try:
        shape = getattr(arr, "shape", ())
        dtype = getattr(arr, "dtype", "?")
        desc = f"{method}() on {dtype}{list(shape)}"
    except Exception:  # noqa: BLE001 - description must never raise
        desc = f"{method}()"
    try:
        for frame in reversed(traceback.extract_stack(limit=16)):
            fn = frame.filename.replace("\\", "/")
            if "check/ledger.py" in fn or "/jax/" in fn \
                    or "jax/_src" in fn:
                continue
            return f"{desc} at {fn.rsplit('/', 1)[-1]}:{frame.lineno}"
    except Exception:  # noqa: BLE001
        pass
    return desc


def _note_sync(method: str, arr) -> None:
    # The description (a stack walk) is built OUTSIDE the lock; the
    # counter bumps and offender appends happen under it — the round's
    # assign-pool and pipeline worker threads sync concurrently with
    # the main thread, and a lost increment would pass the exact
    # violation the budget-0 gate exists to catch.
    global _transfer_count
    with _lock:
        active = list(_transfer_active)
    desc = _describe_sync(method, arr) if active else ""
    with _lock:
        _transfer_count += 1
        for led in _transfer_active:
            led._note(desc)


def _ensure_sync_interposer() -> None:
    """Patch the scalar-sync methods once, permanently (the compile
    listener's install posture).  Backend-free: the array class comes
    from ``jax._src.array``, so a process that must never touch the
    accelerator can still install the counter."""
    global _sync_installed
    if _sync_installed:
        return
    with _lock:
        if _sync_installed:
            return
        from jax._src.array import ArrayImpl

        for name in _SYNC_METHODS:
            orig = getattr(ArrayImpl, name, None)
            if orig is None:
                continue

            def make(method, orig):
                def wrapper(self, *args, **kwargs):
                    _note_sync(method, self)
                    return orig(self, *args, **kwargs)

                wrapper.__name__ = getattr(orig, "__name__", method)
                wrapper.__qualname__ = getattr(
                    orig, "__qualname__", method
                )
                wrapper._poseidon_sync_orig = orig
                return wrapper

            setattr(ArrayImpl, name, make(name, orig))
        _sync_installed = True


def implicit_transfer_count() -> int:
    """Process-wide count of implicit device->host scalar syncs since
    the first ledger/counter use.  Difference around a window exactly
    like ``fresh_compile_count`` — ``RoundMetrics.implicit_transfers``
    is wired this way."""
    _ensure_sync_interposer()
    return _transfer_count


class TransferBudgetExceeded(AssertionError):
    """A ledger window performed more implicit device->host syncs than
    budgeted."""


class TransferLedger:
    """Context manager asserting an implicit-transfer budget.

    >>> with TransferLedger(budget=0, label="warm gang round"):
    ...     planner.schedule_round()

    ``budget=None`` records without asserting (telemetry mode) and holds
    no transfer guard, so production rounds can ride it for free.  With
    ``budget=0`` the window additionally holds
    ``jax.transfer_guard_device_to_host("disallow")``, so on accelerator
    backends even interposer-invisible implicit copies (buffer-protocol
    ``np.asarray``) raise at the op; explicit ``jax.device_get`` — the
    ``transport.host_fetch`` boundary — stays legal.  The exit assertion
    names each offending sync with its call site.
    """

    def __init__(self, budget: Optional[int] = 0, label: str = ""):
        self.budget = budget
        self.label = label
        self._implicit = 0
        self.offenders: List[str] = []
        self._guard_ctx = None

    @property
    def implicit_transfers(self) -> int:
        return self._implicit

    def _note(self, desc: str) -> None:
        # Called under the module _lock (see _note_sync).
        self._implicit += 1
        if len(self.offenders) < 32:  # cap the report, not the count
            # "" happens only for a ledger that registered between the
            # active-check and the note (no description was built).
            self.offenders.append(desc or "<unattributed sync>")

    def __enter__(self) -> "TransferLedger":
        _ensure_sync_interposer()
        if self.budget == 0:
            # The guard raises AT the op, so it can only express a
            # zero budget; positive budgets count via the interposer
            # alone and settle at __exit__.
            import jax

            self._guard_ctx = jax.transfer_guard_device_to_host(
                "disallow"
            )
            self._guard_ctx.__enter__()
        with _lock:
            _transfer_active.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        with _lock:
            if self in _transfer_active:
                _transfer_active.remove(self)
        if self._guard_ctx is not None:
            self._guard_ctx.__exit__(exc_type, exc, tb)
            self._guard_ctx = None
        if exc_type is None and self.budget is not None \
                and self._implicit > self.budget:
            where = f" in {self.label}" if self.label else ""
            names = "; ".join(self.offenders) or "<not attributed>"
            raise TransferBudgetExceeded(
                f"{self._implicit} implicit device->host sync(s){where}, "
                f"budget {self.budget}: {names}.  Each is a blocking "
                "tunnel round trip — batch the values into the explicit "
                "boundary fetch (transport.host_fetch; posecheck "
                "transfer-discipline names the static patterns)."
            )
        return False


# ------------------------------------------------------------- numerics

# Runtime twin of the ``posecheck numerics`` static rule
# (check/numerics_discipline.py): validate what actually crosses the
# declared device->host boundary.  The static rule names the int32
# overflow / inf-sentinel / promotion *patterns*; this ledger catches
# the *values* — a non-finite float or an int32 riding the rails at
# ``transport.host_fetch`` — with the same budget-0 window contract as
# the compile/transfer/lock ledgers.  Validation is off unless the
# POSEIDON_NUMERICS_LEDGER hatch is on or a NumericsLedger window is
# open, so production fetches pay only one dict probe.

# Declared int32 headroom at the fetch boundary: legit solver values
# stay at or below the 2^30 price/sentinel rails (_NEG/_POS, INF_COST,
# PRICE_SPREAD_CAP are all <= 1<<30); a fetched value inside the last
# 2^20 below the int32 rails is either a wrapped accumulation or a
# saturation-clamped one — both are anomalies to surface, never to
# pass silently.
I32_FETCH_HEADROOM = 1 << 20
_I32_HI = (1 << 31) - 1 - I32_FETCH_HEADROOM
_I32_LO = -(1 << 31) + I32_FETCH_HEADROOM

_numeric_count = 0
_numerics_active: List["NumericsLedger"] = []


def numeric_anomaly_count() -> int:
    """Process-wide count of numeric anomalies (non-finite floats or
    int32 headroom violations at the host_fetch boundary, plus
    utils.numerics certificate trips).  Difference around a window
    exactly like ``fresh_compile_count`` —
    ``RoundMetrics.numeric_anomalies`` is wired this way."""
    return _numeric_count


def note_numeric_anomaly(desc: str) -> None:
    """Record one numeric anomaly (also called by utils.numerics when a
    saturation certificate trips)."""
    global _numeric_count
    with _lock:
        _numeric_count += 1
        for led in _numerics_active:
            led._note(desc)


def numerics_enabled() -> bool:
    """Is boundary validation on?  True under the
    ``POSEIDON_NUMERICS_LEDGER`` hatch or inside any open
    ``NumericsLedger`` window — ``transport.host_fetch`` consults this
    before paying the array scans."""
    if _numerics_active:
        return True
    from poseidon_tpu.utils.hatches import hatch_bool

    return hatch_bool("POSEIDON_NUMERICS_LEDGER")


def _validate_leaf(arr, site: str) -> None:
    import numpy as _np

    a = _np.asarray(arr)
    if a.size == 0:
        return
    if _np.issubdtype(a.dtype, _np.floating):
        bad = ~_np.isfinite(a)
        if bad.any():
            note_numeric_anomaly(
                f"{site}: non-finite {a.dtype}{list(a.shape)} "
                f"({int(bad.sum())} element(s), first at index "
                f"{tuple(int(i) for i in _np.argwhere(bad)[0])})"
            )
    elif a.dtype == _np.int32:
        lo, hi = int(a.min()), int(a.max())
        if lo < _I32_LO or hi > _I32_HI:
            note_numeric_anomaly(
                f"{site}: int32{list(a.shape)} within {I32_FETCH_HEADROOM} "
                f"of the int32 rails (min={lo}, max={hi}) — a wrapped or "
                "saturation-clamped accumulation"
            )


def maybe_validate_fetched(values, site: str = "host_fetch") -> None:
    """Validate a fetched pytree when numerics validation is enabled:
    floats must be finite, int32 must hold the declared fetch headroom.
    Anomalies are counted (and attributed to open ledgers), never
    raised here — the budget assertion belongs to the window's exit, so
    a fetch inside a telemetry-mode window still completes."""
    if not numerics_enabled():
        return
    import jax

    for leaf in jax.tree_util.tree_leaves(values):
        if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
            try:
                _validate_leaf(leaf, site)
            except Exception:  # noqa: BLE001 - validation must never break a fetch
                pass


class NumericsBudgetExceeded(AssertionError):
    """A ledger window observed more numeric anomalies than budgeted."""


class NumericsLedger:
    """Context manager asserting a numeric-anomaly budget.

    >>> with NumericsLedger(budget=0, label="warm gang round"):
    ...     planner.schedule_round()

    While the window is open, every ``transport.host_fetch`` /
    ``_fetch_with_retry`` boundary crossing is validated (finiteness for
    floats, declared int32 headroom for int32) and every
    ``utils.numerics`` saturation-certificate trip is attributed to the
    window.  ``budget=None`` records without asserting (telemetry
    mode).  The assertion is raised from ``__exit__`` only when the body
    itself did not raise, naming each offender by array/site."""

    def __init__(self, budget: Optional[int] = 0, label: str = ""):
        self.budget = budget
        self.label = label
        self._anomalies = 0
        self.offenders: List[str] = []

    @property
    def anomalies(self) -> int:
        return self._anomalies

    def _note(self, desc: str) -> None:
        # Called under the module _lock (see note_numeric_anomaly).
        self._anomalies += 1
        if len(self.offenders) < 32:  # cap the report, not the count
            self.offenders.append(desc)

    def __enter__(self) -> "NumericsLedger":
        with _lock:
            _numerics_active.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        with _lock:
            if self in _numerics_active:
                _numerics_active.remove(self)
        if exc_type is None and self.budget is not None \
                and self._anomalies > self.budget:
            where = f" in {self.label}" if self.label else ""
            names = "; ".join(self.offenders) or "<not attributed>"
            raise NumericsBudgetExceeded(
                f"{self._anomalies} numeric anomaly(ies){where}, budget "
                f"{self.budget}: {names}.  A value wrapped, saturated, "
                "or went non-finite at the host boundary — posecheck "
                "numerics names the static patterns; utils.numerics "
                "carries the certified widening/narrowing helpers."
            )
        return False
