"""posecheck: codebase-aware static analysis for poseidon_tpu.

Three rule families, each scoped to the subsystem whose failure mode it
guards (see docs/CHECKS.md):

- ``jit-purity``   — host-sync escapes inside jitted solver kernels
                     (``ops/``, ``solver/``);
- ``lock-discipline`` — unlocked writes to lock-guarded state in the
                     threaded glue layer (``glue/``);
- ``determinism``  — wall clock / unseeded RNG / unordered-set iteration
                     in the replay and planning path (``replay/``,
                     ``graph/``).

CLI: ``python -m poseidon_tpu.check poseidon_tpu/`` (exit 1 on findings).
Suppress a finding with a trailing ``# posecheck: ignore[rule-id]``.
"""

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    all_rules,
    check_file,
    rules_by_name,
    run,
)

__all__ = [
    "Finding",
    "Rule",
    "all_rules",
    "check_file",
    "rules_by_name",
    "run",
]
