"""posecheck: codebase-aware static analysis for poseidon_tpu.

Eight rules, each scoped to the subsystem whose failure mode it guards
(see docs/CHECKS.md):

- ``jit-purity``   — host-sync escapes inside jitted solver kernels
                     (``ops/``, ``solver/``);
- ``lock-discipline`` — unlocked writes to lock-guarded state in the
                     threaded layers (``glue/``, ``graph/pipeline.py``,
                     ``costmodel/delta.py``, ``chaos/soak.py``);
- ``determinism``  — wall clock / unseeded RNG / unordered-set iteration
                     / import-time env reads in the replay, planning,
                     and kernel paths (``replay/``, ``graph/``,
                     ``ops/``);
- ``retrace-guard`` — recompile hazards at jit boundaries: per-call jit
                     construction, dropped ``static_argnames``,
                     unpadded shapes, weak-float promotion (``ops/``,
                     ``graph/``);
- ``dispatch-budget`` — every jitted kernel in ``ops/`` must be
                     reachable from the precompile path (cross-file
                     closure; judged in ``Rule.finalize``);
- ``transfer-discipline`` — implicit device->host syncs (scalar
                     coercions / np materialization of jitted results
                     outside the declared ``host_fetch`` boundary) and
                     missed/misused donation (``ops/``, ``graph/``,
                     ``costmodel/``);
- ``shard-discipline`` — collectives under shard_map scope with
                     declared mesh axes, PartitionSpec consistency,
                     pad-to-mesh-multiple at sharded boundaries, and
                     precompile reachability for sharded kernels;
- ``hatch-registry`` — every ``POSEIDON_*`` escape hatch reads through
                     the typed call-time registry
                     (``utils/hatches.py``); bypasses, undeclared
                     names, and dead flags are findings.

The runtime complement is ``poseidon_tpu.check.ledger``: a
``jax.monitoring``-fed ``CompileLedger`` asserting exact fresh-compile
budgets and a transfer-guard/interposer-fed ``TransferLedger``
asserting implicit device->host-sync budgets around warm rounds
(imported separately — it pulls in jax, which the static CLI
deliberately does not).

CLI: ``python -m poseidon_tpu.check poseidon_tpu/`` (exit 1 on findings;
``--format=json`` for machines, ``--changed`` for pre-commit speed).
Suppress a finding with a trailing ``# posecheck: ignore[rule-id]``.
"""

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    all_rules,
    check_file,
    rules_by_name,
    run,
)

__all__ = [
    "Finding",
    "Rule",
    "all_rules",
    "check_file",
    "rules_by_name",
    "run",
]
