"""shard-discipline: mesh/collective/sharding hygiene for multi-device code.

The ROADMAP's top open item grafts the sharded band solve
(``ops/transport_sharded.py``) into the planner as a fourth ladder tier
— which means multi-device programs join the compile-key /
``ensure_precompiled`` / budget-0 discipline the single-chip kernels
already live under.  The failure modes are sharding-specific and all
silent on a single-device CI box:

- a collective (``psum``/``all_gather``/``ppermute``/...) naming an
  axis that no declared mesh carries traces fine in single-device tests
  (jax binds the axis lazily) and dies — or worse, silently reduces
  over the wrong axis — on the real mesh;
- a collective in a function that is never wrapped in
  ``shard_map``/``pmap`` relies on being inlined into some caller's
  mesh scope: refactor the caller and the kernel breaks;
- a ``PartitionSpec`` naming an unknown axis silently replicates (XLA
  treats it as an unpartitioned dim on meshes without the axis);
- a sharded jit boundary whose operand extent is not padded to a mesh
  multiple fails with an uneven-sharding error only at the first real
  multi-device run (``transport_sharded`` rounds ``m_pad`` up to a mesh
  multiple for exactly this reason);
- a sharded jitted def outside the precompile closure ships PR 3's
  silent-first-dispatch-compile failure mode to the multi-device tier,
  where a fresh compile through the tunnel costs minutes, not seconds.

Axis declarations are collected ACROSS the scan (``finalize``-judged,
like ``dispatch-budget``): module constants named ``*_AXIS`` bound to a
string literal, plus literal axis-name tuples/lists in ``Mesh(...)``
constructions — so ``transport_sharded.MACHINE_AXIS`` is visible to
every scanned file that imports it.  The reachability sub-check reuses
the dispatch-budget seeds (``precompile``/``ensure_precompiled``) and
honors BOTH ``ignore[shard-discipline]`` and
``ignore[dispatch-budget]`` on the def line (a deliberately
dispatch-time-compiled sharded kernel is the same opt-out either way).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    from_imports,
    suppressions,
)
from poseidon_tpu.check.dispatch_budget import _referenced_names
from poseidon_tpu.check.jit_purity import (
    _is_jit_expr,
    _jit_names,
    _partial_names,
)

# lax/jax collectives whose axis_name argument must match a declared
# mesh axis.  (name -> axis_name positional index when passed
# positionally; None = keyword-only in practice.)
_COLLECTIVES: Dict[str, Optional[int]] = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "pshuffle": 1,
    "axis_index": 0, "axis_size": 0,
}

_SHARD_MAP_NAMES = ("shard_map", "smap")


@dataclass
class _FileFacts:
    path: str
    # function name -> referenced names (for the precompile closure)
    refs: Dict[str, Set[str]] = field(default_factory=dict)
    defs: Set[str] = field(default_factory=set)
    # sharded jitted defs: name -> lineno
    sharded_jitted: Dict[str, int] = field(default_factory=dict)
    # lines suppressed for this rule OR dispatch-budget
    suppressed: Set[int] = field(default_factory=set)
    # collected collective call sites:
    # (lineno, collective, axis literal or None, in_mesh_scope)
    collectives: List[Tuple[int, str, Optional[str], bool]] = \
        field(default_factory=list)
    # PartitionSpec literal axis uses: (lineno, axis)
    spec_axes: List[Tuple[int, str]] = field(default_factory=list)
    # declared axis names (module constants + Mesh constructions)
    declared_axes: Set[str] = field(default_factory=set)
    # functions that build NamedSharding + device_put without a visible
    # pad-to-multiple: (lineno, fn name)
    unpadded: List[Tuple[int, str]] = field(default_factory=list)


def _ceil_multiple_present(fn: ast.AST) -> bool:
    """True when the function body contains a visible pad-to-multiple
    computation: ``((a + b - 1) // b) * b``, ``-(-a // b) * b``, a
    ``math.ceil(a / b) * b``, or an explicit ``% b == 0`` divisibility
    guard."""
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            left, right = node.left, node.right
            for a, b in ((left, right), (right, left)):
                if isinstance(a, ast.BinOp) and isinstance(
                    a.op, ast.FloorDiv
                ):
                    return True
        if isinstance(node, ast.Compare) and isinstance(
            node.left, ast.BinOp
        ) and isinstance(node.left.op, ast.Mod):
            if any(
                isinstance(c, ast.Constant) and c.value == 0
                for c in node.comparators
            ):
                return True
    return False


class ShardDisciplineRule(Rule):
    name = "shard-discipline"
    # Facts collect everywhere (axis constants can live anywhere);
    # collectives/specs are only FLAGGED under these fragments.
    scopes: tuple = ()

    _SEED_NAMES = ("precompile", "ensure_precompiled")

    def __init__(self, flag_fragments=("poseidon_tpu/",)) -> None:
        self._flag_fragments = tuple(flag_fragments)
        self._files: List[_FileFacts] = []
        self._dir_roots = None

    def begin(self, paths: Sequence[str]) -> None:
        # Same partial-graph posture as dispatch-budget: reachability
        # and cross-file axis declarations are only judged for files
        # under a directory scan root.
        from pathlib import Path

        self._dir_roots = [
            Path(p).resolve() for p in paths if Path(p).is_dir()
        ]

    # ---------------------------------------------------------------- check

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        assert isinstance(tree, ast.Module)
        facts = _FileFacts(path=path)
        for lineno, rules in suppressions(source).items():
            if rules is None or rules & {self.name, "dispatch-budget"}:
                facts.suppressed.add(lineno)

        jit = _jit_names(tree)
        partials = _partial_names(tree)
        shard_wrapped: Set[str] = set()
        uses_sharding = False

        # Declared axes: X_AXIS = "name" constants; Mesh(..., (names,)).
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant
            ) and isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id.endswith("_AXIS"):
                        facts.declared_axes.add(node.value.value)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func) or ""
                tail = fname.rpartition(".")[2]
                if tail == "Mesh":
                    for arg in list(node.args[1:2]) + [
                        kw.value for kw in node.keywords
                        if kw.arg == "axis_names"
                    ]:
                        if isinstance(arg, (ast.Tuple, ast.List)):
                            for e in arg.elts:
                                if isinstance(e, ast.Constant) and \
                                        isinstance(e.value, str):
                                    facts.declared_axes.add(e.value)
                        elif isinstance(arg, ast.Constant) and \
                                isinstance(arg.value, str):
                            facts.declared_axes.add(arg.value)
                if tail in ("NamedSharding", "PartitionSpec") or (
                    tail == "P" and self._p_is_partition_spec(tree)
                ):
                    uses_sharding = True

        # shard_map-wrapped functions: decorators and g = shard_map(f,…)
        def is_shard_map(expr: ast.AST) -> bool:
            if isinstance(expr, ast.Call):
                t = (dotted_name(expr.func) or "").rpartition(".")[2]
                return t in _SHARD_MAP_NAMES
            return False

        # shard_map-wrapped functions: decorators plus ANY
        # ``shard_map(f, ...)`` call in the module — including the
        # nested-closure idiom ``return shard_map(body, mesh=...)``
        # where ``body`` is a local def.
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                if any(is_shard_map(d) for d in node.decorator_list):
                    shard_wrapped.add(node.name)
            elif isinstance(node, ast.Call) and is_shard_map(node):
                inner = node.args[0] if node.args else None
                nm = dotted_name(inner) if inner is not None else None
                if nm and "." not in nm:
                    shard_wrapped.add(nm)

        # Mesh-scope closure: shard_wrapped functions plus everything
        # they reference (the jit-purity closure shape).  The table
        # includes NESTED defs — a shard_map'ed local closure pulls its
        # module-level helpers into scope too.
        table: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                table[node.name] = node
                facts.refs.setdefault(node.name, set()).update(
                    _referenced_names(node)
                )
                facts.defs.add(node.name)

        mesh_scope: Set[str] = set()
        frontier = [n for n in shard_wrapped if n in table]
        while frontier:
            nm = frontier.pop()
            if nm in mesh_scope:
                continue
            mesh_scope.add(nm)
            for ref in facts.refs.get(nm, ()):
                if ref in table and ref not in mesh_scope:
                    frontier.append(ref)

        # Sharded jitted defs (for the reachability sub-check) + the
        # divisibility heuristic, judged per jitted/sharding function.
        p_spec = self._p_is_partition_spec(tree)
        for node in tree.body:
            defs: List[ast.FunctionDef] = []
            if isinstance(node, ast.FunctionDef):
                defs = [node]
            elif isinstance(node, ast.ClassDef):
                defs = [
                    s for s in node.body
                    if isinstance(s, ast.FunctionDef)
                ]
            for fn in defs:
                jitted = any(
                    _is_jit_expr(d, jit, partials)
                    for d in fn.decorator_list
                )
                body_shards = self._fn_uses_sharding(fn, p_spec)
                if jitted and (uses_sharding or fn.name in shard_wrapped):
                    facts.sharded_jitted[fn.name] = fn.lineno
                if body_shards["named_sharding"] and \
                        body_shards["device_put"] and \
                        not _ceil_multiple_present(fn):
                    facts.unpadded.append((fn.lineno, fn.name))

        # Collective + PartitionSpec call sites (with mesh-scope info).
        self._collect_sites(tree, table, mesh_scope, facts, p_spec)

        self._files.append(facts)
        return []

    @staticmethod
    def _p_is_partition_spec(tree: ast.AST) -> bool:
        """True when ``P`` is bound to PartitionSpec in this module
        (``from jax.sharding import PartitionSpec as P``)."""
        for mod in ("jax.sharding", "jax.experimental.pjit"):
            for local, orig in from_imports(tree, mod).items():
                if orig == "PartitionSpec" and local == "P":
                    return True
        return False

    @staticmethod
    def _fn_uses_sharding(fn: ast.AST, p_spec: bool) -> Dict[str, bool]:
        out = {"named_sharding": False, "device_put": False}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                tail = (dotted_name(node.func) or "").rpartition(".")[2]
                if tail == "NamedSharding":
                    out["named_sharding"] = True
                elif tail == "device_put":
                    out["device_put"] = True
        return out

    def _collect_sites(self, tree, table, mesh_scope, facts,
                       p_spec) -> None:
        # Walk each function body (collectives outside any def — module
        # level — are always outside mesh scope).
        def visit(scope_name: Optional[str], node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.FunctionDef):
                    visit(child.name, child)
                    continue
                if isinstance(child, ast.Call):
                    self._classify_call(
                        child, scope_name, mesh_scope, facts, p_spec
                    )
                visit(scope_name, child)

        visit(None, tree)

    def _classify_call(self, node, scope_name, mesh_scope, facts,
                       p_spec) -> None:
        fname = dotted_name(node.func) or ""
        tail = fname.rpartition(".")[2]
        if tail in _COLLECTIVES:
            # Only count the real jax/lax collectives, not same-named
            # local helpers: require a dotted path mentioning lax/jax
            # or a bare name imported from jax.lax.
            if "." in fname and not (
                "lax" in fname or fname.startswith("jax.")
            ):
                return
            axis: Optional[str] = None
            pos = _COLLECTIVES[tail]
            if pos is not None and len(node.args) > pos:
                a = node.args[pos]
                if isinstance(a, ast.Constant) and isinstance(
                    a.value, str
                ):
                    axis = a.value
            for kw in node.keywords:
                if kw.arg == "axis_name" and isinstance(
                    kw.value, ast.Constant
                ) and isinstance(kw.value.value, str):
                    axis = kw.value.value
            in_scope = scope_name is not None and scope_name in mesh_scope
            facts.collectives.append(
                (node.lineno, tail, axis, in_scope)
            )
        elif tail == "PartitionSpec" or (tail == "P" and p_spec):
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(
                    a.value, str
                ):
                    facts.spec_axes.append((node.lineno, a.value))
                elif isinstance(a, (ast.Tuple, ast.List)):
                    for e in a.elts:
                        if isinstance(e, ast.Constant) and isinstance(
                            e.value, str
                        ):
                            facts.spec_axes.append(
                                (node.lineno, e.value)
                            )

    # ------------------------------------------------------------- finalize

    def _judgeable(self, path: str) -> bool:
        if self._dir_roots is None:
            return True
        from pathlib import Path

        try:
            resolved = Path(path).resolve()
        except OSError:
            return False
        return any(
            root == resolved or root in resolved.parents
            for root in self._dir_roots
        )

    def finalize(self) -> List[Finding]:
        files, self._files = self._files, []
        dir_roots, self._dir_roots = self._dir_roots, None

        declared: Set[str] = set()
        for f in files:
            declared.update(f.declared_axes)

        findings: List[Finding] = []

        def in_flag_scope(f: _FileFacts) -> bool:
            return any(
                frag in f.path for frag in self._flag_fragments
            )

        for f in files:
            if not in_flag_scope(f):
                continue
            for lineno, name, axis, in_scope in f.collectives:
                if lineno in f.suppressed:
                    continue
                if axis is not None and axis not in declared:
                    findings.append(Finding(
                        f.path, lineno, self.name,
                        f"collective `{name}` names axis `{axis}`, "
                        "which no declared mesh carries (declared: "
                        f"{sorted(declared) or 'none'}); use the "
                        "shared axis constant (MACHINE_AXIS) so a mesh "
                        "rename cannot orphan the collective",
                    ))
                if not in_scope:
                    findings.append(Finding(
                        f.path, lineno, self.name,
                        f"collective `{name}` outside any shard_map/"
                        "mesh-scoped function: it relies on being "
                        "inlined into a caller's mesh scope, which a "
                        "refactor silently breaks — wrap the kernel in "
                        "shard_map (or suppress with a justification "
                        "if the scope is established dynamically)",
                    ))
            for lineno, axis in f.spec_axes:
                if lineno in f.suppressed:
                    continue
                if axis not in declared:
                    findings.append(Finding(
                        f.path, lineno, self.name,
                        f"PartitionSpec axis `{axis}` is not a "
                        "declared mesh axis (declared: "
                        f"{sorted(declared) or 'none'}): an unknown "
                        "axis silently replicates instead of sharding",
                    ))
            for lineno, fn_name in f.unpadded:
                if lineno in f.suppressed:
                    continue
                findings.append(Finding(
                    f.path, lineno, self.name,
                    f"`{fn_name}` device_puts NamedSharding-annotated "
                    "operands without a visible pad-to-mesh-multiple "
                    "(`((n + d - 1) // d) * d` or a `% d == 0` guard): "
                    "uneven shards fail at the first real multi-device "
                    "run",
                ))

        # Reachability: sharded jitted defs must reach a precompile
        # seed (same closure + partial-graph posture as dispatch-budget).
        all_refs: Dict[str, Set[str]] = {}
        defined: Set[str] = set()
        for f in files:
            defined.update(f.defs)
            for name, refs in f.refs.items():
                all_refs.setdefault(name, set()).update(refs)
        seeds = [
            s for s in self._SEED_NAMES
            if any(s in f.defs for f in files)
        ]
        if seeds:
            reached: Set[str] = set()
            frontier = list(seeds)
            while frontier:
                nm = frontier.pop()
                if nm in reached:
                    continue
                reached.add(nm)
                for ref in all_refs.get(nm, ()):
                    if ref in defined and ref not in reached:
                        frontier.append(ref)
            for f in files:
                if not in_flag_scope(f) or not self._judgeable(f.path):
                    continue
                for name, lineno in sorted(f.sharded_jitted.items()):
                    if name in reached or lineno in f.suppressed:
                        continue
                    findings.append(Finding(
                        f.path, lineno, self.name,
                        f"sharded jitted `{name}` is not reachable "
                        "from precompile/ensure_precompiled: its first "
                        "multi-device dispatch pays a fresh XLA "
                        "compile through the tunnel (wire it in, or "
                        "opt out with `# posecheck: "
                        "ignore[dispatch-budget]` plus a "
                        "justification)",
                    ))
        findings.sort(key=lambda x: (x.path, x.line, x.message))
        return findings
