"""CLI: ``python -m poseidon_tpu.check [paths...]``.

Exit codes: 0 clean, 1 findings, 2 bad invocation.  Findings print as
``file:line rule-id message`` (the Makefile's ``lint`` target and editors
both parse that shape).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from poseidon_tpu.check.core import (
    all_rules,
    load_baseline,
    run,
    rules_by_name,
    write_baseline,
)

_DEFAULT_BASELINE = Path(__file__).parent / "baseline.txt"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.check",
        description="posecheck: jit-purity / lock-discipline / determinism",
    )
    parser.add_argument(
        "paths", nargs="*", default=["poseidon_tpu/"],
        help="files or directories to scan (default: poseidon_tpu/)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="RULE-ID",
        help="run only this rule, on every given path regardless of its "
             "default scope (repeatable); known: "
             + ", ".join(r.name for r in all_rules()),
    )
    parser.add_argument(
        "--baseline", type=Path, default=_DEFAULT_BASELINE,
        help="baseline file of grandfathered findings "
             "(default: the committed package baseline)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    args = parser.parse_args(argv)

    try:
        rules = rules_by_name(args.rules) if args.rules else None
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    baseline = None if (args.no_baseline or args.write_baseline) \
        else args.baseline
    findings = run(
        args.paths, rules=rules, baseline=baseline, root=Path.cwd()
    )

    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(
            f"wrote {len(findings)} finding(s) to {args.baseline}",
            file=sys.stderr,
        )
        return 0

    for f in findings:
        print(f.render())
    if findings:
        n_base = len(load_baseline(args.baseline)) if baseline else 0
        suffix = f" ({n_base} baselined)" if n_base else ""
        print(
            f"posecheck: {len(findings)} finding(s){suffix}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
