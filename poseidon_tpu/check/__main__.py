"""CLI: ``python -m poseidon_tpu.check [paths...]``.

Exit codes: 0 clean, 1 findings, 2 bad invocation.  Findings print as
``file:line rule-id message`` (the Makefile's ``lint`` target and editors
both parse that shape) or, under ``--format=json``, as one JSON object
per line (``{"path", "line", "rule", "message"}``) for machine
consumers (pre-commit hooks, CI annotators).

``--changed`` scans only files touched relative to git HEAD (staged,
unstaged, and untracked), intersected with the given paths — the fast
pre-commit mode (``make lint-fast``).  Scope filters still apply, so a
touched glue file gets the glue rules, not everything.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import List, Optional

from poseidon_tpu.check.core import (
    all_rules,
    iter_py_files,
    load_baseline,
    run,
    rules_by_name,
    write_baseline,
)

_DEFAULT_BASELINE = Path(__file__).parent / "baseline.txt"


def changed_files(paths: List[str]) -> Optional[List[str]]:
    """Python files changed vs HEAD (staged + unstaged + untracked),
    restricted to ``paths``.  None when git itself fails (not a repo,
    no git) — the caller reports a usage error rather than silently
    scanning nothing.

    git prints toplevel-relative names (and ``ls-files --others`` would
    be cwd-scoped), so both commands run from the toplevel and the
    comparison happens on RESOLVED absolute paths — a run from a
    subdirectory must not silently drop tracked changes elsewhere in
    the checkout.
    """
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, check=True, cwd=top,
        ).stdout.splitlines()
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, check=True, cwd=top,
        ).stdout.splitlines()
    except (OSError, subprocess.CalledProcessError):
        return None
    scoped = {f.resolve(): f.as_posix() for f in iter_py_files(paths)}
    out = []
    for name in dict.fromkeys([*diff, *untracked]):  # ordered de-dup
        resolved = Path(top, name).resolve()
        if name.endswith(".py") and resolved in scoped \
                and resolved.exists():
            out.append(scoped[resolved])
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.check",
        description="posecheck: jit-purity / lock-discipline / determinism"
                    " / retrace-guard / dispatch-budget /"
                    " transfer-discipline / shard-discipline /"
                    " hatch-registry",
    )
    parser.add_argument(
        "paths", nargs="*", default=["poseidon_tpu/"],
        help="files or directories to scan (default: poseidon_tpu/)",
    )
    parser.add_argument(
        "--rule", action="append", dest="rules", metavar="RULE-ID",
        help="run only this rule, on every given path regardless of its "
             "default scope (repeatable); known: "
             + ", ".join(r.name for r in all_rules()),
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="finding output shape: `file:line rule message` lines "
             "(text, default) or one JSON object per line (json)",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="scan only files changed vs git HEAD (staged, unstaged, "
             "untracked) within the given paths — fast pre-commit mode",
    )
    parser.add_argument(
        "--baseline", type=Path, default=_DEFAULT_BASELINE,
        help="baseline file of grandfathered findings "
             "(default: the committed package baseline)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    args = parser.parse_args(argv)

    try:
        rules = rules_by_name(args.rules) if args.rules else None
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    paths = args.paths
    if args.changed:
        paths = changed_files(args.paths)
        if paths is None:
            print("--changed requires a git checkout", file=sys.stderr)
            return 2
        if not paths:
            print("posecheck: no changed files in scope", file=sys.stderr)
            return 0

    baseline = None if (args.no_baseline or args.write_baseline) \
        else args.baseline
    findings = run(paths, rules=rules, baseline=baseline, root=Path.cwd())

    if args.write_baseline:
        write_baseline(args.baseline, findings)
        print(
            f"wrote {len(findings)} finding(s) to {args.baseline}",
            file=sys.stderr,
        )
        return 0

    for f in findings:
        if args.format == "json":
            print(json.dumps(
                {"path": f.path, "line": f.line, "rule": f.rule,
                 "message": f.message},
                sort_keys=True,
            ))
        else:
            print(f.render())
    if findings:
        n_base = len(load_baseline(args.baseline)) if baseline else 0
        suffix = f" ({n_base} baselined)" if n_base else ""
        print(
            f"posecheck: {len(findings)} finding(s){suffix}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
