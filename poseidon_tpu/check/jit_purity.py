"""jit-purity: host-sync escapes inside jitted hot paths.

Scope: ``poseidon_tpu/ops/`` and ``poseidon_tpu/solver/`` — the solver
kernels whose latency is the critical path of a scheduling round.  A
``np.asarray`` / ``.item()`` / ``float()`` on a tracer inside a jitted
function either fails at trace time or (worse, under ``jax.pure_callback``
-style escapes) silently forces a device->host round trip per dispatch —
on the tunneled production TPU that is a ~60-116 ms tax per occurrence
(tools/profile_transfer.py), invisible in CPU tests.

Detection is call-graph aware within a module: every function decorated
with ``jax.jit`` / ``functools.partial(jax.jit, ...)`` (or wrapped via a
module-level ``g = jax.jit(f)``) seeds the *jit scope*; any module-level
function a scoped function references (direct call, ``lax.scan``/``cond``
operand, ``partial`` argument) joins the scope transitively.  Host-side
wrapper code around the dispatch — the bulk of ``ops/transport.py`` —
stays out of scope and may use numpy freely.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    from_imports,
    import_aliases,
)


def _jit_names(tree: ast.AST) -> Set[str]:
    """Dotted names that denote jax.jit in this module."""
    names = {"jax.jit"}
    for alias in import_aliases(tree, "jax"):
        names.add(f"{alias}.jit")
    for local, orig in from_imports(tree, "jax").items():
        if orig == "jit":
            names.add(local)
    return names


def _partial_names(tree: ast.AST) -> Set[str]:
    names = {"functools.partial"}
    for alias in import_aliases(tree, "functools"):
        names.add(f"{alias}.partial")
    for local, orig in from_imports(tree, "functools").items():
        if orig == "partial":
            names.add(local)
    return names


def _is_jit_expr(node: ast.AST, jit: Set[str], partials: Set[str]) -> bool:
    """Does this decorator/value expression produce a jitted callable?"""
    name = dotted_name(node)
    if name in jit:
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in jit:
            return True
        if fname in partials and node.args:
            return _is_jit_expr(node.args[0], jit, partials)
    return False


class JitPurityRule(Rule):
    name = "jit-purity"
    scopes = ("poseidon_tpu/ops/", "poseidon_tpu/solver/")

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        assert isinstance(tree, ast.Module)
        jit = _jit_names(tree)
        partials = _partial_names(tree)
        np_aliases = import_aliases(tree, "numpy")
        jax_aliases = import_aliases(tree, "jax") | {"jax"}

        table: Dict[str, ast.FunctionDef] = {}
        seeds: Set[str] = set()
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                table[node.name] = node
                if any(
                    _is_jit_expr(d, jit, partials)
                    for d in node.decorator_list
                ):
                    seeds.add(node.name)
            elif isinstance(node, ast.Assign):
                # g = jax.jit(f) / g = partial(jax.jit, ...)(f)
                v = node.value
                if (
                    isinstance(v, ast.Call)
                    and _is_jit_expr(v.func, jit, partials)
                    and v.args
                ):
                    inner = dotted_name(v.args[0])
                    if inner and "." not in inner:
                        seeds.add(inner)

        # Transitive same-module closure over name references.
        scope: Set[str] = set()
        frontier = [s for s in seeds if s in table]
        while frontier:
            fn = frontier.pop()
            if fn in scope:
                continue
            scope.add(fn)
            for node in ast.walk(table[fn]):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in table
                    and node.id not in scope
                ):
                    frontier.append(node.id)

        findings: List[Finding] = []
        for fn in sorted(scope):
            findings.extend(
                self._check_function(table[fn], path, np_aliases, jax_aliases)
            )
        return findings

    def _check_function(
        self,
        fn: ast.FunctionDef,
        path: str,
        np_aliases: Set[str],
        jax_aliases: Set[str],
    ) -> List[Finding]:
        out: List[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            out.append(
                Finding(path, node.lineno, self.name,
                        f"{message} [in jit scope `{fn.name}`]")
            )

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname:
                head, _, rest = fname.partition(".")
                if head in np_aliases and rest in ("asarray", "array"):
                    flag(node, f"host materialization `{fname}()`; use "
                               "jnp equivalents or hoist out of the jit")
                    continue
                if head in jax_aliases and rest == "device_get":
                    flag(node, f"`{fname}()` forces a device->host "
                               "transfer; return the array instead")
                    continue
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                flag(node, "`.item()` synchronizes device->host; keep the "
                           "value as a traced scalar")
                continue
            if isinstance(node.func, ast.Name):
                if node.func.id == "print":
                    flag(node, "bare `print()` does not trace; use "
                               "`jax.debug.print`")
                    continue
                if node.func.id in ("float", "int") and any(
                    not isinstance(a, ast.Constant) for a in node.args
                ):
                    flag(node, f"`{node.func.id}()` cast concretizes a "
                               "tracer (host sync); use jnp casts/astype")
        return out
