"""lock-discipline: unlocked writes to lock-guarded instance state.

Scope: ``poseidon_tpu/glue/`` — the multi-threaded watcher/queue layer
(KeyedQueue, pod/node watchers, SharedState, FakeKube, stats plumbing),
the role Go's race detector played for the reference repo.  CPython's GIL
makes single-bytecode ops atomic, but the invariants here are compound
(queue + parked + processing must agree; the id maps must stay mutually
consistent), so every write to guarded state must hold the class's lock.

Inference is codebase-aware rather than annotation-driven:

- a class participates iff some method assigns ``self.X =
  threading.Lock() / RLock() / Condition()``;
- an attribute counts as *guarded* iff it is accessed (read or write)
  somewhere lexically inside a ``with self.<lock>:`` block — the lock's
  observed coverage defines the guarded set, so unshared helpers
  (thread handles, config) don't false-positive;
- a private method whose every intra-class call site is inside a locked
  region (fixpoint, so recursion and helper chains work) is treated as
  executing under the lock — the ``SharedState._register_subtree``
  pattern;
- ``__init__`` writes are construction-time (no concurrent threads yet)
  and exempt.

Flagged: any other write — assignment, augmented assignment, ``del``,
subscript store, or a mutating method call (``.append``/``.pop``/...) —
to a guarded attribute outside a locked region.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    from_imports,
    import_aliases,
)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# Method names that mutate their receiver in place.
_MUTATORS = {
    "append", "appendleft", "add", "extend", "insert", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "sort",
    "reverse", "move_to_end",
}


def _lock_factory_names(tree: ast.AST) -> Set[str]:
    names = set()
    for alias in import_aliases(tree, "threading"):
        names.update(f"{alias}.{f}" for f in _LOCK_FACTORIES)
    for local, orig in from_imports(tree, "threading").items():
        if orig in _LOCK_FACTORIES:
            names.add(local)
    # The TrackedLock migration (utils/locks.py) must not take classes
    # OUT of scope: the wrappers are lock factories too.
    for local, orig in from_imports(
        tree, "poseidon_tpu.utils.locks"
    ).items():
        if orig in ("TrackedLock", "tracked_condition"):
            names.add(local)
    for alias in import_aliases(tree, "poseidon_tpu.utils.locks"):
        names.add(f"{alias}.TrackedLock")
        names.add(f"{alias}.tracked_condition")
    return names


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a ``self.x`` attribute expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class _Access:
    attr: str
    line: int
    write: bool
    locked: bool
    method: str
    what: str  # description of the write kind for the message


class _MethodScanner(ast.NodeVisitor):
    """Collects self-attribute accesses and call sites with lock context."""

    def __init__(self, method: str, lock_attrs: Set[str],
                 method_names: Set[str]) -> None:
        self.method = method
        self.lock_attrs = lock_attrs
        self.method_names = method_names
        self.locked = False
        self.accesses: List[_Access] = []
        # (callee method name, locked at call site)
        self.calls: List[Tuple[str, bool]] = []
        # Methods referenced WITHOUT being called (thread targets,
        # callbacks): they can be entered from anywhere, so lock-held
        # inference must never apply to them.
        self.escaped: Set[str] = set()
        self._call_funcs: Set[int] = set()

    # -- lock context ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        holds = any(
            _self_attr(item.context_expr) in self.lock_attrs
            for item in node.items
        )
        for item in node.items:
            self.visit(item.context_expr)
        prev, self.locked = self.locked, self.locked or holds
        for stmt in node.body:
            self.visit(stmt)
        self.locked = prev

    def _visit_nested(self, node: ast.AST) -> None:
        # A nested def/lambda runs later, possibly on another thread —
        # never inherit the enclosing lock context.
        prev, self.locked = self.locked, False
        self.generic_visit(node)
        self.locked = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested(node)

    # -- accesses ----------------------------------------------------------

    def _record(self, attr: Optional[str], node: ast.AST, write: bool,
                what: str) -> None:
        if attr is None:
            return
        self.accesses.append(
            _Access(attr, node.lineno, write, self.locked, self.method, what)
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self._record(attr, node, True, f"assignment to self.{attr}")
            else:
                self._record(attr, node, False, "read")
                if (
                    attr in self.method_names
                    and id(node) not in self._call_funcs
                ):
                    # Bare ``self.meth`` (e.g. Thread(target=self.meth)):
                    # an escaped entry point.
                    self.escaped.add(attr)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        attr = _self_attr(node.value)
        if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
            self._record(attr, node, True, f"subscript store to self.{attr}")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            recv = _self_attr(node.func.value)
            if recv is not None and node.func.attr in _MUTATORS:
                self._record(
                    recv, node, True,
                    f"self.{recv}.{node.func.attr}(...) mutation",
                )
            callee = _self_attr(node.func)
            if callee is not None:
                self.calls.append((callee, self.locked))
                self._call_funcs.add(id(node.func))
        self.generic_visit(node)


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    # graph/pipeline.py: the cross-band cost-build pipeline's worker
    # shares the plane cache with the planner thread — its lock
    # discipline (every cache touch joins the outstanding future under
    # _lock) is exactly this rule's compound-invariant territory.
    # costmodel/delta.py and chaos/soak.py joined in PR 11: the plane
    # cache is mutated from both the pipeline worker and the planner
    # thread, and the soak harness drives watcher + loop threads over
    # shared round state — both are threaded consumers added since the
    # rule's PR 1 scope was drawn.  obs/, service/, replay/ and
    # graph/residency.py joined with the concurrency rules (PR 16):
    # every module the TrackedLock migration touches is in scope.
    scopes = (
        "poseidon_tpu/glue/", "poseidon_tpu/graph/pipeline.py",
        "poseidon_tpu/costmodel/delta.py", "poseidon_tpu/chaos/",
        "poseidon_tpu/obs/", "poseidon_tpu/service/",
        "poseidon_tpu/replay/", "poseidon_tpu/graph/residency.py",
    )

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        factories = _lock_factory_names(tree)
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, factories, path))
        return findings

    def _check_class(
        self, cls: ast.ClassDef, factories: Set[str], path: str
    ) -> List[Finding]:
        methods = [
            n for n in cls.body if isinstance(n, ast.FunctionDef)
        ]
        lock_attrs: Set[str] = set()
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    if dotted_name(node.value.func) in factories:
                        for t in node.targets:
                            attr = _self_attr(t)
                            if attr:
                                lock_attrs.add(attr)
        if not lock_attrs:
            return []

        method_names = {m.name for m in methods}
        scanners: Dict[str, _MethodScanner] = {}
        for m in methods:
            sc = _MethodScanner(m.name, lock_attrs, method_names)
            for stmt in m.body:
                sc.visit(stmt)
            scanners[m.name] = sc
        escaped: Set[str] = set()
        for sc in scanners.values():
            escaped |= sc.escaped

        guarded: Set[str] = set()
        for sc in scanners.values():
            for a in sc.accesses:
                if a.locked and a.attr not in lock_attrs:
                    guarded.add(a.attr)
        if not guarded:
            return []

        # Greatest fixpoint: a PRIVATE method is lock-held iff every
        # intra-class call site either holds the lock lexically or sits in
        # another lock-held method.  Starting from "all private methods
        # with call sites" and pruning lets recursion self-justify
        # (SharedState._register_subtree calls itself unlocked but is only
        # ever entered under the lock).  Public methods are excluded —
        # external callers reach them directly, so a locked internal call
        # site proves nothing.
        call_sites: Dict[str, List[Tuple[str, bool]]] = {}
        for caller, sc in scanners.items():
            for callee, locked in sc.calls:
                call_sites.setdefault(callee, []).append((caller, locked))
        lock_held: Set[str] = {
            name for name in scanners
            if name in call_sites
            and name.startswith("_") and not name.startswith("__")
            # A method whose reference escapes (thread target, callback)
            # can be entered without any lock, whatever its call sites say.
            and name not in escaped
        }
        changed = True
        while changed:
            changed = False
            for name in sorted(lock_held):
                if any(
                    not locked and caller not in lock_held
                    for caller, locked in call_sites[name]
                ):
                    lock_held.discard(name)
                    changed = True

        locks = "/".join(f"self.{a}" for a in sorted(lock_attrs))
        findings: List[Finding] = []
        for sc in scanners.values():
            if sc.method == "__init__" or sc.method in lock_held:
                continue
            for a in sc.accesses:
                if a.write and a.attr in guarded:
                    if not a.locked:
                        findings.append(
                            Finding(
                                path, a.line, self.name,
                                f"{a.what} outside `with {locks}` "
                                f"({cls.name}.{a.method}); the lock guards "
                                "this attribute elsewhere",
                            )
                        )
        return findings
