"""determinism: wall-clock, unseeded RNG, and unordered-set iteration.

Scope: ``poseidon_tpu/replay/`` and ``poseidon_tpu/graph/`` — the
trace-replay and round-planning path whose whole value is bit-for-bit
reproducibility (BASELINE parity runs, solver-vs-oracle verification,
warm-start reuse across rounds).  Three leak classes:

- ``time.time()``: real wall-clock in a virtual-time replay makes runs
  incomparable.  (``time.perf_counter`` for *measuring* a round is fine
  — it feeds telemetry, not decisions — so only ``time.time`` flags.)
- unseeded RNG: module-level ``random.*`` / ``np.random.*`` draw from
  process-global state seeded by the OS; ``np.random.default_rng(seed)``
  / ``random.Random(seed)`` thread explicit streams instead.  A bare
  ``default_rng()`` with no seed flags too.
- iteration over bare ``set``s: set order varies with insertion history
  and (for str keys) per-process hash randomization, so any ordering-
  sensitive consumer — event lists, cost-matrix row order, serialized
  output — silently diverges between runs.  ``sorted(set(...))`` is the
  fix and never flags.
- import-time environment reads: ``os.environ``/``os.getenv`` at module
  (or class-body) level pins the value at whatever the environment held
  when the module was FIRST imported — tests and bench runs that set
  the variable later silently no-op, and two processes with different
  import orders can disagree (the ``POSEIDON_ITER_UNROLL`` pattern this
  check exists to keep out: the value was baked into traced programs at
  import).  Read at call time, or through an accessor.  This sub-check
  also covers ``poseidon_tpu/ops/`` — env-tuned kernels are where the
  pattern keeps trying to return.
"""

from __future__ import annotations

import ast
from typing import List, Set

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    from_imports,
    import_aliases,
)

# Module-level random functions that draw from the global stream.
_RANDOM_FNS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "lognormvariate",
    "expovariate", "betavariate", "gammavariate", "triangular",
    "vonmisesvariate", "paretovariate", "weibullvariate", "getrandbits",
    "randbytes",
}

# Call wrappers whose argument order is observable output order.
_ORDER_SENSITIVE_WRAPPERS = {"list", "tuple", "enumerate", "iter"}


def _is_set_expr(
    node: ast.AST, set_vars: Set[str], set_fields: Set[str] = frozenset()
) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("set", "frozenset"):
            return True
    if isinstance(node, ast.Name) and node.id in set_vars:
        return True
    # Attribute whose name is a set-annotated field of a class defined in
    # this module (e.g. a dataclass field ``subtree_uuids: Set[str]``):
    # any ``x.subtree_uuids`` is assumed to be that set.
    if isinstance(node, ast.Attribute) and node.attr in set_fields:
        return True
    return False


def _set_annotated_fields(tree: ast.AST) -> Set[str]:
    """Field names with a set-typed annotation on any class in the module
    (class-level AnnAssign: ``name: Set[str]`` / ``name: set``)."""
    fields: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                ann = stmt.annotation
                base = ann.value if isinstance(ann, ast.Subscript) else ann
                name = dotted_name(base)
                if name and name.split(".")[-1] in (
                    "Set", "set", "FrozenSet", "frozenset", "MutableSet",
                ):
                    fields.add(stmt.target.id)
    return fields


def _collect_set_vars(fn: ast.AST) -> Set[str]:
    """Names bound to set expressions and never rebound to anything else
    within this scope (module or one function; nested defs excluded)."""
    sets: Set[str] = set()
    other: Set[str] = set()

    def walk_shallow(node: ast.AST):
        # Walk statements without descending into nested function/class
        # scopes (their bindings are theirs).
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                 ast.Lambda),
            ):
                continue
            yield child
            yield from walk_shallow(child)

    for node in walk_shallow(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if _is_set_expr(node.value, set()):
                        sets.add(t.id)
                    else:
                        other.add(t.id)
        elif isinstance(node, ast.AugAssign):
            t = node.target
            if isinstance(t, ast.Name):
                # Set-algebra updates (s |= other, s -= dead, ...) keep a
                # tracked set a set; anything else unmarks it.
                keeps = isinstance(
                    node.op, (ast.BitOr, ast.BitAnd, ast.BitXor, ast.Sub)
                ) and (t.id in sets or _is_set_expr(node.value, sets))
                if not keeps:
                    other.add(t.id)
        elif isinstance(node, ast.AnnAssign):
            t = node.target
            if isinstance(t, ast.Name):
                other.add(t.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            t = node.target
            if isinstance(t, ast.Name):
                other.add(t.id)
    return sets - other


class DeterminismRule(Rule):
    name = "determinism"
    # chaos/ is in scope because fault plans MUST be seed-reproducible:
    # a soak whose faults fire off the wall clock or an OS-entropy RNG
    # cannot be re-driven from its flight trace, which voids the whole
    # subsystem's replayability contract (docs/CHAOS.md).  obs/ is in
    # scope with an extra confinement sub-check: the tracer
    # (obs/trace.py) is the ONE module in the telemetry plane allowed
    # to read a clock — everything else (metrics registry, exporters)
    # must take durations from it, or metrics and timeline drift apart.
    scopes = (
        "poseidon_tpu/replay/", "poseidon_tpu/graph/", "poseidon_tpu/ops/",
        "poseidon_tpu/chaos/", "poseidon_tpu/obs/",
    )

    # Clock reads confined to obs/trace.py within obs/ (time.time is
    # flagged everywhere in scope already; these are the non-wall clock
    # reads the confinement additionally forbids outside the tracer).
    _CLOCK_FNS = frozenset({
        "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns",
        "time_ns", "process_time", "process_time_ns",
        "clock_gettime", "clock_gettime_ns",
        "thread_time", "thread_time_ns",
    })

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        time_aliases = import_aliases(tree, "time")
        time_fns = {
            local
            for local, orig in from_imports(tree, "time").items()
            if orig == "time"
        }
        random_aliases = import_aliases(tree, "random")
        random_fns = {
            local: orig
            for local, orig in from_imports(tree, "random").items()
            if orig in _RANDOM_FNS
        }
        np_aliases = import_aliases(tree, "numpy")

        findings: List[Finding] = []

        def flag(node: ast.AST, message: str) -> None:
            findings.append(Finding(path, node.lineno, self.name, message))

        norm_path = path.replace("\\", "/")
        clock_confined = (
            "poseidon_tpu/obs/" in norm_path
            and not norm_path.endswith("poseidon_tpu/obs/trace.py")
        )
        clock_fns = (
            {
                local
                for local, orig in from_imports(tree, "time").items()
                if orig in self._CLOCK_FNS
            }
            if clock_confined else frozenset()
        )
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_call(
                    node, flag, time_aliases, time_fns, random_aliases,
                    random_fns, np_aliases,
                )
                if clock_confined:
                    self._check_clock_confinement(
                        node, flag, time_aliases, clock_fns
                    )

        # Set iteration: per-scope variable tracking, then flag iteration
        # sites.  Scopes: the module plus every function (nested included —
        # ast.walk reaches them; each tracks only its own bindings).
        scopes: List[ast.AST] = [tree]
        scopes.extend(
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
        set_fields = _set_annotated_fields(tree)
        for scope in scopes:
            set_vars = _collect_set_vars(scope)
            self._check_set_iteration(scope, set_vars, set_fields, flag)

        self._check_import_time_env(tree, flag)
        return findings

    # -- import-time environment reads -------------------------------------

    def _check_import_time_env(self, tree: ast.AST, flag) -> None:
        os_aliases = import_aliases(tree, "os")
        env_fns = {
            local
            for local, orig in from_imports(tree, "os").items()
            if orig in ("getenv", "environ")
        }

        def is_env_read(node: ast.AST) -> bool:
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname is None:
                    return False
                head, _, rest = fname.partition(".")
                if head in os_aliases and rest in (
                    "getenv", "environ.get",
                ):
                    return True
                if head in env_fns and rest in ("", "get"):
                    return True
            if isinstance(node, ast.Subscript):
                vname = dotted_name(node.value)
                if vname is None:
                    return False
                head, _, rest = vname.partition(".")
                if head in os_aliases and rest == "environ":
                    return True
                if head in env_fns and not rest:
                    return True
            return False

        def walk_import_time(node: ast.AST):
            # Module and class bodies execute at import; function BODIES
            # do not — their env reads are call-time.  But a def's
            # decorators and argument DEFAULTS evaluate when the def
            # statement runs (import time for module/class-level defs),
            # so those subtrees stay in the walk.
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    args = child.args
                    for sub in (
                        *getattr(child, "decorator_list", ()),
                        *args.defaults,
                        *(d for d in args.kw_defaults if d is not None),
                    ):
                        yield sub
                        yield from walk_import_time(sub)
                    continue
                yield child
                yield from walk_import_time(child)

        for node in walk_import_time(tree):
            if is_env_read(node):
                flag(node, "environment read at import time pins the "
                           "value for the process (tests/bench setting "
                           "it later silently no-op); read at call time "
                           "or through an accessor")

    # -- clock confinement (obs/ outside the tracer) -----------------------

    def _check_clock_confinement(self, node, flag, time_aliases,
                                 clock_fns) -> None:
        fname = dotted_name(node.func)
        if fname is None:
            return
        head, _, rest = fname.partition(".")
        if (head in time_aliases and rest in self._CLOCK_FNS) or (
            not rest and head in clock_fns
        ):
            flag(node, f"clock read `{fname}()` outside obs/trace.py; "
                       "the tracer is the one clock owner in the "
                       "telemetry plane — take durations from spans")

    # -- wall clock + RNG --------------------------------------------------

    def _check_call(
        self, node, flag, time_aliases, time_fns, random_aliases,
        random_fns, np_aliases,
    ) -> None:
        fname = dotted_name(node.func)
        if fname is None:
            return
        head, _, rest = fname.partition(".")
        if (head in time_aliases and rest == "time") or (
            not rest and head in time_fns
        ):
            flag(node, "wall-clock `time.time()` in the replay/parity "
                       "path; use the driver's virtual time or inject a "
                       "clock")
            return
        if head in random_aliases and rest in _RANDOM_FNS:
            flag(node, f"unseeded global RNG `{fname}()`; thread a seeded "
                       "`random.Random(seed)` through instead")
            return
        if not rest and head in random_fns:
            flag(node, f"unseeded global RNG `random.{random_fns[head]}()`"
                       "; thread a seeded `random.Random(seed)` through "
                       "instead")
            return
        if head in np_aliases and rest.startswith("random."):
            sub = rest[len("random."):]
            if sub == "default_rng":
                if not node.args and not node.keywords:
                    flag(node, "`default_rng()` without a seed draws OS "
                               "entropy; pass an explicit seed")
            elif sub not in ("Generator", "RandomState", "SeedSequence"):
                flag(node, f"unseeded global RNG `{fname}()`; use "
                           "`np.random.default_rng(seed)` streams")

    # -- set iteration -----------------------------------------------------

    def _check_set_iteration(self, scope, set_vars, set_fields, flag) -> None:
        def shallow(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                yield child
                yield from shallow(child)

        msg = (
            "iteration over an unordered set feeds ordering-sensitive "
            "output; wrap in sorted(...)"
        )
        for node in shallow(scope):
            if isinstance(node, ast.For) and _is_set_expr(
                node.iter, set_vars, set_fields
            ):
                flag(node.iter, msg)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp)):
                for comp in node.generators:
                    if _is_set_expr(comp.iter, set_vars, set_fields):
                        flag(comp.iter, msg)
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _ORDER_SENSITIVE_WRAPPERS
                    and node.args
                    and _is_set_expr(node.args[0], set_vars, set_fields)
                ):
                    flag(node, msg)
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                    and node.args
                    and _is_set_expr(node.args[0], set_vars, set_fields)
                ):
                    flag(node, msg)
