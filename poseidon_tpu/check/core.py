"""posecheck core: finding model, suppressions, baseline, file walking.

The repo-specific analog of the reference's ``hack/verify-*`` scripts and
Go race detector, reduced to the three bug classes that actually kill a
production scheduler built on jax_graft: host syncs inside jitted hot
paths (``jit-purity``), unlocked writes to lock-guarded state in the
watcher/queue threads (``lock-discipline``), and nondeterminism in the
replay/parity path (``determinism``).

Rules are plain objects with a ``name``, a ``scopes`` tuple of
package-relative directory fragments they apply to by default, and a
``check(tree, source, path)`` returning findings.  Suppression is
line-scoped: a trailing ``# posecheck: ignore[rule-id]`` (or a bare
``# posecheck: ignore`` for every rule) on the flagged line silences it.
A committed baseline file can grandfather known findings so the gate
starts clean; the repo's own baseline is kept empty by fixing findings
instead.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set

# ----------------------------------------------------------------- findings


@dataclass(frozen=True)
class Finding:
    path: str       # repo-relative posix path
    line: int       # 1-based line of the offending node
    rule: str       # rule id, e.g. "jit-purity"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"

    def baseline_key(self) -> str:
        # Line numbers rot under unrelated edits; the baseline matches on
        # (path, rule, message) instead.
        return f"{self.path}\t{self.rule}\t{self.message}"


# -------------------------------------------------------------- suppressions

_SUPPRESS_RE = re.compile(
    r"#\s*posecheck:\s*ignore(?:\[(?P<ids>[a-z0-9_,\- ]+)\])?"
)


def suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    """Line -> suppressed rule ids (None = all rules) from inline comments."""
    out: Dict[int, Optional[Set[str]]] = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = m.group("ids")
        if ids is None:
            out[lineno] = None
        else:
            out[lineno] = {s.strip() for s in ids.split(",") if s.strip()}
    return out


def apply_suppressions(
    findings: Iterable[Finding], source: str
) -> List[Finding]:
    supp = suppressions(source)
    kept = []
    for f in findings:
        rules = supp.get(f.line, ())
        if rules is None or (rules and f.rule in rules):
            continue
        kept.append(f)
    return kept


# -------------------------------------------------------------------- rules


class Rule:
    """Base rule: subclasses set ``name``/``scopes`` and implement check."""

    name: str = ""
    # Default path scopes (posix fragments); a file is in scope when any
    # fragment occurs in its repo-relative path.  Empty = everywhere.
    scopes: Sequence[str] = ()

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        raise NotImplementedError

    def begin(self, paths: Sequence[str]) -> None:
        """Called by ``run()`` with the raw scan paths before any file's
        ``check()``.  Per-file rules ignore it; cross-file rules use it
        to judge scan completeness (dispatch-budget only trusts its
        reachability graph when whole directories were walked — a
        file-list scan like ``--changed`` sees a partial graph)."""

    def finalize(self) -> List[Finding]:
        """Project-scoped findings, emitted once after every file's
        ``check()`` ran.  Per-file rules return nothing; cross-file rules
        (dispatch-budget's precompile-reachability closure) accumulate
        facts in ``check()`` and judge here.  Implementations handle
        their own suppressions (``check_file``'s line-scoped filter only
        sees per-file findings) and must reset their accumulated state."""
        return []

    def applies_to(self, path: str) -> bool:
        if not self.scopes:
            return True
        return any(frag in path for frag in self.scopes)


def all_rules() -> List[Rule]:
    # Local imports: the rule modules import this one for Rule/Finding.
    from poseidon_tpu.check.concurrency import (
        BlockingUnderLockRule,
        LockOrderRule,
        UnsafePublicationRule,
    )
    from poseidon_tpu.check.determinism import DeterminismRule
    from poseidon_tpu.check.dispatch_budget import DispatchBudgetRule
    from poseidon_tpu.check.hatch_registry import HatchRegistryRule
    from poseidon_tpu.check.jit_purity import JitPurityRule
    from poseidon_tpu.check.lock_discipline import LockDisciplineRule
    from poseidon_tpu.check.numerics_discipline import (
        NumericsDisciplineRule,
    )
    from poseidon_tpu.check.retrace_guard import RetraceGuardRule
    from poseidon_tpu.check.shard_discipline import ShardDisciplineRule
    from poseidon_tpu.check.transfer_discipline import (
        TransferDisciplineRule,
    )

    return [
        JitPurityRule(),
        LockDisciplineRule(),
        DeterminismRule(),
        RetraceGuardRule(),
        DispatchBudgetRule(),
        TransferDisciplineRule(),
        ShardDisciplineRule(),
        HatchRegistryRule(),
        LockOrderRule(),
        BlockingUnderLockRule(),
        UnsafePublicationRule(),
        NumericsDisciplineRule(),
    ]


def rules_by_name(names: Iterable[str]) -> List[Rule]:
    registry = {r.name: r for r in all_rules()}
    out = []
    for n in names:
        if n not in registry:
            raise KeyError(
                f"unknown rule {n!r}; known: {sorted(registry)}"
            )
        out.append(registry[n])
    return out


# -------------------------------------------------------------- AST helpers


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.AST, module: str) -> Set[str]:
    """Local names bound to ``module`` by import statements.

    ``import numpy as np`` -> {"np"}; ``import numpy`` -> {"numpy"}.
    """
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module:
                    names.add(a.asname or a.name.split(".")[0])
    return names


def from_imports(tree: ast.AST, module: str) -> Dict[str, str]:
    """Local name -> original name for ``from module import ...``."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            for a in node.names:
                out[a.asname or a.name] = a.name
    return out


# ------------------------------------------------------------------ running

# Directories never scanned by the default walk: fixtures hold seeded
# violations on purpose; generated protos are gated by the drift check.
_SKIP_FRAGMENTS = ("check/fixtures", "__pycache__", "protos/")


def iter_py_files(paths: Sequence[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            for f in sorted(path.rglob("*.py")):
                rel = f.as_posix()
                if any(frag in rel for frag in _SKIP_FRAGMENTS):
                    continue
                files.append(f)
        elif path.suffix == ".py":
            files.append(path)
    return files


def check_file(
    path: Path,
    rules: Sequence[Rule],
    *,
    forced: bool = False,
    root: Optional[Path] = None,
) -> List[Finding]:
    """All findings for one file (suppressions applied, baseline not).

    ``forced`` bypasses per-rule scope filters (the CLI's --rule mode and
    the fixture self-tests).
    """
    rel = path.as_posix()
    if root is not None:
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:
            pass
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [
            Finding(rel, e.lineno or 1, "parse-error", str(e.msg))
        ]
    findings: List[Finding] = []
    for rule in rules:
        if not forced and not rule.applies_to(rel):
            continue
        findings.extend(rule.check(tree, source, rel))
    findings = apply_suppressions(findings, source)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def load_baseline(path: Path) -> Set[str]:
    if not path.exists():
        return set()
    keys: Set[str] = set()
    for line in path.read_text().splitlines():
        line = line.rstrip("\n")
        if not line or line.startswith("#"):
            continue
        keys.add(line)
    return keys


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    lines = [
        "# posecheck baseline: grandfathered findings (path<TAB>rule<TAB>"
        "message).",
        "# Regenerate with: python -m poseidon_tpu.check --write-baseline "
        "poseidon_tpu/",
    ]
    lines.extend(sorted({f.baseline_key() for f in findings}))
    path.write_text("\n".join(lines) + "\n")


def run(
    paths: Sequence[str],
    *,
    rules: Optional[Sequence[Rule]] = None,
    baseline: Optional[Path] = None,
    root: Optional[Path] = None,
) -> List[Finding]:
    forced = rules is not None
    active = list(rules) if rules is not None else all_rules()
    baseline_keys = load_baseline(baseline) if baseline else set()
    findings: List[Finding] = []
    for rule in active:
        rule.begin(paths)
    for f in iter_py_files(paths):
        findings.extend(check_file(f, active, forced=forced, root=root))
    for rule in active:
        findings.extend(rule.finalize())
    if baseline_keys:
        findings = [
            f for f in findings if f.baseline_key() not in baseline_keys
        ]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
