"""Concurrency discipline: lock ordering, blocking under locks, unsafe
publication — the static half of the TrackedLock/LockLedger runtime
(utils/locks.py), scoped to the threaded layers (glue watchers/queue,
the cost-build pipeline, the obs plane, chaos, service, replay).

Three rules share one class-level analysis, built on lock-discipline's
machinery (``_lock_factory_names``/``_self_attr`` plus the same
greatest-fixpoint lock-held-helper inference, extended from a boolean
"some lock held" to the *set* of held locks):

``lock-order`` (project-scoped, finalize())
    Builds a cross-file lock-acquisition graph: ``with self.<A>:``
    nesting adds the edge ``Class.A -> Class.B`` for every lock B
    acquired inside (lexically, through lock-held private helpers, and
    through calls into *other* scanned classes' lock-taking public
    methods — linked by unambiguous method name, the same
    over-approximation posture dispatch-budget takes).  Any cycle is a
    potential deadlock: two code paths acquire the same locks in
    opposite orders, and the finding lists every edge with its site.

``blocking-under-lock`` (per-file)
    Flags calls that can park the thread while a lock is held: ``time
    .sleep``, thread/queue ``.join()``, blocking ``.get()``, ``Future
    .result()``, ``.wait()`` on anything but the held lock itself,
    socket ops, RPC stubs, and jitted device dispatch (``jax.*`` calls,
    ``.block_until_ready()``) — the tracer/metrics hot paths must stay
    wait-free, and a device dispatch under a glue lock serializes the
    watcher threads behind the TPU tunnel.

``unsafe-publication`` (per-file)
    In classes that spawn threads, flags mutable state (dict/list/set
    literals and factories, lambdas) assigned to ``self.<attr>`` outside
    ``__init__`` and outside any lock: the new object is published to
    every thread with no happens-before edge.  A documented handoff —
    state swapped before the consuming thread starts, or a deliberate
    benign race — carries a ``# handoff: <why>`` comment on the line,
    the annotation analog of ``# posecheck: ignore[...]``.

The runtime complement: TrackedLock records the orders these rules
predict, and the soak's LockLedger budget-0 window asserts warm rounds
explore no new ones (docs/CHECKS.md).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from poseidon_tpu.check.core import (
    Finding,
    Rule,
    dotted_name,
    from_imports,
    import_aliases,
    suppressions,
)
from poseidon_tpu.check.lock_discipline import (
    _lock_factory_names,
    _self_attr,
)

# The threaded layers: every module the TrackedLock migration covers.
_SCOPES = (
    "poseidon_tpu/glue/",
    "poseidon_tpu/graph/pipeline.py",
    "poseidon_tpu/obs/",
    "poseidon_tpu/chaos/",
    "poseidon_tpu/service/",
    "poseidon_tpu/replay/",
    "poseidon_tpu/costmodel/delta.py",
)

_HANDOFF_RE = re.compile(r"#\s*handoff:")

# Method names that block on a socket receiver.
_SOCKET_METHODS = {
    "connect", "accept", "recv", "recv_into", "recvfrom", "sendall",
}

# Mutable-container factories whose result, published unlocked, is
# visible half-initialized to other threads.
_MUTABLE_FACTORIES = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter",
}

_THREAD_FACTORIES = {"Thread", "Timer"}


def _tracked_factory_names(tree: ast.AST) -> Set[str]:
    """Lock factories: threading's plus the TrackedLock migration's
    (utils/locks.py) — post-migration code must stay in scope."""
    names = _lock_factory_names(tree)
    for local, orig in from_imports(
        tree, "poseidon_tpu.utils.locks"
    ).items():
        if orig in ("TrackedLock", "tracked_condition"):
            names.add(local)
    for alias in import_aliases(tree, "poseidon_tpu.utils.locks"):
        names.add(f"{alias}.TrackedLock")
        names.add(f"{alias}.tracked_condition")
    return names


@dataclass
class _Blocking:
    desc: str
    held: frozenset
    line: int
    method: str


@dataclass
class _Publish:
    attr: str
    line: int
    method: str
    what: str
    held: frozenset


@dataclass
class _MethodInfo:
    name: str
    # (lock attr, lexically-held locks at that point, line)
    acquires: List[Tuple[str, frozenset, int]] = field(default_factory=list)
    # (callee method name, lexically-held locks, line)
    self_calls: List[Tuple[str, frozenset, int]] = field(
        default_factory=list
    )
    # (callee method name, lexically-held locks, line) on non-self
    # receivers — cross-class edge candidates.
    ext_calls: List[Tuple[str, frozenset, int]] = field(
        default_factory=list
    )
    blocking: List[_Blocking] = field(default_factory=list)
    publishes: List[_Publish] = field(default_factory=list)
    escaped: Set[str] = field(default_factory=set)
    spawns_thread: bool = False


class _Scanner(ast.NodeVisitor):
    """One method's walk: tracks the SET of lexically-held locks (the
    lock-discipline scanner's boolean, widened for ordering)."""

    def __init__(self, method: str, lock_attrs: Set[str],
                 method_names: Set[str], env: "_FileEnv") -> None:
        self.info = _MethodInfo(method)
        self.lock_attrs = lock_attrs
        self.method_names = method_names
        self.env = env
        self.held: List[str] = []
        self._call_funcs: Set[int] = set()

    # -- lock context ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            self.visit(item.context_expr)
            attr = _self_attr(item.context_expr)
            if attr in self.lock_attrs:
                self.info.acquires.append(
                    (attr, frozenset(self.held), item.context_expr.lineno)
                )
                self.held.append(attr)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def _visit_nested(self, node: ast.AST) -> None:
        # A nested def/lambda runs later, possibly on another thread —
        # never inherit the enclosing lock context.
        prev, self.held = self.held, []
        self.generic_visit(node)
        self.held = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested(node)

    # -- accesses ----------------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if (
            attr is not None
            and isinstance(node.ctx, ast.Load)
            and attr in self.method_names
            and id(node) not in self._call_funcs
        ):
            # Bare ``self.meth`` (thread target, callback): an escaped
            # entry point — lock-held inference must never apply to it.
            self.info.escaped.add(attr)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        what = self._mutable_kind(node.value)
        if what is not None:
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None and attr not in self.lock_attrs:
                    self.info.publishes.append(_Publish(
                        attr, node.lineno, self.info.name, what,
                        frozenset(self.held),
                    ))
        self.generic_visit(node)

    def _mutable_kind(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, (ast.List, ast.ListComp)):
            return "list"
        if isinstance(value, (ast.Dict, ast.DictComp)):
            return "dict"
        if isinstance(value, (ast.Set, ast.SetComp)):
            return "set"
        if isinstance(value, ast.Lambda):
            return "callback"
        if isinstance(value, ast.Call):
            name = dotted_name(value.func)
            tail = name.rsplit(".", 1)[-1] if name else None
            if tail in _MUTABLE_FACTORIES:
                return tail
        return None

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        held = frozenset(self.held)
        name = dotted_name(node.func)
        if name is not None:
            tail = name.rsplit(".", 1)[-1]
            if tail in _THREAD_FACTORIES or tail == "ThreadPoolExecutor":
                self.info.spawns_thread = True
        if isinstance(node.func, ast.Attribute):
            callee = _self_attr(node.func)
            if callee is not None:
                self.info.self_calls.append(
                    (callee, held, node.lineno)
                )
                self._call_funcs.add(id(node.func))
            elif not isinstance(node.func.value, ast.Constant):
                # x.meth(...) / self.attr.meth(...): a cross-object call
                # (string-literal receivers — "sep".join — excluded).
                self.info.ext_calls.append(
                    (node.func.attr, held, node.lineno)
                )
        desc = self._blocking_desc(node, name)
        if desc is not None:
            self.info.blocking.append(
                _Blocking(desc, held, node.lineno, self.info.name)
            )
        self.generic_visit(node)

    def _blocking_desc(self, node: ast.Call,
                       name: Optional[str]) -> Optional[str]:
        env = self.env
        if name is not None:
            if name in env.sleep_names:
                return f"{name}(...) sleep"
            if name in env.urlopen_names or name in env.create_conn_names:
                return f"{name}(...) network call"
            head = name.split(".", 1)[0]
            if head in env.jax_aliases and "." in name:
                return f"{name}(...) jitted device dispatch"
            if "stub" in name.lower() and isinstance(
                node.func, ast.Attribute
            ):
                return f"{name}(...) RPC"
        if not isinstance(node.func, ast.Attribute):
            return None
        meth = node.func.attr
        npos = len(node.args)
        kwnames = {k.arg for k in node.keywords}
        if meth == "join" and npos == 0:
            # str.join always takes one positional; a no-positional
            # join is a thread/queue join.
            return ".join() thread/queue join"
        if meth == "get" and npos == 0 and kwnames <= {"block", "timeout"}:
            # dict.get always takes a positional key; a no-positional
            # get is a blocking queue get.
            return ".get() blocking queue get"
        if meth == "result":
            return ".result() future join"
        if meth == "wait":
            recv = _self_attr(node.func.value)
            if recv is not None and recv in self.held:
                # Condition.wait on the held lock RELEASES it — the
                # one legal wait inside a critical section.
                return None
            return ".wait() event/condition wait"
        if meth in _SOCKET_METHODS:
            return f".{meth}() socket op"
        if meth == "block_until_ready":
            return ".block_until_ready() device sync"
        return None


@dataclass
class _ClassInfo:
    path: str
    name: str
    lock_attrs: Set[str]
    methods: Dict[str, _MethodInfo]
    # method -> inferred entry-held lock set (greatest fixpoint over
    # private, non-escaped methods; public methods enter lock-free).
    entry_held: Dict[str, Set[str]] = field(default_factory=dict)

    def qual(self, lock: str) -> str:
        return f"{self.name}.{lock}"

    def effective_held(self, method: str, lexical: frozenset) -> Set[str]:
        return set(lexical) | self.entry_held.get(method, set())


def _analyze_class(cls: ast.ClassDef, factories: Set[str],
                   env: "_FileEnv", path: str) -> Optional[_ClassInfo]:
    methods = [n for n in cls.body if isinstance(n, ast.FunctionDef)]
    lock_attrs: Set[str] = set()
    for m in methods:
        for node in ast.walk(m):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if dotted_name(node.value.func) in factories:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr:
                            lock_attrs.add(attr)

    method_names = {m.name for m in methods}
    infos: Dict[str, _MethodInfo] = {}
    for m in methods:
        sc = _Scanner(m.name, lock_attrs, method_names, env)
        for stmt in m.body:
            sc.visit(stmt)
        infos[m.name] = sc.info
    info = _ClassInfo(path, cls.name, lock_attrs, infos)
    if not lock_attrs:
        # Threadless-lockless classes still matter to unsafe-publication
        # (they may spawn threads); entry inference is lock-only.
        return info

    escaped: Set[str] = set()
    for mi in infos.values():
        escaped |= mi.escaped
    call_sites: Dict[str, List[Tuple[str, frozenset]]] = {}
    for caller, mi in infos.items():
        for callee, held, _line in mi.self_calls:
            call_sites.setdefault(callee, []).append((caller, held))

    # Greatest fixpoint over the held SET: a private method's entry-held
    # locks are the intersection over its intra-class call sites of
    # (site-held | caller's entry-held).  Same shape as lock-discipline's
    # boolean fixpoint; recursion self-justifies from the full set.
    entry: Dict[str, Set[str]] = {
        name: set(lock_attrs) for name in infos
        if name in call_sites
        and name.startswith("_") and not name.startswith("__")
        and name not in escaped
    }
    changed = True
    while changed:
        changed = False
        for name in sorted(entry):
            new: Optional[Set[str]] = None
            for caller, held in call_sites[name]:
                eff = set(held) | entry.get(caller, set())
                new = eff if new is None else (new & eff)
            new = new or set()
            if new != entry[name]:
                entry[name] = new
                changed = True
    info.entry_held = entry
    return info


class _FileEnv:
    """Per-file import context shared by the scanners."""

    def __init__(self, tree: ast.AST) -> None:
        self.sleep_names: Set[str] = set()
        for alias in import_aliases(tree, "time"):
            self.sleep_names.add(f"{alias}.sleep")
        for local, orig in from_imports(tree, "time").items():
            if orig == "sleep":
                self.sleep_names.add(local)
        self.jax_aliases = import_aliases(tree, "jax")
        self.urlopen_names: Set[str] = set()
        for local, orig in from_imports(
            tree, "urllib.request"
        ).items():
            if orig == "urlopen":
                self.urlopen_names.add(local)
        for alias in import_aliases(tree, "urllib.request"):
            self.urlopen_names.add(f"{alias}.urlopen")
        self.create_conn_names: Set[str] = set()
        for alias in import_aliases(tree, "socket"):
            self.create_conn_names.add(f"{alias}.create_connection")
        for local, orig in from_imports(tree, "socket").items():
            if orig == "create_connection":
                self.create_conn_names.add(local)


def _file_classes(tree: ast.AST, path: str) -> List[_ClassInfo]:
    factories = _tracked_factory_names(tree)
    env = _FileEnv(tree)
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            info = _analyze_class(node, factories, env, path)
            if info is not None:
                out.append(info)
    return out


# ------------------------------------------------------------- lock-order


@dataclass(frozen=True)
class _Edge:
    src: str
    dst: str
    path: str
    line: int


class LockOrderRule(Rule):
    """Cross-file acquisition-order graph; any cycle is a deadlock
    finding.  Evidence-positive (edges must exist to form a cycle), so
    partial scans (--changed) can miss cycles but never invent them —
    no scan-completeness gate is needed."""

    name = "lock-order"
    scopes = _SCOPES

    def __init__(self) -> None:
        self._classes: List[_ClassInfo] = []
        self._suppressed: Dict[str, Set[int]] = {}

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        self._classes.extend(_file_classes(tree, path))
        lines: Set[int] = set()
        for lineno, rules in suppressions(source).items():
            if rules is None or self.name in rules:
                lines.add(lineno)
        if lines:
            self._suppressed[path] = lines
        return []

    def _edges(self, classes: Sequence[_ClassInfo]) -> List[_Edge]:
        # Public lock-taking entry points across the scan, for linking
        # cross-object calls made under a lock: method name -> list of
        # (class info, locks acquired with no lock lexically held).
        entries: Dict[str, List[Tuple[_ClassInfo, Set[str]]]] = {}
        for ci in classes:
            if not ci.lock_attrs:
                continue
            for mname, mi in ci.methods.items():
                if mname.startswith("_"):
                    continue
                top = {
                    lock for lock, held, _ in mi.acquires if not held
                }
                if top:
                    entries.setdefault(mname, []).append((ci, top))

        seen: Set[Tuple[str, str]] = set()
        edges: List[_Edge] = []

        def add(src: str, dst: str, path: str, line: int) -> None:
            if src == dst or (src, dst) in seen:
                return
            seen.add((src, dst))
            edges.append(_Edge(src, dst, path, line))

        for ci in classes:
            if not ci.lock_attrs:
                continue
            for mname, mi in ci.methods.items():
                for lock, lexical, line in mi.acquires:
                    for h in ci.effective_held(mname, lexical):
                        add(ci.qual(h), ci.qual(lock), ci.path, line)
                # Same-class call into a public lock-taking method
                # while holding a lock (private helpers are covered by
                # the entry-held inference above).
                for callee, lexical, line in mi.self_calls:
                    held = ci.effective_held(mname, lexical)
                    if not held or callee not in ci.methods:
                        continue
                    for lock, chold, _ in ci.methods[callee].acquires:
                        if chold:
                            continue
                        for h in held:
                            add(ci.qual(h), ci.qual(lock), ci.path, line)
                # Cross-object call under a lock, linked by unambiguous
                # public method name (two candidate classes = ambiguous
                # = no edge; heuristic linking must not invent cycles
                # out of generic names).
                for callee, lexical, line in mi.ext_calls:
                    held = ci.effective_held(mname, lexical)
                    if not held:
                        continue
                    cands = [
                        (other, locks)
                        for other, locks in entries.get(callee, ())
                        if other.name != ci.name
                    ]
                    if len(cands) != 1:
                        continue
                    other, locks = cands[0]
                    for lock in locks:
                        for h in held:
                            add(ci.qual(h), other.qual(lock),
                                ci.path, line)
        return edges

    def finalize(self) -> List[Finding]:
        classes, self._classes = self._classes, []
        suppressed, self._suppressed = self._suppressed, {}
        edges = self._edges(classes)
        succ: Dict[str, List[_Edge]] = {}
        for e in edges:
            succ.setdefault(e.src, []).append(e)

        def path_back(src: str, dst: str) -> Optional[List[_Edge]]:
            """A path of edges from src to dst, if one exists."""
            seen = {src}
            stack: List[Tuple[str, List[_Edge]]] = [(src, [])]
            while stack:
                node, trail = stack.pop()
                if node == dst:
                    return trail
                for e in succ.get(node, ()):
                    if e.dst not in seen or e.dst == dst:
                        seen.add(e.dst)
                        stack.append((e.dst, trail + [e]))
            return None

        findings: List[Finding] = []
        reported: Set[frozenset] = set()
        for e in edges:
            back = path_back(e.dst, e.src)
            if back is None:
                continue
            cycle = [e] + back
            key = frozenset((c.src, c.dst) for c in cycle)
            if key in reported:
                continue
            reported.add(key)
            if any(
                c.line in suppressed.get(c.path, ())
                for c in cycle
            ):
                continue
            desc = ", ".join(
                f"{c.src} -> {c.dst} ({c.path}:{c.line})" for c in cycle
            )
            findings.append(Finding(
                e.path, e.line, self.name,
                f"lock-order cycle (potential deadlock): {desc}; two "
                "paths acquire these locks in opposite orders — pick "
                "one global order (deepest-last) and restructure the "
                "odd one out",
            ))
        findings.sort(key=lambda f: (f.path, f.line))
        return findings


# ----------------------------------------------------- blocking-under-lock


class BlockingUnderLockRule(Rule):
    name = "blocking-under-lock"
    scopes = _SCOPES

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        findings: List[Finding] = []
        for ci in _file_classes(tree, path):
            if not ci.lock_attrs:
                continue
            for mname, mi in ci.methods.items():
                for b in mi.blocking:
                    held = ci.effective_held(mname, b.held)
                    if not held:
                        continue
                    locks = "/".join(
                        f"self.{h}" for h in sorted(held)
                    )
                    findings.append(Finding(
                        path, b.line, self.name,
                        f"{b.desc} while holding {locks} "
                        f"({ci.name}.{mname}): the thread parks inside "
                        "the critical section and every contender "
                        "parks behind it — move the wait outside the "
                        "lock",
                    ))
        return findings


# ------------------------------------------------------ unsafe-publication


class UnsafePublicationRule(Rule):
    name = "unsafe-publication"
    scopes = _SCOPES

    def check(self, tree: ast.AST, source: str, path: str) -> List[Finding]:
        handoff_lines = {
            lineno
            for lineno, text in enumerate(source.splitlines(), start=1)
            if _HANDOFF_RE.search(text)
        }
        findings: List[Finding] = []
        for ci in _file_classes(tree, path):
            threaded = any(
                mi.spawns_thread for mi in ci.methods.values()
            )
            if not threaded:
                continue
            for mname, mi in ci.methods.items():
                if mname == "__init__":
                    continue
                for p in mi.publishes:
                    if p.line in handoff_lines:
                        continue
                    if ci.effective_held(mname, p.held):
                        continue
                    findings.append(Finding(
                        path, p.line, self.name,
                        f"{p.what} assigned to self.{p.attr} outside "
                        f"a lock ({ci.name}.{mname}): the object is "
                        "published to the class's threads with no "
                        "happens-before edge — assign under the lock, "
                        "or annotate a documented handoff with "
                        "`# handoff: <why>`",
                    ))
        return findings
