"""Pod watcher: K8s pod lifecycle -> Firmament task RPCs.

Re-creates the reference's pod watcher semantics (pkg/k8sclient/podwatcher.go):

- only pods with ``spec.schedulerName == poseidon`` are watched (:81-90);
- pods are grouped into jobs by owner reference, with a deterministic job
  UUID and FNV hash-combine task uids (:377-422);
- the phase machine maps Pending/Succeeded/Failed/Deleted/Updated to
  TaskSubmitted/TaskCompleted/TaskFailed/TaskRemoved/TaskUpdated (:249-351);
- nodeSelector terms become IN_SET label selectors (:455-465), the
  ``networkRequirement`` label becomes a NetRxBw request (:467-476), and
  the ``taskType`` label selects the interference class (:478-495);
- a keyed queue + N workers guarantee per-pod ordered processing (:91-129).
"""

from __future__ import annotations

import copy
import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List

from poseidon_tpu.glue.fake_kube import KubeAPI, Pod
from poseidon_tpu.glue.keyed_queue import KeyedQueue
from poseidon_tpu.glue.types import SharedState
from poseidon_tpu.obs import metrics as obs_metrics
from poseidon_tpu.obs import trace as obs_trace
from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.service.client import FirmamentClient
from poseidon_tpu.utils.ids import generate_uuid, task_uid
from poseidon_tpu.utils.locks import TrackedLock

log = logging.getLogger("poseidon.podwatcher")

# taskType label -> interference class (podwatcher.go:478-495).
_TASK_TYPES = {
    "sheep": fpb.TaskDescriptor.SHEEP,
    "rabbit": fpb.TaskDescriptor.RABBIT,
    "devil": fpb.TaskDescriptor.DEVIL,
    "turtle": fpb.TaskDescriptor.TURTLE,
}


@dataclass
class _JobEntry:
    uuid: str
    # Pod key -> task index within the job (index 0 = root task).
    indices: Dict[str, int] = field(default_factory=dict)
    next_index: int = 0


class PodWatcher:
    def __init__(
        self,
        kube: KubeAPI,
        firmament: FirmamentClient,
        shared: SharedState,
        scheduler_name: str = "poseidon",
        workers: int = 10,
    ) -> None:
        self.kube = kube
        self.fc = firmament
        self.shared = shared
        self.scheduler_name = scheduler_name
        self.workers = workers
        self.queue = KeyedQueue()
        self._jobs: Dict[str, _JobEntry] = {}
        self._jobs_lock = TrackedLock("glue.PodWatcher._jobs_lock")
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # Observability: how many times the watch dropped and re-synced.
        self.resyncs = 0

    # ------------------------------------------------------------- job model

    def _job_for(self, pod: Pod) -> _JobEntry:
        """Owner-ref grouping with deterministic ids (podwatcher.go:377-422).

        Pods without an owner are singleton jobs keyed by their own name
        (GetOwnerReference falls back to the pod itself, :425-453).
        """
        owner = pod.owner_uid or f"pod:{pod.key}"
        with self._jobs_lock:
            entry = self._jobs.get(owner)
            if entry is None:
                entry = _JobEntry(uuid=generate_uuid(owner))
                self._jobs[owner] = entry
            if pod.key not in entry.indices:
                entry.indices[pod.key] = entry.next_index
                entry.next_index += 1
            return entry

    def _task_uid(self, pod: Pod) -> int:
        entry = self._job_for(pod)
        return task_uid(entry.uuid, entry.indices[pod.key])

    # ----------------------------------------------------------- descriptors

    def _descriptor(self, pod: Pod) -> fpb.TaskDescription:
        entry = self._job_for(pod)
        td = fpb.TaskDescriptor(
            uid=self._task_uid(pod),
            name=pod.key,
            job_id=entry.uuid,
            index=entry.indices[pod.key],
        )
        td.resource_request.cpu_cores = pod.cpu_request
        td.resource_request.ram_cap = pod.ram_request
        # networkRequirement label -> net receive bandwidth request
        # (podwatcher.go:467-476; value in Mbps in the reference, carried
        # through as-is).
        net = pod.labels.get("networkRequirement")
        if net:
            try:
                td.resource_request.net_rx_bw = int(net)
            except ValueError:
                log.warning("pod %s: bad networkRequirement %r", pod.key, net)
        ttype = pod.labels.get("taskType")
        if ttype:
            td.task_type = _TASK_TYPES.get(ttype.lower(), fpb.TaskDescriptor.SHEEP)
        for k, v in sorted(pod.labels.items()):
            td.labels.add(key=k, value=v)
        # nodeSelector -> IN_SET constraints (podwatcher.go:455-465).
        for k, v in sorted(pod.node_selector.items()):
            td.label_selectors.add(
                type=fpb.LabelSelector.IN_SET, key=k, values=[v]
            )
        # podAffinity/podAntiAffinity matchLabels -> pod-level selectors
        # (contract extension; resolved against machine residents).
        for k, v in sorted(pod.pod_affinity.items()):
            td.pod_affinity.add(
                type=fpb.LabelSelector.IN_SET, key=k, values=[v]
            )
        for k, v in sorted(pod.pod_anti_affinity.items()):
            td.pod_anti_affinity.add(
                type=fpb.LabelSelector.IN_SET, key=k, values=[v]
            )
        # Already-bound pods (seen on restart re-list) carry their binding
        # so the scheduler state machine can recover the placement
        # (task_desc.proto's scheduled_to_resource field).
        if pod.node_name:
            res = self.shared.resource_for_node(pod.node_name)
            if res is not None:
                td.scheduled_to_resource = res
        jd = fpb.JobDescriptor(uuid=entry.uuid, name=pod.owner_uid or pod.key)
        return fpb.TaskDescription(task_descriptor=td, job_descriptor=jd)

    # -------------------------------------------------------------- lifecycle

    def run(self) -> None:
        """List+watch, then start the worker pool (podwatcher.go:91-129)."""
        watch = self.kube.watch_pods()
        for pod in self.kube.list_pods():
            self._enqueue("ADDED", pod)
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker, name=f"pod-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        pump = threading.Thread(
            target=self._pump, args=(watch,), name="pod-watch", daemon=True
        )
        pump.start()
        self._threads.append(pump)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()

    def _pump(self, watch) -> None:
        while not self._stop.is_set():
            try:
                kind, pod = watch.get(timeout=0.2)
            except Exception:
                continue
            if kind == "ERROR":
                # Watch dropped (stale resourceVersion / connection
                # loss): events between the drop and now are GONE, so a
                # fresh watch alone would leave the scheduler's world
                # diverged forever.  Resync: re-subscribe, re-list, and
                # synthesize the deletions the dead watch swallowed.
                log.warning("pod watch dropped (%s); resyncing", pod)
                watch = self._resync(watch)
                continue
            self._enqueue(kind, pod)

    def _resync(self, old_watch=None):
        """Re-list + re-watch after a dropped watch (the informer
        relist path).  Subscribe-then-list ordering leaves no gap: an
        event racing the list is delivered by the new watch too, and the
        phase machine is idempotent under the duplicate.  Pods the
        tracked world knows but the fresh list lacks were deleted while
        disconnected — synthesize their DELETED events; pods it knows
        that still exist replay as MODIFIED, so a spec change whose
        event died with the watch still lands (the ADDED path ignores
        already-known pods)."""
        self.resyncs += 1
        if old_watch is not None:
            self.kube.unwatch_pods(old_watch)
        watch = self.kube.watch_pods()
        listed = {}
        for pod in self.kube.list_pods():
            listed[pod.key] = pod
        known = self.shared.pods_snapshot()
        for key in sorted(set(known) - set(listed)):
            lost = copy.copy(known[key])
            lost.deleted = True
            self._enqueue("DELETED", lost)
        for key in sorted(listed):
            kind = "MODIFIED" if key in known else "ADDED"
            self._enqueue(kind, listed[key])
        return watch

    def _enqueue(self, kind: str, pod: Pod) -> None:
        if pod.scheduler_name != self.scheduler_name:
            return  # filtered informer (podwatcher.go:81-90)
        self.queue.add(pod.key, (kind, pod))

    # ----------------------------------------------------------- phase machine

    def _worker(self) -> None:
        # The continuous-ingest thread of the streaming round engine:
        # every event becomes RPC state in the service's ClusterState
        # the moment it is processed (the state's own lock publishes
        # it), and watch_event's stamp is the ingest-liveness signal
        # /healthz judges wedged watchers by.  The round's admission
        # cut happens service-side at view-snapshot time — nothing
        # here batches or waits on round boundaries.
        while True:
            batch = self.queue.get()
            if batch is None:
                return
            key, items = batch
            try:
                for kind, pod in items:
                    with obs_trace.span("watch.pod_event", kind=kind,
                                        pod=pod.key):
                        self._process(kind, pod)
                    obs_metrics.watch_event("pod", kind)
            except Exception:
                log.exception("pod worker failed on %s", key)
            finally:
                self.queue.done(key)

    def _process(self, kind: str, pod: Pod) -> None:
        uid = self._task_uid(pod)
        sh = self.shared
        if kind == "DELETED" or pod.deleted:
            if sh.pop_task(uid) is not None:
                self.fc.task_removed(uid)
            self._gc_job(pod)
            return
        if pod.phase == "Succeeded":
            known = sh.get_task(uid)
            if known is not None and not known.finished:
                self.fc.task_completed(uid)
                sh.mark_finished(uid)
            return
        if pod.phase == "Failed":
            known = sh.get_task(uid)
            if known is not None and not known.finished:
                self.fc.task_failed(uid)
                sh.mark_finished(uid)
            return
        if pod.phase in ("Pending", "Running"):
            known = sh.get_task(uid)
            if known is None:
                # Fresh Pending pod — or an already-bound pod re-listed
                # after a glue restart, whose binding the descriptor
                # carries via scheduled_to_resource.
                desc = self._descriptor(pod)
                sh.put_task(uid, pod, desc.task_descriptor)
                self.fc.task_submitted(
                    desc.task_descriptor, desc.job_descriptor
                )
            elif kind == "MODIFIED" and self._spec_changed(known.pod, pod):
                desc = self._descriptor(pod)
                sh.put_task(uid, pod, desc.task_descriptor)
                self.fc.task_updated(desc.task_descriptor, desc.job_descriptor)

    @staticmethod
    def _spec_changed(old: Pod, new: Pod) -> bool:
        """Request/label mutations trigger TaskUpdated (podwatcher.go:362-375);
        phase/binding transitions do not."""
        return (
            old.cpu_request != new.cpu_request
            or old.ram_request != new.ram_request
            or old.labels != new.labels
            or old.node_selector != new.node_selector
            or old.pod_affinity != new.pod_affinity
            or old.pod_anti_affinity != new.pod_anti_affinity
        )

    def _gc_job(self, pod: Pod) -> None:
        """Drop the job entry once its last task is gone (podwatcher.go:288-309)."""
        owner = pod.owner_uid or f"pod:{pod.key}"
        with self._jobs_lock:
            entry = self._jobs.get(owner)
            if entry is None:
                return
            entry.indices.pop(pod.key, None)
            if not entry.indices:
                del self._jobs[owner]
