"""Node watcher: K8s node lifecycle -> Firmament node RPCs.

Re-creates the reference's node watcher (pkg/k8sclient/nodewatcher.go):

- ``Unschedulable`` nodes are skipped entirely (:124-132);
- conditions map to phases: Ready -> Added, NotReady/OutOfDisk -> Failed,
  deletion -> Removed (:134-178);
- each node becomes a 2-level Machine -> PU#0 topology with the capacity
  vector (RAM KB, CPU millicores) and labels copied onto the machine
  descriptor (:292-339);
- deterministic resource UUIDs from the node name, per-node ordered
  processing via the keyed queue + N workers (:219-283).
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import List

from poseidon_tpu.glue.fake_kube import KubeAPI, Node
from poseidon_tpu.glue.keyed_queue import KeyedQueue
from poseidon_tpu.glue.types import SharedState
from poseidon_tpu.obs import metrics as obs_metrics
from poseidon_tpu.obs import trace as obs_trace
from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.service.client import FirmamentClient
from poseidon_tpu.utils.ids import resource_uuid

log = logging.getLogger("poseidon.nodewatcher")

DEFAULT_TASK_SLOTS = 100


def topology_for_node(node: Node) -> fpb.ResourceTopologyNodeDescriptor:
    """Machine + single PU#0 child (nodewatcher.go:292-339)."""
    rtnd = fpb.ResourceTopologyNodeDescriptor()
    rd = rtnd.resource_desc
    rd.uuid = resource_uuid(node.name)
    rd.friendly_name = node.name
    rd.descriptive_name = node.name
    rd.type = fpb.ResourceDescriptor.RESOURCE_MACHINE
    rd.state = fpb.ResourceDescriptor.RESOURCE_IDLE
    rd.schedulable = True
    rd.task_capacity = DEFAULT_TASK_SLOTS
    rd.resource_capacity.cpu_cores = node.cpu_capacity
    rd.resource_capacity.ram_cap = node.ram_capacity
    rd.available_resources.cpu_cores = node.cpu_capacity
    rd.available_resources.ram_cap = node.ram_capacity
    for k, v in sorted(node.labels.items()):
        rd.labels.add(key=k, value=v)

    pu = rtnd.children.add()
    pu.parent_id = rd.uuid
    prd = pu.resource_desc
    prd.uuid = resource_uuid(f"{node.name}/pu0")
    prd.friendly_name = f"{node.name}_pu0"
    prd.type = fpb.ResourceDescriptor.RESOURCE_PU
    prd.state = fpb.ResourceDescriptor.RESOURCE_IDLE
    prd.schedulable = True
    prd.task_capacity = DEFAULT_TASK_SLOTS
    return rtnd


class NodeWatcher:
    def __init__(
        self,
        kube: KubeAPI,
        firmament: FirmamentClient,
        shared: SharedState,
        workers: int = 10,
    ) -> None:
        self.kube = kube
        self.fc = firmament
        self.shared = shared
        self.workers = workers
        self.queue = KeyedQueue()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # Observability: how many times the watch dropped and re-synced.
        self.resyncs = 0

    def run(self) -> None:
        watch = self.kube.watch_nodes()
        for node in self.kube.list_nodes():
            self.queue.add(node.name, ("ADDED", node))
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker, name=f"node-worker-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        pump = threading.Thread(
            target=self._pump, args=(watch,), name="node-watch", daemon=True
        )
        pump.start()
        self._threads.append(pump)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()

    def _pump(self, watch) -> None:
        while not self._stop.is_set():
            try:
                kind, node = watch.get(timeout=0.2)
            except Exception:
                continue
            if kind == "ERROR":
                # Same contract as the pod watcher: a dropped watch
                # swallowed events; re-subscribe, re-list, synthesize
                # the deletions the gap hid.
                log.warning("node watch dropped (%s); resyncing", node)
                watch = self._resync(watch)
                continue
            self.queue.add(node.name, (kind, node))

    def _resync(self, old_watch=None):
        """Re-list + re-watch after a dropped node watch; nodes the
        tracked world knows but the fresh list lacks were removed while
        disconnected — synthesize their DELETED events so the scheduler
        evicts their tasks.  (Replaying known nodes as ADDED is sound
        here: the node phase machine diffs capacity/labels/health
        regardless of event kind.)"""
        self.resyncs += 1
        if old_watch is not None:
            self.kube.unwatch_nodes(old_watch)
        watch = self.kube.watch_nodes()
        listed = {n.name: n for n in self.kube.list_nodes()}
        known = self.shared.nodes_snapshot()
        for name in sorted(set(known) - set(listed)):
            lost = copy.copy(known[name])
            lost.deleted = True
            self.queue.add(name, ("DELETED", lost))
        for name in sorted(listed):
            self.queue.add(name, ("ADDED", listed[name]))
        return watch

    def _worker(self) -> None:
        # Continuous ingest (see PodWatcher._worker): node deltas land
        # in ClusterState as they arrive; watch_event stamps ingest
        # liveness for /healthz's streaming wedge gate.
        while True:
            batch = self.queue.get()
            if batch is None:
                return
            key, items = batch
            try:
                for kind, node in items:
                    with obs_trace.span("watch.node_event", kind=kind,
                                        node=node.name):
                        self._process(kind, node)
                    obs_metrics.watch_event("node", kind)
            except Exception:
                log.exception("node worker failed on %s", key)
            finally:
                self.queue.done(key)

    # ----------------------------------------------------------- phase machine

    def _process(self, kind: str, node: Node) -> None:
        sh = self.shared
        known = sh.get_node(node.name)
        if kind == "DELETED" or node.deleted:
            entry = sh.pop_node(node.name)
            if entry is not None:
                self.fc.node_removed(entry.rtnd.resource_desc.uuid)
            return
        if node.unschedulable:
            # Unschedulable gate (nodewatcher.go:124-132): treat a known
            # node turning unschedulable as a removal, never add it.
            entry = sh.pop_node(node.name)
            if entry is not None:
                self.fc.node_removed(entry.rtnd.resource_desc.uuid)
            return
        healthy = node.ready and not node.out_of_disk
        if known is None:
            if healthy:
                rtnd = topology_for_node(node)
                sh.put_node(node, rtnd)
                self.fc.node_added(rtnd)
            return
        if not healthy:
            # Ready=False / OutOfDisk=True -> Failed (nodewatcher.go:151-165).
            # Store the failed condition so a later recovery event is
            # detectable (and re-armed via NodeUpdated below).
            sh.put_node(node, known.rtnd)
            self.fc.node_failed(known.rtnd.resource_desc.uuid)
            return
        if (
            node.cpu_capacity != known.node.cpu_capacity
            or node.ram_capacity != known.node.ram_capacity
            or node.labels != known.node.labels
        ):
            rtnd = topology_for_node(node)
            sh.put_node(node, rtnd)
            self.fc.node_updated(rtnd)
        elif not known.node.ready or known.node.out_of_disk:
            # Healthy again after a Failed phase: NodeUpdated re-arms it.
            sh.put_node(node, known.rtnd)
            self.fc.node_updated(known.rtnd)
        else:
            sh.put_node(node, known.rtnd)
