"""The Poseidon glue process: schedule loop + watchers + stats server.

Re-creates the reference entry point (cmd/poseidon/poseidon.go:32-103):
connect to Firmament, gate on its health check, then run three concurrent
families — the schedule loop (Schedule() -> enact deltas), the stats
server, and the pod/node watchers.

Delta enactment (poseidon.go:36-67): PLACE binds the pod to the node;
PREEMPT and MIGRATE delete the pod (K8s has no native preemption — the
owning controller resubmits, and a MIGRATEd pod's replacement lands on the
new node next round); NOOP is skipped.  Unknown task/resource ids in a
delta are fatal in the reference (poseidon.go:43); here they raise.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from poseidon_tpu.glue.fake_kube import KubeAPI
from poseidon_tpu.glue.nodewatcher import NodeWatcher
from poseidon_tpu.glue.podwatcher import PodWatcher
from poseidon_tpu.glue.stats_server import StatsServer
from poseidon_tpu.glue.types import SharedState
from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.service.client import FirmamentClient
from poseidon_tpu.utils.config import PoseidonConfig

log = logging.getLogger("poseidon")


@dataclass
class LoopStats:
    rounds: int = 0
    placed: int = 0
    preempted: int = 0
    migrated: int = 0


class Poseidon:
    """One glue process; ``start()`` spawns the goroutine families."""

    def __init__(
        self,
        kube: KubeAPI,
        config: Optional[PoseidonConfig] = None,
        firmament: Optional[FirmamentClient] = None,
        stats_address: Optional[str] = None,
        run_loop: bool = True,
    ) -> None:
        # run_loop=False: callers drive rounds via schedule_once() — the
        # deterministic mode for tests/replay (the background loop fires
        # immediately on start, racing explicit rounds otherwise).
        self.run_loop = run_loop
        self.config = config or PoseidonConfig()
        self.kube = kube
        self.fc = firmament or FirmamentClient(self.config.firmament_address)
        self.shared = SharedState()
        # Watchers own a second client connection in the reference
        # (k8sclient.go:74); one python client object is thread-safe here.
        self.pod_watcher = PodWatcher(
            kube, self.fc, self.shared,
            scheduler_name=self.config.scheduler_name,
        )
        self.node_watcher = NodeWatcher(kube, self.fc, self.shared)
        self.stats_server: Optional[StatsServer] = None
        if stats_address is not None:
            self.stats_server = StatsServer(
                self.shared, self.fc, address=stats_address
            )
        self.loop_stats = LoopStats()
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None

    # --------------------------------------------------------------- lifecycle

    def start(self, health_timeout: float = 600.0) -> "Poseidon":
        if not self.fc.wait_for_service(
            timeout=health_timeout, poll_interval=0.1
        ):
            raise RuntimeError("firmament service never became healthy")
        if self.stats_server is not None:
            self.stats_server.start()
        self.node_watcher.run()
        # Initial node sync before pods start flowing (the informer
        # cache-sync ordering): a re-listed bound pod resolves its node's
        # resource uuid through SharedState, which must be populated first.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(self.node_watcher.queue):
            time.sleep(0.01)
        self.pod_watcher.run()
        if self.run_loop:
            self._loop_thread = threading.Thread(
                target=self._loop, name="schedule-loop", daemon=True
            )
            self._loop_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.pod_watcher.stop()
        self.node_watcher.stop()
        if self.stats_server is not None:
            self.stats_server.stop()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)

    def __enter__(self) -> "Poseidon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ the hot loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.schedule_once()
            except Exception:
                log.exception("schedule round failed")
            self._stop.wait(self.config.scheduling_interval)

    def schedule_once(self) -> List[fpb.SchedulingDelta]:
        """One Schedule() call + delta enactment (poseidon.go:32-67)."""
        deltas = self.fc.schedule()
        for delta in deltas:
            if delta.type == fpb.SchedulingDelta.PLACE:
                pod = self.shared.task_for_uid(delta.task_id)
                node = self.shared.node_for_resource(delta.resource_id)
                if pod is None or node is None:
                    raise RuntimeError(
                        f"PLACE delta references unknown ids: {delta}"
                    )
                self.kube.bind_pod(pod.namespace, pod.name, node)
                self.loop_stats.placed += 1
            elif delta.type in (
                fpb.SchedulingDelta.PREEMPT,
                fpb.SchedulingDelta.MIGRATE,
            ):
                pod = self.shared.task_for_uid(delta.task_id)
                if pod is None:
                    raise RuntimeError(
                        f"PREEMPT/MIGRATE delta references unknown task: {delta}"
                    )
                self.kube.delete_pod(pod.namespace, pod.name)
                if delta.type == fpb.SchedulingDelta.PREEMPT:
                    self.loop_stats.preempted += 1
                else:
                    self.loop_stats.migrated += 1
            # NOOP: skip (poseidon.go:64).
        self.loop_stats.rounds += 1
        return list(deltas)

    # -------------------------------------------------------------- test hooks

    def drain_watchers(self, timeout: float = 5.0) -> bool:
        """Wait until both work queues are empty (integration-test barrier)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.pod_watcher.queue) == 0 and \
               len(self.node_watcher.queue) == 0:
                # One extra beat for in-flight worker batches.
                time.sleep(0.05)
                if len(self.pod_watcher.queue) == 0 and \
                   len(self.node_watcher.queue) == 0:
                    return True
            time.sleep(0.01)
        return False
