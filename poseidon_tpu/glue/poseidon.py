"""The Poseidon glue process: schedule loop + watchers + stats server.

Re-creates the reference entry point (cmd/poseidon/poseidon.go:32-103):
connect to Firmament, gate on its health check, then run three concurrent
families — the schedule loop (Schedule() -> enact deltas), the stats
server, and the pod/node watchers.

Delta enactment (poseidon.go:36-67): PLACE binds the pod to the node;
PREEMPT and MIGRATE delete the pod (K8s has no native preemption — the
owning controller resubmits, and a MIGRATEd pod's replacement lands on the
new node next round); NOOP is skipped.  Unknown task/resource ids in a
delta are fatal in the reference (poseidon.go:43); here they raise.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from concurrent import futures as cf
from dataclasses import dataclass
from typing import List, Optional

import grpc

from poseidon_tpu.glue.fake_kube import KubeAPI
from poseidon_tpu.glue.nodewatcher import NodeWatcher
from poseidon_tpu.glue.podwatcher import PodWatcher
from poseidon_tpu.glue.stats_server import StatsServer
from poseidon_tpu.glue.types import SharedState
from poseidon_tpu.obs import metrics as obs_metrics
from poseidon_tpu.obs import trace as obs_trace
from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.service.client import FirmamentClient, rpc_code
from poseidon_tpu.utils.config import PoseidonConfig
from poseidon_tpu.utils.hatches import hatch_bool, hatch_float
from poseidon_tpu.utils.locks import TrackedLock

log = logging.getLogger("poseidon")


@dataclass
class LoopStats:
    rounds: int = 0
    placed: int = 0
    preempted: int = 0
    migrated: int = 0
    # Hardening counters (the chaos soak's observability surface):
    # rounds that raised, the running consecutive-failure count feeding
    # the crash-loop budget, PLACE enactments the API server rejected
    # (each rolled back + requeued), and tasks requeued — by the bind
    # rollback or by the suspect reconciler after a commit-ambiguous
    # Schedule failure.
    failed_rounds: int = 0
    consecutive_failures: int = 0
    bind_failures: int = 0
    requeued: int = 0


class Poseidon:
    """One glue process; ``start()`` spawns the goroutine families."""

    def __init__(
        self,
        kube: KubeAPI,
        config: Optional[PoseidonConfig] = None,
        firmament: Optional[FirmamentClient] = None,
        stats_address: Optional[str] = None,
        metrics_address: Optional[str] = None,
        run_loop: bool = True,
    ) -> None:
        # run_loop=False: callers drive rounds via schedule_once() — the
        # deterministic mode for tests/replay (the background loop fires
        # immediately on start, racing explicit rounds otherwise).
        self.run_loop = run_loop
        self.config = config or PoseidonConfig()
        self.kube = kube
        self.fc = firmament or FirmamentClient(
            self.config.firmament_address,
            rpc_timeout_s=self.config.rpc_timeout_s,
            rpc_retries=self.config.rpc_retries,
            rpc_backoff_s=self.config.rpc_backoff_s,
        )
        self.shared = SharedState()
        # Watchers own a second client connection in the reference
        # (k8sclient.go:74); one python client object is thread-safe here.
        self.pod_watcher = PodWatcher(
            kube, self.fc, self.shared,
            scheduler_name=self.config.scheduler_name,
        )
        self.node_watcher = NodeWatcher(kube, self.fc, self.shared)
        self.stats_server: Optional[StatsServer] = None
        if stats_address is not None:
            self.stats_server = StatsServer(
                self.shared, self.fc, address=stats_address
            )
        # Prometheus exporter (obs/metrics.py): the scrape endpoint the
        # deploy manifest annotates.  Explicit arg wins; else the config
        # field (empty = disabled, the test-harness default).
        self.metrics_server: Optional[obs_metrics.MetricsServer] = None
        metrics_address = metrics_address or getattr(
            self.config, "metrics_address", ""
        ) or None
        if metrics_address is not None:
            self.metrics_server = obs_metrics.MetricsServer(metrics_address)
        self.loop_stats = LoopStats()
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        # Crash-loop hardening state: the fatal-stop reason once the
        # budget is exhausted (None while healthy), seeded jitter for the
        # failure backoff (seeded: chaos soaks re-run bit-for-bit).
        self.fatal: Optional[str] = None
        self._backoff_jitter = random.Random(0)
        # Suspect-reconciler state: glue's own record of enacted
        # placements (uid -> node), and whether the last Schedule()
        # attempt failed in flight — the commit-ambiguous window in
        # which the service may hold placements whose deltas were lost.
        self._enacted: dict = {}
        self._schedule_suspect = False
        # Suspicion generation: bumped on every _mark_suspect.  The
        # streaming enact worker clears the flag only if the generation
        # it captured at submit is still current — new suspicion raised
        # concurrently (a schedule RPC failing mid-enact) survives.
        self._suspect_gen = 0
        # Half-completed rollbacks: uid -> (td, jd) whose task_removed
        # landed but whose resubmit RPC failed (replayed every round).
        self._resubmit_pending: dict = {}
        # Guards the glue state that BOTH the round thread and the
        # streaming enact worker mutate: the resubmit-pending map and
        # the suspect flag/generation.  Held only around dict/flag
        # writes, never across an RPC.  Synchronous mode takes it
        # uncontended on the one round thread.
        self._state_lock = TrackedLock("glue.Poseidon._state_lock")
        # Streaming round engine (POSEIDON_STREAMING): the single-worker
        # enactment executor and the in-flight round's future.  With the
        # hatch off neither is ever created and schedule_once runs the
        # round-synchronous path bit-identically.
        self._enact_pool: Optional[cf.ThreadPoolExecutor] = None
        self._enact_future: Optional[cf.Future] = None
        # Sustained-throughput gauge state: placements/sec over the
        # window since the previous metrics observation.
        self.placements_per_sec = 0.0
        self._pps_t: Optional[float] = None
        self._pps_placed = 0
        # Last successful round's deltas (the flight recorder's view).
        self.last_deltas: List[fpb.SchedulingDelta] = []

    # --------------------------------------------------------------- lifecycle

    def start(self, health_timeout: float = 600.0) -> "Poseidon":
        if not self.fc.wait_for_service(
            timeout=health_timeout, poll_interval=0.1
        ):
            raise RuntimeError("firmament service never became healthy")
        if self.stats_server is not None:
            self.stats_server.start()
        if self.metrics_server is not None:
            self.metrics_server.start()
            log.info("metrics server on %s", self.metrics_server.address)
        self.node_watcher.run()
        # Initial node sync before pods start flowing (the informer
        # cache-sync ordering): a re-listed bound pod resolves its node's
        # resource uuid through SharedState, which must be populated first.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(self.node_watcher.queue):
            time.sleep(0.01)
        self.pod_watcher.run()
        if self.run_loop:
            self._loop_thread = threading.Thread(
                target=self._loop, name="schedule-loop", daemon=True
            )
            self._loop_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)
        # Quiesce the streaming engine AFTER the loop thread (it is the
        # only submitter): join the in-flight enactment so no worker
        # races the watcher/server teardown below.
        try:
            self._join_enact()
        except Exception:  # noqa: BLE001 - shutdown path
            log.exception("in-flight enactment failed during stop")
        if self._enact_pool is not None:
            self._enact_pool.shutdown(wait=True)
        self.pod_watcher.stop()
        self.node_watcher.stop()
        if self.stats_server is not None:
            self.stats_server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()

    def __enter__(self) -> "Poseidon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ the hot loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            delay = self.try_round()
            if delay is None:
                return  # crash-loop budget exhausted; try_round stopped us
            self._stop.wait(delay)

    def try_round(self) -> Optional[float]:
        """One loop iteration's round + failure policy.

        Returns the delay before the next round: the scheduling interval
        after a healthy round, an exponentially-backed-off (jittered)
        retry delay after a failed one, or ``None`` once the crash-loop
        budget is exhausted — the loop then stops FATALLY with
        ``self.fatal`` set, instead of the old unbounded
        log-and-spin-on-the-interval swallow.  Factored out of ``_loop``
        so the soak harness drives the exact production failure policy
        without a thread."""
        try:
            with obs_trace.span("glue.try_round") as sp:
                self.schedule_once()
                sp.set(deltas=len(self.last_deltas))
        except Exception:
            self.loop_stats.failed_rounds += 1
            self.loop_stats.consecutive_failures += 1
            n = self.loop_stats.consecutive_failures
            log.exception(
                "schedule round failed (consecutive failure %d/%d)",
                n, self.config.crash_loop_budget,
            )
            if n >= self.config.crash_loop_budget:
                self.fatal = (
                    f"schedule loop stopping: {n} consecutive round "
                    f"failures exhausted the crash-loop budget "
                    f"({self.config.crash_loop_budget})"
                )
                log.error("%s", self.fatal)
                self._stop.set()
                self._observe_metrics()
                return None
            backoff = min(
                self.config.crash_backoff_s * (2 ** (n - 1)),
                self.config.crash_backoff_max_s,
            )
            self._observe_metrics()
            # Full jitter on [backoff/2, backoff].
            return backoff * (0.5 + 0.5 * self._backoff_jitter.random())
        self.loop_stats.consecutive_failures = 0
        self._observe_metrics()
        delay = self.config.scheduling_interval
        if hatch_bool("POSEIDON_STREAMING"):
            # The bounded-staleness deadline IS the streaming cadence:
            # cut the next round's admission no later than the staleness
            # bound, even when the configured interval is longer.
            delay = min(
                delay, hatch_float("POSEIDON_ADMISSION_STALENESS_S")
            )
        return delay

    def _observe_metrics(self) -> None:
        """Refresh the Prometheus registry from the loop's state (every
        round outcome, success or failure — the exporter thread only
        reads)."""
        # Sustained throughput over the window since the last
        # observation.  In streaming mode placed is bumped by the enact
        # worker concurrently — a torn read here skews one gauge sample,
        # never the stats themselves.
        now = time.monotonic()
        placed = self.loop_stats.placed
        if self._pps_t is not None and now > self._pps_t:
            self.placements_per_sec = (
                (placed - self._pps_placed) / (now - self._pps_t)
            )
        self._pps_t = now
        self._pps_placed = placed
        ages = [
            a for a in (
                self.pod_watcher.queue.oldest_age_s(),
                self.node_watcher.queue.oldest_age_s(),
            ) if a is not None
        ]
        obs_metrics.observe_loop(
            self.loop_stats,
            resyncs=(
                self.pod_watcher.resyncs + self.node_watcher.resyncs
            ),
            crash_loop_budget=self.config.crash_loop_budget,
            fatal=self.fatal is not None,
            placements_per_sec=self.placements_per_sec,
            ingest_lag_s=max(ages) if ages else 0.0,
        )
        obs_metrics.observe_ledger()

    def schedule_once(self) -> List[fpb.SchedulingDelta]:
        """One Schedule() call + transactional delta enactment
        (poseidon.go:32-67).

        Enactment is per-delta transactional: a PLACE whose bind the API
        server rejects is ROLLED BACK on the scheduler (task_removed +
        task_submitted requeues the pod as runnable, freeing the
        reservation) instead of leaving the scheduler's view diverged
        from the kube truth, and the remaining deltas still enact.
        Unknown ids stay fatal (poseidon.go:43) — they mean the id maps
        themselves are broken, which no retry fixes.

        POSEIDON_STREAMING=1 switches to the streaming round engine:
        this round's Schedule() RPC overlaps the PREVIOUS round's
        enactment (running on a single-worker executor), and the new
        round's enactment is handed to that worker in turn.  With the
        hatch off (default) the synchronous path below runs — schedule,
        enact, reconcile, GC, in program order on the round thread,
        bit-identical to the pre-streaming loop."""
        if hatch_bool("POSEIDON_STREAMING"):
            return self._schedule_once_streaming()
        # Round-thread confinement: only the thread driving try_round
        # (the loop thread, or the soak's main thread with
        # run_loop=False) writes last_deltas/_enacted; readers consume
        # AFTER the round returns on that same thread (chaos/soak.py
        # records last_deltas post-try_round), so these publications
        # carry their happens-before in program order.
        self.last_deltas = []  # handoff: round-thread-confined (above)
        with obs_trace.span("glue.flush_resubmits"):
            self._flush_resubmits()
        try:
            with obs_trace.span("glue.schedule_rpc"):
                deltas = self.fc.schedule()
        except Exception as e:
            # Commit-ambiguity is code-aware: UNAVAILABLE means the
            # request was never processed (and the client already
            # retries it), so nothing committed; every other failure —
            # DEADLINE after commit, a codeless channel error, a
            # non-RPC exception — may have run the round and lost the
            # reply.  Mark the window; the next fully-enacted round
            # reconciles (see below).
            if rpc_code(e) != grpc.StatusCode.UNAVAILABLE:
                self._mark_suspect()
            raise
        # Recorded before enactment so a round that fails mid-enactment
        # still attributes THESE deltas (not a previous round's) to
        # itself in the flight trace.
        self.last_deltas = list(deltas)  # handoff: round-thread-confined
        if getattr(self.fc, "schedule_retried", False):
            # The client absorbed an UNAVAILABLE with a retry.  On a
            # real network that code can surface AFTER the service
            # processed the request (reply lost mid-stream), making the
            # retry's reply the diff against an already-committed round
            # — so a retried schedule is commit-ambiguous too.  The
            # sweep is cheap next to a permanent phantom divergence.
            self._mark_suspect()
        suspect = self._schedule_suspect
        gen = self._suspect_gen
        self._enact_phase(deltas, suspect, gen)
        return list(deltas)

    def _schedule_once_streaming(self) -> List[fpb.SchedulingDelta]:
        """The streaming round: overlap this round's Schedule() RPC with
        the previous round's enactment, then hand this round's deltas to
        the enact worker.

        Round order: (1) flush parked resubmits (lock-disciplined — the
        worker may be adding to the map concurrently); (2) Schedule()
        RPC, overlapping enact(N-1) on the worker; (3) JOIN enact(N-1) —
        its failure is surfaced as THIS round's failure, and this
        round's already-committed deltas are dropped un-enacted, so the
        suspect reconciler is armed exactly as for a lost reply;
        (4) submit enact(N) to the worker with the suspect snapshot.
        The worker clears suspicion only if no NEW suspicion arrived
        while it ran (the generation check in _enact_phase)."""
        self.last_deltas = []  # handoff: round-thread-confined — the
        # enact worker receives its deltas by argument, never through
        # this attribute; readers (spans, soak, tests) run on or after
        # the round thread (same discipline as the synchronous path).
        with obs_trace.span("glue.flush_resubmits"):
            self._flush_resubmits()
        try:
            with obs_trace.span("glue.schedule_rpc"):
                deltas = self.fc.schedule()
        except Exception as e:
            if rpc_code(e) != grpc.StatusCode.UNAVAILABLE:
                self._mark_suspect()
            # The in-flight enactment keeps running through the failure
            # backoff; the NEXT round (or drain/stop) joins it.
            raise
        self.last_deltas = list(deltas)  # handoff: round-thread-confined
        if getattr(self.fc, "schedule_retried", False):
            self._mark_suspect()
        try:
            self._join_enact()
        except Exception:
            # enact(N-1) failed AND this round's committed deltas are
            # now dropped un-enacted — both are phantom-placement
            # shapes; arm the reconciler before surfacing.
            self._mark_suspect()
            raise
        suspect = self._schedule_suspect
        gen = self._suspect_gen
        if self._enact_pool is None:
            self._enact_pool = cf.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="enact-worker"
            )
        self._enact_future = self._enact_pool.submit(
            self._enact_phase, deltas, suspect, gen
        )
        return list(deltas)

    def _enact_phase(self, deltas, suspect: bool, gen: int) -> None:
        """The round's enactment tail: enact, reconcile if the round
        opened suspect, GC the enacted map, conditionally clear
        suspicion, count the round.  Runs on the round thread
        synchronously; on the single enact worker under streaming (at
        most one in flight — the next round joins before submitting)."""
        delta_uids = set()
        try:
            with obs_trace.span("glue.enact", deltas=len(deltas)):
                self._enact(deltas, delta_uids)
        except Exception:
            # A mid-enactment abort orphans this round's remaining
            # committed deltas — the same phantom shape as a lost
            # reply.  Arm the reconciler; the next fully-enacted round
            # requeues whatever never got bound.
            self._mark_suspect()
            raise
        if suspect:
            with obs_trace.span("glue.reconcile"):
                self._reconcile_after_failure(delta_uids)
        # Lifecycle GC: placements whose tasks finished or left the
        # cluster (the pod watcher owns those transitions) must leave
        # the enacted map, or it grows one entry per pod ever placed.
        live = self.shared.live_uids()
        self._enacted = {  # handoff: enact-phase-confined
            uid: node for uid, node in self._enacted.items() if uid in live
        }
        # Cleared only here, after enactment AND reconcile completed —
        # and only if no NEW suspicion arrived while this phase ran (a
        # concurrent Schedule() failure under streaming): a round that
        # raises mid-way keeps the flag, so the pending reconcile is
        # retried instead of silently dropped.
        with self._state_lock:
            if self._suspect_gen == gen:
                self._schedule_suspect = False
        self.loop_stats.rounds += 1

    def _mark_suspect(self) -> None:
        """Open (or re-open) the commit-ambiguity window; the bumped
        generation keeps a concurrent enact phase from clearing it."""
        with self._state_lock:
            self._schedule_suspect = True
            self._suspect_gen += 1

    def _join_enact(self) -> None:
        """Consume the in-flight enactment's outcome (streaming); no-op
        when nothing is in flight (synchronous mode always)."""
        fut = self._enact_future
        if fut is None:
            return
        self._enact_future = None
        with obs_trace.span("glue.enact_join"):
            fut.result()

    def drain_rounds(self, timeout: float = 30.0) -> bool:
        """Wait for the in-flight enactment WITHOUT consuming its
        outcome — the next round's join still surfaces a failure to the
        loop's failure policy.  The soak harness calls this after every
        try_round so its per-round kube-truth gates see a quiesced
        engine; a no-op in synchronous mode."""
        fut = self._enact_future
        if fut is None:
            return True
        done, _ = cf.wait([fut], timeout=timeout)
        return bool(done)

    def enact_failed(self) -> bool:
        """True when a drained-but-unconsumed streaming enactment
        failed; that failure surfaces at the next round's join.  Lets
        round-by-round drivers (the soak) keep retrying until a round
        both scheduled AND enacted cleanly."""
        fut = self._enact_future
        return bool(
            fut is not None and fut.done()
            and fut.exception() is not None
        )

    def _enact(self, deltas, delta_uids: set) -> None:
        """Apply one round's deltas to the cluster (transactional per
        delta; see ``schedule_once``)."""
        for delta in deltas:
            delta_uids.add(delta.task_id)
            if delta.type == fpb.SchedulingDelta.PLACE:
                pod = self.shared.task_for_uid(delta.task_id)
                node = self.shared.node_for_resource(delta.resource_id)
                if pod is None or node is None:
                    raise RuntimeError(
                        f"PLACE delta references unknown ids: {delta}"
                    )
                try:
                    self.kube.bind_pod(pod.namespace, pod.name, node)
                except Exception as e:  # noqa: BLE001 - per-delta rollback
                    log.warning(
                        "PLACE %s -> %s failed (%s); rolling back and "
                        "requeueing", pod.key, node, e,
                    )
                    self.loop_stats.bind_failures += 1
                    self._requeue_task(delta.task_id)
                    continue
                self._enacted[delta.task_id] = node
                self.loop_stats.placed += 1
            elif delta.type in (
                fpb.SchedulingDelta.PREEMPT,
                fpb.SchedulingDelta.MIGRATE,
            ):
                pod = self.shared.task_for_uid(delta.task_id)
                if pod is None:
                    raise RuntimeError(
                        f"PREEMPT/MIGRATE delta references unknown task: {delta}"
                    )
                try:
                    self.kube.delete_pod(pod.namespace, pod.name)
                except KeyError:
                    # Already gone (deleted out from under us): the
                    # watcher's DELETED event hands TaskRemoved to the
                    # scheduler; the enactment's intent already holds.
                    log.warning(
                        "PREEMPT/MIGRATE delete of %s: pod already gone",
                        pod.key,
                    )
                self._enacted.pop(delta.task_id, None)
                if delta.type == fpb.SchedulingDelta.PREEMPT:
                    self.loop_stats.preempted += 1
                else:
                    self.loop_stats.migrated += 1
            # NOOP: skip (poseidon.go:64).

    # ------------------------------------------------- divergence containment

    def _requeue_task(self, uid: int) -> None:
        """Roll one placement back on the scheduler: remove + resubmit
        re-enters the task RUNNABLE with its reservation freed, so the
        scheduler's view returns to the kube truth (pod Pending) and the
        next round re-places it.  Uses only the existing RPC vocabulary —
        the state machine answers TASK_SUBMITTED_OK because the removal
        landed first."""
        entry = self.shared.get_task(uid)
        if entry is None:
            return
        td = fpb.TaskDescriptor()
        td.CopyFrom(entry.descriptor)
        td.scheduled_to_resource = ""  # requeue as unbound
        jd = fpb.JobDescriptor(
            uuid=td.job_id, name=entry.pod.owner_uid or entry.pod.key
        )
        self.fc.task_removed(uid)
        self._enacted.pop(uid, None)
        try:
            self.fc.task_submitted(td, jd)
        except Exception:
            # Half rolled back: removed server-side, resubmit lost.
            # Left alone the task would exist NOWHERE and the pod would
            # pend forever — park the descriptor; _flush_resubmits
            # replays it at the top of every round until it lands.  The
            # raise fails this round, so the crash-loop budget governs
            # the retry cadence.  Lock: under streaming this runs on the
            # enact worker while the round thread may be flushing.
            with self._state_lock:
                self._resubmit_pending[uid] = (td, jd)
            raise
        self.loop_stats.requeued += 1

    def _flush_resubmits(self) -> None:
        """Finish half-completed rollbacks (see ``_requeue_task``):
        replay parked resubmits until each lands or its pod left the
        cluster.  TASK_SUBMITTED_OK / ALREADY_SUBMITTED are both
        tolerated replies, so a replay that raced a watcher resubmit is
        harmless.  The map is snapshotted and pruned under the state
        lock (the streaming enact worker parks entries concurrently);
        the RPCs themselves run outside it."""
        with self._state_lock:
            pending = sorted(self._resubmit_pending.items())
        for uid, (td, jd) in pending:
            if self.shared.get_task(uid) is None:
                with self._state_lock:
                    self._resubmit_pending.pop(uid, None)
                continue  # pod left the cluster
            self.fc.task_submitted(td, jd)
            with self._state_lock:
                self._resubmit_pending.pop(uid, None)
            self.loop_stats.requeued += 1

    def _reconcile_after_failure(self, delta_uids) -> None:
        """Heal the commit-ambiguity window after a failed Schedule()
        call (the suspect flag): if that call's round committed on the
        service but its reply was lost, the service holds placements
        whose PLACE deltas no one enacted — the pods sit Pending in kube
        forever while the scheduler believes them running.

        Candidates: tracked, non-finished tasks that (a) glue never
        enacted a placement for, (b) got no delta in THIS round either,
        and (c) did not arrive already-bound (the glue-restart adoption
        path).  Requeueing them (remove + resubmit) is idempotent kube-
        truth re-assertion: a phantom placement is freed and re-placed
        next round; a genuinely pending pod just re-enters the queue.
        Runs only in rounds following a commit-ambiguous Schedule
        failure, until one fully enacts (the suspect flag survives a
        round that raises mid-enactment) — never in steady state, so
        the wait-fairness escalator is undisturbed."""
        healed = 0
        for uid, pod in sorted(self.shared.live_uids().items()):
            if uid in delta_uids or uid in self._enacted:
                continue
            if pod.node_name:
                continue  # adopted pre-bound on restart; not ours to touch
            self._requeue_task(uid)
            healed += 1
        if healed:
            log.warning(
                "post-failure reconcile requeued %d possibly-phantom "
                "placements", healed,
            )

    # -------------------------------------------------------------- test hooks

    def drain_watchers(self, timeout: float = 5.0) -> bool:
        """Wait until both work queues are empty (integration-test barrier)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.pod_watcher.queue) == 0 and \
               len(self.node_watcher.queue) == 0:
                # One extra beat for in-flight worker batches.
                time.sleep(0.05)
                if len(self.pod_watcher.queue) == 0 and \
                   len(self.node_watcher.queue) == 0:
                    return True
            time.sleep(0.01)
        return False
