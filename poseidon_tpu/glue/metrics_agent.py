"""Metrics agent: the TPU-native stand-in for the forked Heapster sink.

The reference wires cluster telemetry as Heapster -> Poseidon's stats
server -> Firmament's knowledge base (reference
deploy/heapster-poseidon.yaml:46-50 pointing --sink=poseidon at the
stats port; pkg/stats/stats.go:77-159 forwards).  Heapster is long dead
upstream; the equivalent here is a small agent process that polls a
usage source and streams ``NodeStats``/``PodStats`` over the same bidi
gRPC surface the stats server already serves
(poseidon_tpu/glue/stats_server.py), closing the knowledge-base loop.

Sources are pluggable: ``metrics_api_source`` reads the metrics.k8s.io
API (metrics-server, the modern Heapster replacement; gated on the
``kubernetes`` package), and tests inject synthetic callables.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Iterable, List, Optional, Tuple

import grpc

from poseidon_tpu.protos import stats_pb2 as spb
from poseidon_tpu.protos.services import STATS_METHODS, STATS_SERVICE, make_stubs

log = logging.getLogger("poseidon.metrics_agent")

# A source returns one sample batch per call.
Sample = Tuple[List[spb.NodeStats], List[spb.PodStats]]
Source = Callable[[], Sample]


def metrics_api_source(kubeconfig: str = "") -> Source:
    """Usage from the metrics.k8s.io API (metrics-server).

    Units follow the stats server's conventions: CPU in millicores,
    memory in KB (stats_server.py conversion into ResourceStats /
    TaskStats).
    """
    from kubernetes import client as k8s_client
    from kubernetes import config as k8s_config

    from poseidon_tpu.glue.kube_convert import parse_cpu, parse_mem_kb

    if kubeconfig:
        k8s_config.load_kube_config(config_file=kubeconfig)
    else:
        try:
            k8s_config.load_incluster_config()
        except Exception:
            k8s_config.load_kube_config()
    api = k8s_client.CustomObjectsApi()
    core = k8s_client.CoreV1Api()

    def poll() -> Sample:
        now = int(time.time())
        nodes: List[spb.NodeStats] = []
        pods: List[spb.PodStats] = []
        caps = {}
        for n in core.list_node().items:
            cap = n.status.capacity or {}
            caps[n.metadata.name] = (
                parse_cpu(cap.get("cpu", "")),
                parse_mem_kb(cap.get("memory", "")),
            )
        node_metrics = api.list_cluster_custom_object(
            "metrics.k8s.io", "v1beta1", "nodes"
        )
        for item in node_metrics.get("items", []):
            name = item["metadata"]["name"]
            usage = item.get("usage", {})
            cpu_m = parse_cpu(usage.get("cpu", "0"))
            mem_kb = parse_mem_kb(usage.get("memory", "0"))
            cap_cpu, cap_mem = caps.get(name, (0, 0))
            nodes.append(
                spb.NodeStats(
                    hostname=name,
                    timestamp=now,
                    cpu_capacity=cap_cpu,
                    cpu_allocatable=max(cap_cpu - cpu_m, 0),
                    cpu_utilization=(cpu_m / cap_cpu) if cap_cpu else 0.0,
                    mem_capacity=cap_mem,
                    mem_allocatable=max(cap_mem - mem_kb, 0),
                    mem_utilization=(mem_kb / cap_mem) if cap_mem else 0.0,
                )
            )
        pod_metrics = api.list_cluster_custom_object(
            "metrics.k8s.io", "v1beta1", "pods"
        )
        for item in pod_metrics.get("items", []):
            meta = item["metadata"]
            cpu_m = 0
            mem_kb = 0
            for c in item.get("containers", []):
                usage = c.get("usage", {})
                cpu_m += parse_cpu(usage.get("cpu", "0"))
                mem_kb += parse_mem_kb(usage.get("memory", "0"))
            pods.append(
                spb.PodStats(
                    name=meta["name"],
                    namespace=meta.get("namespace", "default"),
                    cpu_usage=cpu_m,
                    mem_usage=mem_kb,
                )
            )
        return nodes, pods

    return poll


class MetricsAgent:
    """Polls a source on an interval and streams batches to the stats
    server, logging NOT_FOUND answers (unknown pods/nodes) at debug."""

    def __init__(
        self,
        source: Source,
        stats_address: str,
        interval: float = 10.0,
    ) -> None:
        self.source = source
        self.interval = interval
        self._channel = grpc.insecure_channel(stats_address)
        self._stubs = make_stubs(
            self._channel, STATS_SERVICE, STATS_METHODS
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # One-shot push, usable directly (tests, cron-style invocations).
    def push_once(self) -> Tuple[int, int]:
        nodes, pods = self.source()
        n_ok = p_ok = 0
        if nodes:
            for reply in self._stubs.ReceiveNodeStats(iter(nodes)):
                if reply.type == spb.NODE_STATS_OK:
                    n_ok += 1
                else:
                    log.debug("node stats dropped: %s", reply.hostname)
        if pods:
            for reply in self._stubs.ReceivePodStats(iter(pods)):
                if reply.type == spb.POD_STATS_OK:
                    p_ok += 1
                else:
                    log.debug(
                        "pod stats dropped: %s/%s",
                        reply.namespace, reply.name,
                    )
        return n_ok, p_ok

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.push_once()
            except Exception as e:  # noqa: BLE001 - the poll loop must
                # survive transient API/stream failures (metrics-server
                # rollouts, channel resets) and retry next interval.
                log.warning("stats push failed: %s", e)
            self._stop.wait(self.interval)

    def start(self) -> "MetricsAgent":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._channel.close()


def main(argv: Optional[Iterable[str]] = None) -> int:
    import argparse

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s] %(message)s",
    )
    p = argparse.ArgumentParser(prog="poseidon-metrics-agent")
    p.add_argument("--stats-address", default="poseidon-stats.kube-system:9091")
    p.add_argument("--kube-config", default="")
    p.add_argument("--interval", type=float, default=10.0)
    args = p.parse_args(list(argv) if argv is not None else None)

    agent = MetricsAgent(
        metrics_api_source(args.kube_config),
        args.stats_address,
        interval=args.interval,
    )
    try:
        agent.run()
    except KeyboardInterrupt:
        agent.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
