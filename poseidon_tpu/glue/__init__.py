"""Poseidon glue: the K8s-integration half of the framework.

Re-creates the reference's Go client process (reference pkg/k8sclient/,
pkg/stats/, cmd/poseidon/) as a Python package: watchers translate pod/node
lifecycle events into FirmamentScheduler RPCs, a keyed queue serializes
per-object event processing, a stats server ingests Heapster-style metrics,
and the schedule loop enacts SchedulingDeltas as bind/delete calls.

Cluster access goes through the ``KubeAPI`` interface; ``FakeKube`` is the
in-process fake cluster used by the test/benchmark harness (the reference
only has a cluster-backed e2e tier — SURVEY.md section 4 flags the missing
in-process tier as a gap to fill).
"""

from poseidon_tpu.glue.keyed_queue import KeyedQueue
from poseidon_tpu.glue.fake_kube import FakeKube, Pod, Node
from poseidon_tpu.glue.podwatcher import PodWatcher
from poseidon_tpu.glue.nodewatcher import NodeWatcher
from poseidon_tpu.glue.poseidon import Poseidon

__all__ = [
    "KeyedQueue",
    "FakeKube",
    "Pod",
    "Node",
    "PodWatcher",
    "NodeWatcher",
    "Poseidon",
]
