"""Real-cluster KubeAPI adapter (gated on the ``kubernetes`` client).

Maps the watcher seam (poseidon_tpu.glue.fake_kube.KubeAPI) onto the
official Kubernetes Python client the way the reference maps it onto
client-go: list+watch informers for pods/nodes (reference
pkg/k8sclient/podwatcher.go:81-129, nodewatcher.go:47-81), the
pods/binding subresource for actuation (k8sclient.go:33-46), and pod
deletion for preemption (k8sclient.go:49-54).

The ``kubernetes`` package is not part of the baked image; importing this
module without it raises ImportError with a clear message, and everything
else in the framework (service, glue against FakeKube, replay, bench)
works without it.
"""

from __future__ import annotations

import queue
import threading
from typing import List

try:
    from kubernetes import client as k8s_client
    from kubernetes import config as k8s_config
    from kubernetes import watch as k8s_watch
except ImportError as _exc:  # pragma: no cover - gated dependency
    raise ImportError(
        "poseidon_tpu.glue.kube_client requires the `kubernetes` package "
        "(pip install kubernetes); in-process use goes through FakeKube"
    ) from _exc

from poseidon_tpu.glue.fake_kube import Event, KubeAPI, Node, Pod
from poseidon_tpu.glue.kube_convert import node_from_v1 as _node_from_v1
from poseidon_tpu.glue.kube_convert import pod_from_v1 as _pod_from_v1


class RealKube(KubeAPI):
    """KubeAPI over the official client; in- or out-of-cluster config
    (k8sclient.go:57-62)."""

    def __init__(self, kubeconfig: str = "") -> None:
        if kubeconfig:
            k8s_config.load_kube_config(config_file=kubeconfig)
        else:
            try:
                k8s_config.load_incluster_config()
            except Exception:
                k8s_config.load_kube_config()
        self._core = k8s_client.CoreV1Api()
        self._stop = threading.Event()

    def list_pods(self) -> List[Pod]:
        out = self._core.list_pod_for_all_namespaces()
        return [_pod_from_v1(p) for p in out.items]

    def list_nodes(self) -> List[Node]:
        out = self._core.list_node()
        return [_node_from_v1(n) for n in out.items]

    def _watch_loop(self, q, list_fn, convert) -> None:
        w = k8s_watch.Watch()
        while not self._stop.is_set():
            try:
                for ev in w.stream(list_fn, timeout_seconds=30):
                    q.put((ev["type"], convert(ev["object"])))
                    if self._stop.is_set():
                        return
            except Exception:
                continue  # resync on watch errors, as informers do

    def watch_pods(self) -> "queue.Queue[Event]":
        q: "queue.Queue[Event]" = queue.Queue()
        threading.Thread(
            target=self._watch_loop,
            args=(q, self._core.list_pod_for_all_namespaces, _pod_from_v1),
            daemon=True,
        ).start()
        return q

    def watch_nodes(self) -> "queue.Queue[Event]":
        q: "queue.Queue[Event]" = queue.Queue()
        threading.Thread(
            target=self._watch_loop,
            args=(q, self._core.list_node, _node_from_v1),
            daemon=True,
        ).start()
        return q

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        # POST pods/{name}/binding (k8sclient.go:33-46).
        body = k8s_client.V1Binding(
            metadata=k8s_client.V1ObjectMeta(name=name, namespace=namespace),
            target=k8s_client.V1ObjectReference(
                api_version="v1", kind="Node", name=node_name
            ),
        )
        self._core.create_namespaced_pod_binding(
            name=name, namespace=namespace, body=body, _preload_content=False
        )

    def delete_pod(self, namespace: str, name: str) -> None:
        self._core.delete_namespaced_pod(name=name, namespace=namespace)

    def stop(self) -> None:
        self._stop.set()
