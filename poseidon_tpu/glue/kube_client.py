"""Real-cluster KubeAPI adapter (gated on the ``kubernetes`` client).

Maps the watcher seam (poseidon_tpu.glue.fake_kube.KubeAPI) onto the
official Kubernetes Python client the way the reference maps it onto
client-go: list+watch informers for pods/nodes (reference
pkg/k8sclient/podwatcher.go:81-129, nodewatcher.go:47-81), the
pods/binding subresource for actuation (k8sclient.go:33-46), and pod
deletion for preemption (k8sclient.go:49-54).

The ``kubernetes`` package is not part of the baked image; importing this
module without it raises ImportError with a clear message, and everything
else in the framework (service, glue against FakeKube, replay, bench)
works without it.
"""

from __future__ import annotations

import queue
import threading
from typing import List

try:
    from kubernetes import client as k8s_client
    from kubernetes import config as k8s_config
    from kubernetes import watch as k8s_watch
except ImportError as _exc:  # pragma: no cover - gated dependency
    raise ImportError(
        "poseidon_tpu.glue.kube_client requires the `kubernetes` package "
        "(pip install kubernetes); in-process use goes through FakeKube"
    ) from _exc

from poseidon_tpu.glue.fake_kube import Event, KubeAPI, Node, Pod

_CPU_MULT = {"m": 1, "": 1000}


def _parse_cpu(q: str) -> int:
    """K8s CPU quantity -> millicores (podwatcher.go:135-147 semantics)."""
    if not q:
        return 0
    if q.endswith("m"):
        return int(q[:-1])
    return int(float(q) * 1000)


_MEM_SUFFIX = {
    "Ki": 1, "Mi": 1 << 10, "Gi": 1 << 20, "Ti": 1 << 30,
    "K": 1, "M": 10 ** 3, "G": 10 ** 6, "T": 10 ** 9,
}


def _parse_mem_kb(q: str) -> int:
    """K8s memory quantity -> KB (the node watcher's unit)."""
    if not q:
        return 0
    for suf, mult in _MEM_SUFFIX.items():
        if q.endswith(suf):
            return int(float(q[: -len(suf)]) * mult)
    return int(q) >> 10  # plain bytes


def _pod_from_v1(p) -> Pod:
    cpu = ram = 0
    for c in p.spec.containers or []:
        req = (c.resources and c.resources.requests) or {}
        cpu += _parse_cpu(req.get("cpu", ""))
        ram += _parse_mem_kb(req.get("memory", ""))
    owner = ""
    if p.metadata.owner_references:
        owner = p.metadata.owner_references[0].uid
    affinity = {}
    anti = {}
    aff = p.spec.affinity
    if aff and aff.pod_affinity:
        for term in (
            aff.pod_affinity
            .required_during_scheduling_ignored_during_execution or []
        ):
            if term.label_selector and term.label_selector.match_labels:
                affinity.update(term.label_selector.match_labels)
    if aff and aff.pod_anti_affinity:
        for term in (
            aff.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution or []
        ):
            if term.label_selector and term.label_selector.match_labels:
                anti.update(term.label_selector.match_labels)
    return Pod(
        name=p.metadata.name,
        namespace=p.metadata.namespace,
        owner_uid=owner,
        scheduler_name=p.spec.scheduler_name or "",
        phase=p.status.phase or "Unknown",
        node_name=p.spec.node_name or "",
        cpu_request=cpu,
        ram_request=ram,
        labels=dict(p.metadata.labels or {}),
        node_selector=dict(p.spec.node_selector or {}),
        pod_affinity=affinity,
        pod_anti_affinity=anti,
        deleted=p.metadata.deletion_timestamp is not None,
    )


def _node_from_v1(n) -> Node:
    cap = n.status.capacity or {}
    ready = True
    out_of_disk = False
    for cond in n.status.conditions or []:
        if cond.type == "Ready":
            ready = cond.status == "True"
        if cond.type == "OutOfDisk":
            out_of_disk = cond.status == "True"
    return Node(
        name=n.metadata.name,
        cpu_capacity=_parse_cpu(cap.get("cpu", "")),
        ram_capacity=_parse_mem_kb(cap.get("memory", "")),
        unschedulable=bool(n.spec.unschedulable),
        ready=ready,
        out_of_disk=out_of_disk,
        labels=dict(n.metadata.labels or {}),
    )


class RealKube(KubeAPI):
    """KubeAPI over the official client; in- or out-of-cluster config
    (k8sclient.go:57-62)."""

    def __init__(self, kubeconfig: str = "") -> None:
        if kubeconfig:
            k8s_config.load_kube_config(config_file=kubeconfig)
        else:
            try:
                k8s_config.load_incluster_config()
            except Exception:
                k8s_config.load_kube_config()
        self._core = k8s_client.CoreV1Api()
        self._stop = threading.Event()

    def list_pods(self) -> List[Pod]:
        out = self._core.list_pod_for_all_namespaces()
        return [_pod_from_v1(p) for p in out.items]

    def list_nodes(self) -> List[Node]:
        out = self._core.list_node()
        return [_node_from_v1(n) for n in out.items]

    def _watch_loop(self, q, list_fn, convert) -> None:
        w = k8s_watch.Watch()
        while not self._stop.is_set():
            try:
                for ev in w.stream(list_fn, timeout_seconds=30):
                    q.put((ev["type"], convert(ev["object"])))
                    if self._stop.is_set():
                        return
            except Exception:
                continue  # resync on watch errors, as informers do

    def watch_pods(self) -> "queue.Queue[Event]":
        q: "queue.Queue[Event]" = queue.Queue()
        threading.Thread(
            target=self._watch_loop,
            args=(q, self._core.list_pod_for_all_namespaces, _pod_from_v1),
            daemon=True,
        ).start()
        return q

    def watch_nodes(self) -> "queue.Queue[Event]":
        q: "queue.Queue[Event]" = queue.Queue()
        threading.Thread(
            target=self._watch_loop,
            args=(q, self._core.list_node, _node_from_v1),
            daemon=True,
        ).start()
        return q

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        # POST pods/{name}/binding (k8sclient.go:33-46).
        body = k8s_client.V1Binding(
            metadata=k8s_client.V1ObjectMeta(name=name, namespace=namespace),
            target=k8s_client.V1ObjectReference(
                api_version="v1", kind="Node", name=node_name
            ),
        )
        self._core.create_namespaced_pod_binding(
            name=name, namespace=namespace, body=body, _preload_content=False
        )

    def delete_pod(self, namespace: str, name: str) -> None:
        self._core.delete_namespaced_pod(name=name, namespace=namespace)

    def stop(self) -> None:
        self._stop.set()
