"""Poseidon glue process entry point.

The analog of the reference's ``cmd/poseidon/poseidon.go:90-103`` main:
parse config, connect to the scheduler service, gate on its health check,
then run the watcher/stats/schedule-loop families until signalled.

Runs against a real cluster when the ``kubernetes`` client package is
available (``--kube-config`` / in-cluster); ``--demo`` runs the in-process
fake cluster with a small synthetic workload instead (no dependencies),
which is also the integration-smoke path.
"""

from __future__ import annotations

import logging
import signal
import sys
import threading

from poseidon_tpu.utils.config import PoseidonConfig, load_config

log = logging.getLogger("poseidon.main")


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s] %(message)s",
    )
    demo = False
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--demo" in argv:
        demo = True
        argv.remove("--demo")
    cfg = load_config(PoseidonConfig, argv=argv)

    if demo:
        from poseidon_tpu.glue.fake_kube import FakeKube, Node, Pod

        kube = FakeKube()
        for i in range(4):
            kube.add_node(
                Node(name=f"demo-n{i}", cpu_capacity=8000,
                     ram_capacity=16 << 20)
            )
        for i in range(12):
            kube.create_pod(
                Pod(name=f"demo-p{i}", cpu_request=250,
                    ram_request=1 << 19)
            )
    else:
        from poseidon_tpu.glue.kube_client import RealKube

        kube = RealKube(kubeconfig=cfg.kube_config)

    from poseidon_tpu.glue.poseidon import Poseidon

    poseidon = Poseidon(
        kube, config=cfg, stats_address=cfg.stats_server_address
    )
    poseidon.start()
    log.info(
        "poseidon running: firmament=%s stats=%s interval=%.1fs",
        cfg.firmament_address, cfg.stats_server_address,
        cfg.scheduling_interval,
    )

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    poseidon.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
