"""Per-key serializing work queue.

Semantics match the reference's keyed queue (pkg/k8sclient/keyed_queue.go:24-135):

- ``add(key, item)`` enqueues work for a key.  Multiple items for the same
  key coalesce in arrival order.
- ``get()`` blocks for the next (key, items) batch, marking the key as
  *processing*; further adds for that key park in a side queue.
- ``done(key)`` releases the key; parked items (if any) re-enter the main
  queue.  This guarantees ordered, non-concurrent processing per pod/node
  while allowing many workers.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Any, Hashable, List, Optional, Tuple

from poseidon_tpu.utils.locks import tracked_condition


class KeyedQueue:
    def __init__(self) -> None:
        self._cond = tracked_condition("glue.KeyedQueue._cond")
        self._queue: "OrderedDict[Hashable, List[Any]]" = OrderedDict()
        self._parked: "OrderedDict[Hashable, List[Any]]" = OrderedDict()
        self._processing: set = set()
        self._shutdown = False
        # First-enqueue timestamp per queued key (monotonic), kept in
        # the same arrival order as _queue: the head entry is the
        # oldest undelivered event, whose age is the glue-side ingest
        # lag (oldest_age_s) the streaming engine's staleness bound is
        # judged against.
        self._enqueued_at: "OrderedDict[Hashable, float]" = OrderedDict()

    def add(self, key: Hashable, item: Any) -> None:
        with self._cond:
            if self._shutdown:
                return
            if key in self._processing:
                self._parked.setdefault(key, []).append(item)
            else:
                self._queue.setdefault(key, []).append(item)
                self._enqueued_at.setdefault(key, time.monotonic())
                self._cond.notify()

    def get(self) -> Optional[Tuple[Hashable, List[Any]]]:
        """Next batch; None after shutdown drains."""
        with self._cond:
            while not self._queue and not self._shutdown:
                self._cond.wait()
            if not self._queue:
                return None
            key, items = self._queue.popitem(last=False)
            self._enqueued_at.pop(key, None)
            self._processing.add(key)
            return key, items

    def oldest_age_s(self) -> Optional[float]:
        """Age of the oldest QUEUED (undelivered) batch, or None when
        nothing waits.  A worker mid-batch does not count — delivery
        latency, not processing latency, is the ingest-lag signal."""
        with self._cond:
            for ts in self._enqueued_at.values():
                return time.monotonic() - ts
            return None

    def done(self, key: Hashable) -> None:
        with self._cond:
            self._processing.discard(key)
            parked = self._parked.pop(key, None)
            if parked:
                self._queue.setdefault(key, []).extend(parked)
                # Unparked work re-enters the queue NOW; its wait while
                # parked was serialization, not delivery lag.
                self._enqueued_at.setdefault(key, time.monotonic())
                self._cond.notify()

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        """Outstanding work: queued + parked items, plus keys whose batch a
        worker is still processing (popped but not yet ``done()``) — so a
        zero length really means the queue has drained."""
        with self._cond:
            return (
                sum(len(v) for v in self._queue.values())
                + sum(len(v) for v in self._parked.values())
                + len(self._processing)
            )
