"""K8s API object -> watcher-seam conversion (no client dependency).

The quantity parsing and V1Pod/V1Node mapping the real-cluster adapter
(poseidon_tpu.glue.kube_client) applies, split out so it is importable —
and unit-testable — without the ``kubernetes`` package.  The functions
are duck-typed over the official client's models (attribute access only),
exactly the surface the reference unit-tests against fake clientset
objects (reference pkg/k8sclient/nodewatcher_test.go:120-216).
"""

from __future__ import annotations

from poseidon_tpu.glue.fake_kube import Node, Pod


def parse_cpu(q: str) -> int:
    """K8s CPU quantity -> millicores (podwatcher.go:135-147 semantics).

    Also accepts the nanocore/microcore forms metrics.k8s.io serializes
    usage in (e.g. ``231584746n``) — requests use ``m``/plain cores, but
    the metrics agent feeds usage through the same parser.
    """
    if not q:
        return 0
    if q.endswith("n"):
        return int(int(q[:-1]) / 1_000_000)
    if q.endswith("u"):
        return int(int(q[:-1]) / 1_000)
    if q.endswith("m"):
        return int(q[:-1])
    return int(float(q) * 1000)


_MEM_SUFFIX = {
    "Ki": 1, "Mi": 1 << 10, "Gi": 1 << 20, "Ti": 1 << 30,
    "K": 1, "M": 10 ** 3, "G": 10 ** 6, "T": 10 ** 9,
}


def parse_mem_kb(q: str) -> int:
    """K8s memory quantity -> KB (the node watcher's unit)."""
    if not q:
        return 0
    for suf, mult in _MEM_SUFFIX.items():
        if q.endswith(suf):
            return int(float(q[: -len(suf)]) * mult)
    return int(q) >> 10  # plain bytes


def pod_from_v1(p) -> Pod:
    """V1Pod -> watcher-seam Pod (podwatcher.go:135-175 parsing)."""
    cpu = ram = 0
    for c in p.spec.containers or []:
        req = (c.resources and c.resources.requests) or {}
        cpu += parse_cpu(req.get("cpu", ""))
        ram += parse_mem_kb(req.get("memory", ""))
    owner = ""
    if p.metadata.owner_references:
        owner = p.metadata.owner_references[0].uid
    affinity = {}
    anti = {}
    aff = p.spec.affinity
    if aff and aff.pod_affinity:
        for term in (
            aff.pod_affinity
            .required_during_scheduling_ignored_during_execution or []
        ):
            if term.label_selector and term.label_selector.match_labels:
                affinity.update(term.label_selector.match_labels)
    if aff and aff.pod_anti_affinity:
        for term in (
            aff.pod_anti_affinity
            .required_during_scheduling_ignored_during_execution or []
        ):
            if term.label_selector and term.label_selector.match_labels:
                anti.update(term.label_selector.match_labels)
    return Pod(
        name=p.metadata.name,
        namespace=p.metadata.namespace,
        owner_uid=owner,
        scheduler_name=p.spec.scheduler_name or "",
        phase=p.status.phase or "Unknown",
        node_name=p.spec.node_name or "",
        cpu_request=cpu,
        ram_request=ram,
        labels=dict(p.metadata.labels or {}),
        node_selector=dict(p.spec.node_selector or {}),
        pod_affinity=affinity,
        pod_anti_affinity=anti,
        deleted=p.metadata.deletion_timestamp is not None,
    )


def node_from_v1(n) -> Node:
    """V1Node -> watcher-seam Node: Unschedulable gate + Ready/OutOfDisk
    condition mapping (nodewatcher.go:123-178)."""
    cap = n.status.capacity or {}
    ready = True
    out_of_disk = False
    for cond in n.status.conditions or []:
        if cond.type == "Ready":
            ready = cond.status == "True"
        if cond.type == "OutOfDisk":
            out_of_disk = cond.status == "True"
    return Node(
        name=n.metadata.name,
        cpu_capacity=parse_cpu(cap.get("cpu", "")),
        ram_capacity=parse_mem_kb(cap.get("memory", "")),
        unschedulable=bool(n.spec.unschedulable),
        ready=ready,
        out_of_disk=out_of_disk,
        labels=dict(n.metadata.labels or {}),
    )
