"""Shared glue-process state: the id maps joining watchers, stats, and the
schedule loop.

Mirrors the reference's shared maps + RW mutexes (pkg/k8sclient/types.go:31-48):
PodToTD / TaskIDToPod / NodeToRTND / ResIDToNode, here folded into one
lock-guarded registry with typed accessors.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from poseidon_tpu.glue.fake_kube import Node, Pod
from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.utils.locks import TrackedLock


@dataclass
class TaskEntry:
    pod: Pod
    descriptor: fpb.TaskDescriptor
    # Task reached a terminal phase (Succeeded/Failed reported to the
    # scheduler).  The entry stays until the pod object is DELETED — the
    # uid must remain resolvable for the TaskRemoved hand-off — but stats
    # for finished tasks are dropped (the reference answers NOT_FOUND for
    # pods it no longer tracks, stats.go:132-134).
    finished: bool = False


@dataclass
class NodeEntry:
    node: Node
    rtnd: fpb.ResourceTopologyNodeDescriptor


class SharedState:
    def __init__(self) -> None:
        self._lock = TrackedLock("glue.SharedState._lock", reentrant=True)
        self._tasks: Dict[int, TaskEntry] = {}          # task uid -> entry
        self._pod_to_uid: Dict[str, int] = {}           # pod key -> task uid
        self._nodes: Dict[str, NodeEntry] = {}          # node name -> entry
        self._res_to_node: Dict[str, str] = {}          # resource uuid -> name

    # ------------------------------------------------------------------ tasks

    def put_task(self, uid: int, pod: Pod, td: fpb.TaskDescriptor) -> None:
        with self._lock:
            self._tasks[uid] = TaskEntry(pod=pod, descriptor=td)
            self._pod_to_uid[pod.key] = uid

    def get_task(self, uid: int) -> Optional[TaskEntry]:
        with self._lock:
            return self._tasks.get(uid)

    def pop_task(self, uid: int) -> Optional[TaskEntry]:
        with self._lock:
            entry = self._tasks.pop(uid, None)
            if entry is not None:
                self._pod_to_uid.pop(entry.pod.key, None)
            return entry

    def mark_finished(self, uid: int) -> None:
        with self._lock:
            entry = self._tasks.get(uid)
            if entry is not None:
                entry.finished = True

    def uid_for_pod(self, pod_key: str) -> Optional[int]:
        """Task uid for a live pod; None for unknown or finished pods
        (the stats path — finished tasks answer NOT_FOUND)."""
        with self._lock:
            uid = self._pod_to_uid.get(pod_key)
            if uid is None:
                return None
            entry = self._tasks.get(uid)
            if entry is None or entry.finished:
                return None
            return uid

    def task_for_uid(self, uid: int) -> Optional[Pod]:
        with self._lock:
            entry = self._tasks.get(uid)
            return entry.pod if entry else None

    def pods_snapshot(self) -> Dict[str, Pod]:
        """pod key -> last-seen Pod for every tracked task (the watcher
        resync path diffs this against a fresh list to synthesize the
        DELETED events a dropped watch swallowed)."""
        with self._lock:
            return {
                entry.pod.key: entry.pod for entry in self._tasks.values()
            }

    def live_uids(self) -> Dict[int, Pod]:
        """uid -> Pod for every non-finished tracked task (the
        suspect-reconciler's candidate set after a commit-ambiguous
        Schedule failure)."""
        with self._lock:
            return {
                uid: entry.pod
                for uid, entry in self._tasks.items()
                if not entry.finished
            }

    # ------------------------------------------------------------------ nodes

    def put_node(
        self, node: Node, rtnd: fpb.ResourceTopologyNodeDescriptor
    ) -> None:
        with self._lock:
            self._nodes[node.name] = NodeEntry(node=node, rtnd=rtnd)
            self._register_subtree(node.name, rtnd)

    def _register_subtree(self, name, rtnd) -> None:
        self._res_to_node[rtnd.resource_desc.uuid] = name
        for child in rtnd.children:
            self._register_subtree(name, child)

    def get_node(self, name: str) -> Optional[NodeEntry]:
        with self._lock:
            return self._nodes.get(name)

    def pop_node(self, name: str) -> Optional[NodeEntry]:
        with self._lock:
            entry = self._nodes.pop(name, None)
            if entry is not None:
                dead = [
                    r for r, n in self._res_to_node.items() if n == name
                ]
                for r in dead:
                    del self._res_to_node[r]
            return entry

    def node_for_resource(self, uuid: str) -> Optional[str]:
        with self._lock:
            return self._res_to_node.get(uuid)

    def nodes_snapshot(self) -> Dict[str, Node]:
        """node name -> last-seen Node for every tracked node (the node
        watcher's resync diff, mirroring ``pods_snapshot``)."""
        with self._lock:
            return {
                name: entry.node for name, entry in self._nodes.items()
            }

    def resource_for_node(self, name: str) -> Optional[str]:
        with self._lock:
            entry = self._nodes.get(name)
            return entry.rtnd.resource_desc.uuid if entry else None
