"""In-process fake Kubernetes cluster + the KubeAPI seam.

The reference talks to a real API server via client-go informers and the
pods/binding subresource (pkg/k8sclient/k8sclient.go:33-54, watchers at
podwatcher.go:81-129, nodewatcher.go:47-81).  This module defines the same
seam as a minimal interface — list/watch of pods and nodes, bind, delete —
plus ``FakeKube``, a thread-safe in-process implementation used by the
integration tier and the trace-replay harness (the fake plays the role of
client-go's fake.Clientset, nodewatcher_test.go:45, and of the cluster in
the e2e tier).
"""

from __future__ import annotations

import copy
import itertools
import queue
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Tuple

from poseidon_tpu.utils.locks import TrackedLock


@dataclass
class Pod:
    """The scheduling-relevant slice of a K8s Pod (podwatcher.go:135-175)."""

    name: str
    namespace: str = "default"
    # Owner reference UID: groups pods into jobs (podwatcher.go:425-453).
    owner_uid: str = ""
    scheduler_name: str = "poseidon"
    phase: str = "Pending"   # Pending/Running/Succeeded/Failed/Unknown
    node_name: str = ""      # set by bind
    cpu_request: int = 0     # millicores
    ram_request: int = 0     # KB
    labels: Dict[str, str] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    # K8s podAffinity/podAntiAffinity requiredDuringScheduling matchLabels
    # (machine-level topology): match against labels of pods running on
    # the candidate node.
    pod_affinity: Dict[str, str] = field(default_factory=dict)
    pod_anti_affinity: Dict[str, str] = field(default_factory=dict)
    deleted: bool = False

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Node:
    """The scheduling-relevant slice of a K8s Node (nodewatcher.go:120-216)."""

    name: str
    cpu_capacity: int = 0    # millicores
    ram_capacity: int = 0    # KB
    unschedulable: bool = False
    ready: bool = True
    out_of_disk: bool = False
    labels: Dict[str, str] = field(default_factory=dict)
    deleted: bool = False


Event = Tuple[str, object]  # ("ADDED"|"MODIFIED"|"DELETED", Pod|Node)


class KubeAPI:
    """The client-go seam the watchers and actuation depend on."""

    def list_pods(self) -> List[Pod]:
        raise NotImplementedError

    def list_nodes(self) -> List[Node]:
        raise NotImplementedError

    def watch_pods(self) -> "queue.Queue[Event]":
        raise NotImplementedError

    def watch_nodes(self) -> "queue.Queue[Event]":
        raise NotImplementedError

    def unwatch_pods(self, watch) -> None:
        """Unsubscribe a watch returned by ``watch_pods`` (the watcher's
        resync path drops the dead stream before re-subscribing, or the
        fan-out keeps feeding an abandoned queue forever).  Default
        no-op: adapters whose watch streams die with their server-side
        connection have nothing to release."""

    def unwatch_nodes(self, watch) -> None:
        """Unsubscribe a watch returned by ``watch_nodes`` (see
        ``unwatch_pods``)."""

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        raise NotImplementedError

    def delete_pod(self, namespace: str, name: str) -> None:
        raise NotImplementedError


class FakeKube(KubeAPI):
    """Thread-safe in-process cluster with watch fan-out.

    Mutators (``create_pod``/``set_pod_phase``/``add_node``/...) model the
    API-server + controller side; ``bind_pod``/``delete_pod`` are the
    scheduler-side actuation calls the reference makes
    (k8sclient.go:33-54).  Every mutation fans out a watch event to all
    subscribers, mirroring informer delivery.
    """

    def __init__(self) -> None:
        self._lock = TrackedLock("glue.FakeKube._lock", reentrant=True)
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self._pod_watchers: List["queue.Queue[Event]"] = []
        self._node_watchers: List["queue.Queue[Event]"] = []
        # Actuation log for assertions: (kind, namespace/name, node).
        self.bindings: List[Tuple[str, str]] = []
        self.deletions: List[str] = []
        # Controller emulation: deleted pods of owned sets get recreated.
        self.recreate_on_delete: bool = False
        self._recreate_counter = itertools.count()

    # ------------------------------------------------------------ fan-out

    # Watch delivery hands out *copies*, the way real informers deliver
    # freshly decoded objects: the registry object keeps mutating in place,
    # and if subscribers held the live reference, change detection
    # (old-vs-new spec comparison in the watchers) would compare an object
    # against itself and never fire.

    @staticmethod
    def _copy_pod(pod: Pod) -> Pod:
        clone = copy.copy(pod)
        clone.labels = dict(pod.labels)
        clone.node_selector = dict(pod.node_selector)
        clone.pod_affinity = dict(pod.pod_affinity)
        clone.pod_anti_affinity = dict(pod.pod_anti_affinity)
        return clone

    @staticmethod
    def _copy_node(node: Node) -> Node:
        clone = copy.copy(node)
        clone.labels = dict(node.labels)
        return clone

    def _emit_pod(self, kind: str, pod: Pod) -> None:
        clone = self._copy_pod(pod)
        for q in list(self._pod_watchers):
            q.put((kind, clone))

    def _emit_node(self, kind: str, node: Node) -> None:
        clone = self._copy_node(node)
        for q in list(self._node_watchers):
            q.put((kind, clone))

    # ------------------------------------------------------------- KubeAPI

    def list_pods(self) -> List[Pod]:
        with self._lock:
            return [self._copy_pod(p) for p in self.pods.values()]

    def list_nodes(self) -> List[Node]:
        with self._lock:
            return [self._copy_node(n) for n in self.nodes.values()]

    def watch_pods(self) -> "queue.Queue[Event]":
        q: "queue.Queue[Event]" = queue.Queue()
        with self._lock:
            self._pod_watchers.append(q)
        return q

    def watch_nodes(self) -> "queue.Queue[Event]":
        q: "queue.Queue[Event]" = queue.Queue()
        with self._lock:
            self._node_watchers.append(q)
        return q

    def unwatch_pods(self, watch) -> None:
        with self._lock:
            if watch in self._pod_watchers:
                self._pod_watchers.remove(watch)

    def unwatch_nodes(self, watch) -> None:
        with self._lock:
            if watch in self._node_watchers:
                self._node_watchers.remove(watch)

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        with self._lock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is None or pod.deleted:
                raise KeyError(f"bind: no such pod {namespace}/{name}")
            pod.node_name = node_name
            pod.phase = "Running"
            self.bindings.append((pod.key, node_name))
            self._emit_pod("MODIFIED", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            key = f"{namespace}/{name}"
            pod = self.pods.pop(key, None)
            if pod is None:
                raise KeyError(f"delete: no such pod {key}")
            pod.deleted = True
            self.deletions.append(key)
            self._emit_pod("DELETED", pod)
            if self.recreate_on_delete and pod.owner_uid:
                # The owning controller resubmits a replacement pod — the
                # preemption emulation the reference relies on
                # (cmd/poseidon/poseidon.go:59-63).
                clone = Pod(
                    name=f"{pod.name}-r{next(self._recreate_counter)}",
                    namespace=pod.namespace,
                    owner_uid=pod.owner_uid,
                    scheduler_name=pod.scheduler_name,
                    cpu_request=pod.cpu_request,
                    ram_request=pod.ram_request,
                    labels=dict(pod.labels),
                    node_selector=dict(pod.node_selector),
                )
                self.pods[clone.key] = clone
                self._emit_pod("ADDED", clone)

    # -------------------------------------------------- cluster-side mutators

    def create_pod(self, pod: Pod) -> Pod:
        with self._lock:
            self.pods[pod.key] = pod
            self._emit_pod("ADDED", pod)
            return pod

    def set_pod_phase(self, key: str, phase: str) -> None:
        with self._lock:
            pod = self.pods[key]
            pod.phase = phase
            self._emit_pod("MODIFIED", pod)

    def update_pod(self, key: str, mutate: Callable[[Pod], None]) -> None:
        with self._lock:
            pod = self.pods[key]
            mutate(pod)
            self._emit_pod("MODIFIED", pod)

    def add_node(self, node: Node) -> Node:
        with self._lock:
            self.nodes[node.name] = node
            self._emit_node("ADDED", node)
            return node

    def update_node(self, name: str, mutate: Callable[[Node], None]) -> None:
        with self._lock:
            node = self.nodes[name]
            mutate(node)
            self._emit_node("MODIFIED", node)

    def remove_node(self, name: str) -> None:
        with self._lock:
            node = self.nodes.pop(name)
            node.deleted = True
            self._emit_node("DELETED", node)
