"""PoseidonStats gRPC server: Heapster-style metrics -> Firmament knowledge base.

Re-creates the reference's stats service (pkg/stats/stats.go:33-178): a
bidi-streaming gRPC server receives NodeStats/PodStats from the metrics
sink, converts them to Firmament ResourceStats/TaskStats, joins them to
task/resource ids through the shared maps, and forwards them via
AddTaskStats/AddNodeStats.  Unknown pods/nodes answer NOT_FOUND on the
stream and are dropped (stats.go:89-91,132-134).
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Optional

import grpc

from poseidon_tpu.glue.types import SharedState
from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.protos import stats_pb2 as spb
from poseidon_tpu.protos.services import (
    STATS_METHODS,
    STATS_SERVICE,
    generic_handler,
)
from poseidon_tpu.service.client import FirmamentClient

log = logging.getLogger("poseidon.stats")


def node_stats_to_resource_stats(
    ns: spb.NodeStats, resource_uuid: str
) -> fpb.ResourceStats:
    """NodeStats -> ResourceStats (stats.go:33-54)."""
    rs = fpb.ResourceStats(
        resource_id=resource_uuid,
        timestamp=ns.timestamp,
        mem_allocatable=ns.mem_allocatable,
        mem_capacity=ns.mem_capacity,
        mem_reservation=ns.mem_reservation,
        mem_utilization=ns.mem_utilization,
    )
    rs.cpus_stats.add(
        cpu_allocatable=ns.cpu_allocatable,
        cpu_capacity=ns.cpu_capacity,
        cpu_reservation=ns.cpu_reservation,
        cpu_utilization=ns.cpu_utilization,
    )
    return rs


def pod_stats_to_task_stats(ps: spb.PodStats, task_id: int) -> fpb.TaskStats:
    """PodStats -> TaskStats, field-for-field (stats.go:56-75)."""
    # PodStats carries no timestamp (poseidonstats.proto:38-66); the
    # TaskStats one is left at its default, as in the reference's
    # conversion (stats.go:56-75).
    return fpb.TaskStats(
        task_id=task_id,
        hostname=ps.hostname,
        cpu_limit=ps.cpu_limit,
        cpu_request=ps.cpu_request,
        cpu_usage=ps.cpu_usage,
        mem_limit=ps.mem_limit,
        mem_request=ps.mem_request,
        mem_usage=ps.mem_usage,
        mem_rss=ps.mem_rss,
        mem_cache=ps.mem_cache,
        mem_working_set=ps.mem_working_set,
        mem_page_faults=ps.mem_page_faults,
        mem_page_faults_rate=ps.mem_page_faults_rate,
        major_page_faults=ps.major_page_faults,
        major_page_faults_rate=ps.major_page_faults_rate,
        net_rx=ps.net_rx,
        net_rx_errors=ps.net_rx_errors,
        net_rx_errors_rate=ps.net_rx_errors_rate,
        net_rx_rate=ps.net_rx_rate,
        net_tx=ps.net_tx,
        net_tx_errors=ps.net_tx_errors,
        net_tx_errors_rate=ps.net_tx_errors_rate,
        net_tx_rate=ps.net_tx_rate,
    )


class StatsServicer:
    def __init__(self, shared: SharedState, firmament: FirmamentClient) -> None:
        self.shared = shared
        self.fc = firmament

    def ReceiveNodeStats(self, request_iterator, context):
        for ns in request_iterator:
            uuid = self.shared.resource_for_node(ns.hostname)
            if uuid is None:
                yield spb.NodeStatsResponse(
                    type=spb.NODE_NOT_FOUND, hostname=ns.hostname
                )
                continue
            self.fc.add_node_stats(node_stats_to_resource_stats(ns, uuid))
            yield spb.NodeStatsResponse(
                type=spb.NODE_STATS_OK, hostname=ns.hostname
            )

    def ReceivePodStats(self, request_iterator, context):
        for ps in request_iterator:
            uid = self.shared.uid_for_pod(f"{ps.namespace}/{ps.name}")
            if uid is None:
                yield spb.PodStatsResponse(
                    type=spb.POD_NOT_FOUND, name=ps.name, namespace=ps.namespace
                )
                continue
            self.fc.add_task_stats(pod_stats_to_task_stats(ps, uid))
            yield spb.PodStatsResponse(
                type=spb.POD_STATS_OK, name=ps.name, namespace=ps.namespace
            )


class StatsServer:
    """Owns the gRPC server bound to the stats address (stats.go:163-178)."""

    def __init__(
        self,
        shared: SharedState,
        firmament: FirmamentClient,
        address: str = "0.0.0.0:9091",
        max_workers: int = 8,
    ) -> None:
        self.servicer = StatsServicer(shared, firmament)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers(
            (generic_handler(STATS_SERVICE, STATS_METHODS, self.servicer),)
        )
        self.port = self._server.add_insecure_port(address)
        host = address.rsplit(":", 1)[0]
        if host in ("0.0.0.0", "[::]", ""):
            host = "127.0.0.1"
        self.address = f"{host}:{self.port}"

    def start(self) -> "StatsServer":
        self._server.start()
        log.info("stats server on %s", self.address)
        return self

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace).wait()
