"""Host-side exact min-cost-flow oracle.

Stands in for upstream Firmament's cs2 solver as the placement-cost parity
reference (SURVEY.md section 7 step 3): the TPU auction solver is verified
against this on randomized instances and on the benchmark configs.

Built on networkx's network simplex (exact for integer data).  Slow but
trustworthy; only used in tests and offline parity runs.
"""

from __future__ import annotations

import networkx as nx
import numpy as np

from poseidon_tpu.ops.transport import INF_COST


def transport_objective(
    costs: np.ndarray,
    supply: np.ndarray,
    capacity: np.ndarray,
    unsched_cost: np.ndarray,
    arc_capacity: np.ndarray | None = None,
) -> int:
    """Exact optimal objective of the EC->machine transportation instance.

    Graph: source -> EC (cap s_e) -> machine (cost C[e,m], cap
    arc_capacity[e,m] if given) -> sink (cap c_m), plus EC -> sink fallback
    arcs at the unscheduled cost.  Always feasible because of the fallback.
    """
    costs = np.asarray(costs)
    supply = np.asarray(supply)
    capacity = np.asarray(capacity)
    unsched_cost = np.asarray(unsched_cost)
    E, M = costs.shape
    total = int(supply.sum())

    g = nx.DiGraph()
    g.add_node("src", demand=-total)
    g.add_node("sink", demand=total)
    for e in range(E):
        s = int(supply[e])
        if s == 0:
            continue
        g.add_edge("src", ("ec", e), capacity=s, weight=0)
        g.add_edge(("ec", e), "sink", capacity=s, weight=int(unsched_cost[e]))
        for m in range(M):
            c = int(costs[e, m])
            if c >= INF_COST or capacity[m] <= 0:
                continue
            acap = s if arc_capacity is None else min(s, int(arc_capacity[e, m]))
            if acap <= 0:
                continue
            g.add_edge(("ec", e), ("mach", m), capacity=acap, weight=c)
    for m in range(M):
        if capacity[m] > 0:
            g.add_edge(("mach", m), "sink", capacity=int(capacity[m]), weight=0)

    cost, _flow = nx.network_simplex(g)
    return int(cost)


def transport_solve(
    costs: np.ndarray,
    supply: np.ndarray,
    capacity: np.ndarray,
    unsched_cost: np.ndarray,
    arc_capacity: np.ndarray | None = None,
):
    """Exact solve returning ``(objective, flows, unsched)``.

    The successive-shortest-path ("ssp") verification solver the service
    exposes via ``flow_solver=ssp`` (SURVEY.md section 7: "SSP first
    (correct), Pallas push-relabel second (fast)") — network simplex on
    host, bit-exact optimal, no device involvement.  Same graph as
    ``transport_objective``.
    """
    costs = np.asarray(costs)
    supply = np.asarray(supply)
    capacity = np.asarray(capacity)
    unsched_cost = np.asarray(unsched_cost)
    E, M = costs.shape
    total = int(supply.sum())

    g = nx.DiGraph()
    g.add_node("src", demand=-total)
    g.add_node("sink", demand=total)
    for e in range(E):
        s = int(supply[e])
        if s == 0:
            continue
        g.add_edge("src", ("ec", e), capacity=s, weight=0)
        g.add_edge(("ec", e), "sink", capacity=s, weight=int(unsched_cost[e]))
        for m in range(M):
            c = int(costs[e, m])
            if c >= INF_COST or capacity[m] <= 0:
                continue
            acap = s if arc_capacity is None else min(s, int(arc_capacity[e, m]))
            if acap <= 0:
                continue
            g.add_edge(("ec", e), ("mach", m), capacity=acap, weight=c)
    for m in range(M):
        if capacity[m] > 0:
            g.add_edge(("mach", m), "sink", capacity=int(capacity[m]), weight=0)

    cost, flow = nx.network_simplex(g)
    flows = np.zeros((E, M), dtype=np.int32)
    unsched = np.zeros(E, dtype=np.int32)
    for e in range(E):
        out = flow.get(("ec", e))
        if not out:
            continue
        for dst, amount in out.items():
            if dst == "sink":
                unsched[e] = amount
            else:
                flows[e, dst[1]] = amount
    return int(cost), flows, unsched


def mcmf_objective(
    n: int,
    arcs: list,
    supplies: dict,
) -> int:
    """Exact min-cost flow on a general graph.

    ``arcs`` is a list of (u, v, capacity, cost); ``supplies`` maps node ->
    net supply (positive = source).  Used as the oracle for the dense
    general-graph kernel.
    """
    g = nx.DiGraph()
    for u in range(n):
        g.add_node(u, demand=-int(supplies.get(u, 0)))
    for u, v, cap, cost in arcs:
        if g.has_edge(u, v):
            # networkx MultiDiGraph would be needed for parallel arcs; the
            # callers never produce them.
            raise ValueError("parallel arcs not supported by oracle")
        g.add_edge(u, v, capacity=int(cap), weight=int(cost))
    cost, _ = nx.network_simplex(g)
    return int(cost)
