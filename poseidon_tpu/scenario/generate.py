"""Composable generators for the named, production-shaped scenarios.

Each generator is a pure function of (machines, rounds, seed): the same
inputs always materialize the same ``ScenarioPlan`` bit-for-bit (the
randomized determinism suite pins this).  All generators draw pod
request shapes from the harness's ``POD_SHAPES`` — the narrow factor
range that keeps every round inside the precompiled solver size bands,
so the warm-round budget-0 compile gate holds across every scenario.

The committed registry (``named_scenario``):

================  =========================================================
scenario          shape
================  =========================================================
diurnal           sinusoidal arrival rate over the day-curve period with
                  completions tracking the trough — the baseline
                  production load curve
flash_crowd       quiet baseline, then a one-round arrival burst (one
                  owner-grouped crowd job) decaying over two rounds
node_churn        steady churn while an autoscaler adds fresh nodes and
                  drain+cordons old ones (fleet size roughly constant)
rolling_restart   a fixed fleet of deployments restarted in waves: each
                  round completes the oldest K pods and resubmits K
                  replacements
multi_tenant      three tenants on a zoned fleet under quota weights:
                  gang-scheduled batch jobs (zone a), anti-affinity
                  spread serving replicas (zone b), unconstrained
                  best-effort fill (any zone)
================  =========================================================

Every plan ends with two settle rounds (no arrivals, completions keep
draining) so the end-of-drive "everything placed" gate is meaningful
under the same contract as the chaos soak.
"""

from __future__ import annotations

import hashlib
import math
from typing import Callable, Dict, List, Tuple

import numpy as np

from poseidon_tpu.chaos.harness import POD_SHAPES
from poseidon_tpu.scenario.plan import (
    KVPairs,
    PodArrival,
    ScenarioPlan,
    ScenarioRound,
    kv,
)

SETTLE_ROUNDS = 2

SCENARIOS: Tuple[str, ...] = (
    "diurnal", "flash_crowd", "node_churn", "rolling_restart",
    "multi_tenant",
)


def _rng(name: str, seed: int) -> np.random.Generator:
    """Seeded per-scenario stream: the name is folded in through a
    stable content hash (never Python's randomized ``hash``) so two
    scenarios sharing a seed do not share a stream."""
    name_key = int(hashlib.sha256(name.encode()).hexdigest()[:8], 16)
    return np.random.default_rng([seed, name_key])


def _shape(rng: np.random.Generator) -> Tuple[int, int]:
    return POD_SHAPES[int(rng.integers(len(POD_SHAPES)))]


def _arrival(name: str, rng: np.random.Generator, *, owner: str = "",
             labels: KVPairs = (), node_selector: KVPairs = (),
             pod_affinity: KVPairs = (),
             pod_anti_affinity: KVPairs = ()) -> PodArrival:
    cpu, ram = _shape(rng)
    return PodArrival(
        name=name, cpu=cpu, ram=ram, owner=owner, labels=labels,
        node_selector=node_selector, pod_affinity=pod_affinity,
        pod_anti_affinity=pod_anti_affinity,
    )


def _settle(rounds: List[ScenarioRound], *, completions: int = 0,
            deletions: int = 0) -> None:
    """Append the two settle rounds every plan ends with."""
    for _ in range(SETTLE_ROUNDS):
        rounds.append(ScenarioRound(
            round_index=len(rounds), completions=completions,
            deletions=deletions,
        ))


def gen_diurnal(machines: int, rounds: int, seed: int) -> ScenarioPlan:
    """Sinusoidal load curve: arrivals per round ride one full diurnal
    period across the active rounds; completions lag two rounds so the
    live population breathes with the curve but stays bounded."""
    rng = _rng("diurnal", seed)
    base_pop = machines * 2
    rate = max(machines // 2, 4)
    period = max(rounds - 1, 4)
    plan_rounds: List[ScenarioRound] = []
    arrivals_hist: List[int] = []
    for r in range(rounds):
        if r == 0:
            n = base_pop
        else:
            phase = 2.0 * math.pi * (r - 1) / period
            n = max(int(round(rate * (1.0 + 0.8 * math.sin(phase)))), 1)
        arrivals = tuple(
            _arrival(
                f"diurnal-r{r}-{i}", rng,
                owner=f"diurnal-job-r{r}-{i % 3}" if i % 4 == 0 else "",
            )
            for i in range(n)
        )
        completions = arrivals_hist[r - 2] if r >= 2 else 0
        deletions = arrivals_hist[r - 3] if r >= 3 else 0
        arrivals_hist.append(n)
        plan_rounds.append(ScenarioRound(
            round_index=r, arrivals=arrivals, completions=completions,
            deletions=deletions,
        ))
    _settle(plan_rounds, completions=rate, deletions=rate)
    return ScenarioPlan(
        name="diurnal", seed=seed, machines=machines,
        rounds=tuple(plan_rounds),
    )


def gen_flash_crowd(machines: int, rounds: int, seed: int) -> ScenarioPlan:
    """Flash crowd: a quiet baseline churn, then one round admits a
    burst several times the steady rate (owner-grouped into a handful
    of crowd jobs), decaying over the following two rounds; the crowd
    cohort then completes in bulk."""
    rng = _rng("flash_crowd", seed)
    quiet = max(machines // 8, 2)
    burst_round = max(rounds // 2, 2)
    burst = machines * 3
    plan_rounds: List[ScenarioRound] = []
    for r in range(rounds):
        if r == 0:
            n, tag = machines * 2, "base"
        elif r == burst_round:
            n, tag = burst, "crowd"
        elif r == burst_round + 1:
            n, tag = burst // 2, "crowd"
        elif r == burst_round + 2:
            n, tag = burst // 4, "crowd"
        else:
            n, tag = quiet, "base"
        arrivals = tuple(
            _arrival(
                f"flash-{tag}-r{r}-{i}", rng,
                owner=(
                    f"flash-crowd-r{r}-{i % 4}" if tag == "crowd" else ""
                ),
            )
            for i in range(n)
        )
        # The crowd drains as fast as it came: completions shadow the
        # burst two rounds back, so capacity recovers before the end
        # gate.
        if r >= 2 and r - 2 >= burst_round:
            completions = (
                burst if r - 2 == burst_round
                else burst // 2 if r - 2 == burst_round + 1
                else burst // 4 if r - 2 == burst_round + 2
                else quiet
            )
        else:
            completions = quiet if r >= 2 else 0
        plan_rounds.append(ScenarioRound(
            round_index=r, arrivals=arrivals, completions=completions,
            deletions=completions if r >= 3 else 0,
        ))
    _settle(plan_rounds, completions=burst // 4, deletions=burst // 4)
    return ScenarioPlan(
        name="flash_crowd", seed=seed, machines=machines,
        rounds=tuple(plan_rounds),
    )


def gen_node_churn(machines: int, rounds: int, seed: int) -> ScenarioPlan:
    """Autoscaler node churn: steady workload churn while the fleet
    rolls — every other round adds a fresh node, alternating rounds
    drain+cordon one of the originals, holding capacity roughly
    constant while machine add/remove paths run every round."""
    rng = _rng("node_churn", seed)
    churn = max(machines // 4, 4)
    plan_rounds: List[ScenarioRound] = []
    added = 0
    drained = 0
    # Never drain more than a quarter of the fleet: the end gate needs
    # headroom to place everything after the churn stops.
    max_drains = max(machines // 4, 1)
    for r in range(rounds):
        n = machines * 2 if r == 0 else churn
        arrivals = tuple(
            _arrival(f"nodechurn-r{r}-{i}", rng)
            for i in range(n)
        )
        add_nodes: Tuple[str, ...] = ()
        drain_nodes: Tuple[str, ...] = ()
        if r >= 2 and r % 2 == 0:
            add_nodes = (f"m{machines + added:04d}",)
            added += 1
        if r >= 3 and r % 2 == 1 and drained < min(added, max_drains):
            drain_nodes = (f"m{drained:04d}",)
            drained += 1
        plan_rounds.append(ScenarioRound(
            round_index=r, arrivals=arrivals,
            completions=churn if r >= 2 else 0,
            deletions=churn if r >= 3 else 0,
            drain_nodes=drain_nodes, add_nodes=add_nodes,
        ))
    _settle(plan_rounds, completions=churn, deletions=churn)
    return ScenarioPlan(
        name="node_churn", seed=seed, machines=machines,
        rounds=tuple(plan_rounds),
    )


def gen_rolling_restart(machines: int, rounds: int,
                        seed: int) -> ScenarioPlan:
    """Rolling-restart storm: a fixed fleet of deployment pods is
    restarted in waves — each active round completes the K oldest
    Running pods and resubmits K replacements, so the live population
    holds steady while every round exercises the full finish+resubmit
    lifecycle at storm rate."""
    rng = _rng("rolling_restart", seed)
    base_pop = machines * 3
    wave = max(machines // 2, 4)
    plan_rounds: List[ScenarioRound] = []
    for r in range(rounds):
        if r == 0:
            arrivals = tuple(
                _arrival(
                    f"restart-base-{i}", rng,
                    owner=f"restart-deploy-{i % 4}",
                )
                for i in range(base_pop)
            )
            completions = 0
        else:
            arrivals = tuple(
                _arrival(
                    f"restart-r{r}-{i}", rng,
                    owner=f"restart-deploy-{i % 4}",
                )
                for i in range(wave)
            )
            completions = wave
        plan_rounds.append(ScenarioRound(
            round_index=r, arrivals=arrivals, completions=completions,
            deletions=wave if r >= 2 else 0,
        ))
    _settle(plan_rounds, completions=wave, deletions=wave)
    return ScenarioPlan(
        name="rolling_restart", seed=seed, machines=machines,
        rounds=tuple(plan_rounds),
    )


def _zones(machines: int) -> Dict[str, Dict[str, str]]:
    """Three equal zones over the initial fleet (multi_tenant)."""
    labels: Dict[str, Dict[str, str]] = {}
    for i in range(machines):
        labels[f"m{i:04d}"] = {"zone": f"z{i % 3}"}
    return labels


def gen_multi_tenant(machines: int, rounds: int,
                     seed: int) -> ScenarioPlan:
    """Mixed multi-tenant fleet on zoned machines, quota-weighted:

    - tenant-batch (quota 50%): gang-scheduled jobs (``gangScheduling``
      label, one owner per gang) pinned to zone z0 by nodeSelector;
    - tenant-serving (quota 30%): replica sets spread by
      pod_anti_affinity on their own app label, pinned to zone z1;
    - tenant-be (quota 20%): unconstrained best-effort fill, any zone.

    Quota is admission-shaped: each tenant's arrivals are capped at its
    weight of the per-round budget, so the generated demand respects
    the fleet split the way a quota admission controller would."""
    rng = _rng("multi_tenant", seed)
    budget = max(machines, 12)  # pods per active round, all tenants
    quotas = {"batch": 0.5, "serving": 0.3, "be": 0.2}
    zone_nodes = max(machines // 3, 1)
    gang_size = min(4, max(zone_nodes // 2, 2))
    plan_rounds: List[ScenarioRound] = []
    gang_seq = 0
    app_seq = 0
    for r in range(rounds):
        scale = 2 if r == 0 else 1
        arrivals: List[PodArrival] = []
        # tenant-batch: whole gangs only (a partial gang would violate
        # the atomic-placement contract this scenario exists to drive).
        n_batch = int(budget * quotas["batch"] * scale)
        for _ in range(max(n_batch // gang_size, 1)):
            owner = f"mt-batch-gang-{gang_seq}"
            gang_seq += 1
            cpu, ram = _shape(rng)
            for m in range(gang_size):
                arrivals.append(PodArrival(
                    name=f"mt-batch-r{r}-{owner.rsplit('-', 1)[-1]}-{m}",
                    cpu=cpu, ram=ram, owner=owner,
                    labels=kv({
                        "tenant": "batch", "gangScheduling": "true",
                    }),
                    node_selector=kv({"zone": "z0"}),
                ))
        # tenant-serving: small replica sets, one app label each,
        # anti-affinity against themselves -> at most one replica per
        # machine (spread), zone-pinned.
        n_serving = int(budget * quotas["serving"] * scale)
        replicas = min(3, zone_nodes)
        for _ in range(max(n_serving // replicas, 1)):
            app = f"mt-app-{app_seq}"
            app_seq += 1
            cpu, ram = _shape(rng)
            for m in range(replicas):
                arrivals.append(PodArrival(
                    name=f"mt-serve-r{r}-{app.rsplit('-', 1)[-1]}-{m}",
                    cpu=cpu, ram=ram,
                    labels=kv({"tenant": "serving", "app": app}),
                    node_selector=kv({"zone": "z1"}),
                    pod_anti_affinity=kv({"app": app}),
                ))
        # tenant-be: unconstrained fill.
        n_be = int(budget * quotas["be"] * scale)
        for i in range(max(n_be, 1)):
            arrivals.append(_arrival(
                f"mt-be-r{r}-{i}", rng,
                labels=kv({"tenant": "be"}),
            ))
        plan_rounds.append(ScenarioRound(
            round_index=r, arrivals=tuple(arrivals),
            completions=budget if r >= 2 else 0,
            deletions=budget if r >= 3 else 0,
        ))
    _settle(plan_rounds, completions=budget, deletions=budget)
    return ScenarioPlan(
        name="multi_tenant", seed=seed, machines=machines,
        rounds=tuple(plan_rounds),
        node_labels=tuple(
            (name, kv(labels))
            for name, labels in sorted(_zones(machines).items())
        ),
    )


_GENERATORS: Dict[str, Callable[[int, int, int], ScenarioPlan]] = {
    "diurnal": gen_diurnal,
    "flash_crowd": gen_flash_crowd,
    "node_churn": gen_node_churn,
    "rolling_restart": gen_rolling_restart,
    "multi_tenant": gen_multi_tenant,
}


def named_scenario(name: str, *, machines: int = 32, rounds: int = 8,
                   seed: int = 0) -> ScenarioPlan:
    """The committed scenario registry (bench scenario rung + make
    scenario-smoke)."""
    try:
        gen = _GENERATORS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {', '.join(SCENARIOS)}"
        ) from None
    return gen(machines, rounds, seed)
