"""Trace-driven scenario harness: production-shaped workload
generators, full-stack drives in both loop modes, robustness scoring
under chaos-seeded cost perturbation, and flight-recorder replay.

- ``plan``: declarative, seeded ``ScenarioPlan`` (the FaultPlan twin);
- ``generate``: the named scenario registry (diurnal, flash_crowd,
  node_churn, rolling_restart, multi_tenant);
- ``drive``: a plan through the FULL glue loop with the shared harness
  gates (chaos/harness.py), sync or streaming;
- ``score``: robustness = objective-regression quantiles across
  perturbation seeds (docs/SCENARIOS.md has the metric definition).
"""

from poseidon_tpu.scenario.drive import (
    drive_scenario,
    scenario_digest,
    scenario_out_dir,
)
from poseidon_tpu.scenario.generate import (
    SCENARIOS,
    SETTLE_ROUNDS,
    named_scenario,
)
from poseidon_tpu.scenario.plan import (
    PodArrival,
    ScenarioPlan,
    ScenarioRound,
    workload_events,
)
from poseidon_tpu.scenario.score import (
    PerturbedCostModel,
    score_scenario,
)

__all__ = [
    "SCENARIOS",
    "SETTLE_ROUNDS",
    "PodArrival",
    "PerturbedCostModel",
    "ScenarioPlan",
    "ScenarioRound",
    "drive_scenario",
    "named_scenario",
    "scenario_digest",
    "scenario_out_dir",
    "score_scenario",
    "workload_events",
]
