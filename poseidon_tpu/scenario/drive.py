"""The scenario driver: a ScenarioPlan through the FULL glue stack.

One drive = the shared ``chaos/harness.py`` ``DriveStack`` (FakeKube +
the real pod/node watchers + the real gRPC firmament-tpu service + the
production ``Poseidon.try_round`` loop) executing a declarative
``ScenarioPlan`` round by round, in EITHER loop mode — the
``streaming`` flag flips ``POSEIDON_STREAMING`` for the drive and
restores it, exactly like the throughput rung, so synchronous and
streaming drives of the same plan are drain-equivalent and must place
identically.

Per-round gates (single-sourced in the harness, same as the chaos
soak): kube-truth/scheduler byte-identity, the warm-window budget-0
ledger quartet (Compile/Transfer/Lock/Numerics), solve-tier vocabulary,
and seeded determinism (per-round placement digests + per-round delta
digests; ``scenario_digest`` folds them all).  Every round records to
the flight recorder; on failure the trace lands under the scenario out
dir (``POSEIDON_SCENARIO_OUT``) and ``replay/flight.redrive_flight``
re-drives it offline to the identical round.

Robustness scoring (``scenario/score.py``) re-enters here with
``perturb_seed`` set: the planner's cost model is swapped for a
chaos-seeded ``PerturbedCostModel`` before the first round, and every
correctness gate stays armed — only placements/objective may move.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Callable, List, Optional, Sequence, Union

from poseidon_tpu.chaos.harness import (
    DriveFailure,
    DriveStack,
    LedgerWindow,
    await_effect,
    metrics_wire,
    view_digest,
)
from poseidon_tpu.chaos.plan import named_plan
from poseidon_tpu.chaos.recorder import FlightRecorder
from poseidon_tpu.obs import trace as obs_trace
from poseidon_tpu.scenario.generate import named_scenario
from poseidon_tpu.scenario.plan import ScenarioPlan
from poseidon_tpu.utils.hatches import hatch_str

log = logging.getLogger("poseidon.scenario.drive")


def scenario_out_dir() -> str:
    """Flight-trace output dir for scenario drives (hatch-controlled)."""
    return hatch_str("POSEIDON_SCENARIO_OUT")


def _delta_digest(deltas: List[dict]) -> str:
    """Digest of one round's enacted delta stream (the generator-
    determinism suite compares these bit-for-bit across runs/modes)."""
    return hashlib.sha256(
        json.dumps(deltas, sort_keys=True).encode()
    ).hexdigest()[:16]


def scenario_digest(plan: ScenarioPlan, digests: Sequence[str],
                    delta_digests: Sequence[str]) -> str:
    """One digest for the whole drive: the plan content plus every
    round's placement digest and delta-stream digest."""
    h = hashlib.sha256()
    h.update(plan.digest().encode())
    for d in digests:
        h.update(d.encode())
    for d in delta_digests:
        h.update(d.encode())
    return h.hexdigest()[:16]


def drive_scenario(
    plan: Union[ScenarioPlan, str],
    *,
    streaming: bool = False,
    machines: Optional[int] = None,
    rounds: Optional[int] = None,
    seed: int = 0,
    perturb_seed: Optional[int] = None,
    amplitude: Optional[float] = None,
    out_dir: Optional[str] = None,
    until_round: Optional[int] = None,
    expect_digests: Optional[Sequence[str]] = None,
    on_round: Optional[Callable[[int, dict], None]] = None,
) -> dict:
    """Drive one scenario plan; returns the result artifact (never
    raises for drive failures — they come back as ``ok=False`` plus a
    written flight trace).

    ``plan`` is a materialized ``ScenarioPlan`` or a registry name
    (``machines``/``rounds``/``seed`` parameterize generation then).
    ``until_round``/``expect_digests`` are the re-drive interface
    (replay/flight.py).  ``perturb_seed`` installs a chaos-seeded
    ``PerturbedCostModel`` (scenario/score.py) over the planner's cost
    model before the first round.  ``on_round(r, ctx)`` is a test hook
    fired before the round's workload mutations; ``ctx`` exposes the
    live pieces (server, kube, poseidon, stack)."""
    from poseidon_tpu.glue.fake_kube import Node, Pod
    from poseidon_tpu.ops.transport import bucket_size

    if isinstance(plan, str):
        plan = named_scenario(
            plan, machines=machines or 32, rounds=rounds or 8, seed=seed
        )
    out_dir = out_dir if out_dir is not None else scenario_out_dir()
    mode = "streaming" if streaming else "synchronous"
    spec = {
        "kind": "scenario",
        "name": plan.name,
        "seed": plan.seed,
        "machines": plan.machines,
        "rounds": plan.total_rounds,
        "streaming": streaming,
        "perturb_seed": perturb_seed,
        "amplitude": amplitude,
        # The materialized plan rides in the spec: a recorded trace
        # stays re-drivable bit-for-bit even if generator logic evolves
        # (the FaultPlan trace makes the same promise for faults).
        "plan": plan.to_dict(),
    }
    # Scenario drives are fault-free (chaos belongs to the soak); the
    # recorder still wants a plan object for the trace.
    recorder = FlightRecorder(
        spec, named_plan("none", plan.total_rounds, plan.seed),
        out_dir=out_dir,
    )
    total_rounds = plan.total_rounds
    if until_round is not None:
        total_rounds = min(total_rounds, until_round)

    result: dict = {
        "ok": False, "scenario": plan.name, "seed": plan.seed,
        "machines": plan.machines, "mode": mode,
        "perturb_seed": perturb_seed,
        "rounds_requested": plan.total_rounds, "rounds_run": 0,
        "digests": [], "delta_digests": [], "tiers": [],
        "objective": 0, "objectives": [],
        "placements_per_sec": 0.0, "round_placements_per_sec": [],
        "admission_staleness_p50_s": 0.0,
        "admission_staleness_p99_s": 0.0,
        "warm_fresh_compiles": 0, "warm_implicit_transfers": 0,
        "warm_numeric_anomalies": 0, "warm_lock_order_edges": [],
        "lock_contention_ns": 0, "divergent_rounds": 0,
    }
    if expect_digests is not None:
        result["digest_mismatches"] = []

    # Size the EC bucket from the plan itself: the multi-tenant mix
    # (per-gang and per-app ECs) needs more rows than the four shared
    # shapes the soak budgets for.
    max_ecs = bucket_size(
        max(plan.max_window_ec_keys() * 2, 16), lo=8
    )

    # Save/restore of the raw env slot, not a semantic read — the
    # engine itself reads the flag through the hatch registry.
    prev = os.environ.get("POSEIDON_STREAMING")  # posecheck: ignore[hatch-registry]
    os.environ["POSEIDON_STREAMING"] = "1" if streaming else "0"
    stack = DriveStack(
        plan.machines, seed=plan.seed, injector=None, max_ecs=max_ecs,
        node_labels=plan.node_label_map(),
        ledger_label=f"scenario {plan.name}",
    ).start(health_timeout=30.0)
    kube, poseidon = stack.kube, stack.poseidon
    if perturb_seed is not None:
        from poseidon_tpu.scenario.score import (
            PerturbedCostModel,
            perturb_amplitude,
        )

        amplitude = (
            amplitude if amplitude is not None else perturb_amplitude()
        )
        planner = stack.server.servicer.planner
        planner.set_cost_model(PerturbedCostModel(
            planner.cost_model, seed=perturb_seed, amplitude=amplitude,
        ))
        result["amplitude"] = amplitude
    ctx = {
        "server": stack.server, "kube": kube, "poseidon": poseidon,
        "stack": stack,
    }

    staleness: List[float] = []
    solve_seconds = 0.0
    placed_total = 0
    created_order: List[str] = []  # pod keys, creation order

    def _oldest(phase: str, n: int) -> List[str]:
        """The N oldest (by creation order) pods currently in
        ``phase`` — the deterministic completion/GC policy."""
        out: List[str] = []
        for key in created_order:
            if len(out) >= n:
                break
            pod = kube.pods.get(key)
            if pod is not None and pod.phase == phase:
                out.append(key)
        return out

    try:
        stack.arm(sync_timeout=30.0)

        for r in range(total_rounds):
            rnd = plan.for_round(r)
            if on_round is not None:
                on_round(r, ctx)
            # Node churn first: scale-ups join before this round's
            # demand, drains complete their residents and cordon the
            # node inside the SAME round (order matters — the watchers
            # see the evictions before the machine removal, so the
            # scheduler never holds placements on a vanished machine).
            for name in rnd.add_nodes:
                kube.add_node(Node(
                    name=name, cpu_capacity=stack.node_cpu,
                    ram_capacity=stack.node_ram,
                    labels=dict(plan.node_label_map().get(name, {})),
                ))
            drained_off: List[str] = []
            for name in rnd.drain_nodes:
                residents = sorted(
                    pod.key for pod in kube.pods.values()
                    if pod.phase == "Running" and pod.node_name == name
                )
                for key in residents:
                    kube.set_pod_phase(key, "Succeeded")
                drained_off.extend(residents)
                kube.update_node(
                    name, lambda n: setattr(n, "unschedulable", True)
                )
            # Workload mutations: arrivals, then the oldest-first
            # completion/GC policy (deterministic given deterministic
            # placements — which the digest gates themselves pin).
            created: List[str] = []
            for a in rnd.arrivals:
                kube.create_pod(Pod(
                    name=a.name, cpu_request=a.cpu, ram_request=a.ram,
                    owner_uid=a.owner,
                    labels=dict(a.labels),
                    node_selector=dict(a.node_selector),
                    pod_affinity=dict(a.pod_affinity),
                    pod_anti_affinity=dict(a.pod_anti_affinity),
                ))
                key = f"default/{a.name}"
                created.append(key)
                created_order.append(key)
            completed = _oldest("Running", rnd.completions)
            for key in completed:
                kube.set_pod_phase(key, "Succeeded")
            deleted = _oldest("Succeeded", rnd.deletions)
            for key in deleted:
                ns, name = key.split("/", 1)
                kube.delete_pod(ns, name)
                created_order.remove(key)
            # Delivery barrier: created pods resolve to tasks, finished
            # and deleted pods stop resolving, added nodes register,
            # cordoned nodes drop out of the shared map; then the queue
            # drain proves the RPCs behind them completed.
            gone = completed + deleted + drained_off
            await_effect(
                lambda: all(
                    poseidon.shared.uid_for_pod(k) is not None
                    for k in created
                ) and all(
                    poseidon.shared.uid_for_pod(k) is None for k in gone
                ) and all(
                    poseidon.shared.get_node(n) is not None
                    for n in rnd.add_nodes
                ) and all(
                    poseidon.shared.get_node(n) is None
                    for n in rnd.drain_nodes
                ),
                20.0,
            )
            poseidon.drain_watchers(timeout=30.0)

            window = LedgerWindow()
            stack.drive_round(r, drain_timeout=60.0)
            window.close()
            if r >= 1:
                result["warm_fresh_compiles"] += window.fresh_compiles
                result["warm_implicit_transfers"] += (
                    window.implicit_transfers
                )
                result["warm_numeric_anomalies"] += (
                    window.numeric_anomalies
                )
                result["warm_lock_order_edges"].extend(
                    window.new_lock_order_edges
                )

            kube_truth, sched_view = stack.quiesce(heal_timeout=10.0)
            metrics = stack.server.servicer.planner.last_metrics
            metrics_d = window.stamp(
                metrics_wire(metrics), prefix="scenario"
            )
            result["lock_contention_ns"] += window.lock_contention_ns
            result["tiers"].append(stack.check_tier(metrics, r))
            result["objective"] += int(metrics.objective)
            result["objectives"].append(int(metrics.objective))
            result["round_placements_per_sec"].append(
                float(metrics.placements_per_sec)
            )
            staleness.append(float(metrics.admission_staleness_s))
            solve_seconds += float(metrics.total_seconds)
            placed_total += int(metrics.placed)
            digest = view_digest(kube_truth)
            deltas = [
                {"type": int(d.type), "task": int(d.task_id),
                 "resource": d.resource_id}
                for d in poseidon.last_deltas
            ]
            delta_digest = _delta_digest(deltas)
            result["digests"].append(digest)
            result["delta_digests"].append(delta_digest)
            result["rounds_run"] = r + 1
            recorder.record_round(
                r,
                faults=[],
                deltas=deltas,
                metrics=metrics_d,
                digest=digest,
                placements=len(kube_truth),
                spans=obs_trace.drain_spans(),
                counters=obs_trace.drain_counter_samples(),
            )
            if kube_truth != sched_view:
                only_kube = sorted(
                    set(kube_truth.items()) - set(sched_view.items())
                )[:5]
                only_sched = sorted(
                    set(sched_view.items()) - set(kube_truth.items())
                )[:5]
                result["divergent_rounds"] += 1
                raise DriveFailure(
                    "divergence",
                    f"kube-only={only_kube} scheduler-only={only_sched}",
                    r,
                )
            if expect_digests is not None and r < len(expect_digests) \
                    and digest != expect_digests[r]:
                result["digest_mismatches"].append(
                    {"round": r, "expected": expect_digests[r],
                     "got": digest}
                )

        if until_round is None:
            pending = stack.pending_pods()
            if pending:
                raise DriveFailure(
                    "unplaced",
                    f"{len(pending)} pods still Pending after settle: "
                    f"{pending[:5]}",
                    total_rounds,
                )
            if result["warm_fresh_compiles"]:
                raise DriveFailure(
                    "fresh-compiles",
                    f"{result['warm_fresh_compiles']} fresh XLA compiles "
                    "in warm rounds (budget 0)",
                    total_rounds,
                )
            if result["warm_implicit_transfers"]:
                raise DriveFailure(
                    "implicit-transfers",
                    f"{result['warm_implicit_transfers']} implicit "
                    "device->host sync(s) in warm rounds (budget 0)",
                    total_rounds,
                )
            if result["warm_numeric_anomalies"]:
                raise DriveFailure(
                    "numeric-anomalies",
                    f"{result['warm_numeric_anomalies']} numeric "
                    "anomaly(ies) in warm rounds (budget 0)",
                    total_rounds,
                )
            if result["warm_lock_order_edges"]:
                raise DriveFailure(
                    "lock-order-edges",
                    f"{len(result['warm_lock_order_edges'])} new lock-"
                    "acquisition-order edge(s) in warm rounds (budget "
                    f"0): {result['warm_lock_order_edges'][:5]}",
                    total_rounds,
                )
        result["ok"] = True
        if expect_digests is not None:
            result["reproduced"] = not result["digest_mismatches"]
            result["ok"] = result["ok"] and result["reproduced"]
    except DriveFailure as e:
        result["failure"] = {"kind": e.kind, "detail": e.detail,
                             "round": e.round_index}
        result["trace_path"] = recorder.record_failure(
            e.round_index, e.kind, e.detail
        )
        result["failing_round"] = e.round_index
        log.error("scenario %s failed (%s); flight trace: %s",
                  plan.name, e, result["trace_path"])
    finally:
        stack.stop()
        if prev is None:
            os.environ.pop("POSEIDON_STREAMING", None)
        else:
            os.environ["POSEIDON_STREAMING"] = prev

    result["scenario_digest"] = scenario_digest(
        plan, result["digests"], result["delta_digests"]
    )
    result["placements_per_sec"] = (
        round(placed_total / solve_seconds, 2) if solve_seconds > 0
        else 0.0
    )
    if staleness:
        import numpy as np

        result["admission_staleness_p50_s"] = round(
            float(np.percentile(staleness, 50)), 6
        )
        result["admission_staleness_p99_s"] = round(
            float(np.percentile(staleness, 99)), 6
        )
    result["resyncs"] = stack.resyncs
    result["loop_stats"] = stack.loop_stats_dict()
    return result
