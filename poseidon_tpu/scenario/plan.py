"""Declarative, seed-reproducible scenario plans.

A ``ScenarioPlan`` is the workload twin of the chaos ``FaultPlan``
(chaos/plan.py): a frozen per-round schedule — pod arrivals, departures,
node churn — generated from a seeded RNG, so the same (name, seed,
machines, rounds) always yields the same plan bit-for-bit.  The flight
recorder stores both the generation inputs AND the materialized plan, so
a recorded scenario trace stays re-drivable even if generator logic
evolves.

Vocabulary (the scenario driver, ``scenario/drive.py``, executes it
against the full glue stack):

===============  =========================================================
field            meaning
===============  =========================================================
arrivals         pods created this round (name, shape, owner, labels,
                 selectors, affinity) — the production-shaped demand
completions      N oldest Running pods transition to Succeeded (job
                 completion / autoscale-down of the workload)
deletions        N oldest Succeeded pods are deleted (GC lifecycle)
drain_nodes      nodes drained this round: every Running pod on them is
                 completed, then the node is cordoned (unschedulable —
                 the node watcher lowers that to a machine removal), in
                 that order inside one round so the scheduler never holds
                 placements on a vanished machine
add_nodes        nodes added this round (autoscaler scale-up)
===============  =========================================================

Label-ish fields are sorted ``(key, value)`` tuples — plans are frozen
and hashable; the driver lowers them back to dicts at the kube boundary.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

KVPairs = Tuple[Tuple[str, str], ...]


def kv(d: Dict[str, str]) -> KVPairs:
    """Dict -> canonical (sorted) tuple form for frozen plan fields."""
    return tuple(sorted((str(k), str(v)) for k, v in d.items()))


@dataclass(frozen=True)
class PodArrival:
    """One pod creation: the scheduling-relevant slice only (the driver
    fills in namespace/scheduler defaults at the kube boundary)."""

    name: str
    cpu: int                      # millicores
    ram: int                      # KB
    owner: str = ""               # owner UID: groups pods into jobs
    labels: KVPairs = ()
    node_selector: KVPairs = ()
    pod_affinity: KVPairs = ()
    pod_anti_affinity: KVPairs = ()

    def to_dict(self) -> dict:
        return {
            "name": self.name, "cpu": self.cpu, "ram": self.ram,
            "owner": self.owner, "labels": [list(p) for p in self.labels],
            "node_selector": [list(p) for p in self.node_selector],
            "pod_affinity": [list(p) for p in self.pod_affinity],
            "pod_anti_affinity": [
                list(p) for p in self.pod_anti_affinity
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PodArrival":
        def pairs(key: str) -> KVPairs:
            return tuple(
                (str(k), str(v)) for k, v in d.get(key) or []
            )
        return cls(
            name=str(d["name"]), cpu=int(d["cpu"]), ram=int(d["ram"]),
            owner=str(d.get("owner", "")),
            labels=pairs("labels"),
            node_selector=pairs("node_selector"),
            pod_affinity=pairs("pod_affinity"),
            pod_anti_affinity=pairs("pod_anti_affinity"),
        )

    def ec_key(self) -> tuple:
        """The equivalence-class-shaping slice: pods identical here
        aggregate into one EC on the service side (request + selector
        terms + labels; gang jobs additionally split per owner because
        each gang solves as its own atomic row)."""
        gang = dict(self.labels).get("gangScheduling", "") == "true"
        return (
            self.cpu, self.ram, self.labels, self.node_selector,
            self.pod_affinity, self.pod_anti_affinity,
            self.owner if gang else "",
        )


@dataclass(frozen=True)
class ScenarioRound:
    """One round's workload mutations (see module docstring table)."""

    round_index: int
    arrivals: Tuple[PodArrival, ...] = ()
    completions: int = 0
    deletions: int = 0
    drain_nodes: Tuple[str, ...] = ()
    add_nodes: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return {
            "round": self.round_index,
            "arrivals": [a.to_dict() for a in self.arrivals],
            "completions": self.completions,
            "deletions": self.deletions,
            "drain_nodes": list(self.drain_nodes),
            "add_nodes": list(self.add_nodes),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioRound":
        return cls(
            round_index=int(d["round"]),
            arrivals=tuple(
                PodArrival.from_dict(a) for a in d.get("arrivals") or []
            ),
            completions=int(d.get("completions", 0)),
            deletions=int(d.get("deletions", 0)),
            drain_nodes=tuple(
                str(n) for n in d.get("drain_nodes") or []
            ),
            add_nodes=tuple(str(n) for n in d.get("add_nodes") or []),
        )


@dataclass(frozen=True)
class ScenarioPlan:
    """A named, seeded workload schedule over a drive's rounds.

    ``node_labels`` assigns labels to the INITIAL fleet (and to nodes a
    round adds later) — the multi-tenant scenario zones its machines
    this way so nodeSelector terms resolve."""

    name: str
    seed: int
    machines: int
    rounds: Tuple[ScenarioRound, ...]
    node_labels: Tuple[Tuple[str, KVPairs], ...] = field(default=())

    def __post_init__(self) -> None:
        for i, rnd in enumerate(self.rounds):
            if rnd.round_index != i:
                raise ValueError(
                    f"plan {self.name!r}: round {i} carries "
                    f"round_index {rnd.round_index} — rounds must be "
                    "contiguous from 0"
                )

    @property
    def total_rounds(self) -> int:
        return len(self.rounds)

    def for_round(self, round_index: int) -> ScenarioRound:
        return self.rounds[round_index]

    def node_label_map(self) -> Dict[str, Dict[str, str]]:
        return {name: dict(pairs) for name, pairs in self.node_labels}

    def total_arrivals(self) -> int:
        return sum(len(r.arrivals) for r in self.rounds)

    def max_window_ec_keys(self, window: int = 3) -> int:
        """Upper bound on distinct ECs pending in any round: the union
        of arrival EC keys across a sliding window (unplaced work from
        round r-1/r-2 can still be pending alongside round r's).  The
        driver sizes the service's ``max_ecs`` bucket from this."""
        best = 1
        for r in range(self.total_rounds):
            keys = set()
            for rnd in self.rounds[max(r - window + 1, 0):r + 1]:
                keys.update(a.ec_key() for a in rnd.arrivals)
            best = max(best, len(keys))
        return best

    # ------------------------------------------------------------- wire form

    def to_dict(self) -> dict:
        return {
            "name": self.name, "seed": self.seed,
            "machines": self.machines,
            "rounds": [r.to_dict() for r in self.rounds],
            "node_labels": [
                [name, [list(p) for p in pairs]]
                for name, pairs in self.node_labels
            ],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioPlan":
        return cls(
            name=str(d["name"]), seed=int(d["seed"]),
            machines=int(d["machines"]),
            rounds=tuple(
                ScenarioRound.from_dict(r) for r in d["rounds"]
            ),
            node_labels=tuple(
                (str(name), tuple((str(k), str(v)) for k, v in pairs))
                for name, pairs in d.get("node_labels") or []
            ),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "ScenarioPlan":
        return cls.from_dict(json.loads(text))

    def digest(self) -> str:
        """Content digest of the materialized plan: the determinism
        tests pin that two same-seed generations are bit-identical."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:16]


def workload_events(plan: ScenarioPlan):
    """Lower a scenario plan onto the replay harness's ``TraceEvent``
    vocabulary (machines at t=0, arrivals grouped by shape as
    job_submits at 10 s round boundaries, node churn as machine
    add/remove) — the planner-only offline view of the population."""
    from poseidon_tpu.chaos.harness import NODE_CPU, NODE_RAM
    from poseidon_tpu.replay.trace import TraceEvent

    node_index: Dict[str, int] = {}
    events: List[TraceEvent] = []
    for i in range(plan.machines):
        node_index[f"m{i:04d}"] = i
        events.append(TraceEvent(0.0, "machine_add", (i, NODE_CPU, NODE_RAM)))
    horizon = 10.0 * (plan.total_rounds + 1)
    for rnd in plan.rounds:
        t = rnd.round_index * 10.0
        for name in rnd.add_nodes:
            idx = node_index.setdefault(name, len(node_index))
            events.append(TraceEvent(t, "machine_add", (idx, NODE_CPU, NODE_RAM)))
        for name in rnd.drain_nodes:
            if name in node_index:
                events.append(
                    TraceEvent(t, "machine_remove", (node_index[name],))
                )
        by_shape: Dict[tuple, int] = {}
        for a in rnd.arrivals:
            by_shape[(a.cpu, a.ram)] = by_shape.get((a.cpu, a.ram), 0) + 1
        for j, (shape, count) in enumerate(sorted(by_shape.items())):
            events.append(TraceEvent(
                t, "job_submit",
                (rnd.round_index * 100 + j, count, shape[0], shape[1],
                 horizon),
            ))
    events.sort(key=lambda e: (e.time, e.kind))
    return events
