"""Robustness scoring: scenarios under chaos-seeded cost perturbation.

The scorer re-drives a scenario with the planner's cost model wrapped
in a ``PerturbedCostModel``: every admissible cost cell gets a bounded,
deterministic noise term keyed on (perturbation seed, EC id, machine
uuid) — a pure per-cell hash, so the SAME (plan, perturbation seed)
always prices identically regardless of row/column order, and two
different seeds price like two different production cost surfaces.
Inadmissible arcs (INF_COST) are never touched, costs stay clipped to
the inner model's static ``max_cost`` bound (no fresh compile keys),
and EVERY correctness gate stays armed — byte-identity, the budget-0
ledger quartet, tier vocabulary.  Only the placements and the objective
are allowed to move.

The robustness metric is the objective-regression distribution across
perturbation seeds (the framing of "Robust Scheduling with GFlowNets",
PAPERS.md 2302.05446): for each seed, the relative objective regression
vs the unperturbed baseline; reported as p50/p90/max quantiles plus

    robustness_score = 1 / (1 + p90(|regression|))

so 1.0 means the schedule quality is insensitive to cost noise and the
score decays toward 0 as sensitivity grows.  A perturbed run that fails
ANY gate zeroes the score — a scheduler that diverges or recompiles
under cost noise is not robust, whatever its objective says.
"""

from __future__ import annotations

import hashlib
import logging
from typing import List, Optional, Sequence, Union

import numpy as np

from poseidon_tpu.costmodel.base import (
    CostMatrices,
    CostModel,
    NORMALIZED_COST,
)
from poseidon_tpu.scenario.plan import ScenarioPlan
from poseidon_tpu.utils.hatches import hatch_float, hatch_int

log = logging.getLogger("poseidon.scenario.score")

# Cost cells at or above this are inadmissibility sentinels, never
# perturbed (ops/transport.INF_COST is 1 << 28; every finite model cost
# is clipped to max_cost() <= 8 * NORMALIZED_COST, far below).
_ADMISSIBLE_BELOW = 1 << 28


def perturb_amplitude() -> float:
    """Perturbation amplitude as a fraction of NORMALIZED_COST
    (hatch-controlled)."""
    return hatch_float("POSEIDON_SCENARIO_AMPLITUDE")


def perturb_seed_count() -> int:
    """How many chaos-seeded perturbation runs a score uses
    (hatch-controlled)."""
    return hatch_int("POSEIDON_SCENARIO_SEEDS")


def _uuid_keys(uuids: Sequence[str]) -> np.ndarray:
    """Stable uint64 key per machine uuid (content hash, never
    Python's randomized ``hash``)."""
    return np.array(
        [
            int.from_bytes(
                hashlib.blake2b(u.encode(), digest_size=8).digest(),
                "little",
            )
            for u in uuids
        ],
        dtype=np.uint64,
    )


def _cell_noise(ec_ids: np.ndarray, uuid_keys: np.ndarray, seed: int,
                amplitude: float) -> np.ndarray:
    """int32 [E, M] noise in [-amplitude, +amplitude] * NORMALIZED_COST,
    a pure function of (seed, EC id, machine uuid) per cell — row/column
    slicing or reordering cannot change any cell's value."""
    with np.errstate(over="ignore"):
        row = ec_ids.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        col = uuid_keys * np.uint64(0xC2B2AE3D27D4EB4F)
        mix = (
            row[:, None] ^ col[None, :]
        ) + np.uint64(seed & 0xFFFFFFFFFFFFFFFF) * np.uint64(
            0xD6E8FEB86659FD93
        )
        # splitmix64-style finalizer: decorrelate the low bits.
        mix ^= mix >> np.uint64(30)
        mix *= np.uint64(0xBF58476D1CE4E5B9)
        mix ^= mix >> np.uint64(27)
        mix *= np.uint64(0x94D049BB133111EB)
        mix ^= mix >> np.uint64(31)
    frac = (mix >> np.uint64(11)).astype(np.float64) / float(1 << 53)
    return np.rint(
        (frac * 2.0 - 1.0) * amplitude * NORMALIZED_COST
    ).astype(np.int32)


class PerturbedCostModel(CostModel):
    """A cost model wrapper adding deterministic per-cell noise.

    ``delta_plane`` is forced off: the wrapper prices full builds only,
    so the delta-plane cache can never mix perturbed and unperturbed
    cells.  Capacity, arc capacity, and the unscheduled-cost vector are
    forwarded untouched — the perturbation moves preferences, not
    feasibility."""

    def __init__(self, inner: CostModel, *, seed: int,
                 amplitude: Optional[float] = None) -> None:
        self.inner = inner
        self.seed = int(seed)
        self.amplitude = (
            float(amplitude) if amplitude is not None
            else perturb_amplitude()
        )
        self.name = f"{inner.name}+perturb{self.seed}"

    delta_plane = False

    def build(self, ecs, machines) -> CostMatrices:
        cm = self.inner.build(ecs, machines)
        noise = _cell_noise(
            ecs.ec_ids, _uuid_keys(machines.uuids), self.seed,
            self.amplitude,
        )
        costs = cm.costs.copy()
        admissible = costs < _ADMISSIBLE_BELOW
        perturbed = np.clip(
            costs.astype(np.int64) + noise.astype(np.int64),
            0, self.inner.max_cost(),
        ).astype(np.int32)
        costs[admissible] = perturbed[admissible]
        return CostMatrices(
            costs=costs,
            unsched_cost=cm.unsched_cost,
            capacity=cm.capacity,
            arc_capacity=cm.arc_capacity,
        )

    def build_unsched(self, ecs) -> np.ndarray:
        return self.inner.build_unsched(ecs)

    def build_capacity(self, machines) -> np.ndarray:
        return self.inner.build_capacity(machines)

    def max_cost(self) -> int:
        return self.inner.max_cost()


def score_scenario(
    plan: Union[ScenarioPlan, str],
    *,
    machines: Optional[int] = None,
    rounds: Optional[int] = None,
    seed: int = 0,
    streaming: bool = False,
    baseline: Optional[dict] = None,
    perturb_seeds: Optional[Sequence[int]] = None,
    amplitude: Optional[float] = None,
) -> dict:
    """Robustness score for one scenario (see module docstring).

    ``baseline`` may pass in an existing unperturbed drive result (the
    bench rung reuses its identity-leg drive) — otherwise one is driven
    here.  ``perturb_seeds`` defaults to ``1..POSEIDON_SCENARIO_SEEDS``.
    """
    from poseidon_tpu.scenario.drive import drive_scenario
    from poseidon_tpu.scenario.generate import named_scenario

    if isinstance(plan, str):
        plan = named_scenario(
            plan, machines=machines or 32, rounds=rounds or 8, seed=seed
        )
    amplitude = (
        float(amplitude) if amplitude is not None else perturb_amplitude()
    )
    seeds = (
        tuple(perturb_seeds) if perturb_seeds is not None
        else tuple(range(1, perturb_seed_count() + 1))
    )
    base = baseline or drive_scenario(plan, streaming=streaming)
    runs: List[dict] = [
        drive_scenario(
            plan, streaming=streaming, perturb_seed=s,
            amplitude=amplitude,
        )
        for s in seeds
    ]
    base_obj = max(int(base.get("objective", 0)), 1)
    regressions = [
        (int(r.get("objective", 0)) - base_obj) / base_obj for r in runs
    ]
    abs_reg = [abs(x) for x in regressions]
    gates_ok = bool(base.get("ok")) and all(r.get("ok") for r in runs)
    p90 = float(np.percentile(abs_reg, 90)) if abs_reg else 0.0
    # How far the noise moves the PLACEMENTS, not just the price tag:
    # fraction of rounds whose placement digest left the baseline's.
    moved = []
    for r in runs:
        pairs = list(zip(base.get("digests") or [],
                         r.get("digests") or []))
        if pairs:
            moved.append(
                sum(1 for a, b in pairs if a != b) / len(pairs)
            )
    out = {
        "ok": gates_ok,
        "scenario": plan.name,
        "seed": plan.seed,
        "mode": base.get("mode"),
        "amplitude": amplitude,
        "perturb_seeds": list(seeds),
        "objective_base": int(base.get("objective", 0)),
        "objectives": [int(r.get("objective", 0)) for r in runs],
        "regressions": [round(x, 6) for x in regressions],
        "regression_p50": round(
            float(np.percentile(abs_reg, 50)) if abs_reg else 0.0, 6
        ),
        "regression_p90": round(p90, 6),
        "regression_max": round(max(abs_reg) if abs_reg else 0.0, 6),
        "placement_divergence": round(
            float(np.mean(moved)) if moved else 0.0, 4
        ),
        "robustness_score": (
            round(1.0 / (1.0 + p90), 4) if gates_ok else 0.0
        ),
        "gates_ok": gates_ok,
    }
    if not gates_ok:
        out["failures"] = [
            {"perturb_seed": s, "failure": r.get("failure")}
            for s, r in zip(seeds, runs) if not r.get("ok")
        ] + ([{"perturb_seed": None, "failure": base.get("failure")}]
             if not base.get("ok") else [])
    return out
