#!/usr/bin/env bash
# Real-cluster e2e driver — the analog of the reference's
# test/e2e-poseidon-local.sh (build release -> load images -> deploy ->
# run suite).  Requires docker + a kind cluster (https://kind.sigs.k8s.io).
#
# What it does:
#   1. builds the three images (deploy/Dockerfile targets)
#   2. loads them into the kind cluster
#   3. applies the manifests (scheduler core, glue, metrics agent)
#   4. submits the fixture workloads and asserts they get bound by
#      schedulerName=poseidon
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/.."

CLUSTER="${CLUSTER:-poseidon-e2e}"
NS=kube-system

command -v kind >/dev/null || { echo "kind not installed"; exit 1; }
kind get clusters | grep -qx "$CLUSTER" || kind create cluster --name "$CLUSTER"

./deploy/build_images.sh
for img in firmament-tpu poseidon metrics-agent; do
  kind load docker-image "poseidon-tpu/${img}:latest" --name "$CLUSTER"
done

kubectl apply -f deploy/firmament-tpu-deployment.yaml
kubectl apply -f deploy/poseidon-deployment.yaml
kubectl apply -f deploy/metrics-agent.yaml

kubectl -n "$NS" rollout status deploy/firmament-tpu-scheduler --timeout=300s
kubectl -n "$NS" rollout status deploy/poseidon --timeout=300s

# Workload smoke: a bare deployment scheduled by poseidon must go Running.
kubectl apply -f deploy/configs/nginx-deployment.yaml
kubectl rollout status deploy/nginx-poseidon --timeout=300s
echo "e2e: nginx-poseidon pods scheduled by poseidon:"
kubectl get pods -l app=nginx -o wide

# Scheduler-behavior predicates (the reference's
# test/e2e/poseidon_integration.go:409-478 nodeSelector pair; the full
# predicate set incl. 70%-fill runs in-process in tests/test_e2e_predicates.py).

# 1. NodeSelector NOT matching: must stay Pending.
kubectl delete pod restricted-pod --ignore-not-found
cat <<'POD' | kubectl apply -f -
apiVersion: v1
kind: Pod
metadata: {name: restricted-pod, labels: {name: restricted}}
spec:
  schedulerName: poseidon
  nodeSelector: {label: nonempty}
  containers:
  - name: pause
    image: registry.k8s.io/pause:3.9
POD
sleep 30
phase="$(kubectl get pod restricted-pod -o jsonpath='{.status.phase}')"
[ "$phase" = "Pending" ] || { echo "FAIL: restricted-pod phase=$phase (want Pending)"; exit 1; }
echo "e2e: non-matching nodeSelector stayed Pending"

# 2. NodeSelector matching: label a worker node, pod must land on it.
# (grep may match nothing on a single-node kind cluster — don't let
# pipefail kill the script; fall back to the control-plane node.)
node="$(kubectl get nodes -o name | { grep -v control-plane || true; } | head -1 | cut -d/ -f2)"
node="${node:-$(kubectl get nodes -o jsonpath='{.items[0].metadata.name}')}"
kubectl label node "$node" poseidon-e2e=42 --overwrite
kubectl delete pod with-labels --ignore-not-found
cat <<POD | kubectl apply -f -
apiVersion: v1
kind: Pod
metadata: {name: with-labels}
spec:
  schedulerName: poseidon
  nodeSelector: {poseidon-e2e: "42"}
  containers:
  - name: pause
    image: registry.k8s.io/pause:3.9
POD
kubectl wait pod/with-labels --for=jsonpath='{.spec.nodeName}'="$node" --timeout=120s
echo "e2e: matching nodeSelector landed on $node"
kubectl delete pod restricted-pod with-labels --ignore-not-found

# Throughput fixture (optional, big): uncomment to run the 1000-pod job.
# kubectl apply -f deploy/configs/cpu_spin_1000_pods.yaml

echo "e2e-local: PASS"
