#!/usr/bin/env bash
# Real-cluster e2e driver — the analog of the reference's
# test/e2e-poseidon-local.sh (build release -> load images -> deploy ->
# run suite).  Requires docker + a kind cluster (https://kind.sigs.k8s.io).
#
# What it does:
#   1. builds the three images (deploy/Dockerfile targets)
#   2. loads them into the kind cluster
#   3. applies the manifests (scheduler core, glue, metrics agent)
#   4. submits the fixture workloads and asserts they get bound by
#      schedulerName=poseidon
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/.."

CLUSTER="${CLUSTER:-poseidon-e2e}"
NS=kube-system

command -v kind >/dev/null || { echo "kind not installed"; exit 1; }
kind get clusters | grep -qx "$CLUSTER" || kind create cluster --name "$CLUSTER"

./deploy/build_images.sh
for img in firmament-tpu poseidon metrics-agent; do
  kind load docker-image "poseidon-tpu/${img}:latest" --name "$CLUSTER"
done

kubectl apply -f deploy/firmament-tpu-deployment.yaml
kubectl apply -f deploy/poseidon-deployment.yaml
kubectl apply -f deploy/metrics-agent.yaml

kubectl -n "$NS" rollout status deploy/firmament-tpu-scheduler --timeout=300s
kubectl -n "$NS" rollout status deploy/poseidon --timeout=300s

# Workload smoke: a bare deployment scheduled by poseidon must go Running.
kubectl apply -f deploy/configs/nginx-deployment.yaml
kubectl rollout status deploy/nginx-poseidon --timeout=300s
echo "e2e: nginx-poseidon pods scheduled by poseidon:"
kubectl get pods -l app=nginx -o wide

# Throughput fixture (optional, big): uncomment to run the 1000-pod job.
# kubectl apply -f deploy/configs/cpu_spin_1000_pods.yaml

echo "e2e-local: PASS"
