"""Tiny-scale features-config regression gate (``make bench-smoke``).

Runs bench.run_features at ~200 machines on CPU — the same code path the
cluster-scale bench drives, with the same semantic predicates (selector
violations zero, affinity co-location total, gang atomicity) — so a
feature-path latency or semantics breakage is caught without paying the
full 10k-machine bench.  Slow-marked: excluded from the tier-1 gate, run
via ``make bench-smoke`` or ``pytest -m slow``.
"""

import pytest

pytestmark = pytest.mark.slow


def test_features_config_smoke():
    import bench

    # rounds=2 so a WARM churn round exists: bench wraps it (and the
    # gang round) in CompileLedger(budget=0), so a silent retrace in
    # the warm path fails this test with the compiled program names —
    # the runtime side of PR 3's zero-fresh-compiles invariant.
    out = bench.run_features(200, rounds=2)
    assert out["ok"], out

    sel = out["selectors"]
    assert sel["violations"] == 0
    assert sel["zoned_placed"] == sel["zoned_total"] > 0
    # The ledger-fed artifact columns: warm rounds compiled nothing.
    assert len(sel["fresh_compiles"]) == 2
    assert sel["warm_fresh_compiles"] == 0
    assert out["pod_affinity"]["fresh_compiles"] == 0
    assert out["gang"]["fresh_compiles"] == 0

    aff = out["pod_affinity"]
    assert aff["colocated"] == aff["targets"] > 0

    g = out["gang"]
    assert g["placed_gangs"] == g["gangs"] > 0
    assert g["partial_gangs"] == 0
    assert g["oversized_gang_placed"] == 0
    # The solve-side telemetry contract: repair/pruned work must be
    # visible in the artifact, not inferred from wall time.
    for key in ("solve_iters", "bf_sweeps", "repair_firings", "pruned"):
        assert key in g, f"gang sub-report missing {key}"
    for key in ("bands", "shortlist_width", "price_out_rounds",
                "escalations"):
        assert key in g["pruned"], f"pruned stats missing {key}"
