"""Tiny-scale features-config regression gate (``make bench-smoke``).

Runs bench.run_features at ~200 machines on CPU — the same code path the
cluster-scale bench drives, with the same semantic predicates (selector
violations zero, affinity co-location total, gang atomicity) — so a
feature-path latency or semantics breakage is caught without paying the
full 10k-machine bench.  Slow-marked: excluded from the tier-1 gate, run
via ``make bench-smoke`` or ``pytest -m slow``.
"""

import pytest

pytestmark = pytest.mark.slow


def test_features_config_smoke():
    import bench

    # rounds=2 so a WARM churn round exists: bench wraps it (and the
    # gang round) in CompileLedger(budget=0), so a silent retrace in
    # the warm path fails this test with the compiled program names —
    # the runtime side of PR 3's zero-fresh-compiles invariant.
    out = bench.run_features(200, rounds=2)
    assert out["ok"], out

    sel = out["selectors"]
    assert sel["violations"] == 0
    assert sel["zoned_placed"] == sel["zoned_total"] > 0
    # The ledger-fed artifact columns: warm rounds compiled nothing.
    assert len(sel["fresh_compiles"]) == 2
    assert sel["warm_fresh_compiles"] == 0
    assert out["pod_affinity"]["fresh_compiles"] == 0
    assert out["gang"]["fresh_compiles"] == 0

    aff = out["pod_affinity"]
    assert aff["colocated"] == aff["targets"] > 0

    # Delta-plane telemetry rides the artifact (the hits themselves
    # belong to the steady-state churn loop — see
    # test_churn_rounds_serve_incrementally below).
    assert "cost_delta_hits" in sel
    assert "cost_delta_hits" in out["pod_affinity"]["round_metrics"]

    g = out["gang"]
    assert g["placed_gangs"] == g["gangs"] > 0
    assert g["partial_gangs"] == 0
    assert g["oversized_gang_placed"] == 0
    # The solve-side telemetry contract: repair/pruned work must be
    # visible in the artifact, not inferred from wall time.
    for key in ("solve_iters", "bf_sweeps", "repair_firings", "pruned"):
        assert key in g, f"gang sub-report missing {key}"
    for key in ("bands", "shortlist_width", "price_out_rounds",
                "escalations"):
        assert key in g["pruned"], f"pruned stats missing {key}"


def test_churn_rounds_serve_incrementally(monkeypatch):
    """The acceptance invariant for the incremental round engine:
    steady-state churn rounds (same-shape resubmissions, the
    ``churn_step`` loop the rung bench measures) NEVER rebuild the full
    cost plane — every one is a delta hit with small rebuild counts."""
    monkeypatch.setenv("POSEIDON_COST_DELTA_MIN_CELLS", "1")
    monkeypatch.setenv("POSEIDON_COST_DELTA_MIN_ROWS", "1")
    import numpy as np

    import bench

    # The rung's steady-state regime scaled down PRESERVING the churn-
    # tasks-per-EC-shape ratio (10k rung: 1000 churn tasks over 100
    # shapes -> every pending EC row recurs round over round; rows stay
    # clean and only the churned columns rebuild).  A shape-rich tiny
    # cluster instead turns over its whole pending EC set each round,
    # where the full rebuild is the RIGHT answer.
    state = bench.build_cluster(200, 2000, 4, seed=0)
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    planner.schedule_round()  # cold round: full builds expected
    rng = np.random.default_rng(7)
    delta_rounds = 0
    for r in range(5):
        bench.churn_step(state, rng, frac=200)
        _, m = planner.schedule_round()
        if m.cost_delta_hits:
            delta_rounds += 1
            # A hit must be INCREMENTAL: only the churned columns
            # rebuild, not the plane (200 machines here).
            assert m.cost_cols_rebuilt <= 40 * m.cost_delta_hits, (
                f"round {r}: delta hit rebuilt "
                f"{m.cost_cols_rebuilt} columns"
            )
    # Round 1 pays the band's first snapshot, and a round whose tiny
    # pending-EC set turned over legitimately full-rebuilds (one new
    # row is 200 columns of work against a 3x200/4 budget) — but the
    # steady rounds in between MUST serve incrementally.
    assert delta_rounds >= 2, (
        f"only {delta_rounds}/5 churn rounds served incrementally"
    )


def test_wave_rung_smoke_warm_rounds_compile_free():
    """Tiny wave rung (the satellite the wave path never had): a cold
    wave round compiles, then — after the production-shaped precompile —
    a FRESH-population warm wave and a churn round must both run under
    ``CompileLedger(budget=0)``.  A ladder-schedule or adaptive-cadence
    value leaking into a compile key would retrace here and fail with
    the compiled program names."""
    import numpy as np

    import bench
    from poseidon_tpu.check.ledger import (
        CompileLedger,
        TransferLedger,
    )
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    state = bench.build_cluster(200, 2000, 16, seed=0)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    _, m_cold = planner.schedule_round()  # cold round: compiles expected
    assert m_cold.placed > 0
    planner.precompile(max_ecs=16)

    # Fresh wave: drain + resubmit NEW random shapes (new EC ids, new
    # costs — the bench rung's wave semantics, scaled down).
    for uid in list(state.tasks.keys()):
        state.task_removed(uid)
    bench.submit_population(state, 2000, 16, seed=1)
    with CompileLedger(budget=0, label="warm wave round"), \
            TransferLedger(budget=0, label="warm wave round"):
        _, m_wave = planner.schedule_round()
    assert m_wave.placed > 0
    assert m_wave.converged
    assert m_wave.gap_bound == 0.0
    # The device series the rung artifact now gates ride RoundMetrics:
    # a solved round must carry a real per-phase split, and the entry
    # phase must be in the ladder's range (the field is NUM_PHASES for
    # no-solve rounds — this round solved).
    from poseidon_tpu.ops.transport import NUM_PHASES

    assert 0 <= m_wave.ladder_entry_phase <= NUM_PHASES
    assert len(m_wave.solve_phase_iters) == NUM_PHASES
    assert sum(m_wave.solve_phase_iters) >= 0

    rng = np.random.default_rng(5)
    bench.churn_step(state, rng)
    with CompileLedger(budget=0, label="warm churn round"), \
            TransferLedger(budget=0, label="warm churn round"):
        _, m_churn = planner.schedule_round()
    assert m_churn.converged
    # The warm rounds above just PROVED budget 0; the telemetry field
    # must agree and ride the wire format.
    assert m_wave.implicit_transfers == 0
    assert m_churn.implicit_transfers == 0
    assert "implicit_transfers" in m_churn.to_dict()


def test_sharded_mesh_rung_warm_budget0(monkeypatch):
    """Tiny mesh rung: a warm SHARDED band round must hold both ledgers
    at budget 0 — the mesh-split kernel is a first-class citizen of the
    compile-key ladder and the transfer discipline, not a special case
    (conftest forces 8 virtual CPU devices, so the tier mesh is live
    everywhere this suite runs, including ``make bench-smoke``)."""
    import numpy as np

    import bench
    from poseidon_tpu.check.ledger import (
        CompileLedger,
        TransferLedger,
    )
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo

    monkeypatch.setenv("POSEIDON_SHARDED_BANDS", "1")
    monkeypatch.setenv("POSEIDON_SHARDED_MIN_COLS", "64")
    monkeypatch.setenv("POSEIDON_SHARDED_MIN_CONTENTION", "1")

    # 64 machines: a quarter-octave bucket the 8-device mesh divides.
    state = ClusterState()
    rng = np.random.default_rng(0)
    for i in range(64):
        state.node_added(MachineInfo(
            uuid=f"mr-m{i}", cpu_capacity=int(rng.integers(4000, 16000)),
            ram_capacity=1 << 24, task_slots=8,
        ))
    bench.submit_population(state, 600, 8, seed=0)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    _, m_cold = planner.schedule_round()  # cold: compiles expected
    assert m_cold.solve_tier == "sharded", m_cold.solve_tier
    planner.precompile(max_ecs=8)

    bench.churn_step(state, rng, frac=50)
    with CompileLedger(budget=0, label="warm sharded round"), \
            TransferLedger(budget=0, label="warm sharded round"):
        _, m = planner.schedule_round()
    assert m.solve_tier == "sharded"
    assert m.sharded_bands >= 1 and m.shard_devices == 8
    assert m.converged and m.gap_bound == 0.0
    assert m.fresh_compiles == 0
    assert m.implicit_transfers == 0


def test_strided_shards_flatten_lopsided_lanes(monkeypatch):
    """The PERF.md round-10 pathology, reproduced at smoke scale: when
    machine capacity correlates with column index (fleets are commonly
    listed in provisioning order, so contiguous uuid ranges share a
    hardware generation), contiguous column blocks concentrate the big
    contended machines in one shard and its lane does ~all the sweep
    work.  Strided assignment (machine ``i`` -> shard ``i % n_dev``)
    deals every capacity tier across all lanes.  Same solve either way
    — the permutation is undone before results leave the kernel — so
    objective and placement count must be bit-identical while
    ``shard_imbalance`` drops."""
    import numpy as np

    import bench
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo

    monkeypatch.setenv("POSEIDON_SHARDED_BANDS", "1")
    monkeypatch.setenv("POSEIDON_SHARDED_MIN_COLS", "64")
    monkeypatch.setenv("POSEIDON_SHARDED_MIN_CONTENTION", "1")

    def solve(strided: bool):
        monkeypatch.setenv(
            "POSEIDON_SHARD_STRIDED", "1" if strided else "0"
        )
        state = ClusterState()
        # Ascending capacity ramp: the contended tail of the column
        # range lands entirely in the last contiguous shard.
        for i in range(64):
            state.node_added(MachineInfo(
                uuid=f"mr-m{i:03d}", cpu_capacity=2000 + i * 450,
                ram_capacity=1 << 24, task_slots=8,
            ))
        bench.submit_population(state, 600, 8, seed=0)
        planner = RoundPlanner(state, get_cost_model("cpu_mem"))
        _, m = planner.schedule_round()
        assert m.solve_tier == "sharded", m.solve_tier
        return m

    contig = solve(strided=False)
    strided = solve(strided=True)
    # Solution parity: striding is a layout choice, not a solver change.
    assert strided.objective == contig.objective
    assert strided.placed == contig.placed
    # The point of the satellite: the lopsided lanes flatten.
    assert strided.shard_imbalance < contig.shard_imbalance, (
        f"strided {strided.shard_imbalance} !< "
        f"contiguous {contig.shard_imbalance}"
    )


def test_saturation_probe_never_wraps_silently():
    """The cluster rung's saturation leg at its native tiny scale:
    supplies past the int32 cliff are REFUSED by the host-boundary
    flow-sum certificate, and a dispatchable at-the-cliff instance
    comes back with the telemetry saturation lane clamped+flagged and
    the rail-riding fetch attributed to the open NumericsLedger —
    never a silent two's-complement wrap."""
    import bench

    out = bench.run_saturation_probe()
    assert out["ok"], out
    assert out["certificate_tripped"]
    assert out["saturated_samples"] > 0
    assert out["ledger_anomalies"] > 0
    assert not out["wrap_observed"]
    assert out["max_active_excess"] > 0  # clamped at the rail, not -2^31
