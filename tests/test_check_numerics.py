"""Numerics-discipline suite: runtime ledger + certified helpers + the
static rule's integration seams.

The static rule's fixture counts live in test_check_selfcheck.py; this
file pins the RUNTIME half and the places the two halves meet:

- utils.numerics saturation certificates (widen/narrow/total/headroom)
  raise ``SaturationError`` naming the site and feed the process-wide
  anomaly counter — never a silent wrap;
- ``NumericsLedger(budget=0)`` window semantics: attribution, offender
  naming, telemetry mode, exception transparency;
- a seeded overflow trips the static rule AND the runtime ledger (the
  same hazard, caught by both halves);
- the inf-sentinel lattice follows a plane through a jitted producer;
- a promotion hazard at a jit boundary shaped like the real ops/
  wrappers is flagged;
- ``transport.host_fetch`` validates fetched leaves only when enabled,
  and a real solve is ledger-clean under ``POSEIDON_NUMERICS_LEDGER``;
- regression pins for the audited real findings: the cpu_mem fit-count
  clamp, the residency int64 certified view, the telemetry ring's
  saturating active-excess lane (satellite bugfix) and its decode;
- ``RoundMetrics.numeric_anomalies`` rides the wire format and the
  Prometheus exporter without touching either.
"""

from __future__ import annotations

from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.check import check_file
from poseidon_tpu.check.ledger import (
    I32_FETCH_HEADROOM,
    NumericsBudgetExceeded,
    NumericsLedger,
    maybe_validate_fetched,
    note_numeric_anomaly,
    numeric_anomaly_count,
    numerics_enabled,
)
from poseidon_tpu.check.numerics_discipline import NumericsDisciplineRule
from poseidon_tpu.utils.numerics import (
    COUNT_HEADROOM,
    I32_MAX,
    I32_MIN,
    SaturationError,
    certify_i32,
    certify_i32_total,
    checked_narrow_i32,
    i32_headroom,
    widen_counts,
)

REPO = Path(__file__).parent.parent


def _rule_findings(path: Path, root: Path):
    """check() + finalize() — the numerics rule judges sentinel flow
    and jit-boundary literals in finalize()."""
    rule = NumericsDisciplineRule()
    pre = check_file(path, [rule], forced=True, root=root)
    return pre + rule.finalize()


# ----------------------------------------------------- certified helpers


def test_certify_i32_passes_inside_band():
    a = np.array([0, 5, -5, COUNT_HEADROOM - 1], dtype=np.int32)
    assert certify_i32(a, site="t") is a          # zero-copy certificate
    assert certify_i32(np.empty(0, np.int32), site="t").size == 0


def test_certify_i32_trips_and_counts():
    a = np.array([0, I32_MAX - 3], dtype=np.int32)
    c0 = numeric_anomaly_count()
    with pytest.raises(SaturationError, match="test.site"):
        certify_i32(a, site="test.site")
    assert numeric_anomaly_count() == c0 + 1


def test_widen_counts_certifies_then_widens():
    a = np.array([[1, 2], [3, 4]], dtype=np.int32)
    w = widen_counts(a, site="t")
    assert w.dtype == np.int64
    assert (w == a).all()
    with pytest.raises(SaturationError):
        widen_counts(
            np.array([I32_MAX - 1], dtype=np.int32), site="t"
        )


def test_certify_i32_total_bounds_the_sum():
    a = np.full(8, 1000, dtype=np.int32)
    assert certify_i32_total(a, site="t") == 8000
    assert certify_i32_total(np.empty(0, np.int32), site="t") == 0
    # Each element fits int32; the SUM does not — the in-kernel flow
    # reductions this certificate covers would wrap.
    hot = np.full(4, 1 << 30, dtype=np.int32)
    with pytest.raises(SaturationError, match="flow sums would wrap"):
        certify_i32_total(hot, site="t")


def test_checked_narrow_clamps_or_raises():
    wide = np.array([-5.0, 10.0, 3e10], dtype=np.float64)
    out = checked_narrow_i32(wide, site="t", lo=0, hi=1 << 20)
    assert out.dtype == np.int32
    assert out.tolist() == [0, 10, 1 << 20]
    with pytest.raises(SaturationError, match="not declared legal"):
        checked_narrow_i32(wide, site="t", lo=0, hi=1 << 20, clamp=False)
    with pytest.raises(ValueError):
        checked_narrow_i32(wide, site="t", lo=0, hi=1 << 40)


def test_i32_headroom():
    assert i32_headroom(np.empty(0, np.int32)) is None
    a = np.array([I32_MAX - 7, 0], dtype=np.int32)
    assert i32_headroom(a) == 7


# ------------------------------------------------------- ledger windows


def test_ledger_clean_window_passes():
    c0 = numeric_anomaly_count()
    with NumericsLedger(budget=0, label="clean") as led:
        pass
    assert led.anomalies == 0
    assert numeric_anomaly_count() == c0


def test_ledger_budget_zero_trips_with_offender_name():
    with pytest.raises(NumericsBudgetExceeded, match="seeded.wrap"):
        with NumericsLedger(budget=0, label="unit window"):
            note_numeric_anomaly("seeded.wrap: fixture anomaly")


def test_ledger_telemetry_mode_records_without_raising():
    with NumericsLedger(budget=None, label="telemetry") as led:
        note_numeric_anomaly("t1")
        note_numeric_anomaly("t2")
    assert led.anomalies == 2
    assert led.offenders == ["t1", "t2"]


def test_ledger_does_not_mask_body_exceptions():
    with pytest.raises(KeyError):
        with NumericsLedger(budget=0):
            note_numeric_anomaly("anomaly before the crash")
            raise KeyError("primary failure")


# ------------------------------------- the static rule meets the runtime


def test_seeded_overflow_trips_static_rule_and_ledger(tmp_path):
    """ONE hazard, both halves: an unwidened i32 reduction is a static
    finding, and executing the equivalent accumulation through the
    certified helper trips a budget-0 ledger window at runtime."""
    mod = tmp_path / "counts.py"
    mod.write_text(
        "import numpy as np\n\n\n"
        "def tally():\n"
        "    counts = np.zeros((4, 4), dtype=np.int32)\n"
        "    return np.sum(counts)\n"
    )
    found = _rule_findings(mod, tmp_path)
    assert len(found) == 1
    assert found[0].rule == "numerics"
    assert found[0].message.startswith("i32-overflow:")

    hot = np.full(4, I32_MAX - 2, dtype=np.int32)
    c0 = numeric_anomaly_count()
    with pytest.raises(NumericsBudgetExceeded, match="test.seeded"):
        with NumericsLedger(budget=0, label="seeded overflow"):
            try:
                widen_counts(hot, site="test.seeded")
            except SaturationError:
                pass  # certificate fired; the window still owes budget 0
    assert numeric_anomaly_count() == c0 + 1


def test_sentinel_lattice_through_jitted_producer(tmp_path):
    """The inf-sentinel lattice is cross-function: a plane seeded inside
    a jitted producer taints the CALLER's arithmetic on the result."""
    mod = tmp_path / "plane.py"
    mod.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n\n"
        "INF_COST = 1 << 28\n\n\n"
        "@jax.jit\n"
        "def _plane(c):\n"
        "    p = jnp.where(c > 3, INF_COST, c)\n"
        "    return p\n\n\n"
        "def consume(c):\n"
        "    out = _plane(c)\n"
        "    return np.sum(out)\n"
    )
    found = _rule_findings(mod, tmp_path)
    assert len(found) == 1
    assert found[0].message.startswith("inf-sentinel:")
    assert "sum" in found[0].message


def test_promotion_at_ops_shaped_jit_boundary(tmp_path):
    """The promotion sub-rule at the seam the real ops/ wrappers have:
    a jitted kernel taking a scale argument, called with a bare Python
    float — a weak scalar whose promotion XLA decides, not the author."""
    mod = tmp_path / "kern.py"
    mod.write_text(
        "import jax\n\n\n"
        "@jax.jit\n"
        "def kern(x, s):\n"
        "    return x * s\n\n\n"
        "def boundary(x):\n"
        "    return kern(x, 0.5)\n"
    )
    found = _rule_findings(mod, tmp_path)
    assert len(found) == 1
    assert found[0].message.startswith("promotion:")
    assert found[0].line == 10  # the call site, not the kernel


# -------------------------------------------------- host_fetch boundary


def test_validation_off_by_default(monkeypatch):
    monkeypatch.delenv("POSEIDON_NUMERICS_LEDGER", raising=False)
    assert not numerics_enabled()
    c0 = numeric_anomaly_count()
    maybe_validate_fetched(np.array([np.inf], dtype=np.float32))
    assert numeric_anomaly_count() == c0  # one dict probe, no scan


def test_fetch_validation_flags_nonfinite_and_rails():
    c0 = numeric_anomaly_count()
    with NumericsLedger(budget=None, label="fetch") as led:
        maybe_validate_fetched(
            {"a": np.array([1.0, np.inf], dtype=np.float32)},
            site="unit.fetch",
        )
        maybe_validate_fetched(
            np.array([I32_MAX - 5], dtype=np.int32), site="unit.rails"
        )
        # Clean leaves cost nothing: finite floats, int32 with headroom,
        # non-array leaves.
        maybe_validate_fetched(
            (np.zeros(3, np.float32),
             np.array([I32_MAX - I32_FETCH_HEADROOM], dtype=np.int32),
             7, "label"),
            site="unit.clean",
        )
    assert numeric_anomaly_count() == c0 + 2
    assert led.anomalies == 2
    assert any("unit.fetch" in o and "non-finite" in o
               for o in led.offenders)
    assert any("unit.rails" in o and "rails" in o for o in led.offenders)


def test_real_solve_is_ledger_clean(monkeypatch):
    """The acceptance shape: a real ops/ solve inside a budget-0 window
    with the hatch on — every host_fetch leaf validated, zero
    anomalies."""
    from poseidon_tpu.ops.transport import INF_COST, solve_transport

    monkeypatch.setenv("POSEIDON_NUMERICS_LEDGER", "1")
    assert numerics_enabled()
    rng = np.random.default_rng(7)
    costs = rng.integers(0, 1000, size=(6, 5)).astype(np.int32)
    costs[rng.random((6, 5)) < 0.1] = INF_COST
    supply = rng.integers(1, 4, size=6).astype(np.int32)
    capacity = rng.integers(1, 5, size=5).astype(np.int32)
    unsched = rng.integers(1000, 2000, size=6).astype(np.int32)
    with NumericsLedger(budget=0, label="real solve") as led:
        sol = solve_transport(costs, supply, capacity, unsched)
    assert sol.flows.shape == (6, 5)
    assert led.anomalies == 0


def test_solve_transport_certifies_supply_total():
    """The host-boundary flow-sum certificate: a supply vector whose
    TOTAL would wrap the in-kernel int32 reductions is rejected at
    dispatch, never solved silently."""
    from poseidon_tpu.ops.transport import INF_COST, solve_transport

    E, M = 4, 3
    costs = np.full((E, M), 10, dtype=np.int32)
    supply = np.full(E, 1 << 30, dtype=np.int32)   # sum = 2^32: wraps
    capacity = np.full(M, 2, dtype=np.int32)
    unsched = np.full(E, 100, dtype=np.int32)
    with pytest.raises(SaturationError, match="solve_transport.supply"):
        solve_transport(costs, supply, capacity, unsched)


# ------------------------------------------------- audited-finding pins


def test_cpu_mem_fit_count_clamps_not_wraps():
    """PR 2's bug class, re-audited this PR: a huge-free/tiny-request
    fit count past 2^31 must clamp at big_fit, not wrap negative
    through astype(int32).  Covers the finite-overflow cell, the
    zero-request inf cell, and a normal cell."""
    from poseidon_tpu.costmodel.base import ECTable, MachineTable
    from poseidon_tpu.costmodel.cpu_mem import CpuMemCostModel

    big_fit = np.iinfo(np.int32).max // 4
    ecs = ECTable(
        ec_ids=np.arange(2, dtype=np.uint64),
        cpu_request=np.array([1, 0], dtype=np.int64),
        ram_request=np.array([1, 1], dtype=np.int64),
        supply=np.ones(2, dtype=np.int32),
        priority=np.zeros(2, dtype=np.int32),
        task_type=np.zeros(2, dtype=np.int32),
        max_wait_rounds=np.zeros(2, dtype=np.int32),
        selectors=[(), ()],
    )
    machines = MachineTable(
        uuids=["m0", "m1"],
        cpu_capacity=np.array([3 << 30, 64], dtype=np.int64),
        ram_capacity=np.array([3 << 30, 64], dtype=np.int64),
        cpu_used=np.zeros(2, dtype=np.int64),
        ram_used=np.zeros(2, dtype=np.int64),
        cpu_util=np.zeros(2, dtype=np.float32),
        mem_util=np.zeros(2, dtype=np.float32),
        slots_free=np.full(2, 10, dtype=np.int32),
        labels=[{}, {}],
    )
    mats = CpuMemCostModel().build(ecs, machines)
    assert (mats.arc_capacity >= 0).all()          # no wrap anywhere
    # EC0 x m0: 3*2^30 fits of size 1 — finite, past int32, clamped.
    assert mats.arc_capacity[0, 0] == big_fit
    # EC1 (zero cpu request) x m0: inf fit count, clamped the same way.
    assert mats.arc_capacity[1, 0] == big_fit
    # Normal cell stays exact.
    assert mats.arc_capacity[0, 1] == 64


def test_residency_view_is_certified_int64():
    from poseidon_tpu.graph.residency import ResidentLabelIndex

    idx = ResidentLabelIndex()
    idx.activate()
    idx.add("m0", {"app": "db"})
    idx.add("m0", {"app": "db"})
    idx.add("m1", {"app": "web"})
    view = idx.view(["m0", "m1"])
    assert view.kv_counts.dtype == np.int64
    assert view.key_counts.dtype == np.int64
    assert view.kv_counts[0, view.kv_id[("app", "db")]] == 2
    assert view.kv_counts[1, view.kv_id[("app", "web")]] == 1


# ------------------------------------- telemetry saturation (satellite)


def test_active_excess_exact_below_threshold():
    from poseidon_tpu.ops.transport import _active_excess_sat

    exc_e = jnp.array([100, -50, 200], dtype=jnp.int32)
    exc_m = jnp.array([[5, 0], [-3, 500]], dtype=jnp.int32)
    tot, sat = _active_excess_sat(exc_e, exc_m, jnp.int32(0))
    assert int(tot) == 100 + 200 + 5 + 500        # bit-exact, shapes mix
    assert not bool(sat)


def test_active_excess_saturates_at_cluster_scale():
    from poseidon_tpu.ops.transport import _EXCESS_SAT, _active_excess_sat

    # Each element far below int32; the SUM is past 2^31 and the bare
    # int32 reduction XLA runs would wrap it negative.
    exc_e = jnp.full(5, 1 << 29, dtype=jnp.int32)
    tot, sat = _active_excess_sat(
        exc_e, jnp.zeros(1, jnp.int32), jnp.int32(0)
    )
    assert bool(sat)
    assert int(tot) == _EXCESS_SAT                 # clamped, flagged
    assert int(tot) > 0                            # never negative


def test_decode_telemetry_carries_saturation_lane():
    from poseidon_tpu.ops.transport import (
        TELEM_ROWS,
        _TR_SAT,
        decode_telemetry,
    )

    ring = np.zeros((TELEM_ROWS, 4), dtype=np.int32)
    ring[_TR_SAT, :] = [0, 1, 1, 0]
    t = decode_telemetry(ring, 4)
    assert t.saturated.tolist() == [0, 1, 1, 0]
    assert t.saturated_samples() == 2
    assert t.digest()["saturated_samples"] == 2


# ----------------------------------------------------- metrics plumbing


def test_numeric_anomalies_rides_wire_format_and_metrics():
    from poseidon_tpu.graph.instance import RoundMetrics
    from poseidon_tpu.obs.metrics import Registry, observe_round

    m = RoundMetrics(round_index=4, numeric_anomalies=3)
    d = m.to_dict()
    assert d["numeric_anomalies"] == 3
    back = RoundMetrics.from_dict(d)
    assert back.numeric_anomalies == 3

    reg = Registry()
    observe_round(m, reg)
    assert "poseidon_round_numeric_anomalies 3" in reg.expose()
