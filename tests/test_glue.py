"""Integration tier: FakeKube + Poseidon glue + real firmament-tpu service.

The reference's e2e suite drives real workloads through a cluster
(test/e2e/poseidon_integration.go: bare Pod, Deployment/ReplicaSet/Job
grouping, resource-limit packing, NodeSelector respected/not-matching).
This tier runs the same scenarios fully in-process: the fake cluster feeds
the watchers, the real gRPC service schedules, and the loop enacts deltas
back into the fake cluster.
"""

import threading
import time

import grpc
import pytest

from poseidon_tpu.glue import FakeKube, Node, Pod, Poseidon
from poseidon_tpu.glue.keyed_queue import KeyedQueue
from poseidon_tpu.protos import stats_pb2 as spb
from poseidon_tpu.protos.services import STATS_METHODS, STATS_SERVICE, make_stubs
from poseidon_tpu.service import FirmamentTPUServer
from poseidon_tpu.utils.config import PoseidonConfig


# ---------------------------------------------------------------- keyed queue


class TestKeyedQueue:
    def test_batching_and_ordering(self):
        q = KeyedQueue()
        q.add("a", 1)
        q.add("a", 2)
        q.add("b", 3)
        key, items = q.get()
        assert (key, items) == ("a", [1, 2])
        key2, items2 = q.get()
        assert (key2, items2) == ("b", [3])

    def test_processing_key_parks(self):
        q = KeyedQueue()
        q.add("a", 1)
        key, _ = q.get()          # "a" now processing
        q.add("a", 2)             # parks
        q.add("b", 3)
        key2, items2 = q.get()
        assert key2 == "b"        # parked "a" not re-issued yet
        q.done("a")               # releases parked items
        key3, items3 = q.get()
        assert (key3, items3) == ("a", [2])

    def test_oldest_age_tracks_undelivered_head(self):
        """The ingest-lag gauge's source: age of the oldest key still
        waiting for delivery — None when nothing waits, re-armed when a
        parked key's items re-enter the ready set."""
        q = KeyedQueue()
        assert q.oldest_age_s() is None
        q.add("a", 1)
        age = q.oldest_age_s()
        assert age is not None and age >= 0.0
        q.get()  # "a" delivered (processing)
        assert q.oldest_age_s() is None
        q.add("a", 2)  # parks behind the in-flight batch
        q.done("a")  # parked items re-enter; lag clock restarts here
        assert q.oldest_age_s() is not None
        q.get()
        assert q.oldest_age_s() is None

    def test_shutdown_unblocks(self):
        q = KeyedQueue()
        out = []

        def getter():
            out.append(q.get())

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.05)
        q.shut_down()
        t.join(timeout=2)
        assert out == [None]


# ------------------------------------------------------------ the full system


@pytest.fixture()
def system():
    with FirmamentTPUServer(address="127.0.0.1:0") as server:
        kube = FakeKube()
        cfg = PoseidonConfig(
            firmament_address=server.address, scheduling_interval=3600
        )
        # Loop disabled: tests drive rounds explicitly via schedule_once().
        poseidon = Poseidon(
            kube, config=cfg, stats_address="127.0.0.1:0", run_loop=False
        ).start(health_timeout=10)
        try:
            yield kube, poseidon, server
        finally:
            poseidon.stop()


def test_bare_pod_is_scheduled(system):
    kube, poseidon, _ = system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 20))
    assert poseidon.drain_watchers()
    deltas = poseidon.schedule_once()
    assert len(deltas) == 1
    assert kube.bindings == [("default/p1", "n1")]
    assert kube.pods["default/p1"].phase == "Running"


def test_owner_grouped_pods_one_job(system):
    kube, poseidon, _ = system
    for i in range(3):
        kube.add_node(
            Node(name=f"n{i}", cpu_capacity=4000, ram_capacity=1 << 24)
        )
    for i in range(6):
        kube.create_pod(
            Pod(
                name=f"web-{i}", owner_uid="rs-uid-1",
                cpu_request=500, ram_request=1 << 20,
            )
        )
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert len(kube.bindings) == 6
    assert all(p.phase == "Running" for p in kube.pods.values())


def test_unschedulable_pod_stays_pending(system):
    """Packing predicate (poseidon_integration.go:294-407): an oversized
    pod must stay Pending while a fitting one schedules."""
    kube, poseidon, _ = system
    kube.add_node(Node(name="small", cpu_capacity=1000, ram_capacity=1 << 20))
    kube.create_pod(Pod(name="fits", cpu_request=500, ram_request=1 << 18))
    kube.create_pod(Pod(name="huge", cpu_request=64000, ram_request=1 << 30))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert kube.pods["default/fits"].phase == "Running"
    assert kube.pods["default/huge"].phase == "Pending"
    assert ("default/huge", "small") not in kube.bindings


def test_node_selector_respected(system):
    """NodeSelector predicates (poseidon_integration.go:409-478)."""
    kube, poseidon, _ = system
    kube.add_node(
        Node(name="ssd-node", cpu_capacity=4000, ram_capacity=1 << 24,
             labels={"disktype": "ssd"})
    )
    kube.add_node(
        Node(name="hdd-node", cpu_capacity=4000, ram_capacity=1 << 24)
    )
    kube.create_pod(
        Pod(name="picky", cpu_request=100, ram_request=1 << 18,
            node_selector={"disktype": "ssd"})
    )
    kube.create_pod(
        Pod(name="impossible", cpu_request=100, ram_request=1 << 18,
            node_selector={"disktype": "nvme"})
    )
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert ("default/picky", "ssd-node") in kube.bindings
    assert kube.pods["default/impossible"].phase == "Pending"


def test_unschedulable_node_skipped(system):
    kube, poseidon, _ = system
    kube.add_node(
        Node(name="cordoned", cpu_capacity=4000, ram_capacity=1 << 24,
             unschedulable=True)
    )
    kube.add_node(Node(name="open", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert kube.bindings == [("default/p", "open")]


def test_node_failure_reschedules(system):
    kube, poseidon, _ = system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(
        Pod(name="p", owner_uid="job-1", cpu_request=100, ram_request=1 << 18)
    )
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert kube.bindings == [("default/p", "n1")]

    kube.add_node(Node(name="n2", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.update_node("n1", lambda n: setattr(n, "ready", False))
    assert poseidon.drain_watchers()
    deltas = poseidon.schedule_once()
    # The service re-placed the evicted task; the PLACE lands on n2.
    assert any(d.type == 1 for d in deltas)
    assert ("default/p", "n2") in kube.bindings


def test_node_recovery_rearms(system):
    """A NotReady blip must not permanently remove the node: recovery sends
    NodeUpdated and the node schedules again (regression: the failed
    condition was never stored, so recovery was undetectable)."""
    kube, poseidon, _ = system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    assert poseidon.drain_watchers()
    kube.update_node("n1", lambda n: setattr(n, "ready", False))
    assert poseidon.drain_watchers()
    kube.update_node("n1", lambda n: setattr(n, "ready", True))
    assert poseidon.drain_watchers()
    kube.create_pod(Pod(name="p", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert kube.bindings == [("default/p", "n1")]


def test_pod_spec_update_propagates(system):
    """Mutating a pod's requests must send TaskUpdated (regression: FakeKube
    delivered live references, so old-vs-new comparison never fired)."""
    kube, poseidon, server = system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()

    kube.update_pod(
        "default/p", lambda p: setattr(p, "cpu_request", 3500)
    )
    assert poseidon.drain_watchers()
    uid = poseidon.shared.uid_for_pod("default/p")
    assert server.servicer.state.tasks[uid].cpu_request == 3500


def test_completed_pod_releases_task(system):
    kube, poseidon, _ = system
    kube.add_node(Node(name="n1", cpu_capacity=1000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    kube.set_pod_phase("default/p1", "Succeeded")
    assert poseidon.drain_watchers()
    # Completed task produces no further deltas.
    assert poseidon.schedule_once() == []


def test_deleted_pod_removed(system):
    kube, poseidon, _ = system
    kube.add_node(Node(name="n1", cpu_capacity=1000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    kube.delete_pod("default", "p1")
    assert poseidon.drain_watchers()
    assert poseidon.schedule_once() == []
    assert poseidon.shared.uid_for_pod("default/p1") is None


def test_restart_recovers_bound_pods():
    """Full restart of BOTH processes: a fresh service + fresh glue against
    a cluster that already has bound Running pods.  The re-listed pods
    carry their binding via scheduled_to_resource and the scheduler adopts
    the placement instead of treating the machines as empty (regression:
    bound pods previously fell through the phase machine entirely)."""
    kube = FakeKube()
    kube.add_node(Node(name="n1", cpu_capacity=1000, ram_capacity=1 << 24))
    with FirmamentTPUServer(address="127.0.0.1:0") as server1:
        cfg = PoseidonConfig(
            firmament_address=server1.address, scheduling_interval=3600
        )
        with Poseidon(kube, config=cfg, run_loop=False) as p1:
            assert p1.drain_watchers()
            kube.create_pod(Pod(name="p", cpu_request=900,
                                ram_request=1 << 18))
            assert p1.drain_watchers()
            p1.schedule_once()
            assert kube.pods["default/p"].phase == "Running"

    # Cold restart: brand-new service (empty state) + brand-new glue.
    with FirmamentTPUServer(address="127.0.0.1:0") as server2:
        cfg2 = PoseidonConfig(
            firmament_address=server2.address, scheduling_interval=3600
        )
        with Poseidon(kube, config=cfg2, run_loop=False) as p2:
            assert p2.drain_watchers()
            uid = p2.shared.uid_for_pod("default/p")
            assert uid is not None
            # The new service adopted the carried binding.
            task = server2.servicer.state.tasks[uid]
            assert task.scheduled_to is not None
            # A second 900m pod must NOT fit: n1's capacity is committed
            # to the recovered placement.
            kube.create_pod(Pod(name="q", cpu_request=900,
                                ram_request=1 << 18))
            assert p2.drain_watchers()
            p2.schedule_once()
            assert kube.pods["default/q"].phase == "Pending"
            assert kube.pods["default/p"].phase == "Running"


def test_finished_pod_stats_not_found(system):
    """Succeeded pods stop resolving on the stats path (regression: the
    mapping lived until DELETED and stale stats kept forwarding)."""
    kube, poseidon, _ = system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    kube.set_pod_phase("default/p", "Succeeded")
    assert poseidon.drain_watchers()
    assert poseidon.shared.uid_for_pod("default/p") is None
    # ...but deletion still hands TaskRemoved to the scheduler.
    kube.delete_pod("default", "p")
    assert poseidon.drain_watchers()
    assert poseidon.schedule_once() == []


def test_metrics_agent_pushes_into_knowledge_base(system):
    """The metrics agent (the Heapster-sink analog, glue/metrics_agent.py)
    polls a source and streams usage into the live stats server; the
    firmament state's knowledge base must reflect it."""
    from poseidon_tpu.glue.metrics_agent import MetricsAgent

    kube, poseidon, server = system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()

    def source():
        return (
            [spb.NodeStats(hostname="n1", cpu_utilization=0.7,
                           mem_utilization=0.6)],
            [spb.PodStats(name="p1", namespace="default",
                          cpu_usage=90, mem_usage=1 << 17)],
        )

    agent = MetricsAgent(source, poseidon.stats_server.address)
    try:
        n_ok, p_ok = agent.push_once()
    finally:
        agent.stop()
    assert (n_ok, p_ok) == (1, 1)
    st = server.servicer.state
    machine = next(iter(st.machines.values()))
    assert machine.cpu_util > 0  # EMA moved by the agent's sample
    assert any(e.samples for e in st.node_kb.values())
    assert any(e.samples for e in st.task_kb.values())


def test_stats_stream_roundtrip(system):
    """Heapster-style stream -> stats server -> firmament knowledge base
    (stats.go:77-159), then the cost model steers away from the hot node."""
    kube, poseidon, server = system
    kube.add_node(Node(name="hot", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.add_node(Node(name="cold", cpu_capacity=4000, ram_capacity=1 << 24))
    assert poseidon.drain_watchers()

    channel = grpc.insecure_channel(poseidon.stats_server.address)
    stubs = make_stubs(channel, STATS_SERVICE, STATS_METHODS)
    samples = [
        spb.NodeStats(hostname="hot", cpu_utilization=0.95,
                      mem_utilization=0.95)
        for _ in range(4)
    ] + [spb.NodeStats(hostname="nope", cpu_utilization=0.1)]
    replies = list(stubs.ReceiveNodeStats(iter(samples)))
    assert [r.type for r in replies] == [spb.NODE_STATS_OK] * 4 + [
        spb.NODE_NOT_FOUND
    ]

    # Pod stats for an unknown pod answer POD_NOT_FOUND.
    pod_replies = list(
        stubs.ReceivePodStats(iter([spb.PodStats(name="x", namespace="y")]))
    )
    assert [r.type for r in pod_replies] == [spb.POD_NOT_FOUND]
    channel.close()

    kube.create_pod(Pod(name="p", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert kube.bindings == [("default/p", "cold")]


def test_preemption_recreate_cycle(system):
    """PREEMPT deletes the pod; the owning controller recreates it and the
    replacement is scheduled next round (poseidon.go:52-63 emulation)."""
    kube, poseidon, server = system
    kube.recreate_on_delete = True
    kube.add_node(Node(name="n1", cpu_capacity=1000, ram_capacity=1 << 24))
    kube.create_pod(
        Pod(name="p", owner_uid="rs-1", cpu_request=800, ram_request=1 << 18)
    )
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert kube.bindings == [("default/p", "n1")]

    # Direct deletion (e.g. kubectl): watcher sends TaskRemoved, controller
    # recreates, next round places the clone.
    kube.delete_pod("default", "p")
    assert poseidon.drain_watchers()
    clone_keys = [k for k in kube.pods if k != "default/p"]
    assert len(clone_keys) == 1
    poseidon.schedule_once()
    assert kube.pods[clone_keys[0]].phase == "Running"
