"""Runtime race harness for the glue layer's KeyedQueue.

The lock-discipline rule is lexical; this is the dynamic half (the role
`go test -race` plays in the reference repo): an instrumented wrapper
asserts the queue's core invariant — at most one worker processes a
given key at a time, items per key are processed in arrival order, and
nothing is lost or duplicated — under an 8-thread add/get/done/shutdown
storm.  Plus deterministic edge-case coverage: done() on an unknown
key, add() after shutdown, parked-item re-entry ordering.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Any, Dict, Hashable, List

import pytest

from poseidon_tpu.glue.keyed_queue import KeyedQueue

WORKERS = 8
KEYS = 12
ITEMS_PER_KEY = 60


class InvariantTracker:
    """Records per-key processing sections and fails on any overlap."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._in_flight: Dict[Hashable, str] = {}   # key -> worker name
        self.violations: List[str] = []
        self.processed: Dict[Hashable, List[Any]] = defaultdict(list)

    def enter(self, key: Hashable, items: List[Any], worker: str) -> None:
        with self._mu:
            holder = self._in_flight.get(key)
            if holder is not None:
                self.violations.append(
                    f"key {key!r} processed concurrently by {holder} "
                    f"and {worker}"
                )
            self._in_flight[key] = worker
            self.processed[key].extend(items)

    def exit(self, key: Hashable, worker: str) -> None:
        with self._mu:
            if self._in_flight.get(key) == worker:
                del self._in_flight[key]


@pytest.mark.parametrize("seed", range(3))
def test_keyed_queue_stress_no_concurrent_processing(seed):
    q = KeyedQueue()
    tracker = InvariantTracker()

    def producer(offset: int) -> None:
        # Interleave keys so parking (add during processing) is constant.
        for i in range(ITEMS_PER_KEY):
            for k in range(KEYS):
                q.add(f"k{k}", (k, offset * ITEMS_PER_KEY + i))

    def worker(name: str) -> None:
        while True:
            batch = q.get()
            if batch is None:
                return
            key, items = batch
            tracker.enter(key, items, name)
            # No sleep: maximal contention on done()/add() interleaving.
            tracker.exit(key, name)
            q.done(key)

    producers = [
        threading.Thread(target=producer, args=(p,)) for p in range(2)
    ]
    workers = [
        threading.Thread(target=worker, args=(f"w{i}",))
        for i in range(WORKERS)
    ]
    for t in producers + workers:
        t.start()
    for t in producers:
        t.join(timeout=30)
        assert not t.is_alive(), "producer failed to finish"
    # Drain: wait until queued + parked + in-processing reaches zero,
    # then shut down so workers exit.
    deadline = threading.Event()
    for _ in range(30_000):
        if len(q) == 0:
            break
        deadline.wait(0.001)
    assert len(q) == 0, "queue failed to drain"
    q.shut_down()
    for t in workers:
        t.join(timeout=30)
        assert not t.is_alive(), "worker failed to exit after shutdown"

    assert tracker.violations == []
    total = 2 * KEYS * ITEMS_PER_KEY
    got = sum(len(v) for v in tracker.processed.values())
    assert got == total, f"lost/duplicated items: {got} != {total}"
    for k in range(KEYS):
        items = [i for (kk, i) in tracker.processed[f"k{k}"] if kk == k]
        assert len(items) == 2 * ITEMS_PER_KEY
        # Per-producer arrival order is preserved per key (the two
        # producers interleave arbitrarily between each other).
        first = [i for i in items if i < ITEMS_PER_KEY]
        second = [i for i in items if i >= ITEMS_PER_KEY]
        assert first == sorted(first)
        assert second == sorted(second)


# ------------------------------------------------------------- edge cases


def test_done_on_unknown_key_is_noop():
    q = KeyedQueue()
    q.done("never-seen")          # must not raise or corrupt state
    assert len(q) == 0
    q.add("k", 1)
    q.done("unrelated")
    key, items = q.get()
    assert (key, items) == ("k", [1])
    q.done("k")
    assert len(q) == 0


def test_add_after_shutdown_is_dropped():
    q = KeyedQueue()
    q.add("a", 1)
    q.shut_down()
    q.add("a", 2)                 # dropped, not queued
    q.add("b", 3)                 # dropped, not queued
    key, items = q.get()          # pre-shutdown work still drains
    assert (key, items) == ("a", [1])
    q.done("a")
    assert q.get() is None        # then the queue reports drained
    assert len(q) == 0


def test_parked_items_reenter_in_order():
    q = KeyedQueue()
    q.add("k", "a")
    key, items = q.get()
    assert (key, items) == ("k", ["a"])
    # Adds while "k" is processing park in the side queue...
    q.add("k", "b")
    q.add("k", "c")
    # ...and other keys are still deliverable meanwhile.
    q.add("other", "x")
    key2, items2 = q.get()
    assert (key2, items2) == ("other", ["x"])
    q.done("other")
    # done() releases "k": the parked batch re-enters in arrival order.
    q.done("k")
    key3, items3 = q.get()
    assert (key3, items3) == ("k", ["b", "c"])
    q.done("k")
    assert len(q) == 0


def test_parked_reentry_preserves_fifo_against_later_keys():
    q = KeyedQueue()
    q.add("k", 1)
    assert q.get()[0] == "k"
    q.add("k", 2)      # parks
    q.add("late", 9)   # queued behind nothing
    q.done("k")        # parked batch re-enters AFTER already-queued keys
    assert q.get()[0] == "late"
    assert q.get() == ("k", [2])


def test_len_counts_processing_keys():
    q = KeyedQueue()
    q.add("k", 1)
    assert len(q) == 1
    q.get()
    # Popped but not done(): still outstanding.
    assert len(q) == 1
    q.add("k", 2)      # parked
    assert len(q) == 2
    q.done("k")
    assert len(q) == 1  # parked item re-entered the main queue
    q.get()
    q.done("k")
    assert len(q) == 0
