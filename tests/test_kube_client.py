"""Direct unit tests for the real-cluster adapter (glue/kube_client.py)
with a STUBBED ``kubernetes`` package — no cluster, no dependency.

This is the only code path to a real cluster (watch streams, the
pods/binding subresource, pod deletion), the surface the reference
unit-tests against its fake clientset (reference
pkg/k8sclient/nodewatcher_test.go:120-216).  The stub module is injected
into sys.modules before import and removed after, so the rest of the
suite keeps seeing the dependency as absent.
"""

from __future__ import annotations

import importlib
import queue
import sys
import threading
import types
from types import SimpleNamespace as NS

import pytest


def _v1_pod(name, phase="Pending", node=""):
    return NS(
        metadata=NS(name=name, namespace="default", owner_references=None,
                    labels={"app": name}, deletion_timestamp=None),
        spec=NS(containers=[NS(resources=NS(requests={"cpu": "100m",
                                                      "memory": "64Mi"}))],
                scheduler_name="poseidon", node_name=node,
                node_selector=None, affinity=None),
        status=NS(phase=phase),
    )


def _v1_node(name, ready="True"):
    return NS(
        metadata=NS(name=name, labels={}),
        spec=NS(unschedulable=False),
        status=NS(capacity={"cpu": "4", "memory": "8Gi"},
                  conditions=[NS(type="Ready", status=ready)]),
    )


class _FakeWatch:
    """Scripted Watch: each stream() call pops the next behavior —
    a list of events to yield, or an Exception to raise (the resync
    path informers take on watch errors)."""

    script: list = []

    def stream(self, list_fn, timeout_seconds=None):
        if not _FakeWatch.script:
            # Idle stream: end immediately (the loop re-enters until
            # stopped, exactly like a timed-out K8s watch).
            return iter(())
        step = _FakeWatch.script.pop(0)
        if isinstance(step, Exception):
            raise step
        return iter(step)


@pytest.fixture()
def kube_stub(monkeypatch):
    """Install a minimal fake `kubernetes` package and import the
    adapter against it; undo both afterwards."""
    calls = {"bindings": [], "deletes": [], "config": []}

    class _CoreV1Api:
        def list_pod_for_all_namespaces(self):
            return NS(items=[_v1_pod("p0")])

        def list_node(self):
            return NS(items=[_v1_node("n0")])

        def create_namespaced_pod_binding(self, name, namespace, body,
                                          _preload_content=True):
            calls["bindings"].append((namespace, name, body,
                                      _preload_content))

        def delete_namespaced_pod(self, name, namespace):
            calls["deletes"].append((namespace, name))

    class _V1Binding:
        def __init__(self, metadata=None, target=None):
            self.metadata = metadata
            self.target = target

    kubernetes = types.ModuleType("kubernetes")
    kubernetes.client = types.ModuleType("kubernetes.client")
    kubernetes.client.CoreV1Api = _CoreV1Api
    kubernetes.client.V1Binding = _V1Binding
    kubernetes.client.V1ObjectMeta = lambda **kw: NS(**kw)
    kubernetes.client.V1ObjectReference = lambda **kw: NS(**kw)
    kubernetes.config = types.ModuleType("kubernetes.config")
    kubernetes.config.load_kube_config = (
        lambda config_file=None: calls["config"].append(
            ("kubeconfig", config_file)
        )
    )
    kubernetes.config.load_incluster_config = (
        lambda: calls["config"].append(("incluster", None))
    )
    kubernetes.watch = types.ModuleType("kubernetes.watch")
    kubernetes.watch.Watch = _FakeWatch

    for mod in ("kubernetes", "kubernetes.client", "kubernetes.config",
                "kubernetes.watch"):
        monkeypatch.setitem(sys.modules, mod, getattr(
            kubernetes, mod.split(".", 1)[1]
        ) if "." in mod else kubernetes)
    sys.modules.pop("poseidon_tpu.glue.kube_client", None)
    mod = importlib.import_module("poseidon_tpu.glue.kube_client")
    _FakeWatch.script = []
    yield mod, calls
    sys.modules.pop("poseidon_tpu.glue.kube_client", None)


def test_config_selection(kube_stub):
    mod, calls = kube_stub
    mod.RealKube(kubeconfig="/tmp/kc.yaml")
    assert calls["config"][-1] == ("kubeconfig", "/tmp/kc.yaml")
    mod.RealKube()
    assert calls["config"][-1] == ("incluster", None)


def test_config_incluster_fallback_to_kubeconfig(kube_stub, monkeypatch):
    """Outside a cluster, in-cluster config raises and the adapter falls
    back to the default kubeconfig (k8sclient.go:57-62 semantics)."""
    mod, calls = kube_stub

    def boom():
        raise RuntimeError("not in cluster")

    monkeypatch.setattr(
        sys.modules["kubernetes.config"], "load_incluster_config", boom
    )
    mod.RealKube()
    assert calls["config"][-1] == ("kubeconfig", None)


def test_list_conversion(kube_stub):
    mod, _ = kube_stub
    k = mod.RealKube()
    pods = k.list_pods()
    assert pods[0].name == "p0" and pods[0].cpu_request == 100
    assert pods[0].ram_request == 64 << 10
    nodes = k.list_nodes()
    assert nodes[0].name == "n0" and nodes[0].cpu_capacity == 4000


def test_watch_event_mapping_and_error_resync(kube_stub):
    """Watch events map type+object onto the seam's Event tuples, and a
    stream error resyncs (next stream call) instead of killing the
    watcher thread — informer semantics (kube_client._watch_loop)."""
    mod, _ = kube_stub
    k = mod.RealKube()
    _FakeWatch.script = [
        [{"type": "ADDED", "object": _v1_pod("a")}],
        RuntimeError("watch expired"),          # must resync, not die
        [{"type": "MODIFIED", "object": _v1_pod("a", phase="Running",
                                                node="n0")},
         {"type": "DELETED", "object": _v1_pod("a")}],
    ]
    q = k.watch_pods()
    try:
        ev1 = q.get(timeout=10)
        ev2 = q.get(timeout=10)
        ev3 = q.get(timeout=10)
    finally:
        k.stop()
    assert ev1[0] == "ADDED" and ev1[1].name == "a"
    assert ev2[0] == "MODIFIED" and ev2[1].node_name == "n0"
    assert ev3[0] == "DELETED"


def test_watch_stop_terminates_thread(kube_stub):
    mod, _ = kube_stub
    k = mod.RealKube()
    q = k.watch_nodes()
    assert isinstance(q, queue.Queue)
    k.stop()
    deadline = threading.Event()
    # The loop re-checks _stop between (empty) streams; give it a moment.
    deadline.wait(0.2)
    before = threading.active_count()
    deadline.wait(0.3)
    assert threading.active_count() <= before


def test_bind_pod_posts_binding_subresource(kube_stub):
    """POST pods/{name}/binding with a Node target and _preload_content
    off (the reply is not a typed object) — k8sclient.go:33-46."""
    mod, calls = kube_stub
    k = mod.RealKube()
    k.bind_pod("ns1", "pod-a", "node-7")
    (namespace, name, body, preload) = calls["bindings"][0]
    assert (namespace, name) == ("ns1", "pod-a")
    assert body.target.kind == "Node" and body.target.name == "node-7"
    assert body.metadata.name == "pod-a"
    assert preload is False


def test_delete_pod(kube_stub):
    mod, calls = kube_stub
    k = mod.RealKube()
    k.delete_pod("ns2", "pod-b")
    assert calls["deletes"] == [("ns2", "pod-b")]
