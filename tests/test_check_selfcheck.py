"""Self-tests for the posecheck static-analysis suite.

Each rule runs against a committed clean fixture (zero findings) and a
seeded-violation fixture (exact expected findings), so a regression in a
checker — silently matching nothing is the classic failure mode of
AST lints — fails tier-1, not code review.  The CLI contract (exit
codes, output shape, suppressions, baseline) is covered too, and the
whole repo must scan clean.
"""

from __future__ import annotations

from pathlib import Path

import pytest

from poseidon_tpu.check import check_file, rules_by_name, run
from poseidon_tpu.check.__main__ import main as check_main
from poseidon_tpu.check.core import (
    Finding,
    apply_suppressions,
    load_baseline,
    suppressions,
    write_baseline,
)

FIXTURES = Path(__file__).parent.parent / "poseidon_tpu" / "check" / "fixtures"
REPO = Path(__file__).parent.parent


def _findings(rule: str, fixture: str):
    return check_file(
        FIXTURES / fixture, rules_by_name([rule]), forced=True, root=REPO
    )


# ------------------------------------------------------------------ fixtures


@pytest.mark.parametrize(
    "rule,fixture",
    [
        ("jit-purity", "jit_purity_clean.py"),
        ("lock-discipline", "lock_discipline_clean.py"),
        ("determinism", "determinism_clean.py"),
    ],
)
def test_clean_fixture_has_no_findings(rule, fixture):
    assert _findings(rule, fixture) == []


def test_jit_purity_violations():
    found = _findings("jit-purity", "jit_purity_violations.py")
    msgs = [f.message for f in found]
    assert len(found) == 8
    assert sum("np.asarray" in m or "np.array" in m for m in msgs) == 2
    assert sum(".item()" in m for m in msgs) == 1
    assert sum("cast concretizes" in m for m in msgs) == 2
    assert sum("device_get" in m for m in msgs) == 1
    assert sum("print" in m for m in msgs) == 2
    # The closure reaches same-module callees of jitted functions.
    assert any("_leaky_callee" in m for m in msgs)
    # The suppressed np.asarray on the `ok = ...` line did not count.
    assert all(f.rule == "jit-purity" for f in found)


def test_lock_discipline_violations():
    found = _findings("lock-discipline", "lock_discipline_violations.py")
    assert len(found) == 7
    import re

    by_method = {
        re.search(r"\((\w+\.\w+)\); the lock guards", f.message).group(1)
        for f in found
    }
    assert by_method == {
        "RacyRegistry.racy_set", "RacyRegistry.racy_put",
        "RacyRegistry.racy_append", "RacyRegistry.racy_bump",
        "RacyRegistry._helper", "RacyCond.drop_all",
        "ThreadTargetEscape._worker",
    }


def test_determinism_violations():
    found = _findings("determinism", "determinism_violations.py")
    msgs = [f.message for f in found]
    assert len(found) == 11
    assert sum("wall-clock" in m for m in msgs) == 2
    assert sum("unseeded global RNG" in m for m in msgs) == 3
    assert sum("without a seed" in m for m in msgs) == 1
    assert sum("unordered set" in m for m in msgs) == 5


# ---------------------------------------------------------------- mechanics


def test_suppression_parsing():
    src = (
        "x = 1  # posecheck: ignore[jit-purity]\n"
        "y = 2  # posecheck: ignore[jit-purity, determinism]\n"
        "z = 3  # posecheck: ignore\n"
        "w = 4\n"
    )
    supp = suppressions(src)
    assert supp[1] == {"jit-purity"}
    assert supp[2] == {"jit-purity", "determinism"}
    assert supp[3] is None
    assert 4 not in supp

    findings = [
        Finding("f.py", 1, "jit-purity", "a"),
        Finding("f.py", 1, "determinism", "kept: wrong rule"),
        Finding("f.py", 3, "lock-discipline", "any rule suppressed"),
        Finding("f.py", 4, "determinism", "kept: no comment"),
    ]
    kept = apply_suppressions(findings, src)
    assert [f.message for f in kept] == ["kept: wrong rule",
                                         "kept: no comment"]


def test_baseline_round_trip(tmp_path):
    baseline = tmp_path / "baseline.txt"
    findings = [
        Finding("a.py", 3, "determinism", "msg one"),
        Finding("b.py", 9, "jit-purity", "msg two"),
    ]
    write_baseline(baseline, findings)
    keys = load_baseline(baseline)
    assert len(keys) == 2
    assert all(f.baseline_key() in keys for f in findings)
    # Line drift does not invalidate a baseline entry.
    moved = Finding("a.py", 33, "determinism", "msg one")
    assert moved.baseline_key() in keys


def test_unknown_rule_is_usage_error(capsys):
    assert check_main(["--rule", "no-such-rule", "."]) == 2
    assert check_main(["poseidon_tpu/does/not/exist.py"]) == 2


def test_cli_exit_codes(tmp_path):
    bad = FIXTURES / "determinism_violations.py"
    assert check_main(
        ["--rule", "determinism", str(FIXTURES / "determinism_clean.py")]
    ) == 0
    assert check_main(["--rule", "determinism", str(bad)]) == 1
    # A baseline grandfathers the findings back to exit 0.
    baseline = tmp_path / "b.txt"
    assert check_main(
        ["--rule", "determinism", "--write-baseline",
         "--baseline", str(baseline), str(bad)]
    ) == 0
    assert check_main(
        ["--rule", "determinism", "--baseline", str(baseline), str(bad)]
    ) == 0
    # --no-baseline reports them again.
    assert check_main(
        ["--rule", "determinism", "--baseline", str(baseline),
         "--no-baseline", str(bad)]
    ) == 1


def test_output_shape(capsys):
    check_main(["--rule", "determinism",
                str(FIXTURES / "determinism_violations.py")])
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "violations must print"
    for line in out:
        # file:line rule-id message
        loc, rule, _msg = line.split(" ", 2)
        path, lineno = loc.rsplit(":", 1)
        assert path.endswith("determinism_violations.py")
        assert int(lineno) > 0
        assert rule == "determinism"


# ------------------------------------------------------------------- repo


def test_repo_scans_clean():
    """The gate the Makefile's lint target enforces, as a tier-1 test."""
    findings = run([str(REPO / "poseidon_tpu")], root=REPO)
    assert findings == [], "\n".join(f.render() for f in findings)
