"""Self-tests for the posecheck static-analysis suite.

Each rule runs against a committed clean fixture (zero findings) and a
seeded-violation fixture (exact expected findings), so a regression in a
checker — silently matching nothing is the classic failure mode of
AST lints — fails tier-1, not code review.  The CLI contract (exit
codes, output shape, suppressions, baseline) is covered too, and the
whole repo must scan clean.
"""

from __future__ import annotations

from pathlib import Path

import pytest

from poseidon_tpu.check import check_file, rules_by_name, run
from poseidon_tpu.check.__main__ import main as check_main
from poseidon_tpu.check.core import (
    Finding,
    apply_suppressions,
    load_baseline,
    suppressions,
    write_baseline,
)

FIXTURES = Path(__file__).parent.parent / "poseidon_tpu" / "check" / "fixtures"
REPO = Path(__file__).parent.parent


def _findings(rule: str, fixture: str):
    return check_file(
        FIXTURES / fixture, rules_by_name([rule]), forced=True, root=REPO
    )


# ------------------------------------------------------------------ fixtures


@pytest.mark.parametrize(
    "rule,fixture",
    [
        ("jit-purity", "jit_purity_clean.py"),
        ("lock-discipline", "lock_discipline_clean.py"),
        ("determinism", "determinism_clean.py"),
        ("determinism", "chaos_plan_clean.py"),
        ("retrace-guard", "retrace_guard_clean.py"),
        ("blocking-under-lock", "concurrency_clean.py"),
        ("unsafe-publication", "concurrency_clean.py"),
    ],
)
def test_clean_fixture_has_no_findings(rule, fixture):
    assert _findings(rule, fixture) == []


def test_jit_purity_violations():
    found = _findings("jit-purity", "jit_purity_violations.py")
    msgs = [f.message for f in found]
    assert len(found) == 8
    assert sum("np.asarray" in m or "np.array" in m for m in msgs) == 2
    assert sum(".item()" in m for m in msgs) == 1
    assert sum("cast concretizes" in m for m in msgs) == 2
    assert sum("device_get" in m for m in msgs) == 1
    assert sum("print" in m for m in msgs) == 2
    # The closure reaches same-module callees of jitted functions.
    assert any("_leaky_callee" in m for m in msgs)
    # The suppressed np.asarray on the `ok = ...` line did not count.
    assert all(f.rule == "jit-purity" for f in found)


def test_lock_discipline_violations():
    found = _findings("lock-discipline", "lock_discipline_violations.py")
    assert len(found) == 7
    import re

    by_method = {
        re.search(r"\((\w+\.\w+)\); the lock guards", f.message).group(1)
        for f in found
    }
    assert by_method == {
        "RacyRegistry.racy_set", "RacyRegistry.racy_put",
        "RacyRegistry.racy_append", "RacyRegistry.racy_bump",
        "RacyRegistry._helper", "RacyCond.drop_all",
        "ThreadTargetEscape._worker",
    }


def test_determinism_violations():
    found = _findings("determinism", "determinism_violations.py")
    msgs = [f.message for f in found]
    assert len(found) == 15
    assert sum("wall-clock" in m for m in msgs) == 2
    assert sum("unseeded global RNG" in m for m in msgs) == 3
    assert sum("without a seed" in m for m in msgs) == 1
    assert sum("unordered set" in m for m in msgs) == 5
    assert sum("import time" in m for m in msgs) == 4


def test_chaos_determinism_violations():
    """Satellite (PR 5): the determinism rule scans chaos/ — fault
    plans must be seed-reproducible, so wall-clock timing, OS-entropy
    RNG, and set-ordered fault output are lint failures there."""
    found = _findings("determinism", "chaos_plan_violations.py")
    msgs = [f.message for f in found]
    assert len(found) == 7
    assert sum("wall-clock" in m for m in msgs) == 2
    assert sum("unseeded global RNG" in m for m in msgs) == 2
    assert sum("without a seed" in m for m in msgs) == 1
    assert sum("unordered set" in m for m in msgs) == 2


def test_determinism_scope_covers_chaos():
    from poseidon_tpu.check.determinism import DeterminismRule

    rule = DeterminismRule()
    assert rule.applies_to("poseidon_tpu/chaos/plan.py")
    assert rule.applies_to("poseidon_tpu/chaos/soak.py")
    assert not rule.applies_to("poseidon_tpu/glue/poseidon.py")


def test_retrace_guard_violations():
    found = _findings("retrace-guard", "retrace_guard_violations.py")
    msgs = [f.message for f in found]
    assert len(found) == 12
    assert sum("fresh compile cache" in m for m in msgs) == 6
    assert sum("module-level loop" in m for m in msgs) == 2
    assert sum("drive" in m for m in msgs) == 1  # class-method hazard
    assert sum("retraces per value" in m for m in msgs) == 1
    assert sum("str constant at traced position" in m for m in msgs) == 1
    # Two bool-at-traced cases: the dropped-static-entry shape and the
    # ladder-schedule-as-Python-value shape (adaptive-cadence flag).
    assert sum("bool constant at traced position" in m for m in msgs) == 2
    assert sum("pad through bucket_size" in m for m in msgs) == 1
    assert sum("weak f32/f64" in m for m in msgs) == 1
    # The suppressed float literal did not count.
    assert all(f.rule == "retrace-guard" for f in found)


def _dispatch_findings(fixture: str, flag_fragments=("check/fixtures",)):
    from poseidon_tpu.check.dispatch_budget import DispatchBudgetRule

    rule = DispatchBudgetRule(flag_fragments=flag_fragments)
    pre = check_file(FIXTURES / fixture, [rule], forced=True, root=REPO)
    assert pre == [], "dispatch-budget judges in finalize(), not check()"
    return rule.finalize()


def _project_findings(rule, fixture: str):
    """check() + finalize() for the project-scoped rules (transfer/
    shard/hatch): per-file findings and closure findings combined."""
    pre = check_file(FIXTURES / fixture, [rule], forced=True, root=REPO)
    return pre + rule.finalize()


# ------------------------------------------------- transfer-discipline


def test_transfer_discipline_clean_fixture():
    from poseidon_tpu.check.transfer_discipline import (
        TransferDisciplineRule,
    )

    assert _project_findings(
        TransferDisciplineRule(), "transfer_discipline_clean.py"
    ) == []


def test_transfer_discipline_violations():
    from poseidon_tpu.check.transfer_discipline import (
        TransferDisciplineRule,
    )

    found = _project_findings(
        TransferDisciplineRule(), "transfer_discipline_violations.py"
    )
    msgs = [f.message for f in found]
    assert len(found) == 8
    assert sum("implicit device->host sync" in m for m in msgs) == 4
    assert sum("materializes device memory" in m for m in msgs) == 1
    assert sum("outside a declared host boundary (in" in m
               for m in msgs) == 1
    assert sum("without donate_argnums" in m for m in msgs) == 1
    assert sum("read after being donated" in m for m in msgs) == 1
    # The suppressed np.asarray on the `ok = ...` line did not count.
    assert all(f.rule == "transfer-discipline" for f in found)


def test_transfer_discipline_scope():
    from poseidon_tpu.check.transfer_discipline import (
        TransferDisciplineRule,
    )

    rule = TransferDisciplineRule()
    assert rule.applies_to("poseidon_tpu/ops/transport_sharded.py")
    assert rule.applies_to("poseidon_tpu/graph/instance.py")
    assert rule.applies_to("poseidon_tpu/costmodel/device_build.py")
    assert not rule.applies_to("poseidon_tpu/glue/poseidon.py")


# ----------------------------------------------------- shard-discipline


def test_shard_discipline_clean_fixture():
    from poseidon_tpu.check.shard_discipline import ShardDisciplineRule

    assert _project_findings(
        ShardDisciplineRule(), "shard_discipline_clean.py"
    ) == []


def test_shard_discipline_violations():
    from poseidon_tpu.check.shard_discipline import ShardDisciplineRule

    found = _project_findings(
        ShardDisciplineRule(), "shard_discipline_violations.py"
    )
    msgs = [f.message for f in found]
    assert len(found) == 5
    assert sum("which no declared mesh carries" in m for m in msgs) == 1
    assert sum("outside any shard_map" in m for m in msgs) == 1
    assert sum("not a declared mesh axis" in m for m in msgs) == 1
    assert sum("pad-to-mesh-multiple" in m for m in msgs) == 1
    assert sum("not reachable from precompile" in m for m in msgs) == 1
    # covered_sharded is reached; opted_out_sharded carries the
    # ignore[dispatch-budget] suppression — neither flags.
    assert not any("covered_sharded" in m for m in msgs)
    assert not any("opted_out_sharded" in m for m in msgs)


# ------------------------------------------------------- hatch-registry


def test_hatch_registry_clean_fixture():
    from poseidon_tpu.check.hatch_registry import HatchRegistryRule

    assert _project_findings(
        HatchRegistryRule(), "hatch_registry_clean.py"
    ) == []


def test_hatch_registry_violations():
    from poseidon_tpu.check.hatch_registry import HatchRegistryRule

    found = _project_findings(
        HatchRegistryRule(), "hatch_registry_violations.py"
    )
    msgs = [f.message for f in found]
    assert len(found) == 5
    assert sum("bypasses the hatch registry" in m for m in msgs) == 3
    assert sum(m.startswith("undeclared hatch") for m in msgs) == 1
    assert sum("accessor read of undeclared" in m for m in msgs) == 1
    # The suppressed bypass and the environment WRITE did not count.
    assert all(f.rule == "hatch-registry" for f in found)


def test_hatch_registry_dead_flag(tmp_path):
    """A declared, non-external hatch nothing reads flags at its
    declaration line; external hatches and referenced hatches do not.
    The sub-check only judges when the scan covers the liveness
    roots."""
    from poseidon_tpu.check.core import run
    from poseidon_tpu.check.hatch_registry import HatchRegistryRule

    registry = tmp_path / "utils" / "hatches.py"
    registry.parent.mkdir()
    registry.write_text(
        "class Hatch:\n"
        "    def __init__(self, name, kind, default, doc):\n"
        "        pass\n\n"
        "HATCHES = (\n"
        '    Hatch("POSEIDON_LIVE_FLAG", "flag", "", "read below"),\n'
        '    Hatch("POSEIDON_DEAD_FLAG", "flag", "", "read nowhere"),\n'
        '    Hatch("POSEIDON_EXTERNAL_FLAG", "external", "",\n'
        '          "consumed by make"),\n'
        ")\n"
    )
    reader = tmp_path / "reader.py"
    reader.write_text(
        "from poseidon_tpu.utils.hatches import hatch_flag\n\n\n"
        "def f():\n"
        '    return hatch_flag("POSEIDON_LIVE_FLAG")\n'
    )
    # Scanned paths are root-relative, so liveness roots match on the
    # relative fragments.
    rule = HatchRegistryRule(
        registry_path=registry, liveness_roots=("utils/", "reader.py")
    )
    found = run([str(tmp_path)], rules=[rule], root=tmp_path)
    assert len(found) == 1
    assert "POSEIDON_DEAD_FLAG" in found[0].message
    assert "dead flag" in found[0].message

    # A partial scan (liveness roots not covered) judges nothing.
    rule2 = HatchRegistryRule(
        registry_path=registry,
        liveness_roots=("utils/", "reader.py", "not_scanned_root/"),
    )
    assert run([str(tmp_path)], rules=[rule2], root=tmp_path) == []


def test_hatch_registry_table_committed():
    """docs/HATCHES.md is GENERATED from the registry: a drift between
    the committed table and `python -m poseidon_tpu.utils.hatches`
    output fails tier-1, the same posture as the proto drift gate."""
    from poseidon_tpu.utils.hatches import markdown_table

    committed = (REPO / "docs" / "HATCHES.md").read_text()
    assert committed == markdown_table(), (
        "docs/HATCHES.md is stale: regenerate with "
        "`python -m poseidon_tpu.utils.hatches > docs/HATCHES.md`"
    )


def test_hatch_accessors_semantics(monkeypatch):
    from poseidon_tpu.utils import hatches

    # bool_on: any value but "0" enables; bool_off: only "1" enables.
    monkeypatch.delenv("POSEIDON_HOST_CERT", raising=False)
    assert hatches.hatch_bool("POSEIDON_HOST_CERT") is True
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    assert hatches.hatch_bool("POSEIDON_HOST_CERT") is False
    monkeypatch.delenv("POSEIDON_TRACE", raising=False)
    assert hatches.hatch_bool("POSEIDON_TRACE") is False
    monkeypatch.setenv("POSEIDON_TRACE", "1")
    assert hatches.hatch_bool("POSEIDON_TRACE") is True
    # int: unparseable falls back (operator typo never crashes a solve).
    monkeypatch.setenv("POSEIDON_PRUNE_MIN_ROWS", "banana")
    assert hatches.hatch_int("POSEIDON_PRUNE_MIN_ROWS") == 192
    monkeypatch.setenv("POSEIDON_PRUNE_MIN_ROWS", "64")
    assert hatches.hatch_int("POSEIDON_PRUNE_MIN_ROWS") == 64
    # Unregistered names fail loudly at call time.
    with pytest.raises(KeyError):
        hatches.hatch_raw("POSEIDON_NO_SUCH_HATCH")


def test_dispatch_budget_clean_fixture():
    assert _dispatch_findings("dispatch_budget_clean.py") == []


def test_dispatch_budget_violations():
    found = _dispatch_findings("dispatch_budget_violations.py")
    assert len(found) == 2
    names = {f.message.split("`")[1] for f in found}
    assert names == {"uncovered_kernel", "wrapper_orphan"}
    # covered_kernel is reached; opted_out is line-suppressed.
    assert all("precompile" in f.message for f in found)


def test_dispatch_budget_silent_without_precompile_seed():
    # A partial scan (no precompile def in sight) must not flag
    # anything: reachability is not judgeable on a partial graph.
    assert _dispatch_findings("jit_purity_violations.py") == []


def test_dispatch_budget_never_judges_file_list_scans():
    """A file list that happens to include precompile() is STILL a
    partial graph: {instance.py, transport_fused.py} misses the wiring
    in transport.py, and judging it would false-flag the fused kernel.
    run() passes the scan paths through begin(); only directory roots
    are judgeable."""
    found = run(
        [
            str(REPO / "poseidon_tpu" / "graph" / "instance.py"),
            str(REPO / "poseidon_tpu" / "ops" / "transport_fused.py"),
        ],
        root=REPO,
    )
    assert [f for f in found if f.rule == "dispatch-budget"] == []
    # The directory walk DOES judge (and the live tree is wired clean).
    assert run([str(REPO / "poseidon_tpu")], root=REPO) == []


# ------------------------------------------------------------ numerics


def test_numerics_clean_fixture():
    from poseidon_tpu.check.numerics_discipline import (
        NumericsDisciplineRule,
    )

    assert _project_findings(
        NumericsDisciplineRule(), "numerics_clean.py"
    ) == []


def test_numerics_violations():
    from poseidon_tpu.check.numerics_discipline import (
        NumericsDisciplineRule,
    )

    found = _project_findings(
        NumericsDisciplineRule(), "numerics_violations.py"
    )
    msgs = [f.message for f in found]
    assert len(found) == 12
    assert sum(m.startswith("i32-overflow:") for m in msgs) == 5
    assert sum(m.startswith("inf-sentinel:") for m in msgs) == 4
    assert sum(m.startswith("promotion:") for m in msgs) == 3
    assert sum("narrowing" in m for m in msgs) == 2
    assert sum("weak" in m for m in msgs) == 3
    # The two seeded `ignore[numerics]` hazards did not count (one on
    # the per-file overflow path, one on the finalize sentinel path).
    assert all(f.rule == "numerics" for f in found)


def test_numerics_scope(monkeypatch):
    from poseidon_tpu.check.numerics_discipline import (
        NumericsDisciplineRule,
    )

    rule = NumericsDisciplineRule()
    assert rule.applies_to("poseidon_tpu/ops/transport.py")
    assert rule.applies_to("poseidon_tpu/costmodel/cpu_mem.py")
    assert rule.applies_to("poseidon_tpu/graph/residency.py")
    assert not rule.applies_to("poseidon_tpu/glue/poseidon.py")
    # POSEIDON_NUMERICS_SCOPES narrows (or widens) the walk.
    monkeypatch.setenv(
        "POSEIDON_NUMERICS_SCOPES", "poseidon_tpu/glue/"
    )
    narrowed = NumericsDisciplineRule()
    assert narrowed.applies_to("poseidon_tpu/glue/poseidon.py")
    assert not narrowed.applies_to("poseidon_tpu/ops/transport.py")


# ----------------------------------------------------- concurrency rules


def test_concurrency_clean_fixture():
    from poseidon_tpu.check.concurrency import LockOrderRule

    assert _findings(
        "blocking-under-lock", "concurrency_clean.py"
    ) == []
    assert _findings(
        "unsafe-publication", "concurrency_clean.py"
    ) == []
    assert _project_findings(
        LockOrderRule(), "concurrency_clean.py"
    ) == []


def test_lock_order_violations():
    from poseidon_tpu.check.concurrency import LockOrderRule

    found = _project_findings(
        LockOrderRule(), "concurrency_violations.py"
    )
    assert len(found) == 2
    msgs = [f.message for f in found]
    # The in-class AB/BA cycle and the cross-class call cycle, each
    # reported once (both traversal directions dedupe to one finding).
    assert sum("TwoLocks._a -> TwoLocks._b" in m for m in msgs) == 1
    assert sum("Outer._mu -> Inner._gate" in m for m in msgs) == 1
    assert all("potential deadlock" in m for m in msgs)


def test_blocking_under_lock_violations():
    found = _findings(
        "blocking-under-lock", "concurrency_violations.py"
    )
    msgs = [f.message for f in found]
    assert len(found) == 5
    assert sum("sleep" in m for m in msgs) == 1
    assert sum(".join()" in m for m in msgs) == 1
    assert sum(".get()" in m for m in msgs) == 1
    assert sum(".result()" in m for m in msgs) == 1
    # Event.wait under the lock counts; Condition.wait on the HELD
    # lock (legal_condition_wait) and the suppressed sleep do not.
    assert sum(".wait()" in m for m in msgs) == 1


def test_unsafe_publication_violations():
    found = _findings(
        "unsafe-publication", "concurrency_violations.py"
    )
    assert len(found) == 2
    attrs = {f.message.split("self.")[1].split(" ")[0] for f in found}
    # The locked rebuild, the `# handoff:` swap, and the threadless
    # QuietPublisher are all exempt.
    assert attrs == {"_state", "_snapshots"}


def test_concurrency_scope():
    from poseidon_tpu.check.concurrency import BlockingUnderLockRule

    rule = BlockingUnderLockRule()
    assert rule.applies_to("poseidon_tpu/glue/poseidon.py")
    assert rule.applies_to("poseidon_tpu/obs/metrics.py")
    assert rule.applies_to("poseidon_tpu/service/server.py")
    assert rule.applies_to("poseidon_tpu/graph/pipeline.py")
    assert not rule.applies_to("poseidon_tpu/ops/transport.py")


# ---------------------------------------------------------------- mechanics


def test_suppression_parsing():
    src = (
        "x = 1  # posecheck: ignore[jit-purity]\n"
        "y = 2  # posecheck: ignore[jit-purity, determinism]\n"
        "z = 3  # posecheck: ignore\n"
        "w = 4\n"
    )
    supp = suppressions(src)
    assert supp[1] == {"jit-purity"}
    assert supp[2] == {"jit-purity", "determinism"}
    assert supp[3] is None
    assert 4 not in supp

    findings = [
        Finding("f.py", 1, "jit-purity", "a"),
        Finding("f.py", 1, "determinism", "kept: wrong rule"),
        Finding("f.py", 3, "lock-discipline", "any rule suppressed"),
        Finding("f.py", 4, "determinism", "kept: no comment"),
    ]
    kept = apply_suppressions(findings, src)
    assert [f.message for f in kept] == ["kept: wrong rule",
                                         "kept: no comment"]


def test_baseline_round_trip(tmp_path):
    baseline = tmp_path / "baseline.txt"
    findings = [
        Finding("a.py", 3, "determinism", "msg one"),
        Finding("b.py", 9, "jit-purity", "msg two"),
    ]
    write_baseline(baseline, findings)
    keys = load_baseline(baseline)
    assert len(keys) == 2
    assert all(f.baseline_key() in keys for f in findings)
    # Line drift does not invalidate a baseline entry.
    moved = Finding("a.py", 33, "determinism", "msg one")
    assert moved.baseline_key() in keys


def test_committed_baseline_is_empty_against_live_tree():
    """Grandfathering is for downstream forks: THIS repo fixes findings
    instead of baselining them, so the committed baseline must parse to
    zero keys — and stay unnecessary (the live tree scans clean without
    it, which test_repo_scans_clean enforces with no baseline at all)."""
    committed = (
        REPO / "poseidon_tpu" / "check" / "baseline.txt"
    )
    assert committed.exists()
    assert load_baseline(committed) == set()


def test_write_baseline_round_trips_violation_fixtures(tmp_path):
    """--write-baseline over the seeded-violation fixtures must
    grandfather every finding: the rewritten scan is clean, and
    --no-baseline resurfaces the identical finding set."""
    baseline = tmp_path / "fixture_baseline.txt"
    fixtures = [
        str(FIXTURES / "determinism_violations.py"),
        str(FIXTURES / "retrace_guard_violations.py"),
    ]
    args = ["--rule", "determinism", "--rule", "retrace-guard"]
    assert check_main(
        [*args, "--write-baseline", "--baseline", str(baseline), *fixtures]
    ) == 0
    keys = load_baseline(baseline)
    # Keys are (path, rule, message) — same-message findings on
    # different lines collapse to one line-drift-immune entry.
    assert len(keys) >= 10
    assert any("retrace-guard" in k for k in keys)
    assert any("determinism" in k for k in keys)
    # Grandfathered: the same scan is now clean...
    assert check_main(
        [*args, "--baseline", str(baseline), *fixtures]
    ) == 0
    # ...and --no-baseline resurfaces exactly the written set.
    resurfaced = run(
        fixtures, rules=rules_by_name(["determinism", "retrace-guard"]),
        root=REPO,
    )
    assert {f.baseline_key() for f in resurfaced} == keys


def test_unknown_rule_is_usage_error(capsys):
    assert check_main(["--rule", "no-such-rule", "."]) == 2
    assert check_main(["poseidon_tpu/does/not/exist.py"]) == 2


def test_cli_exit_codes(tmp_path):
    bad = FIXTURES / "determinism_violations.py"
    assert check_main(
        ["--rule", "determinism", str(FIXTURES / "determinism_clean.py")]
    ) == 0
    assert check_main(["--rule", "determinism", str(bad)]) == 1
    # A baseline grandfathers the findings back to exit 0.
    baseline = tmp_path / "b.txt"
    assert check_main(
        ["--rule", "determinism", "--write-baseline",
         "--baseline", str(baseline), str(bad)]
    ) == 0
    assert check_main(
        ["--rule", "determinism", "--baseline", str(baseline), str(bad)]
    ) == 0
    # --no-baseline reports them again.
    assert check_main(
        ["--rule", "determinism", "--baseline", str(baseline),
         "--no-baseline", str(bad)]
    ) == 1


def test_output_shape(capsys):
    check_main(["--rule", "determinism",
                str(FIXTURES / "determinism_violations.py")])
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "violations must print"
    for line in out:
        # file:line rule-id message
        loc, rule, _msg = line.split(" ", 2)
        path, lineno = loc.rsplit(":", 1)
        assert path.endswith("determinism_violations.py")
        assert int(lineno) > 0
        assert rule == "determinism"


def test_json_output_shape(capsys):
    import json

    rc = check_main(["--format=json", "--rule", "determinism",
                     str(FIXTURES / "determinism_violations.py")])
    assert rc == 1
    out = capsys.readouterr().out.strip().splitlines()
    assert out, "violations must print"
    for line in out:
        obj = json.loads(line)  # one machine-parseable finding per line
        assert set(obj) == {"path", "line", "rule", "message"}
        assert obj["path"].endswith("determinism_violations.py")
        assert obj["line"] > 0
        assert obj["rule"] == "determinism"


def test_changed_mode(tmp_path, monkeypatch, capsys):
    """--changed scans only git-touched files: a committed-clean repo
    scans nothing; touching a file with a violation surfaces it; a
    non-repo directory is a usage error."""
    import subprocess

    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=repo, check=True, capture_output=True,
        )

    git("init", "-q")
    git("config", "user.email", "t@example.com")
    git("config", "user.name", "t")
    clean = "import numpy as np\n\n\ndef f(seed):\n" \
            "    return np.random.default_rng(seed)\n"
    (repo / "mod.py").write_text(clean)
    git("add", "mod.py")
    git("commit", "-q", "-m", "seed")

    monkeypatch.chdir(repo)
    # Nothing changed vs HEAD: clean exit, no scan.
    assert check_main(["--changed", "--rule", "determinism", "."]) == 0
    assert capsys.readouterr().out == ""

    # An unstaged edit introduces a violation: --changed finds it.
    (repo / "mod.py").write_text(
        clean + "\n\ndef g():\n    return np.random.default_rng()\n"
    )
    assert check_main(["--changed", "--rule", "determinism", "."]) == 1
    assert "without a seed" in capsys.readouterr().out

    # An untracked new file counts as changed too.
    (repo / "mod.py").write_text(clean)
    (repo / "new.py").write_text(
        "import time\n\n\ndef h():\n    return time.time()\n"
    )
    assert check_main(["--changed", "--rule", "determinism", "."]) == 1
    assert "wall-clock" in capsys.readouterr().out
    (repo / "new.py").unlink()

    # From a SUBDIRECTORY: git prints toplevel-relative names, the scan
    # paths are cwd-relative — tracked changes must still be found.
    sub = repo / "sub"
    sub.mkdir()
    (sub / "inner.py").write_text(clean)
    git("add", "sub/inner.py")
    git("commit", "-q", "-m", "sub")
    (sub / "inner.py").write_text(
        clean + "\n\ndef g():\n    return np.random.default_rng()\n"
    )
    monkeypatch.chdir(sub)
    assert check_main(["--changed", "--rule", "determinism", "."]) == 1
    assert "without a seed" in capsys.readouterr().out
    monkeypatch.chdir(repo)

    # Outside any git checkout: usage error, not a silent no-op scan.
    outside = tmp_path / "not_a_repo"
    outside.mkdir()
    (outside / "x.py").write_text("x = 1\n")
    monkeypatch.chdir(outside)
    monkeypatch.setenv("GIT_DIR", str(outside / "nope"))
    assert check_main(["--changed", "--rule", "determinism", "."]) == 2


# ------------------------------------------------------------------- repo


def test_repo_scans_clean():
    """The gate the Makefile's lint target enforces, as a tier-1 test.

    The scan set matches `make lint` (poseidon_tpu/ plus bench.py,
    tools/, and the driver entry): the hatch-registry rule's dead-flag
    sub-check only judges when every liveness root was walked, and the
    bench/tools hatches live outside the package."""
    findings = run(
        [
            str(REPO / "poseidon_tpu"),
            str(REPO / "bench.py"),
            str(REPO / "tools"),
            str(REPO / "__graft_entry__.py"),
        ],
        root=REPO,
    )
    assert findings == [], "\n".join(f.render() for f in findings)
