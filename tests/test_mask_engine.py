"""Constraint-mask engine: interned/vectorized vs oracle parity, and
incremental resident-count maintenance vs from-scratch rebuild.

The vectorized engine (costmodel/selectors.pod_selector_admissibility
over graph/residency.ResidentCounts; selector_admissibility over
MachineLabelIndex) must be BIT-identical to the original per-machine
dict-probe implementation, which is kept verbatim as the oracle
(pod_selector_admissibility_dicts / the probe path of
selector_admissibility).
"""

import numpy as np

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.costmodel.selectors import (
    EXISTS_KEY,
    IN_SET,
    NOT_EXISTS_KEY,
    NOT_IN_SET,
    pod_selector_admissibility,
    pod_selector_admissibility_dicts,
    selector_admissibility,
)
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.residency import (
    MachineLabelIndex,
    ResidentLabelIndex,
)
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import generate_uuid

KEYS = ["app", "role", "tier", "ver"]
VALUES = ["a", "b", "c", "d", "e"]
ALL_TYPES = [IN_SET, NOT_IN_SET, EXISTS_KEY, NOT_EXISTS_KEY]


def _random_selector(rng) -> tuple:
    stype = ALL_TYPES[int(rng.integers(len(ALL_TYPES)))]
    key = KEYS[int(rng.integers(len(KEYS)))]
    if stype in (EXISTS_KEY, NOT_EXISTS_KEY):
        return (stype, key, ())
    n = int(rng.integers(1, 3))
    vals = tuple(VALUES[int(rng.integers(len(VALUES)))] for _ in range(n))
    return (stype, key, vals)


def _random_labels(rng, p_empty=0.3) -> dict:
    if rng.random() < p_empty:
        return {}
    n = int(rng.integers(1, 4))
    picks = rng.choice(len(KEYS), size=n, replace=False)
    return {
        KEYS[int(k)]: VALUES[int(rng.integers(len(VALUES)))]
        for k in picks
    }


def _dict_aggregates(machine_residents):
    """Oracle-side aggregates from per-machine resident label lists."""
    res_kv, res_key = [], []
    res_total = np.zeros(len(machine_residents), dtype=np.int64)
    for m, residents in enumerate(machine_residents):
        kv, kk = {}, {}
        for labels in residents:
            for k, v in labels.items():
                kv[(k, v)] = kv.get((k, v), 0) + 1
                kk[k] = kk.get(k, 0) + 1
        res_kv.append(kv)
        res_key.append(kk)
        res_total[m] = len(residents)
    return res_kv, res_key, res_total


def _index_view(machine_residents):
    """Interned-engine view built from the same ground truth."""
    idx = ResidentLabelIndex()
    idx.activate()
    uuids = [f"m{m}" for m in range(len(machine_residents))]
    for u, residents in zip(uuids, machine_residents):
        for labels in residents:
            idx.add(u, labels)
    return idx.view(uuids)


class TestRandomizedParity:
    def test_pod_mask_parity_randomized(self):
        """All four selector types, the self-satisfying bootstrap rule,
        and empty-resident machines, across 25 random instances: the
        vectorized engine is bit-identical to the dict-probe oracle."""
        rng = np.random.default_rng(seed=1234)
        for trial in range(25):
            M = int(rng.integers(1, 30))
            E = int(rng.integers(1, 12))
            # Some machines get zero residents; residents get random
            # (often empty) label maps.
            machine_residents = [
                [_random_labels(rng)
                 for _ in range(int(rng.integers(0, 5)))]
                for _ in range(M)
            ]
            ec_aff, ec_anti, ec_labels = [], [], []
            for _ in range(E):
                ec_aff.append(tuple(
                    _random_selector(rng)
                    for _ in range(int(rng.integers(0, 3)))
                ))
                ec_anti.append(tuple(
                    _random_selector(rng)
                    for _ in range(int(rng.integers(0, 2)))
                ))
                # EC labels sometimes self-satisfy an affinity selector
                # (the bootstrap rule's branch).
                ec_labels.append(_random_labels(rng, p_empty=0.4))

            res_kv, res_key, res_total = _dict_aggregates(machine_residents)
            want = pod_selector_admissibility_dicts(
                ec_aff, ec_anti, ec_labels, res_kv, res_key, res_total
            )
            got = pod_selector_admissibility(
                ec_aff, ec_anti, ec_labels, _index_view(machine_residents)
            )
            np.testing.assert_array_equal(got, want, err_msg=f"{trial=}")

    def test_machine_label_parity_randomized(self):
        """Node-selector admissibility: interned index vs probe loop."""
        rng = np.random.default_rng(seed=99)
        for trial in range(25):
            M = int(rng.integers(1, 40))
            E = int(rng.integers(1, 10))
            labels = [_random_labels(rng) for _ in range(M)]
            sels = [
                tuple(_random_selector(rng)
                      for _ in range(int(rng.integers(0, 3))))
                for _ in range(E)
            ]
            want = selector_admissibility(sels, labels)
            got = selector_admissibility(
                sels, labels, MachineLabelIndex.build(labels)
            )
            np.testing.assert_array_equal(got, want, err_msg=f"{trial=}")

    def test_duplicate_values_not_double_counted(self):
        """NOT_IN_SET with repeated values: the oracle sums over
        set(values); the interned engine must dedupe columns the same
        way or a single matching resident double-subtracts."""
        residents = [[{"app": "a"}, {}]]  # one machine, 2 residents
        sel = (NOT_IN_SET, "app", ("a", "a"))
        res_kv, res_key, res_total = _dict_aggregates(residents)
        want = pod_selector_admissibility_dicts(
            [(sel,)], [()], [{}], res_kv, res_key, res_total
        )
        got = pod_selector_admissibility(
            [(sel,)], [()], [{}], _index_view(residents)
        )
        np.testing.assert_array_equal(got, want)
        assert want[0, 0]  # the label-less resident satisfies NOT_IN

    def test_unknown_label_columns(self):
        """Selectors naming labels no resident ever carried: IN/EXISTS
        match nowhere, NOT_IN/NOT_EXISTS match wherever any resident
        runs — on both engines."""
        residents = [[{"app": "a"}], []]
        view = _index_view(residents)
        res_kv, res_key, res_total = _dict_aggregates(residents)
        for sel in [
            (IN_SET, "ghost", ("x",)),
            (EXISTS_KEY, "ghost", ()),
            (NOT_IN_SET, "ghost", ("x",)),
            (NOT_EXISTS_KEY, "ghost", ()),
        ]:
            want = pod_selector_admissibility_dicts(
                [(sel,)], [()], [{}], res_kv, res_key, res_total
            )
            got = pod_selector_admissibility([(sel,)], [()], [{}], view)
            np.testing.assert_array_equal(got, want, err_msg=str(sel))


def _rebuild_counts(state, uuids):
    """From-scratch resident aggregates straight off task state — the
    reference the incremental index must always equal."""
    col = {u: j for j, u in enumerate(uuids)}
    kv, kk = [{} for _ in uuids], [{} for _ in uuids]
    total = np.zeros(len(uuids), dtype=np.int64)
    for t in state.tasks.values():
        if t.scheduled_to is None:
            continue
        j = col.get(t.scheduled_to)
        if j is None:
            continue
        total[j] += 1
        for k, v in t.labels.items():
            kv[j][(k, v)] = kv[j].get((k, v), 0) + 1
            kk[j][k] = kk[j].get(k, 0) + 1
    return kv, kk, total


def _assert_index_matches_rebuild(state):
    uuids = sorted(state.machines)
    want_kv, want_key, want_total = _rebuild_counts(state, uuids)
    view = state._residency.view(uuids)
    np.testing.assert_array_equal(view.total, want_total)
    for j in range(len(uuids)):
        got_kv = {
            pair: int(view.kv_counts[j, c])
            for pair, c in view.kv_id.items()
            if c < view.kv_counts.shape[1] and view.kv_counts[j, c]
        }
        assert got_kv == want_kv[j], uuids[j]
        got_key = {
            k: int(view.key_counts[j, c])
            for k, c in view.key_id.items()
            if c < view.key_counts.shape[1] and view.key_counts[j, c]
        }
        assert got_key == want_key[j], uuids[j]


class TestIncrementalMaintenance:
    def test_interleaved_deltas_match_rebuild(self):
        """Place / complete / preempt / migrate / relabel / fail /
        node-remove deltas interleave; after every batch the maintained
        counts equal a from-scratch rebuild."""
        rng = np.random.default_rng(seed=7)
        st = ClusterState(use_native=False)
        uuids = []
        for i in range(8):
            u = generate_uuid(f"inc{i}")
            uuids.append(u)
            st.node_added(MachineInfo(
                uuid=u, cpu_capacity=64000, ram_capacity=1 << 26,
                task_slots=64,
            ))
        # One pod-selector task keeps the engine active throughout.
        st.task_submitted(TaskInfo(
            uid=1, job_id="anchor", cpu_request=10, ram_request=1 << 10,
            pod_affinity=((IN_SET, "app", ("a",)),),
        ))
        for uid in range(2, 120):
            st.task_submitted(TaskInfo(
                uid=uid, job_id=f"j{uid % 7}", cpu_request=10,
                ram_request=1 << 10, labels=_random_labels(rng),
            ))
        st.build_round_view()  # activates the incremental index
        assert st._residency.active

        live = list(range(2, 120))
        for step in range(40):
            op = int(rng.integers(5))
            pick = [int(u) for u in rng.choice(
                live, size=min(len(live), 8), replace=False
            )]
            if op == 0:  # place / migrate a batch (some to None)
                st.apply_placements([
                    (u, uuids[int(rng.integers(len(uuids)))]
                     if rng.random() < 0.8 else None)
                    for u in pick
                ])
            elif op == 1:  # complete
                for u in pick[:3]:
                    st.task_completed(u)
                    live.remove(u)
            elif op == 2:  # preempt (unplace)
                st.apply_placements([(u, None) for u in pick[:4]])
            elif op == 3:  # relabel in place (TaskUpdated)
                for u in pick[:3]:
                    t = st.tasks[u]
                    st.task_updated(TaskInfo(
                        uid=u, job_id=t.job_id,
                        cpu_request=t.cpu_request,
                        ram_request=t.ram_request,
                        labels=_random_labels(rng),
                    ))
            else:  # remove + resubmit fresh
                for u in pick[:2]:
                    st.task_removed(u)
                    st.task_submitted(TaskInfo(
                        uid=u, job_id="fresh", cpu_request=10,
                        ram_request=1 << 10,
                        labels=_random_labels(rng),
                    ))
            _assert_index_matches_rebuild(st)

        # Machine failure and removal evict residents from the counts.
        st.node_failed(uuids[0])
        _assert_index_matches_rebuild(st)
        st.node_removed(uuids[1])
        _assert_index_matches_rebuild(st)

    def test_deactivates_when_last_pod_selector_task_leaves(self):
        st = ClusterState(use_native=False)
        st.node_added(MachineInfo(
            uuid=generate_uuid("d0"), cpu_capacity=4000,
            ram_capacity=1 << 24,
        ))
        st.task_submitted(TaskInfo(
            uid=1, job_id="a", cpu_request=10, ram_request=1 << 10,
            pod_affinity=((IN_SET, "app", ("a",)),),
        ))
        st.build_round_view()
        assert st._residency.active
        st.task_removed(1)
        assert not st._residency.active
        # Reactivation rebuilds from live task state.
        st.task_submitted(TaskInfo(
            uid=2, job_id="a", cpu_request=10, ram_request=1 << 10,
            pod_anti_affinity=((IN_SET, "app", ("a",)),),
        ))
        st.build_round_view()
        assert st._residency.active

    def test_column_compaction_keeps_counts(self):
        """Rolling label vocabularies (ver=v0, v1, ...) must not grow
        the column space without bound, and compaction must preserve
        the live counts."""
        import poseidon_tpu.graph.residency as R

        idx = ResidentLabelIndex()
        idx.activate()
        for i in range(3 * R._COMPACT_MIN_COLS):
            idx.add("m0", {"ver": f"v{i}"})
            idx.remove("m0", {"ver": f"v{i}"})
        idx.add("m0", {"ver": "live"})
        assert len(idx.kv_id) <= R._COMPACT_MIN_COLS
        view = idx.view(["m0", "m1"])
        assert int(view.total[0]) == 1 and int(view.total[1]) == 0
        c = view.kv_id[("ver", "live")]
        assert int(view.kv_counts[0, c]) == 1

    def test_label_index_cache_keyed_on_node_generation(self):
        st = ClusterState(use_native=False)
        u = generate_uuid("lc0")
        st.node_added(MachineInfo(
            uuid=u, cpu_capacity=4000, ram_capacity=1 << 24,
            labels={"zone": "z1"},
        ))
        st.task_submitted(TaskInfo(
            uid=1, job_id="a", cpu_request=10, ram_request=1 << 10,
        ))
        v1 = st.build_round_view()
        st.apply_placements([(1, None)])  # task churn, nodes unchanged
        v2 = st.build_round_view()
        assert v2.machines.label_index is v1.machines.label_index
        st.node_updated(MachineInfo(
            uuid=u, cpu_capacity=4000, ram_capacity=1 << 24,
            labels={"zone": "z2"},
        ))
        v3 = st.build_round_view()
        assert v3.machines.label_index is not v2.machines.label_index
        mask = selector_admissibility(
            [((IN_SET, "zone", ("z2",)),)], v3.machines.labels,
            v3.machines.label_index,
        )
        assert mask.tolist() == [[True]]


class TestEndToEndThroughPlanner:
    def test_restart_from_checkpoint_keeps_affinity(self, tmp_path):
        """The mask engine's state is derived: a checkpoint restore
        rebuilds it through the mutators and affinity still resolves."""
        from poseidon_tpu.graph.snapshot import (
            load_checkpoint,
            save_checkpoint,
        )

        st = ClusterState(use_native=False)
        for i in range(3):
            st.node_added(MachineInfo(
                uuid=generate_uuid(f"ck{i}"), cpu_capacity=4000,
                ram_capacity=1 << 24,
            ))
        planner = RoundPlanner(st, get_cost_model("cpu_mem"))
        st.task_submitted(TaskInfo(
            uid=1, job_id="db", cpu_request=100, ram_request=1 << 18,
            labels={"app": "db"},
        ))
        planner.schedule_round()
        path = tmp_path / "mask.ckpt"
        save_checkpoint(st, planner, path)
        st2, planner2 = load_checkpoint(path, use_native=False)
        st2.task_submitted(TaskInfo(
            uid=2, job_id="web", cpu_request=100, ram_request=1 << 18,
            pod_affinity=((IN_SET, "app", ("db",)),),
        ))
        planner2.schedule_round()
        assert (st2.tasks[2].scheduled_to
                == st2.tasks[1].scheduled_to is not None)
