"""On-device convergence telemetry (PR 13): the per-iteration sample
ring threaded through the lax/fused/tiled/sharded kernels.

Contracts pinned here:

- telemetry-OFF reproduces today's iterate bit-for-bit (the ring never
  feeds back; with the cap at 0 the traced program is the historical
  one);
- the ring is bit-identical across the lax, fused, and tiled kernels
  (the arithmetic is shared, so the sampled excess sequence must be
  too);
- decode semantics: full curves under the cap, last-cap-samples with
  correct ordering when the ring wraps, per-sample bf sweeps summing to
  the solve's total;
- the sharded path carries per-shard machine-side excess lanes and
  still fetches everything in ONE host_fetch batch
  (TransferLedger(budget=0) holds with telemetry on);
- the planner rolls curves into RoundMetrics and the digest is
  JSON-safe.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.ops import transport as T
from poseidon_tpu.ops.transport import (
    INF_COST,
    TELEM_ROWS,
    _POS,
    SolveTelemetry,
    _host_validate,
    _solve_device,
    decode_telemetry,
    solve_telemetry_cap,
    solve_transport,
)


def _instance(seed, E, M, max_cost=1000, cap_hi=4):
    rng = np.random.default_rng(seed)
    costs = rng.integers(0, max_cost, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < 0.1] = INF_COST
    supply = rng.integers(1, 8, size=E).astype(np.int32)
    capacity = rng.integers(1, cap_hi, size=M).astype(np.int32)
    unsched = rng.integers(max_cost, 2 * max_cost, size=E).astype(np.int32)
    return costs, supply, capacity, unsched


def _device_args(costs, supply, capacity, unsched):
    E, M = costs.shape
    arc_cap = np.full((E, M), _POS, dtype=np.int32)
    prices = np.zeros(E + M + 1, dtype=np.int32)
    flows = np.zeros((E, M), dtype=np.int32)
    fb = np.zeros(E, dtype=np.int32)
    scale, eps_sched, _ = _host_validate(
        costs, supply, capacity, unsched, None, None
    )
    return (
        (costs, supply, capacity, unsched, arc_cap, prices, flows, fb,
         jnp.asarray(eps_sched), jnp.int32(32768), jnp.int32(4),
         jnp.int32(64), jnp.int32(0)),
        int(scale),
    )


# ------------------------------------------------------------ cap semantics


def test_cap_hatch_semantics(monkeypatch):
    monkeypatch.delenv("POSEIDON_SOLVE_TELEMETRY", raising=False)
    monkeypatch.delenv("POSEIDON_SOLVE_TELEMETRY_CAP", raising=False)
    assert solve_telemetry_cap() == 512          # default on, lane-aligned
    monkeypatch.setenv("POSEIDON_SOLVE_TELEMETRY_CAP", "100")
    assert solve_telemetry_cap() == 128          # rounded up to 128
    monkeypatch.setenv("POSEIDON_SOLVE_TELEMETRY_CAP", "0")
    assert solve_telemetry_cap() == 0
    monkeypatch.setenv("POSEIDON_SOLVE_TELEMETRY_CAP", "512")
    monkeypatch.setenv("POSEIDON_SOLVE_TELEMETRY", "0")
    assert solve_telemetry_cap() == 0            # master switch wins


# ------------------------------------------------- off-path bit-identity


def test_telemetry_off_is_bit_identical(monkeypatch):
    costs, supply, capacity, unsched = _instance(1, 16, 96)
    monkeypatch.delenv("POSEIDON_SOLVE_TELEMETRY", raising=False)
    on = solve_transport(costs, supply, capacity, unsched)
    monkeypatch.setenv("POSEIDON_SOLVE_TELEMETRY", "0")
    off = solve_transport(costs, supply, capacity, unsched)
    assert off.telemetry is None
    assert on.objective == off.objective
    assert on.iterations == off.iterations
    assert on.bf_sweeps == off.bf_sweeps
    np.testing.assert_array_equal(on.flows, off.flows)
    np.testing.assert_array_equal(on.unsched, off.unsched)
    np.testing.assert_array_equal(on.prices, off.prices)


def test_seven_tuple_contract_preserved_without_cap():
    costs, supply, capacity, unsched = _instance(2, 8, 64)
    args, scale = _device_args(costs, supply, capacity, unsched)
    out = _solve_device(*args, max_iter=4096, scale=scale)
    assert len(out) == 7


# -------------------------------------------------------- curve semantics


def test_curve_decodes_full_solve():
    costs, supply, capacity, unsched = _instance(3, 16, 96, cap_hi=2)
    sol = solve_transport(costs, supply, capacity, unsched)
    t = sol.telemetry
    assert t is not None and sol.iterations > 0
    assert t.samples() == min(sol.iterations, t.cap)
    assert t.total_iters == sol.iterations
    # Sample ordering: consecutive global iteration indices.
    assert (np.diff(t.iters) == 1).all()
    # Per-iteration BF sweeps sum to the solve's reported total (no
    # wrap at this size), and every global-update firing carried
    # sweeps >= 0 while non-firing iterations carried none.
    assert int(t.bf_sweeps.sum()) == sol.bf_sweeps
    assert t.gu_firings() >= 1
    assert (t.bf_sweeps[t.gu_fired == 0] == 0).all()
    # The first iteration of a cold contended solve has active excess.
    assert int(t.active_excess[0]) > 0
    assert (t.active_rows >= 0).all() and (t.active_cols >= 0).all()
    # eps rungs are drawn from the (descending) ladder.
    assert set(np.unique(t.eps)) <= set(
        T.eps_schedule(int(t.eps.max())).tolist()
    ) | {int(t.eps.max()), 1}


def test_ring_wrap_keeps_last_cap_samples(monkeypatch):
    monkeypatch.setenv("POSEIDON_SOLVE_TELEMETRY_CAP", "128")
    costs, supply, capacity, unsched = _instance(4, 48, 256, cap_hi=2)
    sol = solve_transport(costs, supply, capacity, unsched,
                          greedy_init=False)
    t = sol.telemetry
    assert t is not None
    if sol.iterations <= t.cap:
        pytest.skip(f"solve too short to wrap ({sol.iterations} iters)")
    assert t.cap == 128
    assert t.wrapped() and t.samples() == 128
    # The decoded window is the LAST cap iterations, oldest first.
    assert int(t.iters[-1]) == sol.iterations - 1
    assert (np.diff(t.iters) == 1).all()


def test_decode_telemetry_unit():
    cap = 8
    ring = np.zeros((TELEM_ROWS, cap), dtype=np.int32)
    # Simulate 11 iterations: slot = it % 8.
    for it in range(11):
        ring[T._TR_ITER, it % cap] = it
        ring[T._TR_EXCESS, it % cap] = 100 - it
    t = decode_telemetry(ring, 11)
    assert t.samples() == 8 and t.wrapped()
    assert list(t.iters) == list(range(3, 11))
    assert list(t.active_excess) == [100 - i for i in range(3, 11)]
    # Under-full ring decodes only the written prefix (fresh ring: the
    # wrap simulation above already overwrote the early slots).
    ring5 = np.zeros((TELEM_ROWS, cap), dtype=np.int32)
    for it in range(5):
        ring5[T._TR_ITER, it] = it
    t2 = decode_telemetry(ring5, 5)
    assert list(t2.iters) == list(range(5))
    assert decode_telemetry(ring, 0) is None
    assert decode_telemetry(np.zeros((TELEM_ROWS, 0), np.int32), 5) is None


def test_half_life_and_drain_metrics():
    n = 10
    t = SolveTelemetry(
        iters=np.arange(n),
        active_excess=np.array([100, 90, 55, 49, 30, 20, 11, 9, 4, 0]),
        active_rows=np.ones(n, np.int32),
        active_cols=np.ones(n, np.int32),
        eps=np.full(n, 7, np.int32),
        gu_fired=np.zeros(n, np.int32),
        bf_sweeps=np.zeros(n, np.int32),
        total_iters=n, cap=512,
    )
    assert t.decay_half_life() == 3.0    # first sample <= 50 is index 3
    assert t.iters_to_drain(0.9) == 7    # first sample <= ~10 is index 7
    d = t.digest(max_points=4)
    json.dumps(d)                        # JSON-safe by contract
    assert d["samples"] == n and d["iters"][-1] == n - 1
    assert d["decay_half_life"] == 3.0 and d["iters_to_90"] == 7
    assert len(d["iters"]) <= 5          # stride + forced last point


# ------------------------------------------------------- kernel bit-parity


def test_ring_bit_identical_across_kernels():
    from poseidon_tpu.ops.transport_fused import solve_device_fused
    from poseidon_tpu.ops.transport_tiled import solve_device_tiled

    costs, supply, capacity, unsched = _instance(5, 16, 128, cap_hi=2)
    args, scale = _device_args(costs, supply, capacity, unsched)
    lax_out = _solve_device(*args, max_iter=8192, scale=scale,
                            telem_cap=256)
    fused_out = solve_device_fused(*args, max_iter=8192, scale=scale,
                                   interpret=True, telem_cap=256)
    tiled_out = solve_device_tiled(*args, max_iter=8192, scale=scale,
                                   interpret=True, telem_cap=256)
    ring_lax = np.asarray(lax_out[7])
    assert int(lax_out[3]) > 0
    np.testing.assert_array_equal(ring_lax, np.asarray(fused_out[7]))
    np.testing.assert_array_equal(ring_lax, np.asarray(tiled_out[7]))
    # And the ring really sampled the solve.
    t = decode_telemetry(ring_lax, int(lax_out[3]))
    assert t is not None and t.samples() == min(int(lax_out[3]), 256)


def test_saturation_lane_decodes_identically_across_kernels():
    """The _TR_SAT lane (PR 19) rides the shared ring: every kernel
    must emit the same saturation flags, and at toy scale — where the
    active-excess total sits far below the 2^30 clamp threshold — the
    lane must decode to all-zero (no false positives)."""
    from poseidon_tpu.ops.transport_fused import solve_device_fused
    from poseidon_tpu.ops.transport_tiled import solve_device_tiled

    costs, supply, capacity, unsched = _instance(5, 16, 128, cap_hi=2)
    args, scale = _device_args(costs, supply, capacity, unsched)
    lax_out = _solve_device(*args, max_iter=8192, scale=scale,
                            telem_cap=256)
    fused_out = solve_device_fused(*args, max_iter=8192, scale=scale,
                                   interpret=True, telem_cap=256)
    tiled_out = solve_device_tiled(*args, max_iter=8192, scale=scale,
                                   interpret=True, telem_cap=256)
    decoded = [
        decode_telemetry(np.asarray(out[7]), int(out[3]))
        for out in (lax_out, fused_out, tiled_out)
    ]
    base = decoded[0]
    assert base is not None and base.saturated is not None
    for t in decoded[1:]:
        assert t is not None
        np.testing.assert_array_equal(base.saturated, t.saturated)
        assert t.saturated_samples() == base.saturated_samples()
    # Toy instances never approach the clamp threshold: a nonzero lane
    # here would mean the flag fires spuriously on healthy solves.
    assert base.saturated_samples() == 0
    assert all(t.digest()["saturated_samples"] == 0 for t in decoded)


# ----------------------------------------------------------- sharded lanes


def test_sharded_per_shard_lanes_and_single_fetch():
    import jax

    from poseidon_tpu.check.ledger import TransferLedger
    from poseidon_tpu.ops.transport_sharded import (
        make_solver_mesh,
        solve_transport_sharded,
    )

    assert len(jax.devices()) >= 8
    mesh = make_solver_mesh(8)
    costs, supply, capacity, unsched = _instance(6, 12, 48, cap_hi=2)
    with TransferLedger(budget=0, label="sharded telemetry solve"):
        sol = solve_transport_sharded(
            costs, supply, capacity, unsched, mesh=mesh,
        )
    single = solve_transport(costs, supply, capacity, unsched)
    assert sol.objective == single.objective
    t = sol.telemetry
    if sol.iterations == 0:
        pytest.skip("instance certified without a device ladder")
    assert t is not None
    assert t.shard_excess is not None and t.shard_excess.shape[0] == 8
    # Shard lanes decompose the machine-side active excess: each lane
    # is non-negative and their per-iteration sum is bounded by the
    # total active excess sample (EC-side excess adds on top).
    assert (t.shard_excess >= 0).all()
    assert (t.shard_excess.sum(axis=0) <= t.active_excess).all()
    json.dumps(t.digest())  # shard lanes ride the digest JSON-safely


# -------------------------------------------------------- planner roll-up


def test_planner_rolls_curves_into_round_metrics():
    from bench import contended_cluster
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    # The shared contention recipe (more demand than comfortable
    # capacity) — the solve runs real iterations and captures a curve.
    state = contended_cluster(prefix="tj")
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    _, metrics = planner.schedule_round()
    if metrics.iterations == 0:
        pytest.skip("instance certified without device iterations")
    assert metrics.telem_samples > 0
    assert metrics.telem_iters_to_90 >= 0
    assert planner.last_solve_curves
    d = planner.last_solve_curves[0]
    json.dumps(planner.last_solve_curves)
    assert d["samples"] > 0 and "band" in d
    # The wire format carries the roll-ups end to end.
    from poseidon_tpu.graph.instance import RoundMetrics

    m2 = RoundMetrics.from_dict(json.loads(json.dumps(metrics.to_dict())))
    assert m2.telem_samples == metrics.telem_samples
