"""Whare-Map, CoCo, and net-aware cost models.

Each test drives the model end-to-end through a RoundPlanner so the census
/ bandwidth accounting paths in the round view are exercised, not just the
pure cost arithmetic.
"""


from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import generate_uuid

SHEEP, RABBIT, DEVIL, TURTLE = 0, 1, 2, 3


def two_machines(**kw):
    st = ClusterState()
    for name in ("a", "b"):
        st.node_added(
            MachineInfo(
                uuid=generate_uuid(name), cpu_capacity=8000,
                ram_capacity=1 << 24, **kw,
            )
        )
    return st, generate_uuid("a"), generate_uuid("b")


class TestWhareMap:
    def test_devil_avoids_turtle(self):
        st, a, b = two_machines()
        # A turtle already lives on machine a.
        turtle = TaskInfo(uid=1, job_id="j", cpu_request=100,
                          ram_request=1 << 18, task_type=TURTLE)
        st.task_submitted(turtle)
        st.apply_placement(1, a)
        planner = RoundPlanner(
            st, get_cost_model("whare"), preemption=False
        )
        # A devil arrives: interference pushes it to the empty machine b.
        st.task_submitted(
            TaskInfo(uid=2, job_id="j2", cpu_request=100,
                     ram_request=1 << 18, task_type=DEVIL)
        )
        deltas, _ = planner.schedule_round()
        placed = {d.task_id: d.resource_id for d in deltas}
        assert placed[2] == b

    def test_descriptor_census_counts(self):
        st, a, b = two_machines()
        # Machine a reports resident devils via WhareMapStats.
        st.machines[a].whare_stats = (0, 5, 0, 0, 0)  # idle, devils, ...
        planner = RoundPlanner(st, get_cost_model("whare"))
        st.task_submitted(
            TaskInfo(uid=3, job_id="j", cpu_request=100,
                     ram_request=1 << 18, task_type=TURTLE)
        )
        deltas, _ = planner.schedule_round()
        assert deltas[0].resource_id == b

    def test_sheep_indifferent(self):
        st, a, b = two_machines()
        planner = RoundPlanner(st, get_cost_model("whare"))
        st.task_submitted(
            TaskInfo(uid=4, job_id="j", cpu_request=100,
                     ram_request=1 << 18, task_type=SHEEP)
        )
        deltas, m = planner.schedule_round()
        assert m.placed == 1 and m.gap_bound == 0.0


class TestCoCo:
    def test_penalty_vector_steers(self):
        st, a, b = two_machines()
        # Machine a punishes devils hard; b is indifferent.
        st.machines[a].coco_penalties = (500, 0, 0, 0)  # devil, rabbit, sheep, turtle
        st.machines[b].coco_penalties = (0, 0, 0, 0)
        planner = RoundPlanner(st, get_cost_model("coco"))
        st.task_submitted(
            TaskInfo(uid=1, job_id="j", cpu_request=100,
                     ram_request=1 << 18, task_type=DEVIL)
        )
        deltas, _ = planner.schedule_round()
        assert deltas[0].resource_id == b

    def test_sheep_unaffected_by_devil_penalty(self):
        st, a, b = two_machines()
        st.machines[a].coco_penalties = (500, 0, 0, 0)
        st.machines[b].coco_penalties = (0, 0, 400, 0)  # punishes sheep
        planner = RoundPlanner(st, get_cost_model("coco"))
        st.task_submitted(
            TaskInfo(uid=1, job_id="j", cpu_request=100,
                     ram_request=1 << 18, task_type=SHEEP)
        )
        deltas, _ = planner.schedule_round()
        assert deltas[0].resource_id == a


class TestNetAware:
    def test_bandwidth_gates_admission(self):
        st = ClusterState()
        st.node_added(
            MachineInfo(uuid=generate_uuid("thin"), cpu_capacity=8000,
                        ram_capacity=1 << 24, net_rx_capacity=100)
        )
        st.node_added(
            MachineInfo(uuid=generate_uuid("fat"), cpu_capacity=8000,
                        ram_capacity=1 << 24, net_rx_capacity=10_000)
        )
        planner = RoundPlanner(st, get_cost_model("net"))
        st.task_submitted(
            TaskInfo(uid=1, job_id="j", cpu_request=100,
                     ram_request=1 << 18, net_rx_request=500)
        )
        deltas, _ = planner.schedule_round()
        assert deltas[0].resource_id == generate_uuid("fat")

    def test_bandwidth_saturation_blocks(self):
        st = ClusterState()
        st.node_added(
            MachineInfo(uuid=generate_uuid("only"), cpu_capacity=8000,
                        ram_capacity=1 << 24, net_rx_capacity=1000)
        )
        planner = RoundPlanner(st, get_cost_model("net"))
        for i in range(3):
            st.task_submitted(
                TaskInfo(uid=10 + i, job_id="j", cpu_request=100,
                         ram_request=1 << 18, net_rx_request=400)
            )
        deltas, m = planner.schedule_round()
        # Only 2 x 400 fit into 1000: one task stays unscheduled.
        assert m.placed == 2 and m.unscheduled == 1

    def test_committed_bandwidth_accounted_across_rounds(self):
        st = ClusterState()
        st.node_added(
            MachineInfo(uuid=generate_uuid("m"), cpu_capacity=8000,
                        ram_capacity=1 << 24, net_rx_capacity=1000)
        )
        planner = RoundPlanner(st, get_cost_model("net"))
        st.task_submitted(
            TaskInfo(uid=1, job_id="j", cpu_request=100,
                     ram_request=1 << 18, net_rx_request=800)
        )
        planner.schedule_round()
        # Second round: the running task holds 800 of 1000; 300 more
        # cannot fit.
        st.task_submitted(
            TaskInfo(uid=2, job_id="j", cpu_request=100,
                     ram_request=1 << 18, net_rx_request=300)
        )
        deltas, m = planner.schedule_round()
        # Task 2 waits; the running task must NOT be evicted by its own
        # bandwidth reservation (self-reuse in the fit check).
        assert m.unscheduled == 1
        assert m.preempted == 0 and deltas == []

    def test_zero_capacity_machines_unaccounted(self):
        st, a, b = two_machines()  # net_rx_capacity defaults to 0
        planner = RoundPlanner(st, get_cost_model("net"))
        st.task_submitted(
            TaskInfo(uid=1, job_id="j", cpu_request=100,
                     ram_request=1 << 18, net_rx_request=10_000)
        )
        _, m = planner.schedule_round()
        assert m.placed == 1  # no accounting -> always admits
