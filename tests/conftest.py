"""Test configuration: force an 8-device virtual CPU mesh.

Tests must never depend on TPU hardware; multi-chip sharding is validated on
a virtual CPU mesh (the driver separately dry-runs the multichip path).
These env vars must be set before jax is first imported.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
