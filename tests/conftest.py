"""Test configuration: force an 8-device virtual CPU mesh.

Tests must never depend on TPU hardware; multi-chip sharding is validated on
a virtual CPU mesh (the driver separately dry-runs the multichip path).
The environment may pre-import jax with a TPU platform pinned (sitecustomize
registering an accelerator plugin), so plain env vars are too late —
``jax.config.update`` still works as long as no backend has been used yet,
which is guaranteed here because conftest runs before any test module.
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
