"""Fused Pallas ladder kernel: BIT-parity with the lax solver path.

The fused kernel (ops/transport_fused.py) re-implements the exact same
int32 update sequence as ops/transport.py's ``_solve_device``, so on any
instance its flows, prices, iteration counts, BF sweeps, and per-phase
splits must be IDENTICAL — not merely cost-equal.  These tests run the
kernel in Pallas interpret mode (no TPU in CI) via POSEIDON_FUSED=1.
"""

import numpy as np
import pytest

from poseidon_tpu.ops import transport
from poseidon_tpu.ops.transport import solve_transport
from poseidon_tpu.ops.transport_fused import _kernel_shape, fits_vmem


def _instance(E, M, seed, contended=False):
    rng = np.random.default_rng(seed)
    costs = rng.integers(0, 1000, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < 0.1] = transport.INF_COST
    supply = rng.integers(1, 9, size=E).astype(np.int32)
    cap = (
        np.full(M, max(1, int(supply.sum()) // (2 * M) + 1), np.int32)
        if contended
        else rng.integers(1, 12, size=M).astype(np.int32)
    )
    unsched = rng.integers(1000, 2000, size=E).astype(np.int32)
    arc = rng.integers(1, 6, size=(E, M)).astype(np.int32)
    return costs, supply, cap, unsched, arc


def _solve_both(monkeypatch, *args, **kw):
    monkeypatch.setenv("POSEIDON_FUSED", "0")
    lax_sol = solve_transport(*args, **kw)
    monkeypatch.setenv("POSEIDON_FUSED", "1")
    fused_sol = solve_transport(*args, **kw)
    return lax_sol, fused_sol


def _assert_bit_equal(a, b):
    np.testing.assert_array_equal(a.flows, b.flows)
    np.testing.assert_array_equal(a.unsched, b.unsched)
    np.testing.assert_array_equal(a.prices, b.prices)
    assert a.objective == b.objective
    assert a.gap_bound == b.gap_bound
    assert a.iterations == b.iterations
    assert a.bf_sweeps == b.bf_sweeps
    assert a.phase_iters == b.phase_iters


@pytest.mark.parametrize("seed", range(3))
def test_fused_bit_parity_cold(monkeypatch, seed):
    costs, supply, cap, unsched, arc = _instance(24, 96, seed)
    a, b = _solve_both(
        monkeypatch, costs, supply, cap, unsched, arc_capacity=arc
    )
    _assert_bit_equal(a, b)
    assert a.gap_bound == 0.0


def test_fused_bit_parity_contended(monkeypatch):
    # Contention drives long multi-phase ladders with global updates and
    # sink push-back — the full code path surface.
    costs, supply, cap, unsched, arc = _instance(16, 64, 7, contended=True)
    a, b = _solve_both(
        monkeypatch, costs, supply, cap, unsched, arc_capacity=arc
    )
    _assert_bit_equal(a, b)
    assert a.iterations > 0


def test_fused_bit_parity_warm_start(monkeypatch):
    costs, supply, cap, unsched, arc = _instance(16, 64, 11)
    monkeypatch.setenv("POSEIDON_FUSED", "0")
    first = solve_transport(
        costs, supply, cap, unsched, arc_capacity=arc
    )
    # Drift the costs, then warm-start both paths from the same frame.
    costs2 = np.where(
        costs < transport.INF_COST, costs + 3, costs
    ).astype(np.int32)
    kw = dict(
        arc_capacity=arc, init_flows=first.flows,
        init_unsched=first.unsched, eps_start=4 * 97,
    )
    a, b = _solve_both(
        monkeypatch, costs2, supply, cap, unsched, first.prices, **kw
    )
    _assert_bit_equal(a, b)


def test_fused_bit_parity_unaligned_bucket(monkeypatch):
    # M=280 pads to bucket 320, which is NOT lane-aligned (320 % 128 !=
    # 0): the kernel re-pads to 384 with inert columns — results must be
    # unchanged.
    costs, supply, cap, unsched, arc = _instance(10, 280, 13)
    a, b = _solve_both(
        monkeypatch, costs, supply, cap, unsched, arc_capacity=arc
    )
    _assert_bit_equal(a, b)


def test_kernel_shape_alignment():
    assert _kernel_shape(8, 320) == (8, 384)
    assert _kernel_shape(10, 128) == (16, 128)
    assert _kernel_shape(256, 1024) == (256, 1024)


def test_fits_vmem_gate():
    assert fits_vmem(64, 512)
    assert fits_vmem(128, 1024)    # proven good on live v5e (1.74x)
    assert fits_vmem(128, 1280)    # the calibrated budget edge
    assert not fits_vmem(128, 2048)  # live v5e: scoped-VMEM OOM (20.71M/16M)
    assert not fits_vmem(256, 10240)  # the 10k full-wave width


def test_fused_failure_degrades_to_lax(monkeypatch):
    """A backend whose Mosaic lowering rejects the kernel must fall back
    to the lax path (identical math) and latch off — never fail solves."""
    import poseidon_tpu.ops.transport as T
    import poseidon_tpu.ops.transport_fused as TF

    def boom(*a, **k):
        raise RuntimeError("mosaic lowering failed")

    monkeypatch.setenv("POSEIDON_FUSED", "1")
    monkeypatch.setattr(TF, "solve_device_fused", boom)
    monkeypatch.setattr(T, "_FUSED_BROKEN", set())
    # The packed dispatch wrapper may hold a cached executable for this
    # shape from earlier tests, which would bypass the monkeypatched
    # kernel entirely (a cached trace never re-imports the module attr).
    T._solve_device_packed.clear_cache()
    costs, supply, cap, unsched, arc = _instance(12, 64, 3)
    sol = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    assert sol.gap_bound == 0.0
    assert T._FUSED_BROKEN  # latched: later solves skip the broken path
    monkeypatch.setenv("POSEIDON_FUSED", "0")
    ref = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    assert sol.objective == ref.objective


def test_fused_bit_parity_all_inadmissible(monkeypatch):
    """Everything unscheduled: the fallback-arc-only path through the
    kernel (every unit rides the EC->sink arc)."""
    E, M = 8, 128
    costs = np.full((E, M), transport.INF_COST, dtype=np.int32)
    supply = np.arange(1, E + 1, dtype=np.int32)
    cap = np.full(M, 4, np.int32)
    unsched = np.full(E, 1500, np.int32)
    a, b = _solve_both(monkeypatch, costs, supply, cap, unsched)
    _assert_bit_equal(a, b)
    assert (a.unsched == supply).all()


def test_fused_bit_parity_zero_supply_rows(monkeypatch):
    costs, supply, cap, unsched, arc = _instance(8, 128, 21)
    supply[::2] = 0
    a, b = _solve_both(
        monkeypatch, costs, supply, cap, unsched, arc_capacity=arc
    )
    _assert_bit_equal(a, b)
