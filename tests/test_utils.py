from poseidon_tpu.utils import ids
from poseidon_tpu.utils.config import (
    FirmamentTPUConfig,
    PoseidonConfig,
    load_config,
)


def test_fnv64a_known_vectors():
    # Standard FNV-1a 64 test vectors.
    assert ids.fnv64a("") == 0xCBF29CE484222325
    assert ids.fnv64a("a") == 0xAF63DC4C8601EC8C
    assert ids.fnv64a("foobar") == 0x85944171F73967E8


def test_uuid_deterministic_and_valid():
    u1 = ids.generate_uuid("default/my-job")
    u2 = ids.generate_uuid("default/my-job")
    u3 = ids.generate_uuid("default/other-job")
    assert u1 == u2 != u3
    parts = u1.split("-")
    assert [len(p) for p in parts] == [8, 4, 4, 4, 12]
    assert parts[2][0] == "4"  # version 4
    assert parts[3][0] in "89ab"  # RFC4122 variant


def test_task_uid_hash_combine():
    job = ids.generate_uuid("ns/job")
    uids = {ids.task_uid(job, i) for i in range(100)}
    assert len(uids) == 100  # no collisions across indices
    assert ids.task_uid(job, 0) == ids.task_uid(job, 0)


def test_config_defaults_match_reference():
    cfg = load_config(PoseidonConfig, argv=[])
    assert cfg.scheduler_name == "poseidon"
    assert cfg.firmament_address == "firmament-service.kube-system:9090"
    assert cfg.stats_server_address == "0.0.0.0:9091"
    assert cfg.scheduling_interval == 10.0


def test_config_file_and_flag_precedence(tmp_path):
    f = tmp_path / "cfg.yaml"
    f.write_text("schedulerName: custom\nschedulingInterval: 3\n")
    cfg = load_config(PoseidonConfig, argv=[f"--config-file={f}"])
    assert cfg.scheduler_name == "custom"
    assert cfg.scheduling_interval == 3
    # Explicit flags beat the file (config.go:113-128 semantics).
    cfg = load_config(
        PoseidonConfig,
        argv=[f"--config-file={f}", "--scheduler-name=flagwins"],
    )
    assert cfg.scheduler_name == "flagwins"


def test_service_config():
    cfg = load_config(FirmamentTPUConfig, argv=["--cost-model=trivial"])
    assert cfg.cost_model == "trivial"
    assert cfg.flow_solver == "auction"


def test_config_strictness_and_bool_flags():
    import pytest

    # Unknown flags are errors (pflag semantics), not silently dropped.
    with pytest.raises(SystemExit):
        load_config(PoseidonConfig, argv=["--cost-modle=coco"])
    # Bare bool flag means true; explicit false works; garbage is an error.
    assert load_config(FirmamentTPUConfig, argv=["--gang-scheduling"]).gang_scheduling
    assert not load_config(
        FirmamentTPUConfig, argv=["--gang-scheduling=false"]
    ).gang_scheduling
    with pytest.raises(SystemExit):
        load_config(FirmamentTPUConfig, argv=["--gang-scheduling=ture"])


def test_kube_version_parsing():
    from poseidon_tpu.utils.config import PoseidonConfig
    import pytest

    assert PoseidonConfig(kube_version="1.28").kube_version_tuple() == (1, 28)
    # Malformed versions fail loudly, as the reference's GetKubeVersion
    # fatals (config.go:61-72).
    for bad in ("latest", "1", "1.x"):
        with pytest.raises(ValueError):
            PoseidonConfig(kube_version=bad).kube_version_tuple()


# ---------------------------------------------------------------- device lock


def test_serialize_device_access_noop_on_cpu(monkeypatch):
    # CPU-pinned processes (every test, per conftest) never contend for
    # the accelerator, so the lock is a no-op success.
    from poseidon_tpu.utils import envutil

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(envutil, "_device_lock_fd", None)
    assert envutil.serialize_device_access(timeout=0.1)
    assert envutil._device_lock_fd is None  # no fd opened


def test_serialize_device_access_excludes_second_process(
    monkeypatch, tmp_path
):
    # Holder in a subprocess -> this process's acquire times out (False);
    # after the holder exits, acquire succeeds and is reentrant.
    import subprocess
    import sys
    import textwrap

    from poseidon_tpu.utils import envutil

    lock = tmp_path / "device.lock"
    monkeypatch.setenv("JAX_PLATFORMS", "")  # accelerator-capable
    monkeypatch.setenv("POSEIDON_DEVICE_LOCK", str(lock))
    monkeypatch.setattr(envutil, "_device_lock_fd", None)

    holder = subprocess.Popen(
        [sys.executable, "-c", textwrap.dedent(f"""
            import fcntl, os, sys, time
            fd = os.open({str(lock)!r}, os.O_CREAT | os.O_RDWR)
            fcntl.flock(fd, fcntl.LOCK_EX)
            print("held", flush=True)
            sys.stdin.read()  # hold until stdin closes
        """)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
    )
    try:
        assert holder.stdout.readline().strip() == "held"
        assert not envutil.serialize_device_access(timeout=0.1)
        assert envutil._device_lock_fd is None
    finally:
        holder.stdin.close()
        holder.wait(timeout=30)
    assert envutil.serialize_device_access(timeout=5.0)
    assert envutil._device_lock_fd is not None
    assert envutil.serialize_device_access(timeout=0.0)  # reentrant
    # Cleanup: release for later tests in this process.
    import os as _os

    _os.close(envutil._device_lock_fd)
    monkeypatch.setattr(envutil, "_device_lock_fd", None)


def test_install_graceful_term_exits_at_bytecode_boundary():
    # SIGTERM must terminate the child cleanly (exit 143) from its Python
    # loop — the semantics that let the bench parent stop a chip-holding
    # child without killing it mid-device-op.
    import signal
    import subprocess
    import sys
    import textwrap

    child = subprocess.Popen(
        [sys.executable, "-c", textwrap.dedent("""
            from poseidon_tpu.utils.envutil import install_graceful_term
            install_graceful_term()
            print("ready", flush=True)
            while True:
                pass
        """)],
        stdout=subprocess.PIPE, text=True,
    )
    try:
        assert child.stdout.readline().strip() == "ready"
        child.send_signal(signal.SIGTERM)
        assert child.wait(timeout=30) == 143
    finally:
        if child.poll() is None:
            child.kill()
