"""Trace replay harness: generation + end-to-end replay."""

from poseidon_tpu.replay import ReplayDriver, synthesize_trace


def test_trace_shape():
    events = synthesize_trace(20, 50, seed=1)
    kinds = [e.kind for e in events]
    assert kinds.count("machine_add") == 20
    assert kinds.count("job_submit") == 50
    times = [e.time for e in events]
    assert times == sorted(times)


def test_replay_small_cluster():
    events = synthesize_trace(16, 40, horizon_s=600.0, seed=2)
    driver = ReplayDriver(events, round_interval_s=30.0)
    report = driver.run(max_rounds=40)
    assert report.rounds > 0
    assert report.tasks_submitted > 0
    # The vast majority of the workload gets placed over the replay.
    assert report.placed >= 0.8 * report.tasks_submitted
    # Tasks complete as their durations elapse.
    assert report.tasks_completed > 0
    s = report.summary()
    assert s["round_p50_s"] >= 0.0 and s["rounds"] == report.rounds


def test_replay_gang_mode():
    events = synthesize_trace(16, 20, horizon_s=300.0, seed=3)
    driver = ReplayDriver(events, round_interval_s=30.0, gang_jobs=True)
    report = driver.run(max_rounds=20)
    # Gang atomicity holds per round by construction; the replay must
    # still make progress.
    assert report.placed > 0


def test_trace_machine_remove_events():
    events = synthesize_trace(40, 30, horizon_s=600.0, seed=5,
                              remove_frac=0.25)
    kinds = [e.kind for e in events]
    assert kinds.count("machine_remove") == 10
    # Removals land in the middle half of the horizon, after the fleet
    # joins — pressure on a loaded cluster, not a cold one.
    times = [e.time for e in events if e.kind == "machine_remove"]
    assert all(150.0 <= t <= 450.0 for t in times)


def test_pressure_replay_exercises_preempt_and_migrate():
    """Capacity pressure (machine removals) under continuous rebalancing
    must surface the PREEMPT/MIGRATE delta paths — the reference client
    treats both as first-class (poseidon.go:52-63), and a pure
    submit/complete replay never emits either."""
    events = synthesize_trace(24, 60, horizon_s=600.0, seed=6,
                              remove_frac=0.25)
    driver = ReplayDriver(events, round_interval_s=30.0,
                          reschedule_running=True)
    report = driver.run(max_rounds=20)
    assert report.placed > 0
    assert report.preempted + report.migrated > 0, (
        report.preempted, report.migrated
    )
    # Pressure rounds must stay certified: uncertified placements would
    # make the delta counts meaningless.
    assert report.converged
