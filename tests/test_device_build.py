"""Device-side cost build vs the host cpu_mem build + _solve_banded
column capacities: integer surfaces EXACT, float-derived costs within
one normalized-cost unit (float32 on device vs float64 on host)."""

import numpy as np
import pytest

from poseidon_tpu.costmodel.base import ECTable, MachineTable
from poseidon_tpu.costmodel.cpu_mem import CpuMemCostModel
from poseidon_tpu.costmodel.device_build import (
    device_cost_build,
    extract_band_operands,
)
from poseidon_tpu.ops.transport import INF_COST


def _tables(rng, E, M, *, obs=False, selectors=False, waits=False):
    ecs = ECTable(
        ec_ids=np.arange(E, dtype=np.uint64),
        cpu_request=rng.integers(0, 4000, size=E).astype(np.int64),
        ram_request=rng.integers(1 << 16, 1 << 22, size=E).astype(np.int64),
        supply=rng.integers(1, 8, size=E).astype(np.int32),
        priority=np.zeros(E, dtype=np.int32),
        task_type=np.zeros(E, dtype=np.int32),
        max_wait_rounds=(
            rng.integers(0, 40, size=E).astype(np.int32) if waits
            else np.zeros(E, dtype=np.int32)
        ),
        selectors=[
            ((0, "zone", ("a",)),) if selectors and i % 3 == 0 else ()
            for i in range(E)
        ],
    )
    labels = [
        {"zone": "a" if m % 2 == 0 else "b"} for m in range(M)
    ]
    cpu_cap = rng.integers(4000, 64000, size=M).astype(np.int64)
    ram_cap = rng.integers(1 << 22, 1 << 26, size=M).astype(np.int64)
    cpu_used = (cpu_cap * rng.random(M) * 0.8).astype(np.int64)
    ram_used = (ram_cap * rng.random(M) * 0.8).astype(np.int64)
    mt = MachineTable(
        uuids=[f"m{m}" for m in range(M)],
        cpu_capacity=cpu_cap, ram_capacity=ram_cap,
        cpu_used=cpu_used, ram_used=ram_used,
        cpu_util=rng.random(M).astype(np.float32),
        mem_util=rng.random(M).astype(np.float32),
        slots_free=rng.integers(0, 64, size=M).astype(np.int32),
        labels=labels,
    )
    if obs:
        mt.cpu_obs_used = (cpu_used * rng.uniform(0.5, 1.5, M)).astype(
            np.int64
        )
        mt.ram_obs_used = (ram_used * rng.uniform(0.5, 1.5, M)).astype(
            np.int64
        )
    return ecs, mt


def _host_reference(ecs, mt, model, delta_cpu, delta_ram, delta_slots):
    """What _solve_banded computes: cost build at the committed view +
    the per-column capacity denominator."""
    from dataclasses import replace

    committed_cpu = mt.cpu_used + delta_cpu
    committed_ram = mt.ram_used + delta_ram
    kw = {}
    if mt.cpu_obs_used is not None:
        kw["cpu_obs_used"] = mt.cpu_obs_used + delta_cpu
    if mt.ram_obs_used is not None:
        kw["ram_obs_used"] = mt.ram_obs_used + delta_ram
    mt_b = replace(
        mt, cpu_used=committed_cpu, ram_used=committed_ram,
        slots_free=np.maximum(mt.slots_free - delta_slots, 0).astype(
            np.int32
        ), **kw,
    )
    cm = model.build(ecs, mt_b)
    adm = cm.costs < INF_COST
    col_cap = cm.capacity.astype(np.int64)
    for req, cap_arr, used in (
        (ecs.cpu_request, mt.cpu_capacity, committed_cpu),
        (ecs.ram_request, mt.ram_capacity, committed_ram),
    ):
        denom = np.where(adm, req.astype(np.int64)[:, None], 0).max(axis=0)
        free = np.maximum(cap_arr.astype(np.int64) - used, 0)
        col_cap = np.where(
            denom > 0, np.minimum(col_cap, free // np.maximum(denom, 1)),
            col_cap,
        )
    return cm, np.clip(col_cap, 0, None).astype(np.int32)


@pytest.mark.parametrize("seed,obs,selectors,waits", [
    (0, False, False, False),
    (1, True, False, True),
    (2, False, True, False),
    (3, True, True, True),
])
def test_device_build_matches_host(seed, obs, selectors, waits):
    rng = np.random.default_rng(seed)
    E, M = 24, 60
    model = CpuMemCostModel()
    ecs, mt = _tables(rng, E, M, obs=obs, selectors=selectors, waits=waits)
    # Simulate an earlier band's committed load.
    delta_cpu = rng.integers(0, 2000, size=M).astype(np.int64)
    delta_ram = rng.integers(0, 1 << 20, size=M).astype(np.int64)
    delta_slots = rng.integers(0, 8, size=M).astype(np.int64)

    cm, col_ref = _host_reference(
        ecs, mt, model, delta_cpu, delta_ram, delta_slots
    )
    ops = extract_band_operands(ecs, mt, model)
    costs, arc, capacity, col = (
        np.asarray(x) for x in device_cost_build(
            ops, delta_cpu.astype(np.int32), delta_ram.astype(np.int32),
            delta_slots.astype(np.int32),
        )
    )

    # Integer surfaces: EXACT.
    np.testing.assert_array_equal(arc, cm.arc_capacity)
    np.testing.assert_array_equal(capacity, cm.capacity)
    np.testing.assert_array_equal(col, col_ref)
    # Admissibility (INF placement) must agree everywhere.
    np.testing.assert_array_equal(costs >= INF_COST, cm.costs >= INF_COST)
    # Float-derived finite costs: within one normalized unit.
    finite = cm.costs < INF_COST
    diff = np.abs(
        costs.astype(np.int64)[finite] - cm.costs.astype(np.int64)[finite]
    )
    assert diff.max(initial=0) <= 1
    assert (diff > 0).mean() < 0.02 if diff.size else True


def test_device_build_unsched_escalator():
    rng = np.random.default_rng(9)
    ecs, mt = _tables(rng, 8, 10, waits=True)
    model = CpuMemCostModel()
    ops = extract_band_operands(ecs, mt, model)
    cm = model.build(ecs, mt)
    np.testing.assert_array_equal(ops["unsched"], cm.unsched_cost)


def test_int_surfaces_host_matches_device():
    """The chained path rebuilds band-2's integer surfaces host-side
    from fetched deltas (int_surfaces_host) instead of fetching them;
    they must be BIT-equal to what device_cost_build produced."""
    from poseidon_tpu.costmodel.device_build import int_surfaces_host

    rng = np.random.default_rng(17)
    model = CpuMemCostModel()
    ecs, mt = _tables(rng, 16, 40, obs=True, selectors=True, waits=True)
    ops = extract_band_operands(ecs, mt, model)
    ops["anti_self"] = ops["anti_self"].astype(np.int32)
    delta_cpu = rng.integers(0, 3000, size=40).astype(np.int64)
    delta_ram = rng.integers(0, 1 << 21, size=40).astype(np.int64)
    delta_slots = rng.integers(0, 6, size=40).astype(np.int64)
    _c, arc_d, cap_d, col_d = (
        np.asarray(x) for x in device_cost_build(
            ops, delta_cpu.astype(np.int32), delta_ram.astype(np.int32),
            delta_slots.astype(np.int32),
        )
    )
    arc_h, cap_h, col_h = int_surfaces_host(
        ops, delta_cpu, delta_ram, delta_slots
    )
    np.testing.assert_array_equal(arc_h, arc_d)
    np.testing.assert_array_equal(cap_h, cap_d)
    np.testing.assert_array_equal(col_h, col_d)
