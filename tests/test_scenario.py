"""Scenario subsystem unit tier: generator determinism, plan wire
forms, named-scenario shape properties, the trace lowering, the
perturbed cost model's purity contracts, and the round-metrics
placements_per_sec wire pin.

Everything here is planner-side or pure — no glue stack, no gRPC, no
drives.  The full-stack drive gates (sync/streaming identity, budget-0
warm ledgers, robustness scoring, flight redrive) live in the
slow-marked ``tests/test_scenario_smoke.py`` (``make scenario-smoke``).
"""

from types import SimpleNamespace

import numpy as np
import pytest

from poseidon_tpu.costmodel.base import (
    CostMatrices,
    CostModel,
    NORMALIZED_COST,
)
from poseidon_tpu.graph.instance import RoundMetrics
from poseidon_tpu.scenario.generate import (
    SCENARIOS,
    SETTLE_ROUNDS,
    named_scenario,
)
from poseidon_tpu.scenario.plan import (
    PodArrival,
    ScenarioPlan,
    ScenarioRound,
    kv,
    workload_events,
)
from poseidon_tpu.scenario.score import PerturbedCostModel

MACHINES = 16
ROUNDS = 8

INF_COST = 1 << 28


# --------------------------------------------------------------- generators


def test_generator_determinism_randomized():
    """Same (name, seed, machines, rounds) -> bit-identical plan, for
    every registered scenario across a spread of seeds; different seeds
    must move the digest."""
    seeds = (0, 3, 7, 1234, 999983)
    for name in SCENARIOS:
        digests = set()
        for seed in seeds:
            a = named_scenario(
                name, machines=MACHINES, rounds=ROUNDS, seed=seed
            )
            b = named_scenario(
                name, machines=MACHINES, rounds=ROUNDS, seed=seed
            )
            assert a.to_json() == b.to_json(), (name, seed)
            assert a.digest() == b.digest(), (name, seed)
            digests.add(a.digest())
        assert len(digests) == len(seeds), (
            f"{name}: seeds collided on a digest"
        )


def test_generator_streams_independent_across_names():
    """Two scenarios sharing a seed must not share an RNG stream (the
    name is folded into the seed key)."""
    plans = {
        name: named_scenario(name, machines=MACHINES, rounds=ROUNDS, seed=5)
        for name in SCENARIOS
    }
    digests = {p.digest() for p in plans.values()}
    assert len(digests) == len(SCENARIOS)


def test_plan_wire_roundtrip():
    for name in SCENARIOS:
        p = named_scenario(name, machines=MACHINES, rounds=ROUNDS, seed=2)
        assert ScenarioPlan.from_dict(p.to_dict()) == p
        assert ScenarioPlan.from_json(p.to_json()) == p
        assert ScenarioPlan.from_json(p.to_json()).digest() == p.digest()


def test_registry_unknown_name():
    with pytest.raises(KeyError, match="unknown scenario"):
        named_scenario("thundering_herd")


def test_round_contiguity_enforced():
    with pytest.raises(ValueError, match="contiguous"):
        ScenarioPlan(
            name="bad", seed=0, machines=4,
            rounds=(ScenarioRound(round_index=1),),
        )


def test_every_plan_ends_with_settle_rounds():
    for name in SCENARIOS:
        p = named_scenario(name, machines=MACHINES, rounds=ROUNDS, seed=0)
        assert p.total_rounds == ROUNDS + SETTLE_ROUNDS
        for rnd in p.rounds[-SETTLE_ROUNDS:]:
            assert not rnd.arrivals
            assert rnd.completions > 0  # settle keeps draining


# ------------------------------------------------------- scenario shapes


def test_flash_crowd_burst_shape():
    p = named_scenario(
        "flash_crowd", machines=MACHINES, rounds=ROUNDS, seed=0
    )
    burst_round = max(ROUNDS // 2, 2)
    quiet = len(p.rounds[1].arrivals)
    burst = len(p.rounds[burst_round].arrivals)
    assert burst >= 4 * quiet
    # The crowd is owner-grouped (job-shaped), the baseline is not.
    assert all(a.owner for a in p.rounds[burst_round].arrivals)
    assert all(not a.owner for a in p.rounds[1].arrivals)


def test_node_churn_fleet_motion():
    p = named_scenario(
        "node_churn", machines=MACHINES, rounds=ROUNDS, seed=0
    )
    added = [n for r in p.rounds for n in r.add_nodes]
    drained = [n for r in p.rounds for n in r.drain_nodes]
    assert added and drained
    assert len(drained) <= len(added)  # capacity never net-shrinks
    # Fresh nodes get fresh names; drains hit the original fleet.
    assert all(int(n[1:]) >= MACHINES for n in added)
    assert all(int(n[1:]) < MACHINES for n in drained)


def test_rolling_restart_steady_population():
    p = named_scenario(
        "rolling_restart", machines=MACHINES, rounds=ROUNDS, seed=0
    )
    for r in range(1, ROUNDS):
        rnd = p.rounds[r]
        assert len(rnd.arrivals) == rnd.completions  # wave in == wave out
        assert all(a.owner.startswith("restart-deploy-")
                   for a in rnd.arrivals)


def test_diurnal_curve_breathes():
    p = named_scenario("diurnal", machines=MACHINES, rounds=ROUNDS, seed=0)
    active = [len(r.arrivals) for r in p.rounds[1:ROUNDS]]
    assert max(active) > min(active)  # the sinusoid actually moves


def test_multi_tenant_constraints_and_zones():
    p = named_scenario(
        "multi_tenant", machines=MACHINES, rounds=ROUNDS, seed=0
    )
    labels = p.node_label_map()
    assert set(labels) == {f"m{i:04d}" for i in range(MACHINES)}
    assert {d["zone"] for d in labels.values()} == {"z0", "z1", "z2"}

    arrivals = [a for r in p.rounds for a in r.arrivals]
    gangs = [a for a in arrivals
             if dict(a.labels).get("gangScheduling") == "true"]
    serving = [a for a in arrivals if a.pod_anti_affinity]
    be = [a for a in arrivals if dict(a.labels).get("tenant") == "be"]
    assert gangs and serving and be

    # Whole gangs only: every gang owner groups >= 2 identically-shaped
    # pods (a partial or mixed-shape gang would break atomic placement).
    by_owner = {}
    for a in gangs:
        assert a.owner
        assert dict(a.node_selector) == {"zone": "z0"}
        by_owner.setdefault(a.owner, []).append(a)
    for members in by_owner.values():
        assert len(members) >= 2
        assert len({(m.cpu, m.ram) for m in members}) == 1

    # Serving replicas: zone-pinned, anti-affine against their own app.
    for a in serving:
        assert dict(a.node_selector) == {"zone": "z1"}
        assert dict(a.pod_anti_affinity) == {"app": dict(a.labels)["app"]}

    # Constraint fan-out is why this scenario's EC bucket is the widest.
    assert p.max_window_ec_keys() > named_scenario(
        "diurnal", machines=MACHINES, rounds=ROUNDS, seed=0
    ).max_window_ec_keys()


def test_ec_key_gang_owner_split():
    shape = dict(cpu=400, ram=1 << 19)
    gang = kv({"gangScheduling": "true"})
    a = PodArrival(name="a", owner="j1", labels=gang, **shape)
    b = PodArrival(name="b", owner="j2", labels=gang, **shape)
    c = PodArrival(name="c", owner="j1", **shape)
    d = PodArrival(name="d", owner="j2", **shape)
    assert a.ec_key() != b.ec_key()  # gangs solve per owner
    assert c.ec_key() == d.ec_key()  # plain pods aggregate across owners


# ----------------------------------------------------------- trace lowering


def test_workload_events_lowering():
    p = named_scenario(
        "node_churn", machines=MACHINES, rounds=ROUNDS, seed=0
    )
    events = workload_events(p)
    kinds = {e.kind for e in events}
    assert kinds == {"machine_add", "machine_remove", "job_submit"}
    assert [e.kind for e in events if e.time == 0.0].count(
        "machine_add"
    ) == MACHINES
    assert [(e.time, e.kind) for e in events] == sorted(
        (e.time, e.kind) for e in events
    )
    # job_submit payload is (id, count, cpu, ram, deadline): the counts
    # must account for every planned arrival.
    submitted = sum(e.payload[1] for e in events if e.kind == "job_submit")
    assert submitted == p.total_arrivals()


# ------------------------------------------------------ perturbed cost model


class _StubModel(CostModel):
    """Content-pure stand-in: cost[e, m] depends only on (ec_id, uuid),
    with a deterministic sprinkling of inadmissible (INF) cells — so
    slice-purity of the wrapper is testable against slice-purity of the
    base."""

    name = "stub"
    delta_plane = True  # the wrapper must force its own off

    def _ukeys(self, uuids):
        return np.array([sum(u.encode()) % 300 for u in uuids],
                        dtype=np.int64)

    def build(self, ecs, machines):
        row = (ecs.ec_ids.astype(np.int64) % 500)[:, None]
        col = self._ukeys(machines.uuids)[None, :]
        costs = (row + col + 100).astype(np.int32)
        costs[(row + col) % 5 == 0] = INF_COST
        e, m = costs.shape
        return CostMatrices(
            costs=costs,
            unsched_cost=np.full(e, 7 * NORMALIZED_COST, dtype=np.int32),
            capacity=np.full(m, 16, dtype=np.int32),
            arc_capacity=np.full((e, m), 4, dtype=np.int32),
        )

    def build_unsched(self, ecs):
        return np.full(ecs.ec_ids.shape[0], 7 * NORMALIZED_COST,
                       dtype=np.int32)

    def build_capacity(self, machines):
        return np.full(len(machines.uuids), 16, dtype=np.int32)

    def max_cost(self):
        return 8 * NORMALIZED_COST


def _tables(n_ecs=12, n_machines=9):
    ecs = SimpleNamespace(ec_ids=np.arange(
        101, 101 + 17 * n_ecs, 17, dtype=np.uint64
    ))
    machines = SimpleNamespace(
        uuids=[f"uuid-{i:03d}-{'ab'[i % 2]}" for i in range(n_machines)]
    )
    return ecs, machines


def test_perturbed_model_contracts():
    inner = _StubModel()
    ecs, machines = _tables()
    amplitude = 0.25
    pm = PerturbedCostModel(inner, seed=11, amplitude=amplitude)

    # Wrapper identity: delta-plane forced off, seed in the name,
    # feasibility surfaces forwarded untouched.
    assert pm.delta_plane is False
    assert pm.name == "stub+perturb11"
    assert pm.max_cost() == inner.max_cost()
    np.testing.assert_array_equal(
        pm.build_unsched(ecs), inner.build_unsched(ecs)
    )
    np.testing.assert_array_equal(
        pm.build_capacity(machines), inner.build_capacity(machines)
    )

    base = inner.build(ecs, machines)
    out = pm.build(ecs, machines)
    inf = base.costs >= INF_COST
    # Inadmissible arcs never move; capacity/unsched ride through.
    np.testing.assert_array_equal(out.costs[inf], base.costs[inf])
    np.testing.assert_array_equal(out.capacity, base.capacity)
    np.testing.assert_array_equal(out.arc_capacity, base.arc_capacity)
    np.testing.assert_array_equal(out.unsched_cost, base.unsched_cost)
    # Admissible cells stay inside the static bound (no fresh compile
    # keys) and within the amplitude band, and the noise actually bites.
    adm = ~inf
    assert out.costs[adm].min() >= 0
    assert out.costs[adm].max() <= inner.max_cost()
    bound = amplitude * NORMALIZED_COST + 1
    assert np.abs(
        out.costs[adm].astype(np.int64) - base.costs[adm]
    ).max() <= bound
    assert np.any(out.costs[adm] != base.costs[adm])


def test_perturbed_model_determinism_and_seed_sensitivity():
    inner = _StubModel()
    ecs, machines = _tables()
    a = PerturbedCostModel(inner, seed=3, amplitude=0.2)
    b = PerturbedCostModel(inner, seed=3, amplitude=0.2)
    c = PerturbedCostModel(inner, seed=4, amplitude=0.2)
    np.testing.assert_array_equal(
        a.build(ecs, machines).costs, b.build(ecs, machines).costs
    )
    assert np.any(
        a.build(ecs, machines).costs != c.build(ecs, machines).costs
    )


def test_perturbed_model_slice_purity():
    """A cell's perturbed price is a pure function of (seed, EC id,
    machine uuid): pricing a row/column subset must reproduce the
    corresponding cells of the full build exactly."""
    inner = _StubModel()
    ecs, machines = _tables()
    pm = PerturbedCostModel(inner, seed=9, amplitude=0.3)
    full = pm.build(ecs, machines).costs

    rows = [1, 4, 7, 10]
    cols = [0, 2, 5, 8]
    sub_ecs = SimpleNamespace(ec_ids=ecs.ec_ids[rows])
    sub_machines = SimpleNamespace(
        uuids=[machines.uuids[c] for c in cols]
    )
    sub = pm.build(sub_ecs, sub_machines).costs
    np.testing.assert_array_equal(sub, full[np.ix_(rows, cols)])


# -------------------------------------------------------- scenario metrics


def test_observe_scenario_exposition():
    """The scenario rung's Prometheus face: one gauge family per
    headline series, labelled by scenario name."""
    from poseidon_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.Registry()
    obs_metrics.observe_scenario(
        "diurnal", robustness_score=0.8, placements_per_sec=123.0,
        regression_p90=0.25, placement_divergence=0.5,
        admission_staleness_p50_s=0.01, admission_staleness_p99_s=0.09,
        ok=True, registry=reg,
    )
    obs_metrics.observe_scenario("node_churn", ok=False, registry=reg)
    text = reg.expose()
    assert 'poseidon_scenario_robustness_score{scenario="diurnal"} 0.8' \
        in text
    assert 'poseidon_scenario_placements_per_sec{scenario="diurnal"} ' \
        "123" in text
    assert 'poseidon_scenario_ok{scenario="diurnal"} 1' in text
    assert 'poseidon_scenario_ok{scenario="node_churn"} 0' in text


# ------------------------------------------------- placements/sec wire pin


def test_round_metrics_placements_per_sec_wire():
    """Satellite pin: placements_per_sec is a first-class RoundMetrics
    wire field — serialized by to_dict, round-tripped by from_dict, and
    defaulted (not erred) when absent from an older artifact."""
    m = RoundMetrics(round_index=2, placed=50, total_seconds=2.0,
                     placements_per_sec=25.0)
    d = m.to_dict()
    assert d["placements_per_sec"] == 25.0
    assert RoundMetrics.from_dict(d).placements_per_sec == 25.0
    legacy = {k: v for k, v in d.items() if k != "placements_per_sec"}
    assert RoundMetrics.from_dict(legacy).placements_per_sec == 0.0


def test_planner_stamps_placements_per_sec_sync():
    """The planner itself stamps the throughput figure at the end of
    schedule_round — so the synchronous loop reports it too, not just
    the streaming engine (which used to compute it glue-side)."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    state = ClusterState()
    for i in range(4):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"pps-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=16,
        ))
    for i in range(6):
        state.task_submitted(TaskInfo(
            uid=task_uid("pps", i), job_id="pps-j",
            cpu_request=400, ram_request=1 << 19,
        ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    assert m.placed == 6
    assert m.total_seconds > 0
    assert m.placements_per_sec == round(m.placed / m.total_seconds, 3)
    assert m.placements_per_sec > 0
