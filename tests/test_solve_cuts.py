"""The joint-solve mode (solve_mode="cuts"): one transportation solve
with per-arc fit bounds plus capacity-cut/gang repair passes, vs the
size-banded ladder.  Must never oversubscribe a machine and should place
at least as cheaply as the banded decomposition."""

import numpy as np
import pytest

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import task_uid


def make_state(num_machines=6, num_tasks=30, seed=0, slots=100):
    rng = np.random.default_rng(seed)
    st = ClusterState()
    shapes = [(4000, 1 << 23), (8000, 1 << 24), (16000, 1 << 25)]
    for i in range(num_machines):
        cpu, ram = shapes[i % len(shapes)]
        st.node_added(MachineInfo(
            uuid=f"m-{i:03d}", cpu_capacity=cpu, ram_capacity=ram,
            task_slots=slots,
        ))
    for i in range(num_tasks):
        st.task_submitted(TaskInfo(
            uid=task_uid("cuts", i), job_id=f"j{i % 5}",
            cpu_request=int(rng.integers(1, 30)) * 100,
            ram_request=int(rng.integers(1, 32)) << 18,
        ))
    return st


def resource_safe(st):
    """No machine oversubscribed in any dimension."""
    used_cpu = {}
    used_ram = {}
    count = {}
    for t in st.tasks.values():
        if t.scheduled_to:
            used_cpu[t.scheduled_to] = (
                used_cpu.get(t.scheduled_to, 0) + t.cpu_request
            )
            used_ram[t.scheduled_to] = (
                used_ram.get(t.scheduled_to, 0) + t.ram_request
            )
            count[t.scheduled_to] = count.get(t.scheduled_to, 0) + 1
    for uuid, m in st.machines.items():
        assert used_cpu.get(uuid, 0) <= m.cpu_capacity, uuid
        assert used_ram.get(uuid, 0) <= m.ram_capacity, uuid
        assert count.get(uuid, 0) <= m.task_slots, uuid


@pytest.mark.parametrize("seed", range(5))
def test_cuts_mode_resource_safe(seed):
    st_c = make_state(seed=seed)
    pc = RoundPlanner(st_c, get_cost_model("cpu_mem"), solve_mode="cuts")
    _, mc = pc.schedule_round()
    resource_safe(st_c)
    assert mc.converged
    assert mc.placed + mc.unscheduled == 30


@pytest.mark.parametrize("seed", range(3))
def test_cuts_dominates_banded_when_uncontended(seed, caplog):
    """When no capacity cut fires, the joint solve IS the relaxation
    optimum and the banded ladder's solution is feasible for it, so the
    cuts objective provably matches or beats banded.  (Under contention
    the repaired solution carries no dominance theorem — not asserted.)"""
    import logging

    def plentiful(seed):
        st = make_state(num_machines=12, num_tasks=20, seed=seed)
        for m in st.machines.values():
            m.cpu_capacity *= 8
            m.ram_capacity *= 8
        return st

    st_c, st_b = plentiful(seed), plentiful(seed)
    pc = RoundPlanner(st_c, get_cost_model("cpu_mem"), solve_mode="cuts")
    pb = RoundPlanner(st_b, get_cost_model("cpu_mem"))
    with caplog.at_level(logging.WARNING, "poseidon_tpu.planner"):
        _, mc = pc.schedule_round()
    assert not any("did not settle" in r.message for r in caplog.records)
    _, mb = pb.schedule_round()
    resource_safe(st_c)
    assert mc.converged
    assert mc.objective <= mb.objective, (mc.objective, mb.objective)


def test_cuts_mode_scarce_capacity_repairs():
    """Heavy contention: the first joint solve necessarily overloads
    (task-count capacity >> resource capacity), so the repair loop must
    fire and still end resource-safe."""
    st = make_state(num_machines=3, num_tasks=40, seed=11, slots=100)
    planner = RoundPlanner(st, get_cost_model("cpu_mem"), solve_mode="cuts")
    _, m = planner.schedule_round()
    resource_safe(st)
    assert m.placed + m.unscheduled == 40
    assert m.placed > 0


def test_cuts_mode_gang_atomicity():
    st = ClusterState()
    for i in range(3):
        st.node_added(MachineInfo(
            uuid=f"m-{i}", cpu_capacity=1000, ram_capacity=1 << 24,
        ))
    for i in range(5):
        st.task_submitted(TaskInfo(
            uid=task_uid("gang", i), job_id="gang-job", cpu_request=1000,
            ram_request=1 << 18, gang=True,
        ))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"), solve_mode="cuts")
    _, m = planner.schedule_round()
    # 5-member gang cannot fully fit on 3 machines: all-or-nothing.
    assert m.placed == 0 and m.unscheduled == 5


def test_cuts_mode_through_service_config():
    from poseidon_tpu.service.server import FirmamentServicer
    from poseidon_tpu.utils.config import FirmamentTPUConfig

    sv = FirmamentServicer(config=FirmamentTPUConfig(solve_mode="cuts"))
    assert sv.planner.solve_mode == "cuts"


def test_unknown_solve_mode_rejected():
    st = ClusterState()
    with pytest.raises(ValueError):
        RoundPlanner(st, get_cost_model("cpu_mem"), solve_mode="magic")
