"""Single-dispatch coarse-to-fine solve (ops/transport_coarse.py).

Exactness bar: identical objective to the plain solve and the exact
oracle, zero-gap certificate, with the whole pipeline in ONE device
dispatch.  Pure XLA (no Pallas), so these run compiled on CPU.
"""

import numpy as np
import pytest

import poseidon_tpu.ops.transport as T
from poseidon_tpu.ops.transport_coarse import solve_transport_coarse_fused
from poseidon_tpu.solver import oracle


def _instance(E, M, seed=0, contended=True):
    rng = np.random.default_rng(seed)
    load = rng.integers(0, 400, size=M).astype(np.int32)
    base = rng.integers(50, 800, size=E).astype(np.int32)
    costs = (base[:, None] + load[None, :]).astype(np.int32)
    costs[rng.random((E, M)) < 0.05] = T.INF_COST
    supply = rng.integers(40, 90, size=E).astype(np.int32)
    cap = (rng.integers(1, 3, size=M) if contended
           else rng.integers(4, 9, size=M)).astype(np.int32)
    unsched = np.full(E, 5000, dtype=np.int32)
    arc = rng.integers(1, 6, size=(E, M)).astype(np.int32)
    return costs, supply, cap, unsched, arc


@pytest.fixture()
def small_gates(monkeypatch):
    monkeypatch.setattr(T, "COARSE_MIN_MACHINES", 32)


def test_fused_matches_oracle_and_plain(small_gates):
    costs, supply, cap, unsched, arc = _instance(12, 1200, seed=3)
    calls0 = T.device_call_count()
    sol = solve_transport_coarse_fused(
        costs, supply, cap, unsched, arc_capacity=arc,
    )
    assert sol is not None
    assert T.device_call_count() == calls0 + 1  # ONE dispatch, fused
    plain = T.solve_transport(costs, supply, cap, unsched,
                              arc_capacity=arc)
    assert sol.objective == plain.objective
    assert sol.gap_bound == 0.0
    want = oracle.transport_objective(costs, supply, cap, unsched,
                                      arc_capacity=arc)
    assert sol.objective == want
    # Committed arrays are feasible.
    assert (sol.flows.sum(axis=0) <= cap).all()
    assert (sol.flows.sum(axis=1) + sol.unsched == supply).all()


def test_fused_declines_like_the_host_path(small_gates):
    costs, supply, cap, unsched, arc = _instance(12, 1200, seed=3)
    # Thin supply: below 4 * groups.
    thin = np.ones(12, dtype=np.int32)
    assert solve_transport_coarse_fused(
        costs, thin, cap, unsched, arc_capacity=arc,
    ) is None
    # Small machine axis: below the (patched) COARSE_MIN_MACHINES.
    assert solve_transport_coarse_fused(
        costs[:, :24], supply, cap[:24], unsched,
        arc_capacity=arc[:, :24],
    ) is None
    # Uncontested (disjoint cheap tiers, ample capacity): the greedy
    # pre-check certifies, fused declines so the caller's single plain
    # dispatch wins.
    E2, M2 = 8, 1200
    c2 = np.full((E2, M2), 3000, dtype=np.int32)
    for e in range(E2):
        c2[e, e * 100:(e + 1) * 100] = 10 + e
    s2 = np.full(E2, 50, dtype=np.int32)
    cap2 = np.full(M2, 4, dtype=np.int32)
    u2 = np.full(E2, 6000, dtype=np.int32)
    assert solve_transport_coarse_fused(c2, s2, cap2, u2) is None


def test_fused_through_planner_matches_disabled(monkeypatch):
    """End to end through RoundPlanner with the fused path forced on:
    identical objective/placements to the path disabled."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    monkeypatch.setattr(T, "COARSE_MIN_MACHINES", 32)
    monkeypatch.setattr(T, "COARSE_GROUPS", 8)

    def build():
        state = ClusterState()
        rng = np.random.default_rng(5)
        for i in range(64):
            state.node_added(MachineInfo(
                uuid=f"cf-m{i}", cpu_capacity=int(rng.integers(4000, 16000)),
                ram_capacity=1 << 24, task_slots=6,
            ))
        for i in range(600):
            state.task_submitted(TaskInfo(
                uid=task_uid("cf", i), job_id=f"j{i % 8}",
                cpu_request=int(rng.integers(400, 2000)),
                ram_request=1 << 18,
            ))
        return state

    import poseidon_tpu.ops.transport_coarse as TC

    fused = {"n": 0}
    orig = TC.solve_transport_coarse_fused

    def spy(*a, **k):
        sol = orig(*a, **k)
        if sol is not None:
            fused["n"] += 1
        return sol

    monkeypatch.setattr(TC, "solve_transport_coarse_fused", spy)
    results = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("POSEIDON_COARSE_FUSED", flag)
        state = build()
        planner = RoundPlanner(state, get_cost_model("cpu_mem"))
        _, m = planner.schedule_round()
        assert m.converged and m.gap_bound == 0.0
        # OBJECTIVE equality only: both paths certify an exact optimum,
        # but degenerate optima let two exact solvers legally place
        # different task sets.
        results[flag] = m.objective
    assert fused["n"] > 0, "fused path never produced a solution"
    assert results["0"] == results["1"], results


@pytest.mark.parametrize("seed", range(3))
def test_device_certificate_matches_host(seed):
    """The in-program epsilon certificate must agree exactly with the
    host `_certified_eps` on arbitrary feasible states — the fused full
    ladder starts at this value, so an underestimate would silently
    degrade the lift to an uncertified start."""
    import jax.numpy as jnp

    from poseidon_tpu.ops.transport_coarse import _certified_eps_device

    rng = np.random.default_rng(seed)
    E, M = 16, 96
    costs = rng.integers(0, 3000, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < 0.1] = T.INF_COST
    supply = rng.integers(1, 30, size=E).astype(np.int32)
    cap = rng.integers(1, 6, size=M).astype(np.int32)
    unsched = rng.integers(3000, 6000, size=E).astype(np.int32)
    arc = rng.integers(1, 5, size=(E, M)).astype(np.int32)
    scale = 128

    # An arbitrary feasible state: greedy flows + alternation duals.
    flows = T.greedy_flows(costs, supply, cap, arc)
    left = (supply.astype(np.int64) - flows.sum(axis=1)).astype(np.int32)
    prices = np.concatenate([
        rng.integers(-5000, 0, size=E),
        rng.integers(-5000, 0, size=M),
        [-100],
    ]).astype(np.int32)

    want = T._certified_eps(
        flows, left, prices, costs=costs, supply=supply, capacity=cap,
        unsched_cost=unsched, scale=scale, arc_capacity=arc,
    )
    Cs = np.where(costs >= T.INF_COST, T.INF_COST,
                  costs * scale).astype(np.int32)
    Uem = np.minimum(np.minimum(supply[:, None], cap[None, :]), arc)
    got = int(_certified_eps_device(
        jnp.asarray(flows), jnp.asarray(left), jnp.asarray(prices),
        C=jnp.asarray(Cs), U=jnp.asarray(unsched * scale),
        Uem=jnp.asarray(Uem), capacity=jnp.asarray(cap),
        supply=jnp.asarray(supply), E=E, M=M,
    ))
    assert got == want, (got, want)


def test_fused_rejects_flow_mass_overflow(small_gates):
    """The fused path validates the FULL instance (its second stage runs
    the unclipped full-width push cumsums): int32 flow-mass overflow
    must raise exactly as in solve_transport, not silently aggregate
    past the guard."""
    costs, supply, cap, unsched, arc = _instance(12, 1200, seed=3)
    huge = np.full(1200, (1 << 30), dtype=np.int32)
    with pytest.raises(ValueError):
        solve_transport_coarse_fused(
            costs, supply, huge, unsched, arc_capacity=arc,
        )
