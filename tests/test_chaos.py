"""Chaos subsystem: fault plans, injection seams, hardening regressions.

Covers the deterministic fault-plan contract, the client's
deadline/retry/backoff hardening (against injected faults passing
through the REAL retry path), the glue's crash-loop budget,
transactional bind rollback, watcher resync, the planner's degraded
solve tier, and a full tiny soak (every fault family through the whole
stack).  The cluster-scale soak smoke lives in tests/test_soak_smoke.py
(slow tier, ``make soak-smoke``).
"""

import threading
import time

import grpc
import pytest

from poseidon_tpu.chaos import (
    ChaoticKube,
    FaultInjector,
    InjectedRpcError,
    chaotic_client,
    named_plan,
    run_soak,
)
from poseidon_tpu.chaos.plan import FAMILIES, Fault, FaultPlan
from poseidon_tpu.glue import FakeKube, Node, Pod, Poseidon
from poseidon_tpu.graph.state import TaskState
from poseidon_tpu.service import FirmamentTPUServer
from poseidon_tpu.service.client import FirmamentClient
from poseidon_tpu.utils.config import PoseidonConfig


# ------------------------------------------------------------------ the plan


class TestFaultPlan:
    def test_seed_reproducible(self):
        a = FaultPlan.generate("t", seed=7, rounds=12)
        b = FaultPlan.generate("t", seed=7, rounds=12)
        assert a == b
        assert FaultPlan.generate("t", seed=8, rounds=12) != a

    def test_roundtrip_and_round_lookup(self):
        plan = named_plan("smoke", 10, seed=3)
        assert FaultPlan.from_json(plan.to_json()) == plan
        listed = [f for r in range(10) for f in plan.for_round(r)]
        assert sorted(listed, key=lambda f: (f.round_index, f.kind)) == \
            sorted(plan.faults, key=lambda f: (f.round_index, f.kind))

    def test_smoke_plan_covers_every_family(self):
        plan = named_plan("smoke", 10, seed=0)
        assert plan.families_covered() == tuple(sorted(FAMILIES))

    def test_quiet_head_round_zero_fault_free(self):
        for seed in range(5):
            plan = named_plan("smoke", 10, seed=seed)
            assert plan.for_round(0) == []

    def test_unknown_plan_and_kind(self):
        with pytest.raises(KeyError):
            named_plan("nope", 5)
        with pytest.raises(ValueError):
            FaultPlan.generate("t", 0, 5, kinds=("not_a_kind",))


# ------------------------------------------------- client deadline/retry/backoff


def _plan_with(*faults: Fault) -> FaultPlan:
    return FaultPlan(name="test", seed=0, rounds=32, faults=tuple(faults))


@pytest.fixture()
def server():
    with FirmamentTPUServer(address="127.0.0.1:0") as srv:
        yield srv


def test_client_retry_absorbs_unavailable(server):
    injector = FaultInjector(_plan_with(
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
    ))
    injector.begin_round(0)
    client = chaotic_client(
        server.address, injector,
        rpc_retries=3, rpc_backoff_s=0.005, rpc_backoff_max_s=0.01,
    )
    from poseidon_tpu.protos import firmament_pb2 as fpb

    td = fpb.TaskDescriptor(uid=1, name="p", job_id="j")
    assert client.task_submitted(td) == fpb.TASK_SUBMITTED_OK
    fired = [e["kind"] for e in injector.fired]
    assert fired.count("rpc_unavailable") == 2  # both absorbed by retry
    client.close()


def test_client_retry_budget_exhausts(server):
    faults = tuple(
        Fault(0, "rpc_unavailable", target="TaskSubmitted")
        for _ in range(5)
    )
    injector = FaultInjector(_plan_with(*faults))
    injector.begin_round(0)
    client = chaotic_client(
        server.address, injector,
        rpc_retries=1, rpc_backoff_s=0.005, rpc_backoff_max_s=0.01,
    )
    from poseidon_tpu.protos import firmament_pb2 as fpb

    with pytest.raises(grpc.RpcError):
        client.task_submitted(fpb.TaskDescriptor(uid=1, name="p"))
    client.close()


def test_schedule_does_not_retry_deadline(server):
    """A deadline on Schedule is commit-ambiguous: the client must raise,
    not blind-retry (the glue's suspect reconciler owns the heal)."""
    injector = FaultInjector(_plan_with(
        Fault(0, "rpc_deadline", target="Schedule"),
    ))
    injector.begin_round(0)
    client = chaotic_client(
        server.address, injector, rpc_retries=3, rpc_backoff_s=0.005,
    )
    with pytest.raises(grpc.RpcError):
        client.schedule()
    # The fault fired exactly once: no retry consumed a second one.
    assert [e["kind"] for e in injector.fired] == ["rpc_deadline"]
    # UNAVAILABLE on Schedule IS retried (pre-commit by definition).
    injector2 = FaultInjector(_plan_with(
        Fault(0, "rpc_unavailable", target="Schedule"),
    ))
    injector2.begin_round(0)
    client2 = chaotic_client(
        server.address, injector2, rpc_retries=2, rpc_backoff_s=0.005,
    )
    assert client2.schedule() == []
    client.close()
    client2.close()


def test_wait_for_service_clamps_final_sleep():
    """Regression (satellite 1): the poll loop used to sleep a full
    poll_interval past its deadline."""
    client = FirmamentClient("127.0.0.1:1", rpc_timeout_s=0.5)
    t0 = time.monotonic()
    assert client.wait_for_service(timeout=0.5, poll_interval=0.4) is False
    elapsed = time.monotonic() - t0
    # Old behavior: ~0.5 + full 0.4 sleep past the deadline.  New: the
    # final sleep is clamped to the remaining ~0.1 s.
    assert elapsed < 0.85, elapsed
    client.close()


def test_wait_for_service_raises_on_non_transient_code(server):
    """UNAVAILABLE keeps polling; any other code raises (satellite 1)."""
    client = FirmamentClient(server.address)

    def bad_check(request, timeout=None):
        raise InjectedRpcError(grpc.StatusCode.UNIMPLEMENTED, "not firmament")

    client._stubs.Check = bad_check
    with pytest.raises(grpc.RpcError):
        client.wait_for_service(timeout=1.0, poll_interval=0.05)
    client.close()


# ------------------------------------------------------------ crash-loop budget


class _AlwaysFailingClient:
    """The minimal client surface Poseidon touches, with a schedule()
    that always raises (a permanently dead Firmament)."""

    calls = 0

    def schedule(self):
        self.calls += 1
        raise InjectedRpcError(grpc.StatusCode.UNAVAILABLE, "dead")

    def wait_for_service(self, timeout=0.0, poll_interval=0.0):
        return True


def _budget_poseidon(budget=3):
    cfg = PoseidonConfig(
        crash_loop_budget=budget, crash_backoff_s=0.001,
        crash_backoff_max_s=0.004, scheduling_interval=0.01,
    )
    return Poseidon(
        FakeKube(), config=cfg, firmament=_AlwaysFailingClient(),
        run_loop=False,
    )


def test_crash_loop_budget_fatal_stop():
    """Regression (satellite 2): the loop used to swallow every round
    failure forever; now consecutive failures are budgeted, backed off,
    and fatally stopped with a clear reason."""
    p = _budget_poseidon(budget=3)
    d1 = p.try_round()
    d2 = p.try_round()
    assert d1 is not None and d2 is not None
    assert 0 < d1 <= 0.002  # backoff base, jittered into [base/2, base]
    assert d2 >= d1 * 0.5   # exponential growth modulo jitter
    assert p.loop_stats.consecutive_failures == 2
    assert p.fatal is None
    assert p.try_round() is None           # budget exhausted
    assert p.fatal is not None and "crash-loop budget" in p.fatal
    assert p._stop.is_set()
    assert p.loop_stats.failed_rounds == 3


def test_crash_loop_budget_resets_on_success(server):
    kube = FakeKube()
    cfg = PoseidonConfig(
        firmament_address=server.address, scheduling_interval=3600,
        crash_loop_budget=3, crash_backoff_s=0.001,
    )
    p = Poseidon(kube, config=cfg, run_loop=False)
    p.fc.close()
    p.fc = _AlwaysFailingClient()
    assert p.try_round() is not None
    assert p.loop_stats.consecutive_failures == 1
    # Service recovers: the healthy round resets the budget.
    p.fc = FirmamentClient(server.address)
    assert p.try_round() == cfg.scheduling_interval
    assert p.loop_stats.consecutive_failures == 0
    p.fc.close()


def test_loop_thread_exits_on_exhausted_budget():
    p = _budget_poseidon(budget=2)
    t = threading.Thread(target=p._loop, daemon=True)
    t.start()
    t.join(timeout=10.0)
    assert not t.is_alive()
    assert p.fatal is not None


# ------------------------------------------------------- the chaotic full stack


@pytest.fixture()
def chaotic_system():
    """Full stack with injection seams armed by a per-test plan: the
    test sets ``injector.plan`` faults via begin_round on a plan it
    builds, or pokes the injector hooks directly."""
    with FirmamentTPUServer(address="127.0.0.1:0") as srv:
        injector = FaultInjector(_plan_with())
        kube = ChaoticKube(FakeKube(), injector)
        client = chaotic_client(
            srv.address, injector,
            rpc_timeout_s=10.0, rpc_retries=2, rpc_backoff_s=0.005,
        )
        cfg = PoseidonConfig(
            firmament_address=srv.address, scheduling_interval=3600,
            crash_loop_budget=4, crash_backoff_s=0.005,
            crash_backoff_max_s=0.01,
        )
        poseidon = Poseidon(
            kube, config=cfg, firmament=client, run_loop=False
        ).start(health_timeout=10)
        srv.servicer.planner.chaos = injector
        try:
            yield kube, poseidon, srv, injector
        finally:
            poseidon.stop()


def _views(kube, poseidon, srv):
    from poseidon_tpu.chaos.soak import _placement_views

    return _placement_views(kube, poseidon, srv)


def test_bind_failure_rolls_back_and_requeues(chaotic_system):
    """Transactional enactment: a PLACE whose bind fails must requeue
    the pod and roll the scheduler view back — no divergence, and the
    pod places cleanly next round."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    injector.plan = _plan_with(Fault(0, "bind_fail", value=1))
    injector.begin_round(0)
    poseidon.schedule_once()
    assert poseidon.loop_stats.bind_failures == 1
    assert poseidon.loop_stats.requeued == 1
    assert kube.inner.pods["default/p1"].phase == "Pending"
    # Scheduler rolled back: the task is runnable again, not placed.
    uid = poseidon.shared.uid_for_pod("default/p1")
    task = srv.servicer.state.tasks[uid]
    assert task.state == TaskState.RUNNABLE and task.scheduled_to is None
    kube_truth, sched_view = _views(kube, poseidon, srv)
    assert kube_truth == sched_view == {}
    # Fault consumed: the next round places for real.
    injector.begin_round(1)
    poseidon.schedule_once()
    assert kube.inner.pods["default/p1"].phase == "Running"
    kube_truth, sched_view = _views(kube, poseidon, srv)
    assert kube_truth == sched_view == {"default/p1": "n1"}


def test_schedule_lost_heals_via_reconciler(chaotic_system):
    """The nastiest fault: Schedule() commits on the service and the
    reply is lost.  The glue marks the window suspect and the next
    successful round requeues the phantom placements — the views
    reconverge within one healthy round."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    injector.plan = _plan_with(Fault(0, "schedule_lost"))
    injector.begin_round(0)
    with pytest.raises(grpc.RpcError):
        poseidon.schedule_once()
    # Divergence is real at this instant: service placed, kube did not.
    kube_truth, sched_view = _views(kube, poseidon, srv)
    assert kube_truth == {} and sched_view != {}
    injector.begin_round(1)
    poseidon.schedule_once()   # suspect round: reconciler requeues
    assert poseidon.loop_stats.requeued == 1
    poseidon.schedule_once()   # re-placement enacts
    assert kube.inner.pods["default/p1"].phase == "Running"
    kube_truth, sched_view = _views(kube, poseidon, srv)
    assert kube_truth == sched_view != {}


def test_watch_disconnect_resyncs(chaotic_system):
    """A dropped watch (stale resourceVersion) must resync: the watcher
    re-lists, re-subscribes, and keeps scheduling."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    assert poseidon.drain_watchers()
    injector.plan = _plan_with(Fault(0, "disconnect_pods"))
    injector.begin_round(0)
    # Let the pump observe the disconnect and resync (<= one 0.2s poll).
    deadline = time.monotonic() + 5.0
    while poseidon.pod_watcher.resyncs == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert poseidon.pod_watcher.resyncs == 1
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert kube.inner.pods["default/p1"].phase == "Running"


def test_resync_synthesizes_missed_deletions(chaotic_system):
    """Pods/nodes that vanished while the watch was down must be
    DELETED-synthesized from the re-list diff, or the scheduler keeps
    phantom objects forever."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.add_node(Node(name="n2", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    kube.create_pod(Pod(name="p2", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    # Quiesce the bind MODIFIED events first: a real disconnect drops
    # in-flight events WITH the watch, so none can trail the resync.
    assert poseidon.drain_watchers()
    # Simulate a deletion the watch never saw: remove from the registry
    # without emitting an event (the disconnected-window loss).
    del kube.inner.pods["default/p2"]
    poseidon.pod_watcher._resync()
    assert poseidon.drain_watchers()
    assert poseidon.shared.uid_for_pod("default/p2") is None
    assert poseidon.shared.uid_for_pod("default/p1") is not None
    # Same for nodes: n2 vanishes; its resource must leave the scheduler.
    del kube.inner.nodes["n2"]
    poseidon.node_watcher._resync()
    assert poseidon.drain_watchers()
    assert poseidon.shared.get_node("n2") is None
    assert poseidon.shared.get_node("n1") is not None


def test_resync_applies_missed_spec_change(chaotic_system):
    """A spec MODIFIED lost inside the watch outage must land via the
    resync's MODIFIED replay — an ADDED replay is ignored for a pod the
    watcher already knows, leaving the scheduler solving against the
    stale descriptor forever."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    uid = poseidon.shared.uid_for_pod("default/p1")
    td = poseidon.shared.get_task(uid).descriptor
    assert td.resource_request.cpu_cores == 100
    # Mutate the spec without an event: the MODIFIED died with the watch.
    kube.inner.pods["default/p1"].cpu_request = 250
    poseidon.pod_watcher._resync()
    assert poseidon.drain_watchers()
    td = poseidon.shared.get_task(uid).descriptor
    assert td.resource_request.cpu_cores == 250


def test_resync_unsubscribes_dead_watch(chaotic_system):
    """The dead watch must leave the fan-out registry on resync, or
    every later mutation keeps copying events into abandoned queues."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    assert poseidon.drain_watchers()
    before = len(kube.inner._pod_watchers)
    injector.plan = _plan_with(Fault(0, "disconnect_pods"))
    injector.begin_round(0)
    deadline = time.monotonic() + 5.0
    while poseidon.pod_watcher.resyncs == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert poseidon.pod_watcher.resyncs == 1
    assert len(kube.inner._pod_watchers) == before


def test_half_rolled_back_requeue_replays_next_round(chaotic_system):
    """A bind rollback whose resubmit RPC fails must park the descriptor
    and replay it next round — otherwise the task exists nowhere (removed
    server-side, pod Pending in kube) and nothing ever heals it.  The
    suspect flag must also survive the mid-enactment raise."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    uid = poseidon.shared.uid_for_pod("default/p1")
    # One bind failure; the rollback's TaskSubmitted exhausts the retry
    # budget (rpc_retries=2 -> 3 attempts).
    injector.plan = _plan_with(
        Fault(0, "bind_fail", value=1),
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
    )
    injector.begin_round(0)
    with pytest.raises(grpc.RpcError):
        poseidon.schedule_once()
    assert uid in poseidon._resubmit_pending
    assert poseidon.loop_stats.bind_failures == 1
    # A mid-enactment abort arms the reconciler (the round's remaining
    # committed deltas are orphaned phantoms until it runs).
    assert poseidon._schedule_suspect is True
    # Clean round: the parked resubmit replays first, the round places
    # the pod, and the suspect window closes.
    injector.begin_round(1)
    poseidon.schedule_once()
    assert poseidon._resubmit_pending == {}
    assert poseidon._schedule_suspect is False
    assert kube.inner.pods["default/p1"].phase == "Running"
    kube_truth, sched_view = _views(kube, poseidon, srv)
    assert kube_truth == sched_view == {"default/p1": "n1"}


def test_mid_enactment_abort_heals_orphaned_deltas(chaotic_system):
    """A round that dies mid-enactment leaves its un-enacted PLACE
    deltas committed server-side with their pods Pending — the suspect
    reconciler (armed by the abort) must requeue and re-place them."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    kube.create_pod(Pod(name="p2", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    # The first PLACE's bind fails and its rollback's resubmit RPC dies
    # too: enactment aborts, so the round's OTHER placement (committed
    # on the service) is never bound in kube.
    injector.plan = _plan_with(
        Fault(0, "bind_fail", value=1),
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
        Fault(0, "rpc_unavailable", target="TaskSubmitted"),
    )
    injector.begin_round(0)
    with pytest.raises(grpc.RpcError):
        poseidon.schedule_once()
    assert poseidon._schedule_suspect is True
    injector.begin_round(1)
    # Clean rounds: flush the parked resubmit, reconcile the phantom,
    # re-place everything.
    for _ in range(3):
        poseidon.schedule_once()
    assert kube.inner.pods["default/p1"].phase == "Running"
    assert kube.inner.pods["default/p2"].phase == "Running"
    kube_truth, sched_view = _views(kube, poseidon, srv)
    assert kube_truth == sched_view
    assert len(kube_truth) == 2


def test_retried_schedule_marks_window_suspect(chaotic_system):
    """An UNAVAILABLE absorbed by Schedule's retry can, on a real
    network, hide a post-commit reply loss: the retried call must arm
    the suspect window (healed within the same fully-enacted round)."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    injector.plan = _plan_with(
        Fault(0, "rpc_unavailable", target="Schedule"),
    )
    injector.begin_round(0)
    poseidon.schedule_once()
    assert poseidon.fc.schedule_retried is True
    # The window armed and the same round's reconcile closed it.
    assert poseidon._schedule_suspect is False
    assert kube.inner.pods["default/p1"].phase == "Running"
    injector.begin_round(1)
    poseidon.schedule_once()
    assert poseidon.fc.schedule_retried is False


def test_enacted_map_pruned_after_lifecycle_end(chaotic_system):
    """The enacted map must not grow one entry per pod ever placed:
    tasks that finished or left the cluster leave it on the next
    round."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    uid = poseidon.shared.uid_for_pod("default/p1")
    assert uid in poseidon._enacted
    kube.set_pod_phase("default/p1", "Succeeded")
    assert poseidon.drain_watchers()
    poseidon.schedule_once()
    assert uid not in poseidon._enacted


def test_unavailable_schedule_failure_is_not_suspect(chaotic_system):
    """UNAVAILABLE is pre-commit by contract: it must NOT arm the
    suspect reconciler (a sweep over the whole pending backlog), and the
    failed round must attribute no deltas to itself."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    injector.plan = _plan_with(
        Fault(0, "rpc_unavailable", target="Schedule"),
        Fault(0, "rpc_unavailable", target="Schedule"),
        Fault(0, "rpc_unavailable", target="Schedule"),
    )
    injector.begin_round(0)
    with pytest.raises(grpc.RpcError):
        poseidon.schedule_once()
    assert poseidon._schedule_suspect is False
    assert poseidon.last_deltas == []
    injector.begin_round(1)
    poseidon.schedule_once()
    # No reconcile sweep fired: nothing was requeued on the clean round.
    assert poseidon.loop_stats.requeued == 0
    assert kube.inner.pods["default/p1"].phase == "Running"


def test_stop_while_round_in_flight(chaotic_system):
    """Satellite 3: stop() during an in-flight round must let the round
    finish enacting, then stop the loop cleanly — no torn enactment, no
    hung join."""
    kube, poseidon, srv, injector = chaotic_system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="p1", cpu_request=100, ram_request=1 << 18))
    assert poseidon.drain_watchers()
    injector.hold_schedule = threading.Event()
    loop = threading.Thread(target=poseidon._loop, daemon=True)
    poseidon._loop_thread = loop
    loop.start()
    assert injector.in_schedule.wait(timeout=10.0)
    stopper = threading.Thread(target=poseidon.stop)
    stopper.start()
    time.sleep(0.1)            # stop() is now joining the blocked loop
    injector.hold_schedule.set()
    stopper.join(timeout=10.0)
    loop.join(timeout=10.0)
    assert not loop.is_alive()
    # The in-flight round completed its enactment before the loop exited.
    assert poseidon.loop_stats.rounds == 1
    assert kube.inner.pods["default/p1"].phase == "Running"


def test_drain_watchers_timeout_expires():
    """Satellite 3: drain_watchers must report False (not hang) when a
    queue never empties — here a key held in processing forever."""
    cfg = PoseidonConfig(scheduling_interval=3600)
    p = Poseidon(
        FakeKube(), config=cfg, firmament=_AlwaysFailingClient(),
        run_loop=False,
    )
    p.pod_watcher.queue.add("default/p", ("ADDED", object()))
    p.pod_watcher.queue.get()   # processing, never done()
    t0 = time.monotonic()
    assert p.drain_watchers(timeout=0.3) is False
    assert time.monotonic() - t0 < 2.0


# ---------------------------------------------------------- degraded solve tier


def _tiny_state(tasks=6):
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    state = ClusterState()
    for i in range(4):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"deg-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=16,
        ))
    for i in range(tasks):
        state.task_submitted(TaskInfo(
            uid=task_uid("deg", i), job_id="deg-j",
            cpu_request=400, ram_request=1 << 19,
        ))
    return state


class _SolverChaos:
    def __init__(self, forced=False, frac=None):
        self.forced = forced
        self.frac = frac

    def solver_fault(self):
        return self.forced, self.frac


def test_degraded_tier_forced_uncertified():
    """Injected certificate failure escalates to the host-greedy tier:
    feasible deterministic placements, converged=False, tier recorded;
    the next clean round goes back to a certified tier."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    state = _tiny_state()
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    planner.chaos = _SolverChaos(forced=True)
    deltas, m = planner.schedule_round()
    assert m.solve_tier == "host_greedy"
    assert not m.converged
    assert m.placed == 6 and m.unscheduled == 0
    planner.chaos = _SolverChaos(forced=False)
    state.task_submitted(TaskInfo(
        uid=task_uid("deg", 99), job_id="deg-j",
        cpu_request=400, ram_request=1 << 19,
    ))
    _, m2 = planner.schedule_round()
    assert m2.solve_tier in ("pruned", "dense")
    assert m2.converged


def test_degraded_tier_partial_round():
    """The partial-Schedule-response fault places only a fraction; the
    rest stays pending and lands once the fault clears."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    state = _tiny_state(tasks=8)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    planner.chaos = _SolverChaos(frac=0.5)
    _, m = planner.schedule_round()
    assert m.solve_tier == "host_greedy"
    assert m.placed == 4 and m.unscheduled == 4
    planner.chaos = None
    _, m2 = planner.schedule_round()
    assert m2.placed == 4 and m2.unscheduled == 0
    assert m2.converged


def test_quiet_round_tier():
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    state = _tiny_state()
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    assert m.solve_tier in ("pruned", "dense")
    _, m2 = planner.schedule_round()
    assert m2.solve_tier == "quiet"


# ------------------------------------------------------------- tiny full soak


def test_tiny_soak_all_families(tmp_path):
    """The whole stack under the smoke plan at toy scale: every family
    fires, zero divergence, zero warm compiles, everything places."""
    out = run_soak(
        machines=12, rounds=6, plan="smoke", seed=0,
        out_dir=str(tmp_path),
    )
    assert out["ok"], out.get("failure")
    fired_families = {
        f.family
        for f in named_plan("smoke", 6, seed=0).faults
        if any(e["kind"] == f.kind for e in out["fired"])
    }
    assert {"watch", "events", "rpc", "binding", "solver"} <= fired_families
    assert out["warm_fresh_compiles"] == 0
    assert out["divergent_rounds"] == 0
    assert "host_greedy" in out["tiers"]


def test_streaming_soak_matches_synchronous(tmp_path, monkeypatch):
    """The streaming engine's acceptance gate under faults: the SAME
    seeded fault plan, run once round-synchronously and once with the
    overlapped loop, must leave byte-identical kube truth after every
    round — cross-round speculation and deferred enactment are pure
    overlap, never a semantic change.  (The soak harness drains the
    in-flight enactment before each round's divergence check, so the
    per-round digests compare like-for-like.)"""
    monkeypatch.delenv("POSEIDON_STREAMING", raising=False)
    sync = run_soak(
        machines=12, rounds=6, plan="smoke", seed=0,
        out_dir=str(tmp_path),
    )
    assert sync["ok"], sync.get("failure")

    monkeypatch.setenv("POSEIDON_STREAMING", "1")
    stream = run_soak(
        machines=12, rounds=6, plan="smoke", seed=0,
        out_dir=str(tmp_path),
    )
    assert stream["ok"], stream.get("failure")
    assert stream["divergent_rounds"] == 0
    assert stream["warm_fresh_compiles"] == 0
    assert stream["digests"] == sync["digests"]


def test_streaming_off_is_bit_identical_to_default(tmp_path, monkeypatch):
    """POSEIDON_STREAMING=0 (the hatch's explicit off) must reproduce
    the default synchronous round digests bit-for-bit — the hatch
    registry's off-state really is today's loop, not a third mode."""
    monkeypatch.delenv("POSEIDON_STREAMING", raising=False)
    default = run_soak(
        machines=12, rounds=4, plan="smoke", seed=3,
        out_dir=str(tmp_path),
    )
    assert default["ok"], default.get("failure")

    monkeypatch.setenv("POSEIDON_STREAMING", "0")
    off = run_soak(
        machines=12, rounds=4, plan="smoke", seed=3,
        out_dir=str(tmp_path),
    )
    assert off["ok"], off.get("failure")
    assert off["digests"] == default["digests"]
