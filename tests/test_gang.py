"""Gang scheduling: all-or-nothing job placement (BASELINE config 4).

Each gang job is its own EC row by signature construction; the planner's
repair loop forbids partially-placed gangs and re-solves so freed capacity
serves other work.
"""


from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.glue import FakeKube, Node, Pod, Poseidon
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.service import FirmamentTPUServer
from poseidon_tpu.utils.config import PoseidonConfig
from poseidon_tpu.utils.ids import generate_uuid, task_uid


def gang_task(uid, job, cpu=1000, ram=1 << 18):
    return TaskInfo(
        uid=uid, job_id=job, cpu_request=cpu, ram_request=ram, gang=True,
        labels={"gangScheduling": "true"},
    )


def test_gang_gate_off_allows_partial_placement():
    """gang_scheduling=False (FirmamentTPUConfig gate) disables the
    atomicity repair: a too-big gang places partially like ordinary
    tasks instead of being fully evicted."""
    st = ClusterState()
    for i in range(3):
        st.node_added(
            MachineInfo(
                uuid=f"m-{i}", cpu_capacity=1000, ram_capacity=1 << 24
            )
        )
    for i in range(5):
        st.task_submitted(gang_task(task_uid("gj", i), "gang-job"))
    planner = RoundPlanner(
        st, get_cost_model("cpu_mem"), gang_scheduling=False
    )
    _, m = planner.schedule_round()
    assert m.placed == 3 and m.unscheduled == 2


def test_gang_places_fully_when_it_fits():
    st = ClusterState()
    for i in range(4):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"g{i}"), cpu_capacity=2000,
                        ram_capacity=1 << 24)
        )
    for i in range(6):
        st.task_submitted(gang_task(task_uid("gj", i), "gang-job"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    deltas, m = planner.schedule_round()
    assert m.placed == 6 and m.unscheduled == 0


def test_partial_gang_fully_unscheduled():
    st = ClusterState()
    # Capacity for 3 x 1000m; the 5-member gang cannot fully fit.
    for i in range(3):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"g{i}"), cpu_capacity=1000,
                        ram_capacity=1 << 24)
        )
    for i in range(5):
        st.task_submitted(gang_task(task_uid("gj", i), "gang-big"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    deltas, m = planner.schedule_round()
    assert m.placed == 0
    assert m.unscheduled == 5
    assert deltas == []


def test_forbidden_gang_frees_capacity_for_others():
    st = ClusterState()
    for i in range(3):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"g{i}"), cpu_capacity=1000,
                        ram_capacity=1 << 24)
        )
    # A 5-member gang that cannot fit, plus 3 singletons that can.
    for i in range(5):
        st.task_submitted(gang_task(task_uid("gang", i), "gang-big"))
    for i in range(3):
        st.task_submitted(
            TaskInfo(uid=task_uid("solo", i), job_id=f"solo-{i}",
                     cpu_request=1000, ram_request=1 << 18)
        )
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    deltas, m = planner.schedule_round()
    # All three singletons run; the gang waits whole.
    assert m.placed == 3
    assert m.unscheduled == 5


def test_gang_schedules_when_capacity_arrives():
    st = ClusterState()
    st.node_added(
        MachineInfo(uuid=generate_uuid("first"), cpu_capacity=2000,
                    ram_capacity=1 << 24)
    )
    for i in range(4):
        st.task_submitted(gang_task(task_uid("gw", i), "gang-wait"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m1 = planner.schedule_round()
    assert m1.placed == 0 and m1.unscheduled == 4
    # Another machine joins: now 4000m total fits the 4x1000m gang.
    st.node_added(
        MachineInfo(uuid=generate_uuid("second"), cpu_capacity=2000,
                    ram_capacity=1 << 24)
    )
    _, m2 = planner.schedule_round()
    assert m2.placed == 4 and m2.unscheduled == 0


def test_two_gangs_compete_one_wins_whole():
    st = ClusterState()
    for i in range(3):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"c{i}"), cpu_capacity=1000,
                        ram_capacity=1 << 24)
        )
    for i in range(2):
        st.task_submitted(gang_task(task_uid("ga", i), "gang-a"))
    for i in range(2):
        st.task_submitted(gang_task(task_uid("gb", i), "gang-b"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    # 3 slots, two 2-member gangs: exactly one gang runs whole.
    assert m.placed == 2 and m.unscheduled == 2


def test_cross_ec_overcommit_prevented():
    """Two distinct ECs must not jointly oversubscribe one machine's CPU
    (the transportation relaxation allows it; the feasibility loop cuts
    it).  Regression for the 2x-CPU over-commit the two-gang test exposed."""
    st = ClusterState()
    st.node_added(
        MachineInfo(uuid=generate_uuid("only"), cpu_capacity=1000,
                    ram_capacity=1 << 24)
    )
    # Two singleton tasks of *different* shapes, each 700m: only one fits.
    st.task_submitted(TaskInfo(uid=1, job_id="a", cpu_request=700,
                               ram_request=1 << 18))
    st.task_submitted(TaskInfo(uid=2, job_id="b", cpu_request=700,
                               ram_request=1 << 19))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    assert m.placed == 1 and m.unscheduled == 1


def test_overcommit_check_all_dimensions():
    st = ClusterState()
    st.node_added(
        MachineInfo(uuid=generate_uuid("ram-bound"), cpu_capacity=100_000,
                    ram_capacity=1 << 20)
    )
    # RAM is the binding dimension: 3 x 600KB into 1MB -> only one fits.
    st.task_submitted(TaskInfo(uid=1, job_id="a", cpu_request=100,
                               ram_request=600 << 10))
    st.task_submitted(TaskInfo(uid=2, job_id="b", cpu_request=200,
                               ram_request=600 << 10))
    st.task_submitted(TaskInfo(uid=3, job_id="c", cpu_request=300,
                               ram_request=600 << 10))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    assert m.placed == 1 and m.unscheduled == 2


def test_gang_label_over_the_wire():
    kube = FakeKube()
    for i in range(2):
        kube.add_node(Node(name=f"n{i}", cpu_capacity=1000,
                           ram_capacity=1 << 24))
    with FirmamentTPUServer(address="127.0.0.1:0") as server:
        cfg = PoseidonConfig(firmament_address=server.address,
                             scheduling_interval=3600)
        with Poseidon(kube, config=cfg, run_loop=False) as poseidon:
            for i in range(3):
                kube.create_pod(
                    Pod(name=f"g{i}", owner_uid="gang-rs",
                        cpu_request=900, ram_request=1 << 18,
                        labels={"gangScheduling": "true"})
                )
            assert poseidon.drain_watchers()
            deltas = poseidon.schedule_once()
            # Only 2 of 3 members could fit: nothing places.
            assert deltas == []
            assert all(p.phase == "Pending" for p in kube.pods.values())
