"""Gang scheduling: all-or-nothing job placement (BASELINE config 4).

Each gang job is its own EC row by signature construction; the planner's
repair loop forbids partially-placed gangs and re-solves so freed capacity
serves other work.
"""


from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.glue import FakeKube, Node, Pod, Poseidon
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.service import FirmamentTPUServer
from poseidon_tpu.utils.config import PoseidonConfig
from poseidon_tpu.utils.ids import generate_uuid, task_uid


def gang_task(uid, job, cpu=1000, ram=1 << 18):
    return TaskInfo(
        uid=uid, job_id=job, cpu_request=cpu, ram_request=ram, gang=True,
        labels={"gangScheduling": "true"},
    )


def test_gang_gate_off_allows_partial_placement():
    """gang_scheduling=False (FirmamentTPUConfig gate) disables the
    atomicity repair: a too-big gang places partially like ordinary
    tasks instead of being fully evicted."""
    st = ClusterState()
    for i in range(3):
        st.node_added(
            MachineInfo(
                uuid=f"m-{i}", cpu_capacity=1000, ram_capacity=1 << 24
            )
        )
    for i in range(5):
        st.task_submitted(gang_task(task_uid("gj", i), "gang-job"))
    planner = RoundPlanner(
        st, get_cost_model("cpu_mem"), gang_scheduling=False
    )
    _, m = planner.schedule_round()
    assert m.placed == 3 and m.unscheduled == 2


def test_gang_places_fully_when_it_fits():
    st = ClusterState()
    for i in range(4):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"g{i}"), cpu_capacity=2000,
                        ram_capacity=1 << 24)
        )
    for i in range(6):
        st.task_submitted(gang_task(task_uid("gj", i), "gang-job"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    deltas, m = planner.schedule_round()
    assert m.placed == 6 and m.unscheduled == 0


def test_partial_gang_fully_unscheduled():
    st = ClusterState()
    # Capacity for 3 x 1000m; the 5-member gang cannot fully fit.
    for i in range(3):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"g{i}"), cpu_capacity=1000,
                        ram_capacity=1 << 24)
        )
    for i in range(5):
        st.task_submitted(gang_task(task_uid("gj", i), "gang-big"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    deltas, m = planner.schedule_round()
    assert m.placed == 0
    assert m.unscheduled == 5
    assert deltas == []


def test_forbidden_gang_frees_capacity_for_others():
    st = ClusterState()
    for i in range(3):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"g{i}"), cpu_capacity=1000,
                        ram_capacity=1 << 24)
        )
    # A 5-member gang that cannot fit, plus 3 singletons that can.
    for i in range(5):
        st.task_submitted(gang_task(task_uid("gang", i), "gang-big"))
    for i in range(3):
        st.task_submitted(
            TaskInfo(uid=task_uid("solo", i), job_id=f"solo-{i}",
                     cpu_request=1000, ram_request=1 << 18)
        )
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    deltas, m = planner.schedule_round()
    # All three singletons run; the gang waits whole.
    assert m.placed == 3
    assert m.unscheduled == 5


def test_gang_schedules_when_capacity_arrives():
    st = ClusterState()
    st.node_added(
        MachineInfo(uuid=generate_uuid("first"), cpu_capacity=2000,
                    ram_capacity=1 << 24)
    )
    for i in range(4):
        st.task_submitted(gang_task(task_uid("gw", i), "gang-wait"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m1 = planner.schedule_round()
    assert m1.placed == 0 and m1.unscheduled == 4
    # Another machine joins: now 4000m total fits the 4x1000m gang.
    st.node_added(
        MachineInfo(uuid=generate_uuid("second"), cpu_capacity=2000,
                    ram_capacity=1 << 24)
    )
    _, m2 = planner.schedule_round()
    assert m2.placed == 4 and m2.unscheduled == 0


def test_two_gangs_compete_one_wins_whole():
    st = ClusterState()
    for i in range(3):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"c{i}"), cpu_capacity=1000,
                        ram_capacity=1 << 24)
        )
    for i in range(2):
        st.task_submitted(gang_task(task_uid("ga", i), "gang-a"))
    for i in range(2):
        st.task_submitted(gang_task(task_uid("gb", i), "gang-b"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    # 3 slots, two 2-member gangs: exactly one gang runs whole.
    assert m.placed == 2 and m.unscheduled == 2


def test_cross_ec_overcommit_prevented():
    """Two distinct ECs must not jointly oversubscribe one machine's CPU
    (the transportation relaxation allows it; the feasibility loop cuts
    it).  Regression for the 2x-CPU over-commit the two-gang test exposed."""
    st = ClusterState()
    st.node_added(
        MachineInfo(uuid=generate_uuid("only"), cpu_capacity=1000,
                    ram_capacity=1 << 24)
    )
    # Two singleton tasks of *different* shapes, each 700m: only one fits.
    st.task_submitted(TaskInfo(uid=1, job_id="a", cpu_request=700,
                               ram_request=1 << 18))
    st.task_submitted(TaskInfo(uid=2, job_id="b", cpu_request=700,
                               ram_request=1 << 19))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    assert m.placed == 1 and m.unscheduled == 1


def test_overcommit_check_all_dimensions():
    st = ClusterState()
    st.node_added(
        MachineInfo(uuid=generate_uuid("ram-bound"), cpu_capacity=100_000,
                    ram_capacity=1 << 20)
    )
    # RAM is the binding dimension: 3 x 600KB into 1MB -> only one fits.
    st.task_submitted(TaskInfo(uid=1, job_id="a", cpu_request=100,
                               ram_request=600 << 10))
    st.task_submitted(TaskInfo(uid=2, job_id="b", cpu_request=200,
                               ram_request=600 << 10))
    st.task_submitted(TaskInfo(uid=3, job_id="c", cpu_request=300,
                               ram_request=600 << 10))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    assert m.placed == 1 and m.unscheduled == 2


def test_gang_label_over_the_wire():
    kube = FakeKube()
    for i in range(2):
        kube.add_node(Node(name=f"n{i}", cpu_capacity=1000,
                           ram_capacity=1 << 24))
    with FirmamentTPUServer(address="127.0.0.1:0") as server:
        cfg = PoseidonConfig(firmament_address=server.address,
                             scheduling_interval=3600)
        with Poseidon(kube, config=cfg, run_loop=False) as poseidon:
            for i in range(3):
                kube.create_pod(
                    Pod(name=f"g{i}", owner_uid="gang-rs",
                        cpu_request=900, ram_request=1 << 18,
                        labels={"gangScheduling": "true"})
                )
            assert poseidon.drain_watchers()
            deltas = poseidon.schedule_once()
            # Only 2 of 3 members could fit: nothing places.
            assert deltas == []
            assert all(p.phase == "Pending" for p in kube.pods.values())


def _zoned_gang_cluster(n_machines, zone_size, zone_cpu=16000):
    """Machines with task_slots=1: a small selector-pinned "zone" (lower
    CPU capacity, so open-capable tasks price away from it) plus an open
    pool.  Gangs pinned to the zone contend for its few slots — the
    deterministic multi-firing repair scenario."""
    st = ClusterState()
    for i in range(n_machines):
        in_zone = i < zone_size
        st.node_added(MachineInfo(
            uuid=generate_uuid(f"zg{i}"),
            cpu_capacity=zone_cpu if in_zone else 32000,
            ram_capacity=128 << 20, task_slots=1,
            labels={"pool": "zone" if in_zone else "open"},
        ))
    return st


def _submit_zone_gang(st, name, n, cpu, zone):
    from poseidon_tpu.costmodel.selectors import IN_SET

    sel = ((IN_SET, "pool", ("zone",)),) if zone else ()
    for i in range(n):
        st.task_submitted(TaskInfo(
            uid=task_uid(name, i), job_id=name, cpu_request=cpu,
            ram_request=1 << 20, gang=True, selectors=sel,
        ))


def _run_multi_firing(st):
    """Zone holds 25 slots; B(20) + C(15) + D(14) are pinned there with
    costs B < C < D (cost grows with request).  The optimum places B
    whole and C partially -> firing 1 forbids C; the re-solve places D
    partially -> firing 2 forbids D; B survives whole.  A places in the
    open pool throughout."""
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()

    def placed(name, n):
        return sum(
            1 for i in range(n)
            if st.tasks[task_uid(name, i)].scheduled_to is not None
        )

    assert placed("za", 30) == 30, "open-pool gang must place whole"
    assert placed("zb", 20) == 20, "cheapest zone gang survives whole"
    assert placed("zc", 15) == 0, "first-forbidden gang places nothing"
    assert placed("zd", 14) == 0, "second-forbidden gang places nothing"
    assert m.repair_firings == 2, m.repair_firings
    return m


def _multi_firing_cluster():
    st = _zoned_gang_cluster(800, 25)
    _submit_zone_gang(st, "za", 30, 1000, zone=False)
    _submit_zone_gang(st, "zb", 20, 1200, zone=True)
    _submit_zone_gang(st, "zc", 15, 1500, zone=True)
    _submit_zone_gang(st, "zd", 14, 2000, zone=True)
    return st


def test_gang_repair_multi_firing_dense():
    """>= 2 _forbid_partial_gangs firings before atomicity, dense path
    (default shortlist gate declines at E=4)."""
    m = _run_multi_firing(_multi_firing_cluster())
    assert m.pruned_bands == 0


def test_gang_repair_multi_firing_pruned(monkeypatch):
    """The same scenario with the pruned-plane gate forced down to toy
    scale: identical placement semantics, identical firing count, and
    the band must actually have run on a shortlist."""
    monkeypatch.setenv("POSEIDON_PRUNE_MIN_ROWS", "2")
    monkeypatch.setenv("POSEIDON_PRUNE_MIN_COLS", "64")
    m = _run_multi_firing(_multi_firing_cluster())
    assert m.pruned_bands >= 1, "shortlist gate never fired"
    # The repair re-solves must accept on the REDUCED plane: the
    # incremental excluded-column certificate, fed the first accept's
    # full pass as its anchor, answers the later attempts without the
    # full-plane O(E*M) lift (PR 7's reduced-plane certificates).
    assert m.pruned_cert_accepts >= 1, (
        "every pruned accept fell back to the full-plane pass"
    )


def test_gang_warm_round_is_compile_free():
    """PR 3's invariant as a gang-path gate: a warm gang round — repair
    firings and their hidden re-solves included — must mint ZERO fresh
    XLA compiles.  Round 1 on an identical rebuilt cluster pays any
    cold compiles; round 2 rides the compile ledger at budget 0 and
    fails with the compiled program names if a retrace sneaks into the
    repair path."""
    from poseidon_tpu.check.ledger import CompileLedger

    _run_multi_firing(_multi_firing_cluster())  # warm the compile keys
    with CompileLedger(budget=0, label="warm gang multi-firing round"):
        m = _run_multi_firing(_multi_firing_cluster())
    assert m.fresh_compiles == 0


def test_oversized_gang_places_nothing_on_pruned_path(monkeypatch):
    """A gang bigger than its admissible zone places nothing (atomicity)
    when the band solves on the pruned plane."""
    monkeypatch.setenv("POSEIDON_PRUNE_MIN_ROWS", "2")
    monkeypatch.setenv("POSEIDON_PRUNE_MIN_COLS", "64")
    st = _zoned_gang_cluster(256, 10)
    _submit_zone_gang(st, "oa", 15, 1000, zone=False)
    _submit_zone_gang(st, "oz", 16, 1200, zone=True)
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    _, m = planner.schedule_round()
    assert m.pruned_bands >= 1, "shortlist gate never fired"
    placed_oz = sum(
        1 for i in range(16)
        if st.tasks[task_uid("oz", i)].scheduled_to is not None
    )
    placed_oa = sum(
        1 for i in range(15)
        if st.tasks[task_uid("oa", i)].scheduled_to is not None
    )
    assert placed_oz == 0 and placed_oa == 15
    assert m.repair_firings >= 1
