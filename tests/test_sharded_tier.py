"""Planner-level sharded band tier (POSEIDON_SHARDED_BANDS).

The fourth rung of the solve ladder — pruned -> dense -> sharded ->
host_greedy — mesh-splits wide contended bands over the visible device
mesh.  These tests pin its planner-level contract: the gate's fire and
decline behavior, randomized sharded-vs-dense parity (placements AND
objective — the mesh padding is a no-op at gate widths, so the kernel
is bit-identical to single-chip), warm-start soundness across tier
transitions in BOTH directions, the telemetry ride-through (wire format
-> /metrics -> soak/bench sub-reports), and the equilibrium-robust
churn certificate (satellite: docs/PERF.md round 9's one-in-five
~960-iteration churn re-solve).

conftest.py forces 8 virtual CPU devices, so the tier mesh is always
available here.
"""

import numpy as np
import pytest


def _contended_state(machines=64, seed=5, tasks=600):
    """A wide-for-test-scale contended cluster: 64 machines is a
    quarter-octave bucket divisible by the 8-device mesh, and demand
    near capacity keeps the solve off the trivial host-cert path."""
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    state = ClusterState()
    rng = np.random.default_rng(seed)
    for i in range(machines):
        state.node_added(MachineInfo(
            uuid=f"sh-m{i}", cpu_capacity=int(rng.integers(4000, 16000)),
            ram_capacity=1 << 24, task_slots=6,
        ))
    for i in range(tasks):
        state.task_submitted(TaskInfo(
            uid=task_uid(f"sh{seed}", i), job_id=f"j{i % 8}",
            cpu_request=int(rng.integers(400, 2000)),
            ram_request=1 << 18,
        ))
    return state


def _tier_on(monkeypatch, min_cols="64", min_contention="1"):
    monkeypatch.setenv("POSEIDON_SHARDED_BANDS", "1")
    monkeypatch.setenv("POSEIDON_SHARDED_MIN_COLS", min_cols)
    monkeypatch.setenv("POSEIDON_SHARDED_MIN_CONTENTION", min_contention)


def _planner(state):
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    return RoundPlanner(state, get_cost_model("cpu_mem"))


def _delta_view(deltas):
    return sorted((int(d.type), int(d.task_id), d.resource_id)
                  for d in deltas)


def test_sharded_tier_serves_contended_band(monkeypatch):
    _tier_on(monkeypatch)
    planner = _planner(_contended_state())
    _, m = planner.schedule_round()
    assert m.solve_tier == "sharded"
    assert m.sharded_bands >= 1
    assert m.shard_devices == 8
    assert m.converged and m.gap_bound == 0.0
    assert m.placed > 0
    # The per-shard work lanes reached the round's telemetry fold.
    assert m.shard_imbalance >= 1.0
    # And the curves ring carries the per-shard lanes for the round
    # history / flight recorder.
    assert any(c.get("shard_excess") for c in planner.last_solve_curves)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_vs_dense_parity_randomized(monkeypatch, seed):
    """Same cluster, tier on vs off, strided layout DISABLED: identical
    placements (delta view), objective, and iteration count — the mesh
    solve at gate widths with contiguous column blocks is the
    single-chip solve, split.  (The default strided layout trades this
    bit-parity for balanced lanes; the test below pins what it keeps.)"""
    _tier_on(monkeypatch)
    monkeypatch.setenv("POSEIDON_SHARD_STRIDED", "0")
    d_sh, m_sh = _planner(_contended_state(seed=seed)).schedule_round()
    monkeypatch.setenv("POSEIDON_SHARDED_BANDS", "0")
    d_dn, m_dn = _planner(_contended_state(seed=seed)).schedule_round()
    assert m_sh.solve_tier == "sharded"
    assert m_dn.solve_tier in ("pruned", "dense")
    assert m_sh.objective == m_dn.objective
    assert m_sh.placed == m_dn.placed
    assert m_sh.iterations == m_dn.iterations
    assert _delta_view(d_sh) == _delta_view(d_dn)


@pytest.mark.parametrize("seed", [0, 2])
def test_strided_shards_keep_solution_quality(monkeypatch, seed):
    """The default strided column-to-shard layout preserves everything
    the certificate guarantees — objective, placement count,
    convergence, exact gap — against the dense solve.  Flows may break
    cost ties differently (column memory order changed), which is why
    this leg asserts quality, not bit-parity."""
    _tier_on(monkeypatch)
    d_st, m_st = _planner(_contended_state(seed=seed)).schedule_round()
    monkeypatch.setenv("POSEIDON_SHARDED_BANDS", "0")
    d_dn, m_dn = _planner(_contended_state(seed=seed)).schedule_round()
    assert m_st.solve_tier == "sharded"
    assert m_st.objective == m_dn.objective
    assert m_st.placed == m_dn.placed
    assert len(d_st) == len(d_dn)
    assert m_st.converged and m_st.gap_bound == 0.0


def test_sharded_gate_declines_are_bit_identical(monkeypatch):
    """Hatch ON with the tier gated off (width below MIN_COLS) must be
    indistinguishable from hatch OFF — the gate declining IS the
    production default at under-sized/under-contended widths."""
    _tier_on(monkeypatch, min_cols="100000")
    d_on, m_on = _planner(_contended_state(seed=9)).schedule_round()
    monkeypatch.setenv("POSEIDON_SHARDED_BANDS", "0")
    d_off, m_off = _planner(_contended_state(seed=9)).schedule_round()
    assert m_on.solve_tier != "sharded"
    assert m_on.sharded_bands == 0 and m_on.shard_devices == 0
    assert m_on.solve_tier == m_off.solve_tier
    assert m_on.objective == m_off.objective
    assert m_on.iterations == m_off.iterations
    assert _delta_view(d_on) == _delta_view(d_off)


def test_sharded_gate_declines_under_contention(monkeypatch):
    """An under-contended band (demand below the threshold relative to
    capacity) stays dense even with the width gate satisfied.  This
    cluster runs ~156% contended, so a 1000% threshold must decline."""
    _tier_on(monkeypatch, min_contention="1000")
    _, m = _planner(_contended_state(seed=3)).schedule_round()
    assert m.solve_tier != "sharded"
    assert m.sharded_bands == 0


def test_tier_transition_warm_start_both_directions(monkeypatch):
    """Prices must survive tier transitions in both directions: a warm
    frame saved by a sharded round serves the next dense round, and
    vice versa — the mesh padding no-op at gate widths keeps the drift
    epsilon valid across the switch."""
    import bench

    _tier_on(monkeypatch)
    state = _contended_state(seed=11)
    planner = _planner(state)
    _, m1 = planner.schedule_round()
    assert m1.solve_tier == "sharded" and m1.gap_bound == 0.0
    assert planner._warm_bands, "sharded round saved no warm frame"
    cold_iters = m1.iterations

    rng = np.random.default_rng(2)
    # sharded -> sharded (the steady state), then sharded -> dense,
    # then dense -> sharded.  Every warm round must certify exactly and
    # cost at most the cold solve (a dropped/poisoned carried frame
    # shows up as a full re-derivation or a failed certificate).
    for flip_to in ("1", "0", "1"):
        monkeypatch.setenv("POSEIDON_SHARDED_BANDS", flip_to)
        bench.churn_step(state, rng)
        _, m = planner.schedule_round()
        expected = "sharded" if flip_to == "1" else ("pruned", "dense")
        if flip_to == "1":
            assert m.solve_tier == expected
        else:
            assert m.solve_tier in expected
        assert m.converged and m.gap_bound == 0.0
        assert m.iterations <= cold_iters, (
            f"warm round after tier flip to {flip_to!r} cost "
            f"{m.iterations} iterations vs {cold_iters} cold"
        )
        assert planner._warm_bands


def test_solve_tier_sharded_telemetry_ride_through():
    """RoundMetrics.solve_tier == "sharded" and the shard series ride
    the single wire format end to end: to_dict/from_dict, the /metrics
    one-hot + schema gauges, and the soak/bench sub-report vocabulary."""
    from poseidon_tpu.chaos import soak
    from poseidon_tpu.graph.instance import RoundMetrics
    from poseidon_tpu.obs import metrics as obs_metrics

    m = RoundMetrics(round_index=3, solve_tier="sharded",
                     sharded_bands=2, shard_devices=8,
                     shard_imbalance=1.25, placed=7)
    d = m.to_dict()
    assert d["solve_tier"] == "sharded"
    assert d["sharded_bands"] == 2
    assert d["shard_devices"] == 8
    assert d["shard_imbalance"] == 1.25
    rt = RoundMetrics.from_dict(d)
    assert (rt.solve_tier, rt.sharded_bands, rt.shard_devices,
            rt.shard_imbalance) == ("sharded", 2, 8, 1.25)

    assert "sharded" in obs_metrics.SOLVE_TIERS
    reg = obs_metrics.Registry()
    obs_metrics.observe_round(m, registry=reg)
    text = reg.expose()
    assert 'poseidon_round_solve_tier{tier="sharded"} 1' in text
    assert 'poseidon_round_solve_tier{tier="dense"} 0' in text
    assert "poseidon_round_sharded_bands 2" in text
    assert "poseidon_round_shard_devices 8" in text
    assert "poseidon_round_shard_imbalance 1.25" in text

    # The shared drive harness (soak + scenario) accepts the tier, and
    # the soak's sub-reports are the same to_dict wire format.
    from poseidon_tpu.chaos.harness import KNOWN_TIERS
    assert "sharded" in KNOWN_TIERS
    assert soak._metrics_dict(m)["solve_tier"] == "sharded"


def test_bench_artifact_lifts_shard_series():
    """build_artifact lifts the sharded series + tier fingerprint of
    the scored rung top-level (bench_compare reads them there)."""
    import bench

    rung = {
        "machines": 100, "tasks": 1000, "ok": True, "converged": True,
        "cold_s": 1.0, "wave_p50_s": 0.5, "churn_p50_s": 0.1,
        "wave_solve_iters": [10], "wave_sharded_bands": [1],
        "wave_shard_imbalance": [1.1], "solve_tiers": ["sharded"],
    }
    art = bench.build_artifact(
        [rung], (100, 1000), {"parity_ok": True}, {}, {},
        cluster={"ok": True, "sharded_parity_ok": True},
    )
    assert art["wave_sharded_bands"] == [1]
    assert art["wave_shard_imbalance"] == [1.1]
    assert art["solve_tiers"] == ["sharded"]
    assert art["cluster"]["sharded_parity_ok"] is True


def test_bench_compare_flags_tier_mismatch():
    """Satellite bugfix: a sharded-tier current vs a single-chip
    baseline must be flagged apples-to-oranges, not silently diffed;
    artifacts predating solve_tiers stay comparable (single-chip by
    construction)."""
    import sys

    sys.path.insert(0, "tools")
    try:
        import bench_compare
    finally:
        sys.path.pop(0)

    base = {"backend": "cpu", "machines": 100, "tasks": 1000,
            "wave_p50_s": 0.5, "wave_solve_iters": [10]}
    cur_sharded = dict(base, solve_tiers=["quiet", "sharded"])
    out = bench_compare.compare(base, cur_sharded)
    assert not out["comparable"]
    assert "solver-tier mismatch" in out["reason"]

    # Pre-field baseline vs single-chip current: still comparable.
    cur_single = dict(base, solve_tiers=["dense", "quiet"])
    assert bench_compare.compare(base, cur_single)["comparable"]
    # Sharded on both sides: comparable again.
    assert bench_compare.compare(
        cur_sharded, dict(cur_sharded))["comparable"]


def test_precompile_covers_sharded_tier_key(monkeypatch):
    """With the hatch on, precompile probes the mesh-split kernel at
    the full bucket, so a warm sharded round mints no fresh compile
    (the bench-smoke mesh rung pins the ledger side; this pins the
    compile-count side)."""
    _tier_on(monkeypatch)
    from poseidon_tpu.check.ledger import CompileLedger

    state = _contended_state(seed=21)
    planner = _planner(state)
    planner.precompile(max_ecs=8)
    with CompileLedger(budget=0, label="post-precompile sharded round"):
        _, m = planner.schedule_round()
    assert m.solve_tier == "sharded"
    assert m.fresh_compiles == 0


def test_cert_robust_to_equilibrium_choice():
    """Satellite regression (docs/PERF.md round 9): the zero-dispatch
    churn certificate must not depend on WHICH equally-optimal dual
    surface the previous solve returned.  A warm start whose flows are
    exactly optimal but whose duals are a perturbed (still spread-
    capped) equilibrium used to miss the exact certificate and
    re-solve ~960 iterations; the canonical-duals retry re-derives the
    prices from the primal and returns in zero iterations."""
    from poseidon_tpu.ops.transport import (
        _certified_eps,
        derive_scale,
        padded_shape,
        solve_transport,
    )

    rng = np.random.default_rng(42)
    E, M = 6, 16
    costs = rng.integers(1, 50, size=(E, M)).astype(np.int32)
    supply = rng.integers(1, 4, size=E).astype(np.int32)
    capacity = np.full(M, 2, dtype=np.int32)
    unsched_cost = np.full(E, 100, dtype=np.int32)

    sol = solve_transport(costs, supply, capacity, unsched_cost)
    assert sol.gap_bound == 0.0

    e_pad, m_pad = padded_shape(E, M)
    scale, _ = derive_scale(costs, unsched_cost, 0, e_pad, m_pad)
    # The "other" equilibrium: perturb one row potential.  The FLOWS
    # stay exactly optimal; only the dual surface moved, which is
    # precisely what a different-but-equally-optimal wave solve hands
    # the next churn round.
    perturbed = sol.prices.copy()
    perturbed[0] -= 2 * scale
    eps_perturbed = _certified_eps(
        sol.flows, sol.unsched, perturbed, costs=costs, supply=supply,
        capacity=capacity, unsched_cost=unsched_cost, scale=scale,
    )
    assert eps_perturbed > 1, (
        "perturbation failed to break the exact certificate — the "
        "regression scenario needs a cert-missing equilibrium"
    )

    warm = solve_transport(
        costs, supply, capacity, unsched_cost, perturbed,
        init_flows=sol.flows, init_unsched=sol.unsched,
    )
    assert warm.iterations == 0, (
        f"equilibrium flip re-dispatched: {warm.iterations} iterations"
    )
    assert warm.gap_bound == 0.0
    assert warm.objective == sol.objective
    assert np.array_equal(warm.flows, sol.flows)


def test_exact_equilibrium_prices_certify_any_optimal_primal():
    """The canonical-dual reconstruction depends on the primal alone
    (feeding it a dual surface is impossible by signature), is
    deterministic, and certifies an optimal primal EXACTLY across many
    random instances — the property the host-cert retry leans on."""
    from poseidon_tpu.ops.transport import (
        _certified_eps,
        derive_scale,
        exact_equilibrium_prices,
        padded_shape,
        solve_transport,
    )

    for seed in range(8):
        rng = np.random.default_rng(seed)
        E = int(rng.integers(3, 8))
        M = int(rng.integers(8, 20))
        costs = rng.integers(1, 30, size=(E, M)).astype(np.int32)
        supply = rng.integers(1, 3, size=E).astype(np.int32)
        capacity = np.full(M, 2, dtype=np.int32)
        unsched_cost = np.full(E, 64, dtype=np.int32)
        sol = solve_transport(costs, supply, capacity, unsched_cost)
        assert sol.gap_bound == 0.0
        e_pad, m_pad = padded_shape(E, M)
        scale, _ = derive_scale(costs, unsched_cost, 0, e_pad, m_pad)
        p1 = exact_equilibrium_prices(
            sol.flows, sol.unsched, costs=costs, supply=supply,
            capacity=capacity, arc_capacity=None,
            unsched_cost=unsched_cost, scale=scale,
        )
        assert p1 is not None, f"seed {seed}: relaxation did not settle"
        assert p1.shape == (E + M + 1,)
        p2 = exact_equilibrium_prices(
            sol.flows, sol.unsched, costs=costs, supply=supply,
            capacity=capacity, arc_capacity=None,
            unsched_cost=unsched_cost, scale=scale,
        )
        assert np.array_equal(p1, p2)
        eps = _certified_eps(
            sol.flows, sol.unsched, p1, costs=costs, supply=supply,
            capacity=capacity, unsched_cost=unsched_cost, scale=scale,
        )
        assert eps == 1, f"seed {seed}: canonical duals eps {eps}"
