"""Checkpoint/restore of the scheduling state."""

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.snapshot import load_state, save_state
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import generate_uuid, task_uid


def test_roundtrip_preserves_schedule(tmp_path):
    st = ClusterState()
    for i in range(4):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"sn{i}"), cpu_capacity=4000,
                        ram_capacity=1 << 24, labels={"zone": f"z{i % 2}"})
        )
    st.node_failed(generate_uuid("sn3"))
    for i in range(10):
        st.task_submitted(
            TaskInfo(uid=task_uid("sj", i), job_id="sj",
                     cpu_request=250, ram_request=1 << 18,
                     labels={"app": "x"})
        )
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    planner.schedule_round()
    st.task_completed(task_uid("sj", 0))

    path = tmp_path / "state.json"
    save_state(st, path)
    st2 = load_state(path)

    assert st2.round_index == st.round_index
    assert set(st2.machines) == set(st.machines)
    assert not st2.machines[generate_uuid("sn3")].healthy
    assert set(st2.tasks) == set(st.tasks)
    for uid, t in st.tasks.items():
        t2 = st2.tasks[uid]
        assert t2.scheduled_to == t.scheduled_to
        assert t2.state == t.state
        assert t2.wait_rounds == t.wait_rounds
        assert t2.ec_id == t.ec_id

    # The restored state schedules on: a quiet world yields no deltas.
    planner2 = RoundPlanner(st2, get_cost_model("cpu_mem"))
    deltas, m = planner2.schedule_round()
    assert deltas == [] and m.unscheduled == 0
