"""Checkpoint/restore of the scheduling state."""

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.snapshot import load_state, save_state
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import generate_uuid, task_uid


def test_roundtrip_preserves_schedule(tmp_path):
    st = ClusterState()
    for i in range(4):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"sn{i}"), cpu_capacity=4000,
                        ram_capacity=1 << 24, labels={"zone": f"z{i % 2}"})
        )
    st.node_failed(generate_uuid("sn3"))
    for i in range(10):
        st.task_submitted(
            TaskInfo(uid=task_uid("sj", i), job_id="sj",
                     cpu_request=250, ram_request=1 << 18,
                     labels={"app": "x"})
        )
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    planner.schedule_round()
    st.task_completed(task_uid("sj", 0))

    path = tmp_path / "state.json"
    save_state(st, path)
    st2 = load_state(path)

    assert st2.round_index == st.round_index
    assert set(st2.machines) == set(st.machines)
    assert not st2.machines[generate_uuid("sn3")].healthy
    assert set(st2.tasks) == set(st.tasks)
    for uid, t in st.tasks.items():
        t2 = st2.tasks[uid]
        assert t2.scheduled_to == t.scheduled_to
        assert t2.state == t.state
        assert t2.wait_rounds == t.wait_rounds
        assert t2.ec_id == t.ec_id

    # The restored state schedules on: a quiet world yields no deltas.
    planner2 = RoundPlanner(st2, get_cost_model("cpu_mem"))
    deltas, m = planner2.schedule_round()
    assert deltas == [] and m.unscheduled == 0


def test_checkpoint_restores_warm_frames_and_solves_warm(tmp_path):
    """A restored service's first round must solve WARM: same pending
    backlog => drift-epsilon floor => far fewer iterations than the cold
    ladder a frame-less restore pays (round-3 weak #3)."""
    import numpy as np

    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.snapshot import load_checkpoint, save_checkpoint
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    rng = np.random.default_rng(0)
    state = ClusterState()
    # Contended: capacity holds ~half the backlog, so every round keeps a
    # pending remainder — the state where warm frames pay off.
    for i in range(60):
        state.node_added(MachineInfo(
            uuid=f"wm-{i:03d}", cpu_capacity=4000, ram_capacity=1 << 24,
            task_slots=4,
        ))
    for i in range(500):
        state.task_submitted(TaskInfo(
            uid=task_uid("ckpt", i), job_id=f"j{i % 7}",
            cpu_request=int(rng.integers(2, 12)) * 100,
            ram_request=int(rng.integers(1, 16)) << 18,
        ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    _, m_cold = planner.schedule_round()
    assert m_cold.unscheduled > 0  # a standing backlog exists
    _, m_steady = planner.schedule_round()  # the steady-state warm cost

    ckpt = tmp_path / "svc.ckpt"
    save_checkpoint(state, planner, ckpt)
    assert (tmp_path / "svc.ckpt.warm.npz").exists()

    state2, planner2 = load_checkpoint(ckpt)
    _, m_restored = planner2.schedule_round()
    assert m_restored.converged
    # The restored first round must behave like the steady-state round,
    # not the cold one: identical backlog, frames restored.
    assert m_restored.iterations <= max(2 * m_steady.iterations, 8), (
        m_cold.iterations, m_steady.iterations, m_restored.iterations
    )
    assert m_restored.iterations < m_cold.iterations / 4

    # Placements survive alongside: the same machines stay claimed.
    placed1 = {t.uid: t.scheduled_to for t in state.tasks.values()
               if t.scheduled_to}
    placed2 = {t.uid: t.scheduled_to for t in state2.tasks.values()
               if t.scheduled_to}
    assert placed1.keys() == placed2.keys()


def test_checkpoint_without_frames_degrades_to_cold(tmp_path):
    from poseidon_tpu.graph.snapshot import load_checkpoint, save_checkpoint
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo

    state = ClusterState()
    state.node_added(MachineInfo(uuid="m-0", cpu_capacity=1000,
                                 ram_capacity=1 << 20))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    ckpt = tmp_path / "empty.ckpt"
    save_checkpoint(state, planner, ckpt)
    # No frames were saved (nothing solved): loading must still work.
    assert not (tmp_path / "empty.ckpt.warm.npz").exists()
    state2, planner2 = load_checkpoint(ckpt)
    assert not planner2._warm_bands
