"""Cost-model tests: admissibility gating, fit bounds, load pricing.

Modeled on the reference's table-driven parsing/conversion unit tests
(reference pkg/k8sclient/nodewatcher_test.go:120-216 style).
"""

import numpy as np
import pytest

from poseidon_tpu.costmodel import (
    CpuMemCostModel,
    TrivialCostModel,
    get_cost_model,
    selector_admissibility,
)
from poseidon_tpu.costmodel.base import ECTable, MachineTable
from poseidon_tpu.costmodel.selectors import (
    EXISTS_KEY,
    IN_SET,
    NOT_EXISTS_KEY,
    NOT_IN_SET,
)
from poseidon_tpu.ops.transport import INF_COST


def make_ecs(requests, selectors=None, waits=None):
    n = len(requests)
    return ECTable(
        ec_ids=np.arange(n, dtype=np.uint64),
        cpu_request=np.array([r[0] for r in requests], dtype=np.int64),
        ram_request=np.array([r[1] for r in requests], dtype=np.int64),
        supply=np.ones(n, dtype=np.int32),
        priority=np.zeros(n, dtype=np.int32),
        task_type=np.zeros(n, dtype=np.int32),
        max_wait_rounds=np.array(waits or [0] * n, dtype=np.int32),
        selectors=selectors or [() for _ in range(n)],
    )


def make_machines(caps, labels=None, slots=10):
    m = len(caps)
    return MachineTable(
        uuids=[f"m{i}" for i in range(m)],
        cpu_capacity=np.array([c[0] for c in caps], dtype=np.int64),
        ram_capacity=np.array([c[1] for c in caps], dtype=np.int64),
        cpu_used=np.zeros(m, dtype=np.int64),
        ram_used=np.zeros(m, dtype=np.int64),
        cpu_util=np.zeros(m, dtype=np.float32),
        mem_util=np.zeros(m, dtype=np.float32),
        slots_free=np.full(m, slots, dtype=np.int32),
        labels=labels or [{} for _ in range(m)],
    )


class TestSelectorAdmissibility:
    def test_empty_selectors_admit_all(self):
        mask = selector_admissibility([()], [{}, {"a": "b"}])
        assert mask.all()

    def test_in_set(self):
        sels = [((IN_SET, "zone", ("us-1", "us-2")),)]
        labels = [{"zone": "us-1"}, {"zone": "eu-1"}, {}]
        mask = selector_admissibility(sels, labels)
        assert mask.tolist() == [[True, False, False]]

    def test_not_in_set(self):
        sels = [((NOT_IN_SET, "zone", ("us-1",)),)]
        labels = [{"zone": "us-1"}, {"zone": "eu-1"}, {}]
        mask = selector_admissibility(sels, labels)
        assert mask.tolist() == [[False, True, True]]

    def test_exists_and_not_exists(self):
        sels = [
            ((EXISTS_KEY, "gpu", ()),),
            ((NOT_EXISTS_KEY, "gpu", ()),),
        ]
        labels = [{"gpu": "yes"}, {}]
        mask = selector_admissibility(sels, labels)
        assert mask.tolist() == [[True, False], [False, True]]

    def test_conjunction(self):
        sels = [((IN_SET, "zone", ("z1",)), (EXISTS_KEY, "ssd", ()))]
        labels = [{"zone": "z1", "ssd": "1"}, {"zone": "z1"}, {"ssd": "1"}]
        mask = selector_admissibility(sels, labels)
        assert mask.tolist() == [[True, False, False]]


class TestCpuMemModel:
    def test_no_fit_is_inadmissible(self):
        ecs = make_ecs([(2000, 1000)])
        mt = make_machines([(1000, 4_000_000), (4000, 4_000_000)])
        cm = CpuMemCostModel().build(ecs, mt)
        assert cm.costs[0, 0] == INF_COST
        assert cm.costs[0, 1] < INF_COST
        assert cm.arc_capacity[0, 0] == 0
        assert cm.arc_capacity[0, 1] == 2  # 4000/2000 cpu-bound

    def test_less_loaded_machine_cheaper(self):
        ecs = make_ecs([(500, 100_000)])
        mt = make_machines([(1000, 1_000_000), (8000, 8_000_000)])
        cm = CpuMemCostModel().build(ecs, mt)
        assert cm.costs[0, 1] < cm.costs[0, 0]

    def test_measured_utilization_raises_cost(self):
        ecs = make_ecs([(100, 1000)])
        mt = make_machines([(4000, 4_000_000), (4000, 4_000_000)])
        mt.cpu_util = np.array([0.9, 0.0], dtype=np.float32)
        mt.mem_util = np.array([0.9, 0.0], dtype=np.float32)
        cm = CpuMemCostModel().build(ecs, mt)
        assert cm.costs[0, 0] > cm.costs[0, 1]

    def test_wait_rounds_escalate_unscheduled_cost(self):
        ecs = make_ecs([(1, 1), (1, 1)], waits=[0, 5])
        mt = make_machines([(1000, 1_000_000)])
        cm = CpuMemCostModel().build(ecs, mt)
        assert cm.unsched_cost[1] > cm.unsched_cost[0]

    def test_selector_gates_arcs(self):
        ecs = make_ecs(
            [(1, 1)], selectors=[((IN_SET, "zone", ("z9",)),)]
        )
        mt = make_machines([(1000, 1_000_000)], labels=[{"zone": "z1"}])
        cm = CpuMemCostModel().build(ecs, mt)
        assert cm.costs[0, 0] == INF_COST

    def test_empty_tables(self):
        cm = CpuMemCostModel().build(make_ecs([]), make_machines([]))
        assert cm.costs.shape == (0, 0)


class TestRegistry:
    def test_lookup(self):
        assert isinstance(get_cost_model("cpu_mem"), CpuMemCostModel)
        assert isinstance(get_cost_model("trivial"), TrivialCostModel)

    def test_unknown(self):
        with pytest.raises(ValueError):
            get_cost_model("nope")
