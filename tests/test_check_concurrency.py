"""Runtime-half tests for the concurrency discipline PR: TrackedLock
edge/contention/hold accounting, the LockLedger budget-0 window, the
tracked Condition, and the seeded preemption harness plumbing
(chaos/preempt.py).  The static rules' fixture counts live in
tests/test_check_selfcheck.py; the live interleaving suites in
tests/test_races.py."""

from __future__ import annotations

import threading
import time

import pytest

from poseidon_tpu.chaos.preempt import (
    InvariantTracker,
    PreemptPoints,
    race_seeds,
)
from poseidon_tpu.utils import locks as L


@pytest.fixture(autouse=True)
def _fresh_edge_graph():
    # The edge graph is process-global on purpose (the soak diffs it);
    # these tests mint deliberate edges/cycles, so isolate them.
    L._reset_edges_for_tests()
    yield
    L._reset_edges_for_tests()


# ------------------------------------------------------------ TrackedLock


def test_tracked_lock_basic_accounting():
    lk = L.TrackedLock("t.basic")
    with lk:
        time.sleep(0.001)
    assert lk.acquisitions == 1
    assert lk.hold_ns > 0
    assert lk.contended == 0
    # Uncontended single-lock use records no order edges.
    assert L.lock_order_edge_count() == 0


def test_tracked_lock_nonblocking_acquire():
    lk = L.TrackedLock("t.nonblock")
    assert lk.acquire(blocking=False)
    # Held: a second non-blocking attempt from another thread fails
    # without recording contention time.
    got = []
    t = threading.Thread(
        target=lambda: got.append(lk.acquire(blocking=False))
    )
    t.start()
    t.join()
    assert got == [False]
    lk.release()


def test_tracked_lock_reentrant():
    lk = L.TrackedLock("t.rlock", reentrant=True)
    with lk:
        with lk:  # nested owner re-acquire: no self-edge, no deadlock
            assert lk.acquisitions == 1
    assert L.lock_order_edge_count() == 0
    # Re-acquirable after full release.
    with lk:
        pass
    assert lk.acquisitions == 2


def test_order_edge_recorded_once():
    a = L.TrackedLock("t.edge.a")
    b = L.TrackedLock("t.edge.b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert L.lock_order_edge_count() == 1
    (src, dst, site) = L.lock_order_edges()[0]
    assert (src, dst) == ("t.edge.a", "t.edge.b")
    assert site  # first-observation call site attributed


def test_cycle_detected_on_opposite_order():
    a = L.TrackedLock("t.cyc.a")
    b = L.TrackedLock("t.cyc.b")
    with a:
        with b:
            pass
    assert L.lock_cycles() == []
    with b:
        with a:
            pass
    cycles = L.lock_cycles()
    assert len(cycles) == 1
    assert "t.cyc.a" in cycles[0] and "t.cyc.b" in cycles[0]


def test_contention_accounted():
    lk = L.TrackedLock("t.contend")
    release = threading.Event()
    held = threading.Event()

    def holder():
        with lk:
            held.set()
            release.wait(2.0)

    t = threading.Thread(target=holder)
    t.start()
    assert held.wait(2.0)
    t0 = L.lock_contention_ns()
    threading.Timer(0.02, release.set).start()
    with lk:
        pass
    t.join()
    assert lk.contended == 1
    assert L.lock_contention_ns() - t0 > 0
    stats = L.per_lock_stats()["t.contend"]
    assert stats["contended"] == 1.0
    assert stats["acquisitions"] == 2.0


def test_hatch_disables_tracking(monkeypatch):
    monkeypatch.setenv("POSEIDON_LOCK_LEDGER", "0")
    a = L.TrackedLock("t.off.a")
    b = L.TrackedLock("t.off.b")
    with a:
        with b:
            pass
    assert L.lock_order_edge_count() == 0
    assert a.acquisitions == 0  # degraded to a bare delegate


def test_tracked_condition_wait_notify():
    cond = L.tracked_condition("t.cond")
    ready = []

    def waiter():
        with cond:
            while not ready:
                cond.wait(timeout=2.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.01)
    with cond:
        ready.append(1)
        cond.notify()
    t.join(2.0)
    assert not t.is_alive()
    # Waiting on the condition's own lock is not an order edge.
    assert L.lock_order_edge_count() == 0


# ------------------------------------------------------------- LockLedger


def test_ledger_passes_on_known_edges():
    a = L.TrackedLock("t.led.a")
    b = L.TrackedLock("t.led.b")
    with a:
        with b:
            pass
    # Edge latched before the window: re-walking it is budget-clean.
    with L.LockLedger(label="warm"):
        with a:
            with b:
                pass


def test_ledger_raises_on_new_edge():
    a = L.TrackedLock("t.led2.a")
    b = L.TrackedLock("t.led2.b")
    with pytest.raises(L.LockBudgetExceeded, match="lock-order edge"):
        with L.LockLedger(label="warm"):
            with a:
                with b:
                    pass


def test_ledger_telemetry_mode_records_without_raising():
    a = L.TrackedLock("t.led3.a")
    b = L.TrackedLock("t.led3.b")
    with L.LockLedger(budget=None, label="telemetry") as led:
        with a:
            with b:
                pass
    assert [(s, d) for s, d, _ in led.new_edges] == [
        ("t.led3.a", "t.led3.b")
    ]


def test_ledger_flags_sleep_under_lock():
    lk = L.TrackedLock("t.led4")
    with pytest.raises(L.LockBudgetExceeded, match="blocking call"):
        with L.LockLedger(label="warm"):
            with lk:
                time.sleep(0)


def test_ledger_flags_queue_get_under_lock():
    import queue

    lk = L.TrackedLock("t.led5")
    q = queue.Queue()
    q.put(1)
    with pytest.raises(L.LockBudgetExceeded, match="blocking call"):
        with L.LockLedger(label="warm"):
            with lk:
                q.get()


def test_ledger_allows_blocking_outside_lock():
    import queue

    q = queue.Queue()
    q.put(1)
    with L.LockLedger(label="warm"):
        time.sleep(0)
        q.get()


def test_ledger_covers_threads_started_in_window():
    a = L.TrackedLock("t.led6.a")
    b = L.TrackedLock("t.led6.b")

    def nest():
        with a:
            with b:
                pass

    with pytest.raises(L.LockBudgetExceeded):
        with L.LockLedger(label="warm"):
            t = threading.Thread(target=nest)
            t.start()
            t.join()


def test_ledger_body_exception_wins():
    a = L.TrackedLock("t.led7.a")
    b = L.TrackedLock("t.led7.b")
    with pytest.raises(ValueError):
        with L.LockLedger(label="warm"):
            with a:
                with b:
                    raise ValueError("body failure")


# ---------------------------------------------------------- preempt hooks


def test_preempt_points_fire_and_are_seeded():
    lk = L.TrackedLock("t.pp")
    with PreemptPoints(seed=7) as pp:
        for _ in range(10):
            with lk:
                pass
    first = pp.decisions
    assert first >= 10  # at least one decision per acquire
    with PreemptPoints(seed=7) as pp2:
        for _ in range(10):
            with lk:
                pass
    assert pp2.decisions == first


def test_preempt_points_reject_nesting():
    with PreemptPoints(seed=0):
        with pytest.raises(RuntimeError, match="already installed"):
            with PreemptPoints(seed=1):
                pass
    # Uninstalled on exit: a fresh install works.
    with PreemptPoints(seed=2):
        pass


def test_race_seeds_hatches(monkeypatch):
    monkeypatch.setenv("POSEIDON_RACE_SEED", "100")
    monkeypatch.setenv("POSEIDON_RACE_SWEEP", "4")
    assert list(race_seeds()) == [100, 101, 102, 103]
    assert list(race_seeds(sweep=2)) == [100, 101]
    monkeypatch.setenv("POSEIDON_RACE_SWEEP", "0")
    assert list(race_seeds()) == [100]  # never empty


def test_invariant_tracker_records_overlap():
    tr = InvariantTracker()
    tr.enter("k", "t1")
    tr.enter("k", "t2")
    tr.exit("k", "t2")
    tr.exit("k", "t1")
    assert len(tr.violations) == 1
    assert "t1" in tr.violations[0] and "t2" in tr.violations[0]


# ------------------------------------------------------- metrics export


def test_observe_locks_exports_series():
    from poseidon_tpu.obs import metrics as obs_metrics

    lk = L.TrackedLock("t.metrics")
    with lk:
        pass
    reg = obs_metrics.Registry()
    obs_metrics.observe_locks(reg)
    text = reg.expose()
    assert "poseidon_lock_contention_total" in text
    assert "poseidon_lock_contention_seconds_total" in text
    assert "poseidon_lock_hold_seconds_total" in text
    assert "poseidon_lock_order_edges" in text
