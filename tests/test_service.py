"""End-to-end tests of the firmament-tpu gRPC service.

The reference's integration tier drives a real Firmament deployment through
the 13-RPC surface (test/e2e/poseidon_integration.go); here the service runs
in-process on a loopback port and a FirmamentClient (the typed wrapper with
the reference's fatal-reply semantics) plays the Poseidon role.
"""

import pytest

from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.service import (
    FatalReplyError,
    FirmamentClient,
    FirmamentTPUServer,
)
from poseidon_tpu.utils.config import FirmamentTPUConfig
from poseidon_tpu.utils.ids import generate_uuid, hash_combine


def make_task(uid, job="job-1", cpu=100, ram=1 << 20, selectors=(), prio=0):
    td = fpb.TaskDescriptor(uid=uid, name=f"task-{uid}", job_id=job)
    td.resource_request.cpu_cores = cpu
    td.resource_request.ram_cap = ram
    td.priority = prio
    for stype, key, values in selectors:
        td.label_selectors.add(type=stype, key=key, values=list(values))
    jd = fpb.JobDescriptor(uuid=job, name=job)
    return td, jd


def make_node(uuid, cpu=4000, ram=16 << 20, labels=None, slots=100):
    rtnd = fpb.ResourceTopologyNodeDescriptor()
    rd = rtnd.resource_desc
    rd.uuid = uuid
    rd.friendly_name = f"node-{uuid[:8]}"
    rd.type = fpb.ResourceDescriptor.RESOURCE_MACHINE
    rd.resource_capacity.cpu_cores = cpu
    rd.resource_capacity.ram_cap = ram
    rd.task_capacity = slots
    for k, v in (labels or {}).items():
        rd.labels.add(key=k, value=v)
    pu = rtnd.children.add()
    pu.resource_desc.uuid = uuid + "-pu0"
    pu.resource_desc.type = fpb.ResourceDescriptor.RESOURCE_PU
    pu.parent_id = uuid
    return rtnd


@pytest.fixture(scope="module")
def server():
    with FirmamentTPUServer(address="127.0.0.1:0") as srv:
        yield srv


@pytest.fixture()
def client(server):
    # Fresh state per test: servicer state is reset by rebuilding it.
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState

    sv = server.servicer
    sv.state = ClusterState()
    sv.planner = RoundPlanner(sv.state, get_cost_model(sv.config.cost_model))
    with FirmamentClient(server.address) as c:
        yield c


def test_health_gate(client):
    assert client.check() == fpb.SERVING
    assert client.wait_for_service(timeout=5.0, poll_interval=0.1)


def test_place_all_tasks_one_round(client):
    n1, n2 = generate_uuid("n1"), generate_uuid("n2")
    assert client.node_added(make_node(n1)) == fpb.NODE_ADDED_OK
    assert client.node_added(make_node(n2)) == fpb.NODE_ADDED_OK
    for i in range(6):
        td, jd = make_task(hash_combine(1, i))
        assert client.task_submitted(td, jd) == fpb.TASK_SUBMITTED_OK

    deltas = client.schedule()
    assert len(deltas) == 6
    assert all(d.type == fpb.SchedulingDelta.PLACE for d in deltas)
    assert {d.resource_id for d in deltas} <= {n1, n2}
    # Second round with no changes: no deltas (NOOPs are elided,
    # cmd/poseidon/poseidon.go:64).
    assert client.schedule() == []


def test_task_lifecycle_reply_enums(client):
    td, jd = make_task(42)
    assert client.task_submitted(td, jd) == fpb.TASK_SUBMITTED_OK
    # Re-submission of a runnable task is tolerated (restart re-play).
    assert client.task_submitted(td, jd) == fpb.TASK_ALREADY_SUBMITTED
    assert client.task_completed(42) == fpb.TASK_COMPLETED_OK
    assert client.task_removed(42) == fpb.TASK_REMOVED_OK
    # Unknown uids are fatal to the reference client.
    with pytest.raises(FatalReplyError):
        client.task_completed(42)
    with pytest.raises(FatalReplyError):
        client.task_failed(99)
    with pytest.raises(FatalReplyError):
        client.task_removed(99)


def test_node_lifecycle_reply_enums(client):
    uuid = generate_uuid("node-a")
    rtnd = make_node(uuid)
    assert client.node_added(rtnd) == fpb.NODE_ADDED_OK
    assert client.node_added(rtnd) == fpb.NODE_ALREADY_EXISTS
    assert client.node_updated(rtnd) == fpb.NODE_UPDATED_OK
    # Failure/removal addressed by a PU uuid resolves to the machine.
    assert client.node_failed(uuid + "-pu0") == fpb.NODE_FAILED_OK
    assert client.node_removed(uuid) == fpb.NODE_REMOVED_OK
    with pytest.raises(FatalReplyError):
        client.node_removed(uuid)
    with pytest.raises(FatalReplyError):
        client.node_updated(rtnd)


def test_failed_node_evicts_and_replaces(client):
    n1, n2 = generate_uuid("nf1"), generate_uuid("nf2")
    client.node_added(make_node(n1))
    td, jd = make_task(7)
    client.task_submitted(td, jd)
    (delta,) = client.schedule()
    assert delta.resource_id == n1

    client.node_added(make_node(n2))
    assert client.node_failed(n1) == fpb.NODE_FAILED_OK
    (delta2,) = client.schedule()
    # Task went back to runnable and is re-placed on the healthy node.
    assert delta2.type == fpb.SchedulingDelta.PLACE
    assert delta2.resource_id == n2


def test_selector_gating_over_wire(client):
    labeled = generate_uuid("lab")
    plain = generate_uuid("plain")
    client.node_added(make_node(labeled, labels={"disktype": "ssd"}))
    client.node_added(make_node(plain))
    td, jd = make_task(
        11, selectors=[(fpb.LabelSelector.IN_SET, "disktype", ("ssd",))]
    )
    client.task_submitted(td, jd)
    (delta,) = client.schedule()
    assert delta.resource_id == labeled


def test_oversized_task_stays_unscheduled(client):
    n = generate_uuid("small")
    client.node_added(make_node(n, cpu=1000, ram=1 << 20))
    td, jd = make_task(13, cpu=8000, ram=1 << 22)
    client.task_submitted(td, jd)
    assert client.schedule() == []  # no PLACE: nothing fits


def test_stats_ingestion(client):
    n = generate_uuid("stats-node")
    client.node_added(make_node(n))
    td, jd = make_task(21)
    client.task_submitted(td, jd)

    rs = fpb.ResourceStats(resource_id=n + "-pu0", mem_utilization=0.5)
    rs.cpus_stats.add(cpu_utilization=0.25)
    rs.cpus_stats.add(cpu_utilization=0.75)
    assert client.add_node_stats(rs) == fpb.NODE_ADDED_OK

    ts = fpb.TaskStats(task_id=21, cpu_usage=50, mem_usage=1024)
    assert client.add_task_stats(ts) == fpb.TASK_SUBMITTED_OK

    # Unknown entities: NOT_FOUND, dropped without raising (stats.go:89-91).
    assert (
        client.add_node_stats(fpb.ResourceStats(resource_id="nope"))
        == fpb.NODE_NOT_FOUND
    )
    assert (
        client.add_task_stats(fpb.TaskStats(task_id=999))
        == fpb.TASK_NOT_FOUND
    )


def test_utilization_steers_placement(client):
    """AddNodeStats -> knowledge base -> cost model -> placement choice."""
    hot, cold = generate_uuid("hot"), generate_uuid("cold")
    client.node_added(make_node(hot))
    client.node_added(make_node(cold))
    rs = fpb.ResourceStats(resource_id=hot, mem_utilization=0.95)
    rs.cpus_stats.add(cpu_utilization=0.95)
    for _ in range(4):  # push the EMA up
        client.add_node_stats(rs)
    td, jd = make_task(31)
    client.task_submitted(td, jd)
    (delta,) = client.schedule()
    assert delta.resource_id == cold


def test_service_checkpoint_roundtrip(tmp_path):
    """checkpoint_path config: a new servicer over the same path restores
    placements and warm frames (the restart-recovery path the reference
    lacks -- its README.md:67 lists HA as roadmap)."""
    from poseidon_tpu.protos import firmament_pb2 as fpb
    from poseidon_tpu.service.server import FirmamentServicer
    from poseidon_tpu.utils.config import FirmamentTPUConfig
    from poseidon_tpu.utils.ids import generate_uuid, hash_combine

    ckpt = str(tmp_path / "svc.ckpt")
    cfg = FirmamentTPUConfig(checkpoint_path=ckpt)
    sv = FirmamentServicer(config=cfg)
    for i in range(3):
        rtnd = fpb.ResourceTopologyNodeDescriptor()
        rd = rtnd.resource_desc
        rd.uuid = generate_uuid(f"ck-m{i}")
        rd.type = fpb.ResourceDescriptor.RESOURCE_MACHINE
        rd.resource_capacity.cpu_cores = 4000
        rd.resource_capacity.ram_cap = 1 << 24
        rd.task_capacity = 10
        sv.NodeAdded(rtnd, None)
    for i in range(5):
        req = fpb.TaskDescription()
        req.task_descriptor.uid = hash_combine(99, i)
        req.task_descriptor.name = f"ck-{i}"
        req.task_descriptor.resource_request.cpu_cores = 100
        req.task_descriptor.resource_request.ram_cap = 1 << 20
        req.job_descriptor.uuid = "ck-job"
        sv.TaskSubmitted(req, None)
    deltas = sv.Schedule(fpb.ScheduleRequest(), None)
    assert len(deltas.deltas) == 5
    sv.save_checkpoint()

    sv2 = FirmamentServicer(config=cfg)
    placed = {t.uid: t.scheduled_to for t in sv2.state.tasks.values()
              if t.scheduled_to}
    assert len(placed) == 5
    # A quiet restored round re-places nothing.
    deltas2 = sv2.Schedule(fpb.ScheduleRequest(), None)
    assert len(deltas2.deltas) == 0
