"""Wire-contract tests: the consolidated protos must be wire-compatible with
the reference's proto layout (same packages, message names, field numbers)."""

from poseidon_tpu.protos import firmament_pb2 as fpb
from poseidon_tpu.protos import stats_pb2 as spb


def test_task_descriptor_roundtrip():
    td = fpb.TaskDescriptor(
        uid=42,
        name="default/pod-0",
        state=fpb.TaskDescriptor.RUNNABLE,
        job_id="job-uuid",
        resource_request=fpb.ResourceVector(cpu_cores=250.0, ram_cap=1024),
        priority=5,
        task_type=fpb.TaskDescriptor.DEVIL,
        labels=[fpb.Label(key="a", value="b")],
        label_selectors=[
            fpb.LabelSelector(
                type=fpb.LabelSelector.IN_SET, key="zone", values=["us-east"]
            )
        ],
    )
    blob = td.SerializeToString()
    back = fpb.TaskDescriptor.FromString(blob)
    assert back.uid == 42
    assert back.resource_request.cpu_cores == 250.0
    assert back.label_selectors[0].values == ["us-east"]


def test_field_numbers_match_reference():
    # Spot-check wire numbering against the reference protos
    # (task_desc.proto, resource_desc.proto, scheduling_delta.proto).
    td_fields = {
        f.name: f.number for f in fpb.TaskDescriptor.DESCRIPTOR.fields
    }
    assert td_fields["uid"] == 1
    assert td_fields["resource_request"] == 26
    assert td_fields["task_type"] == 28
    assert td_fields["trace_task_id"] == 31
    assert td_fields["labels"] == 32
    assert td_fields["label_selectors"] == 33

    rd_fields = {
        f.name: f.number for f in fpb.ResourceDescriptor.DESCRIPTOR.fields
    }
    assert rd_fields["task_capacity"] == 5
    assert rd_fields["resource_capacity"] == 18
    assert rd_fields["labels"] == 32

    sd_fields = {
        f.name: f.number for f in fpb.SchedulingDelta.DESCRIPTOR.fields
    }
    assert sd_fields == {"task_id": 1, "resource_id": 2, "type": 3}
    assert fpb.SchedulingDelta.PLACE == 1
    assert fpb.SchedulingDelta.PREEMPT == 2
    assert fpb.SchedulingDelta.MIGRATE == 3


def test_reply_enums_match_reference():
    # firmament_scheduler.proto:110-129
    assert fpb.TASK_COMPLETED_OK == 0
    assert fpb.TASK_SUBMITTED_OK == 1
    assert fpb.TASK_REMOVED_OK == 2
    assert fpb.TASK_FAILED_OK == 3
    assert fpb.TASK_UPDATED_OK == 4
    assert fpb.TASK_NOT_FOUND == 5
    assert fpb.TASK_JOB_NOT_FOUND == 6
    assert fpb.TASK_ALREADY_SUBMITTED == 7
    assert fpb.TASK_STATE_NOT_CREATED == 8
    assert fpb.NODE_ADDED_OK == 0
    assert fpb.NODE_NOT_FOUND == 4
    assert fpb.NODE_ALREADY_EXISTS == 5
    assert fpb.SERVING == 1


def test_stats_protos():
    ps = spb.PodStats(name="p", namespace="ns", hostname="h", cpu_usage=5)
    assert spb.PodStats.FromString(ps.SerializeToString()).cpu_usage == 5
    fields = {f.name: f.number for f in spb.PodStats.DESCRIPTOR.fields}
    assert fields["net_tx_rate"] == 24
    assert spb.POD_NOT_FOUND == 1
    assert spb.NODE_NOT_FOUND == 1


def test_service_method_tables():
    from poseidon_tpu.protos import services

    assert set(services.FIRMAMENT_METHODS) == {
        "Schedule",
        "TaskCompleted",
        "TaskFailed",
        "TaskRemoved",
        "TaskSubmitted",
        "TaskUpdated",
        "NodeAdded",
        "NodeFailed",
        "NodeRemoved",
        "NodeUpdated",
        "AddTaskStats",
        "AddNodeStats",
        "Check",
    }
    assert services.STATS_METHODS["ReceivePodStats"].arity == "stream_stream"
