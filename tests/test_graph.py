"""Graph-layer tests: state machine reply enums, EC collapsing, round
planning with delta extraction and placement stability.

The reply-enum assertions mirror the reference client's fatal checks
(reference pkg/firmament/firmament_client.go:44-50 et al.): any answer the
client would panic on is a bug here.
"""

import numpy as np

from poseidon_tpu.costmodel import CpuMemCostModel
from poseidon_tpu.graph import (
    ClusterState,
    DeltaType,
    MachineInfo,
    NodeReply,
    RoundPlanner,
    TaskInfo,
    TaskReply,
    TaskState,
)
from poseidon_tpu.graph.ecs import ec_signature


def mk_task(uid, cpu=100, ram=1000, job="job-1", **kw):
    return TaskInfo(uid=uid, job_id=job, cpu_request=cpu, ram_request=ram, **kw)


def mk_machine(uuid, cpu=4000, ram=8_000_000, **kw):
    return MachineInfo(
        uuid=uuid, hostname=uuid, cpu_capacity=cpu, ram_capacity=ram, **kw
    )


class TestTaskStateMachine:
    def test_submit_then_duplicate(self):
        st = ClusterState()
        assert st.task_submitted(mk_task(1)) == TaskReply.SUBMITTED_OK
        assert st.task_submitted(mk_task(1)) == TaskReply.ALREADY_SUBMITTED

    def test_resubmit_of_running_task_tolerated(self):
        # A restarted client re-plays its whole world from list+watch,
        # including bound Running pods: live-task resubmission answers
        # ALREADY_SUBMITTED (tolerated by the client wrapper); only
        # terminal states are un-resubmittable under the same uid.
        st = ClusterState()
        st.task_submitted(mk_task(1))
        st.apply_placement(1, "m-0")
        assert st.task_submitted(mk_task(1)) == TaskReply.ALREADY_SUBMITTED
        st.task_completed(1)
        assert st.task_submitted(mk_task(1)) == TaskReply.STATE_NOT_CREATED

    def test_lifecycle_replies(self):
        st = ClusterState()
        assert st.task_completed(9) == TaskReply.NOT_FOUND
        assert st.task_failed(9) == TaskReply.NOT_FOUND
        assert st.task_removed(9) == TaskReply.NOT_FOUND
        assert st.task_updated(mk_task(9)) == TaskReply.NOT_FOUND
        st.task_submitted(mk_task(9))
        assert st.task_updated(mk_task(9, cpu=200)) == TaskReply.UPDATED_OK
        assert st.tasks[9].cpu_request == 200
        assert st.task_completed(9) == TaskReply.COMPLETED_OK
        assert st.task_removed(9) == TaskReply.REMOVED_OK
        assert 9 not in st.tasks

    def test_job_gc_on_last_task_removed(self):
        st = ClusterState()
        st.task_submitted(mk_task(1, job="j"))
        st.task_submitted(mk_task(2, job="j"))
        st.task_removed(1)
        assert "j" in st.jobs
        st.task_removed(2)
        assert "j" not in st.jobs


class TestNodeStateMachine:
    def test_add_exists_remove_notfound(self):
        st = ClusterState()
        assert st.node_added(mk_machine("m-0")) == NodeReply.ADDED_OK
        assert st.node_added(mk_machine("m-0")) == NodeReply.ALREADY_EXISTS
        assert st.node_removed("m-1") == NodeReply.NOT_FOUND
        assert st.node_failed("m-1") == NodeReply.NOT_FOUND
        assert st.node_updated(mk_machine("m-1")) == NodeReply.NOT_FOUND
        assert st.node_removed("m-0") == NodeReply.REMOVED_OK

    def test_pu_uuid_resolves_to_machine(self):
        st = ClusterState()
        m = mk_machine("m-0")
        m.subtree_uuids = {"pu-0"}
        st.node_added(m)
        assert st.add_node_stats("pu-0", {"cpu_utilization": 0.5}) == (
            NodeReply.ADDED_OK
        )
        assert st.machines["m-0"].cpu_util > 0

    def test_node_failure_evicts_tasks(self):
        st = ClusterState()
        st.node_added(mk_machine("m-0"))
        st.task_submitted(mk_task(1))
        st.apply_placement(1, "m-0")
        assert st.node_failed("m-0") == NodeReply.FAILED_OK
        assert st.tasks[1].scheduled_to is None
        assert st.tasks[1].state == TaskState.RUNNABLE


class TestContinuousIngest:
    """The streaming admission layer: arrival accounting the round
    engine cuts at view-build time (POSEIDON_STREAMING)."""

    def test_admission_cut_counts_and_resets(self):
        st = ClusterState()
        assert st.ingest_age_s() is None  # unarmed before any arrival
        st.node_added(mk_machine("m-0"))
        st.task_submitted(mk_task(1))
        st.task_submitted(mk_task(2))
        assert st.ingest_age_s() is not None
        admitted, age = st.admission_cut()
        assert admitted == 3  # node + 2 tasks
        assert age >= 0.0
        # The cut reset the window: nothing pending, next cut is empty.
        assert st.pending_ingest() == 0
        assert st.admission_cut() == (0, 0.0)

    def test_late_arrivals_defer_then_land_next_round(self):
        """The bounded-staleness contract: a delta arriving AFTER the
        cut is this round's ``admission_deferred`` — and the next cut
        (round N+1) admits it, so nothing defers more than one round."""
        st = ClusterState()
        st.node_added(mk_machine("m-0"))
        st.admission_cut()  # round N's view snapshot
        st.task_submitted(mk_task(7))  # arrives mid-round
        assert st.pending_ingest() == 1  # -> metrics.admission_deferred
        # The live state ALREADY holds the task (watchers applied it);
        # only the accounting deferred it.
        assert 7 in st.tasks
        admitted, _ = st.admission_cut()  # round N+1's snapshot
        assert admitted == 1
        assert st.pending_ingest() == 0

    def test_scheduler_commits_are_not_ingest(self):
        """apply_placement is the scheduler's own round commit, not an
        external arrival — it must not look like watcher ingest (it
        would hold staleness permanently high on a busy cluster)."""
        st = ClusterState()
        st.node_added(mk_machine("m-0"))
        st.task_submitted(mk_task(1))
        st.admission_cut()
        st.apply_placement(1, "m-0")
        assert st.pending_ingest() == 0

    def test_ingest_hints_accumulate_and_drain(self):
        st = ClusterState()
        st.node_added(mk_machine("m-0"))
        st.task_submitted(mk_task(1))
        rows, cols = st.take_ingest_hints()
        assert "m-0" in cols
        assert rows == {mk_task(1).ec_id}
        # Drained: a second take is empty until the next mutation.
        assert st.take_ingest_hints() == (set(), set())


class TestECSignature:
    def test_identical_tasks_share_ec(self):
        a = mk_task(1, cpu=100, ram=500)
        b = mk_task(2, cpu=100, ram=500)
        assert a.ec_id == b.ec_id

    def test_request_differs_ec_differs(self):
        assert mk_task(1, cpu=100).ec_id != mk_task(2, cpu=200).ec_id

    def test_selector_order_canonical(self):
        s1 = ((0, "a", ("x", "y")), (2, "b", ()))
        s2 = ((2, "b", ()), (0, "a", ("y", "x")))
        assert ec_signature(1, 1, s1, 0, 0) == ec_signature(1, 1, s2, 0, 0)


class TestRoundPlanner:
    def _planner(self, st):
        return RoundPlanner(st, CpuMemCostModel())

    def test_place_all_when_capacity(self):
        st = ClusterState()
        for i in range(4):
            st.node_added(mk_machine(f"m-{i}"))
        for uid in range(10):
            st.task_submitted(mk_task(uid))
        deltas, metrics = self._planner(st).schedule_round()
        assert metrics.placed == 10
        assert metrics.unscheduled == 0
        assert all(d.type == DeltaType.PLACE for d in deltas)
        assert all(st.tasks[u].state == TaskState.RUNNING for u in range(10))

    def test_respects_fit(self):
        st = ClusterState()
        st.node_added(mk_machine("m-0", cpu=1000, ram=1_000_000))
        # 3 tasks of 400 millicores: only 2 fit.
        for uid in range(3):
            st.task_submitted(mk_task(uid, cpu=400, ram=1000))
        deltas, metrics = self._planner(st).schedule_round()
        assert metrics.placed == 2
        assert metrics.unscheduled == 1

    def test_stability_no_spurious_migrations(self):
        st = ClusterState()
        for i in range(3):
            st.node_added(mk_machine(f"m-{i}"))
        for uid in range(6):
            st.task_submitted(mk_task(uid))
        planner = self._planner(st)
        deltas1, m1 = planner.schedule_round()
        assert m1.placed == 6
        deltas2, m2 = planner.schedule_round()
        assert m2.migrated == 0 and m2.preempted == 0
        assert deltas2 == []

    def test_new_tasks_placed_incrementally(self):
        st = ClusterState()
        for i in range(3):
            st.node_added(mk_machine(f"m-{i}"))
        for uid in range(5):
            st.task_submitted(mk_task(uid))
        planner = self._planner(st)
        planner.schedule_round()
        for uid in range(100, 103):
            st.task_submitted(mk_task(uid))
        deltas, metrics = planner.schedule_round()
        assert metrics.placed == 3
        assert {d.task_id for d in deltas} == {100, 101, 102}

    def test_empty_round(self):
        st = ClusterState()
        deltas, metrics = self._planner(st).schedule_round()
        assert deltas == [] and metrics.num_tasks == 0

    def test_no_machines_all_unscheduled(self):
        st = ClusterState()
        st.task_submitted(mk_task(1))
        deltas, metrics = self._planner(st).schedule_round()
        assert deltas == []
        assert metrics.unscheduled == 1
        assert st.tasks[1].wait_rounds == 1

    def test_completed_task_frees_capacity(self):
        st = ClusterState()
        st.node_added(mk_machine("m-0", cpu=1000, ram=1_000_000))
        st.task_submitted(mk_task(1, cpu=600, ram=1000))
        st.task_submitted(mk_task(2, cpu=600, ram=1000))
        planner = self._planner(st)
        _, m1 = planner.schedule_round()
        assert m1.placed == 1 and m1.unscheduled == 1
        placed_uid = next(
            u for u in (1, 2) if st.tasks[u].state == TaskState.RUNNING
        )
        st.task_completed(placed_uid)
        _, m2 = planner.schedule_round()
        assert m2.placed == 1

    def test_selector_respected_end_to_end(self):
        st = ClusterState()
        st.node_added(mk_machine("m-0"))
        big = mk_machine("m-1")
        big.labels = {"zone": "gold"}
        st.node_added(big)
        t = mk_task(1)
        t.selectors = ((0, "zone", ("gold",)),)  # IN_SET
        st.task_submitted(t)
        deltas, metrics = self._planner(st).schedule_round()
        assert metrics.placed == 1
        assert deltas[0].resource_id == "m-1"

    def test_nonconvergence_alarm_fires(self, caplog, monkeypatch):
        """A round whose solve exhausts the iteration budget (gap inf even
        after the cold retry) must flag converged=False and log.error —
        silent non-convergence was round-2 Weak #5."""
        import logging

        from poseidon_tpu.graph import instance as inst
        from poseidon_tpu.ops.transport import TransportSolution

        def exhausted(self, costs, supply, capacity, unsched_cost,
                      *a, **kw):
            E, M = np.asarray(costs).shape
            return TransportSolution(
                flows=np.zeros((E, M), dtype=np.int32),
                unsched=np.asarray(supply, dtype=np.int32).copy(),
                prices=np.zeros(E + M + 1, dtype=np.int32),
                objective=0,
                gap_bound=float("inf"),
                iterations=123,
            )

        monkeypatch.setattr(
            inst.RoundPlanner, "_dispatch_solve", exhausted
        )
        st = ClusterState()
        st.node_added(mk_machine("m-0"))
        st.task_submitted(mk_task(1))
        planner = self._planner(st)
        with caplog.at_level(logging.ERROR, "poseidon_tpu.planner"):
            _, metrics = planner.schedule_round()
        assert metrics.converged is False
        assert any(
            "did not converge" in r.message for r in caplog.records
        )

    def test_forced_exhaustion_returns_inf_gap(self):
        """Driving the real kernel with a starved iteration budget yields a
        repaired-feasible solution with an unbounded gap, not garbage.

        greedy_init=False: the greedy cold start is feasible by
        construction (leftovers start as unscheduled), so with it even a
        zero-iteration solve exits clean with a finite certified gap —
        the empty start is what exercises the exhaustion path."""
        from poseidon_tpu.ops.transport import solve_transport

        rng = np.random.default_rng(3)
        costs = rng.integers(0, 100, size=(6, 8)).astype(np.int32)
        supply = rng.integers(1, 6, size=6).astype(np.int32)
        cap = rng.integers(1, 4, size=8).astype(np.int32)
        unsched = np.full(6, 200, dtype=np.int32)
        sol = solve_transport(
            costs, supply, cap, unsched, max_iter_per_phase=1,
            greedy_init=False,
        )
        assert sol.gap_bound == float("inf")
        # Still feasible after host repair.
        assert (sol.flows >= 0).all()
        assert (sol.flows.sum(axis=0) <= cap).all()
        np.testing.assert_array_equal(
            sol.flows.sum(axis=1) + sol.unsched, supply
        )

    def test_exhausted_solve_drops_warm_frame(self):
        """A budget-exhausted band solve must not save its junk duals as
        the next round's warm frame (and must evict any stale one)."""
        from poseidon_tpu.costmodel import get_cost_model
        from poseidon_tpu.graph.instance import RoundPlanner
        from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
        from poseidon_tpu.utils.ids import generate_uuid

        state = ClusterState()
        for i in range(4):
            state.node_added(
                MachineInfo(
                    uuid=generate_uuid(f"wf-m{i}"),
                    cpu_capacity=8000, ram_capacity=1 << 24, task_slots=20,
                )
            )
        for i in range(12):
            state.task_submitted(
                TaskInfo(uid=5000 + i, job_id="wf-j", cpu_request=300,
                         ram_request=1 << 19)
            )
        planner = RoundPlanner(state, get_cost_model("cpu_mem"))
        _, m = planner.schedule_round()
        assert m.converged and planner._warm_bands  # healthy frame saved

        # Starve the budgets so the next (churned) round exhausts even
        # the cold retry: every solve returns gap_bound=inf.
        orig = planner._dispatch_solve

        def starved(costs, supply, capacity, unsched_cost, prices=None,
                    **kw):
            kw["max_iter_total"] = 1
            # Any feasible starting state (greedy cold start, carried
            # warm flows) would exit clean with a finite gap; the empty
            # start is what produces the budget-exhausted inf-gap state
            # under test.
            kw["greedy_init"] = False
            kw.pop("eps_start", None)
            kw.pop("init_flows", None)
            kw.pop("init_unsched", None)
            return orig(costs, supply, capacity, unsched_cost, None, **kw)

        planner._dispatch_solve = starved
        state.task_removed(5000)
        state.task_submitted(
            TaskInfo(uid=5000, job_id="wf-j", cpu_request=300,
                     ram_request=1 << 19)
        )
        _, m2 = planner.schedule_round()
        assert not m2.converged
        assert not planner._warm_bands  # junk frame dropped, stale evicted

    def test_starved_greedy_cold_start_is_feasible_with_finite_gap(self):
        """With the greedy cold start, a starved budget still exits with a
        feasible state and a FINITE certified gap bound (the greedy
        assignment plus fallback covers all supply)."""
        from poseidon_tpu.ops.transport import solve_transport

        rng = np.random.default_rng(3)
        costs = rng.integers(0, 100, size=(6, 8)).astype(np.int32)
        supply = rng.integers(1, 6, size=6).astype(np.int32)
        cap = rng.integers(1, 4, size=8).astype(np.int32)
        unsched = np.full(6, 200, dtype=np.int32)
        sol = solve_transport(
            costs, supply, cap, unsched, max_iter_per_phase=1
        )
        assert sol.gap_bound < float("inf")
        assert (sol.flows >= 0).all()
        assert (sol.flows.sum(axis=0) <= cap).all()
        np.testing.assert_array_equal(
            sol.flows.sum(axis=1) + sol.unsched, supply
        )


class TestBandMerging:
    """_next_band_group: merged dispatches under slack, per-band ladder
    under tightness, live-commitment slack accounting."""

    @staticmethod
    def _mixed_state(machines, slots, big_tasks, small_tasks,
                     cpu_cap=16000):
        from poseidon_tpu.utils.ids import task_uid

        st = ClusterState()
        for i in range(machines):
            st.node_added(MachineInfo(
                uuid=f"bm-{i:03d}", cpu_capacity=cpu_cap,
                ram_capacity=1 << 26, task_slots=slots,
            ))
        for i in range(big_tasks):
            st.task_submitted(TaskInfo(
                uid=task_uid("big", i), job_id="big",
                cpu_request=4000, ram_request=1 << 20,
            ))
        for i in range(small_tasks):
            st.task_submitted(TaskInfo(
                uid=task_uid("small", i), job_id="small",
                cpu_request=100, ram_request=1 << 18,
            ))
        return st

    @staticmethod
    def _force_per_band(planner):
        orig = planner._next_band_group

        def one_band(remaining, bands, ecs, mt, *commit):
            import numpy as np

            return 1, np.nonzero(bands == remaining[0])[0]

        planner._next_band_group = one_band
        return orig

    def test_slack_merges_to_one_dispatch_same_objective(
        self, monkeypatch
    ):
        monkeypatch.setenv("POSEIDON_MERGE_BANDS", "1")
        # Dispatch-structure test: the host certificate would answer
        # these slack-heavy instances with zero dispatches on BOTH
        # sides, erasing the count contrast under test.
        monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
        # Plenty of slack (640 big-task units of CPU vs 220 tasks):
        # big and small bands merge into one dispatch.
        st1 = self._mixed_state(40, 32, 20, 200, cpu_cap=64000)
        p1 = RoundPlanner(st1, CpuMemCostModel())
        _, m1 = p1.schedule_round()
        st2 = self._mixed_state(40, 32, 20, 200, cpu_cap=64000)
        p2 = RoundPlanner(st2, CpuMemCostModel())
        self._force_per_band(p2)
        _, m2 = p2.schedule_round()
        assert m1.device_calls < m2.device_calls  # fewer dispatches
        assert m1.unscheduled == m2.unscheduled == 0
        assert m1.objective <= m2.objective  # joint solve >= as good
        assert m1.converged and m2.converged

    def test_tight_capacity_keeps_per_band_ladder(self, monkeypatch):
        monkeypatch.setenv("POSEIDON_MERGE_BANDS", "1")
        # Demand ~= capacity in units of the big request: the gate must
        # close and behave exactly like the old per-band ladder.
        st1 = self._mixed_state(6, 4, 20, 60, cpu_cap=8000)
        p1 = RoundPlanner(st1, CpuMemCostModel())
        _, m1 = p1.schedule_round()
        st2 = self._mixed_state(6, 4, 20, 60, cpu_cap=8000)
        p2 = RoundPlanner(st2, CpuMemCostModel())
        self._force_per_band(p2)
        _, m2 = p2.schedule_round()
        assert m1.device_calls == m2.device_calls
        assert m1.objective == m2.objective
        assert m1.unscheduled == m2.unscheduled

    def test_merge_gate_sees_live_commitments(self, monkeypatch):
        """The slack seen by group k+1 must reflect what groups 1..k
        committed THIS round (a stale pre-round snapshot would merge
        bands the committed capacity can no longer hold)."""
        monkeypatch.setenv("POSEIDON_MERGE_BANDS", "1")
        # The cross-band pipeline probes _next_band_group a second time
        # per iteration with FROZEN pre-commit usage (a speculative
        # grouping guess, by design) — pin it off so the spy sequence
        # below observes only the authoritative gate calls this test is
        # about.
        monkeypatch.setenv("POSEIDON_PIPELINE_BANDS", "0")
        import numpy as np

        st = self._mixed_state(4, 64, 14, 40, cpu_cap=16000)
        planner = RoundPlanner(st, CpuMemCostModel())
        seen_units = []
        orig = planner._next_band_group

        def spy(remaining, bands, ecs, mt, ccpu, cram, cnet):
            seen_units.append(int(np.maximum(
                mt.cpu_capacity.astype(np.int64) - ccpu, 0
            ).sum()))
            return orig(remaining, bands, ecs, mt, ccpu, cram, cnet)

        planner._next_band_group = spy
        _, m = planner.schedule_round()
        assert m.converged
        if len(seen_units) > 1:
            # Later gate calls observed strictly less free CPU.
            assert seen_units[1] < seen_units[0]

    def test_cpu_backend_defaults_to_per_band(self, monkeypatch):
        """On CPU (dispatches ~free) merging is off by default: the
        measured trade reverses at 10k scale (see _next_band_group)."""
        monkeypatch.delenv("POSEIDON_MERGE_BANDS", raising=False)
        monkeypatch.setenv("POSEIDON_HOST_CERT", "0")  # count the bands
        st = self._mixed_state(40, 32, 20, 200, cpu_cap=64000)
        planner = RoundPlanner(st, CpuMemCostModel())
        _, m = planner.schedule_round()
        assert m.device_calls >= 2  # one dispatch per band, as before
