"""Scheduler-BEHAVIOR predicates, ported from the reference e2e suite
(reference test/e2e/poseidon_integration.go), run fully in-process:
FakeKube feeds the watchers, the real gRPC firmament-tpu service
schedules, the glue loop enacts deltas back into the fake cluster.

Ported predicates:
- resource limits: fill every node to 70% CPU, then an oversized pod
  must stay Pending (poseidon_integration.go:294-407);
- NodeSelector not matching: stays Pending (:409-440);
- NodeSelector matching: schedules onto exactly the labeled node
  (:442-478);
- Job / ReplicaSet lifecycles: owner-grouped pods all run, complete /
  get replaced, and clean up (:60-292).
"""

import pytest

from poseidon_tpu.glue import FakeKube, Node, Pod, Poseidon
from poseidon_tpu.service import FirmamentTPUServer
from poseidon_tpu.utils.config import PoseidonConfig


@pytest.fixture()
def system():
    with FirmamentTPUServer(address="127.0.0.1:0") as server:
        kube = FakeKube()
        cfg = PoseidonConfig(
            firmament_address=server.address, scheduling_interval=3600
        )
        poseidon = Poseidon(
            kube, config=cfg, stats_address="127.0.0.1:0", run_loop=False
        ).start(health_timeout=10)
        try:
            yield kube, poseidon, server
        finally:
            poseidon.stop()


def _round(kube, poseidon):
    assert poseidon.drain_watchers()
    return poseidon.schedule_once()


def test_resource_limits_oversized_pod_stays_pending(system):
    """poseidon_integration.go:294-407: one filler pod per node at 70% of
    that node's CPU all run; an additional pod needing 50% of the largest
    node's CPU must stay Pending (30% is free everywhere)."""
    kube, poseidon, _ = system
    capacities = {"n1": 4000, "n2": 8000, "n3": 16000}
    for name, cpu in capacities.items():
        kube.add_node(Node(name=name, cpu_capacity=cpu,
                           ram_capacity=1 << 24))
    # Fillers pin to their node via a unique label selector, exactly how
    # the reference directs one filler at each node.
    for i, (name, cpu) in enumerate(capacities.items()):
        kube.update_node(name, lambda n, i=i: n.labels.update(
            {"fill": f"slot{i}"}
        ))
        kube.create_pod(Pod(
            name=f"filler-{i}", cpu_request=cpu * 7 // 10,
            ram_request=1 << 18, node_selector={"fill": f"slot{i}"},
        ))
    _round(kube, poseidon)
    fillers = {f"default/filler-{i}" for i in range(3)}
    for key in fillers:
        assert kube.pods[key].phase == "Running", key
    bound = dict(kube.bindings)
    for i, name in enumerate(capacities):
        assert bound[f"default/filler-{i}"] == name

    # 50% of the largest node: no node has that much CPU left.
    kube.create_pod(Pod(name="additional-pod",
                        cpu_request=max(capacities.values()) * 5 // 10,
                        ram_request=1 << 18))
    for _ in range(3):  # several rounds: it must KEEP not scheduling
        _round(kube, poseidon)
        assert kube.pods["default/additional-pod"].phase == "Pending"
    assert "default/additional-pod" not in dict(kube.bindings)


def test_node_selector_not_matching_stays_pending(system):
    """poseidon_integration.go:409-440: nodes carry no matching label, so
    a nonempty NodeSelector must never schedule."""
    kube, poseidon, _ = system
    kube.add_node(Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24))
    kube.create_pod(Pod(name="restricted-pod", cpu_request=100,
                        ram_request=1 << 18,
                        node_selector={"label": "nonempty"}))
    for _ in range(3):
        _round(kube, poseidon)
        assert kube.pods["default/restricted-pod"].phase == "Pending"


def test_node_selector_matching_schedules_on_labeled_node(system):
    """poseidon_integration.go:442-478: label one node, the selector pod
    lands on exactly that node."""
    kube, poseidon, _ = system
    for name in ("n1", "n2", "n3"):
        kube.add_node(Node(name=name, cpu_capacity=4000,
                           ram_capacity=1 << 24))
    kube.update_node("n2", lambda n: n.labels.update(
        {"kubernetes.io/e2e-42": "42"}
    ))
    kube.create_pod(Pod(name="with-labels", cpu_request=100,
                        ram_request=1 << 18,
                        node_selector={"kubernetes.io/e2e-42": "42"}))
    _round(kube, poseidon)
    assert kube.pods["default/with-labels"].phase == "Running"
    assert dict(kube.bindings)["default/with-labels"] == "n2"


def test_job_lifecycle_runs_and_completes(system):
    """poseidon_integration.go:171-292 (Job): owner-grouped pods all get
    placed, report completion, and deletion cleans up state — the
    service answers the full TaskSubmitted/Completed/Removed sequence."""
    kube, poseidon, server = system
    for i in range(2):
        kube.add_node(Node(name=f"n{i}", cpu_capacity=8000,
                           ram_capacity=1 << 24))
    for i in range(4):
        kube.create_pod(Pod(name=f"job-pod-{i}", owner_uid="job-77",
                            cpu_request=500, ram_request=1 << 18))
    _round(kube, poseidon)
    for i in range(4):
        assert kube.pods[f"default/job-pod-{i}"].phase == "Running"
    # All four tasks belong to ONE service-side job (owner grouping).
    assert len({t.job_id for t in server.servicer.state.tasks.values()}) == 1

    # Completion: pods Succeed, the watcher reports TaskCompleted, and a
    # follow-up round has nothing to do.
    for i in range(4):
        kube.set_pod_phase(f"default/job-pod-{i}", "Succeeded")
    deltas = _round(kube, poseidon)
    assert deltas == []
    # Deletion cleans the service state (job GC'd with its tasks).
    for i in range(4):
        kube.delete_pod("default", f"job-pod-{i}")
    _round(kube, poseidon)
    assert not server.servicer.state.tasks


def test_replicaset_lifecycle_replacement_pod_reschedules(system):
    """poseidon_integration.go:110-169 (ReplicaSet): N replicas run;
    when one dies the controller's replacement pod (same owner) is
    scheduled in the next round."""
    kube, poseidon, _ = system
    for i in range(2):
        kube.add_node(Node(name=f"n{i}", cpu_capacity=8000,
                           ram_capacity=1 << 24))
    for i in range(3):
        kube.create_pod(Pod(name=f"rs-pod-{i}", owner_uid="rs-5",
                            cpu_request=500, ram_request=1 << 18))
    _round(kube, poseidon)
    assert all(kube.pods[f"default/rs-pod-{i}"].phase == "Running"
               for i in range(3))

    # One replica fails; the controller resubmits a replacement.
    kube.set_pod_phase("default/rs-pod-1", "Failed")
    kube.delete_pod("default", "rs-pod-1")
    kube.create_pod(Pod(name="rs-pod-1-repl", owner_uid="rs-5",
                        cpu_request=500, ram_request=1 << 18))
    _round(kube, poseidon)
    assert kube.pods["default/rs-pod-1-repl"].phase == "Running"
