"""Delta-maintained cost planes (costmodel/delta.py), the reduced-plane
excluded-column certificate (ops/transport_pruned.ExcludedColumnCert),
the accepted-shortlist revival, and the cross-band cost-build pipeline
(graph/pipeline.py).

The contract under test everywhere: the incremental paths are
PERFORMANCE paths — bit-identical planes (the full ``model.build`` is
kept verbatim as the oracle), certified-or-escalate accepts, and
placements identical to the all-paths-off planner.
"""

import os
import threading
import time

import numpy as np
import pytest

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.costmodel.delta import CostPlaneCache
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import generate_uuid, task_uid

DELTA_ENV = {
    "POSEIDON_COST_DELTA_MIN_CELLS": "1",
    "POSEIDON_COST_DELTA_MIN_ROWS": "1",
}


@pytest.fixture
def delta_env(monkeypatch):
    for k, v in DELTA_ENV.items():
        monkeypatch.setenv(k, v)


def _cluster(n_machines, rng, labeled=True):
    state = ClusterState()
    for i in range(n_machines):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"cd-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=16,
            labels={"zone": f"z{i % 3}"} if labeled else {},
        ))
    return state


def _submit(state, uid_counter, n, rng, shapes, gang=False, labels=None):
    for _ in range(n):
        i = uid_counter[0]
        uid_counter[0] += 1
        cpu, ram = shapes[int(rng.integers(len(shapes)))]
        state.task_submitted(TaskInfo(
            uid=task_uid("cd-t", i), job_id=f"cd-j{i % 9}",
            cpu_request=cpu, ram_request=ram, gang=gang,
            labels=dict(labels) if labels else {},
        ))


class TestChurnParity:
    def test_randomized_churn_parity(self, delta_env):
        """Long-churn rounds through a real ClusterState (placements
        move residents, stats move utilization, nodes relabel/leave):
        the delta-maintained plane is bit-identical to the full-rebuild
        oracle every round, and actually serves incrementally on
        steady-state rounds (this is not a vacuous gate)."""
        rng = np.random.default_rng(42)
        state = _cluster(40, rng)
        shapes = [(200, 1 << 19), (400, 1 << 20), (800, 1 << 19)]
        uidc = [0]
        # More tasks than slots (40 x 16 = 640): a persistent backlog
        # keeps the same EC rows pending round after round — the
        # steady-state shape the delta path exists for.  (All-new churn
        # ECs legitimately full-rebuild: every row is dirty.)
        _submit(state, uidc, 900, rng, shapes,
                labels={"app": "seed"})
        model = get_cost_model("cpu_mem")
        cache = CostPlaneCache(model)
        planner = RoundPlanner(state, model)
        delta_rounds = 0
        for rnd in range(14):
            view = state.build_round_view()
            if view.ecs.num_ecs and view.machines.num_machines:
                got = cache.build(0, view.ecs, view.machines)
                want = model.build(view.ecs, view.machines)
                assert (got.costs == want.costs).all(), f"round {rnd}"
                assert (got.arc_capacity == want.arc_capacity).all()
                assert (got.unsched_cost == want.unsched_cost).all()
                assert (got.capacity == want.capacity).all()
                if cache.last_stats["delta_hit"]:
                    delta_rounds += 1
                    E, M = view.ecs.num_ecs, view.machines.num_machines
                    assert (cache.last_stats["rows_rebuilt"] < E
                            or cache.last_stats["cols_rebuilt"] < M)
            planner.schedule_round()  # placements move residents/usage
            # Churn: small task turnover; occasional node events.
            live = [t for t in state.tasks.values() if t.scheduled_to]
            for t in live[: int(rng.integers(0, 6))]:
                state.task_removed(t.uid)
            _submit(state, uidc, int(rng.integers(1, 6)), rng, shapes,
                    labels={"app": f"a{rnd % 4}"})
            if rnd == 5:  # relabel one node in place
                u = next(iter(state.machines))
                m = state.machines[u]
                state.node_updated(MachineInfo(
                    uuid=u, cpu_capacity=m.cpu_capacity,
                    ram_capacity=m.ram_capacity, task_slots=m.task_slots,
                    labels={"zone": "relabeled"},
                ))
            if rnd == 8:  # usage update via the knowledge-base path
                for u in list(state.machines)[:7]:
                    state.add_node_stats(
                        state.machines[u].resource_uuid
                        if hasattr(state.machines[u], "resource_uuid")
                        else u,
                        {"cpu_utilization": 0.7, "mem_utilization": 0.5},
                    )
            if rnd == 10:  # machine leaves, another arrives
                state.node_removed(next(iter(state.machines)))
                state.node_added(MachineInfo(
                    uuid=generate_uuid("cd-m-new"), cpu_capacity=16000,
                    ram_capacity=64 << 20, task_slots=16,
                    labels={"zone": "z9"},
                ))
        assert delta_rounds >= 3, (
            f"delta path served only {delta_rounds} rounds — the "
            "incremental engine silently fell back to full rebuilds"
        )

    def test_relabel_dirties_only_that_column(self, delta_env):
        """Steady state, one machine relabeled: exactly that column is
        rebuilt (plus any the placements dirtied), and the plane stays
        oracle-identical."""
        rng = np.random.default_rng(7)
        state = _cluster(24, rng)
        uidc = [0]
        _submit(state, uidc, 40, rng, [(300, 1 << 19)])
        model = get_cost_model("cpu_mem")
        cache = CostPlaneCache(model)
        view = state.build_round_view()
        cache.build(0, view.ecs, view.machines)
        # Relabel machine column 3 in place; nothing else moves.
        u = view.machines.uuids[3]
        m = state.machines[u]
        state.node_updated(MachineInfo(
            uuid=u, cpu_capacity=m.cpu_capacity,
            ram_capacity=m.ram_capacity, task_slots=m.task_slots,
            labels={"zone": "flipped"},
        ))
        view2 = state.build_round_view()
        got = cache.build(0, view2.ecs, view2.machines)
        want = model.build(view2.ecs, view2.machines)
        assert (got.costs == want.costs).all()
        assert (got.arc_capacity == want.arc_capacity).all()
        stats = cache.last_stats
        assert stats["path"] == "delta", stats
        new_col = list(view2.machines.uuids).index(u)
        assert new_col in stats["dirty_cols"].tolist()
        assert stats["cols_rebuilt"] <= 2
        assert stats["rows_rebuilt"] == 0

    def test_ingest_hints_force_hinted_cells_dirty(self, delta_env):
        """The continuous-ingest seam (POSEIDON_STREAMING): hints
        installed via set_round_hints union into the next build's dirty
        sets — an UNCHANGED plane still rebuilds exactly the hinted
        row/column (correct either way; the hint only spends work), and
        unknown identities cost nothing."""
        rng = np.random.default_rng(11)
        state = _cluster(24, rng)
        uidc = [0]
        # 8 shapes -> 8 EC rows: one hinted row + one hinted column
        # stays under the dirty-fraction gate (a 1-row plane would trip
        # it and full-rebuild, proving nothing about the seam).
        shapes = [(300 + 50 * i, (1 << 19) + (i << 12)) for i in range(8)]
        _submit(state, uidc, 40, rng, shapes)
        model = get_cost_model("cpu_mem")
        cache = CostPlaneCache(model)
        view = state.build_round_view()
        cache.build(0, view.ecs, view.machines)

        hint_ec = int(view.ecs.ec_ids[0])
        hint_uuid = view.machines.uuids[5]
        # Watcher-thread half (additive), then the round's install —
        # plus identities no band contains, which must be skipped free.
        cache.ingest(ec_ids=[hint_ec])
        cache.set_round_hints([hint_ec, 999_999_999],
                              [hint_uuid, "no-such-machine"])
        got = cache.build(0, view.ecs, view.machines)
        want = model.build(view.ecs, view.machines)
        assert (got.costs == want.costs).all()
        stats = cache.last_stats
        assert stats["path"] == "delta", stats
        assert cache.ingest_hints_applied >= 2
        assert 0 in stats["dirty_rows"].tolist()
        assert 5 in stats["dirty_cols"].tolist()

        # Hints persist until replaced (every band's build this round
        # sees them); an empty install clears the seam.
        cache.set_round_hints([], [])
        cache.build(0, view.ecs, view.machines)
        assert cache.last_stats["cols_rebuilt"] == 0
        assert cache.last_stats["rows_rebuilt"] == 0

    def test_interner_identity_change_falls_back_to_oracle(
            self, delta_env, monkeypatch):
        """Resident-interner compaction installs new id dicts, remapping
        count-matrix columns — the cache must detect the identity change
        and take the oracle rebuild, never diff across the remap."""
        from poseidon_tpu.graph import residency

        monkeypatch.setattr(residency, "_COMPACT_MIN_COLS", 8)
        rng = np.random.default_rng(3)
        state = _cluster(48, rng)
        uidc = [0]
        model = get_cost_model("cpu_mem")
        cache = CostPlaneCache(model)
        planner = RoundPlanner(state, model)
        # A persistent UNPLACEABLE backlog (requests exceed every
        # machine) keeps stable EC rows pending — the delta path's
        # steady state — while leaving all slots free, so the unique-
        # labeled churn residents below always place, then leave,
        # minting and killing kv columns until compaction fires and
        # installs new interner id dicts.  The backlog carries pod
        # anti-affinity so the resident interner is ACTIVE (it only
        # runs while pod-selector tasks exist).
        from poseidon_tpu.costmodel.selectors import IN_SET

        for _ in range(40):
            i = uidc[0]
            uidc[0] += 1
            state.task_submitted(TaskInfo(
                uid=task_uid("cd-t", i), job_id=f"cd-j{i % 9}",
                # Distinct shapes: 40 stable EC ROWS (a single merged
                # EC would leave every round mostly-dirty by fraction).
                cpu_request=64000 + 50 * i, ram_request=1 << 19,
                labels={"app": "base"},
                pod_anti_affinity=((IN_SET, "nope", ("x",)),),
            ))
        saw_identity_change = saw_delta = False
        prev_kv_id = None
        for rnd in range(12):
            view = state.build_round_view()
            if view.ecs.num_ecs:
                got = cache.build(0, view.ecs, view.machines)
                want = model.build(view.ecs, view.machines)
                assert (got.costs == want.costs).all(), f"round {rnd}"
                assert (got.arc_capacity == want.arc_capacity).all()
                res = view.machines.residents
                if res is not None:
                    if (prev_kv_id is not None
                            and res.kv_id is not prev_kv_id):
                        saw_identity_change = True
                        # Columns remapped: the cell-level diff is
                        # meaningless; the oracle must own this round.
                        assert cache.last_stats["path"] != "delta", (
                            f"round {rnd}: diffed across an interner "
                            "compaction"
                        )
                    prev_kv_id = res.kv_id
                if cache.last_stats["path"] == "delta":
                    saw_delta = True
            planner.schedule_round()
            # Remove LAST round's churn residents (their unique labels'
            # kv columns die), then mint fresh ones this round.
            placed_churn = 0
            for t in list(state.tasks.values()):
                if t.labels.get("gen") and t.scheduled_to:
                    placed_churn += 1
                    state.task_removed(t.uid)
            if rnd:
                assert placed_churn > 0, "churn residents never placed"
            first = uidc[0]
            _submit(state, uidc, 3, rng, [(250, 1 << 19)],
                    labels={"gen": f"g{rnd}", "u": f"v{first}"})
        assert saw_identity_change, (
            "compaction never fired — the identity guard went untested"
        )
        assert saw_delta, "delta path never served"

    def test_dirty_fraction_gate_escalates_to_full(self, delta_env):
        """A round that moves most machine columns crosses the dirty-
        fraction gate: one full rebuild, never a slower patchwork."""
        rng = np.random.default_rng(11)
        state = _cluster(20, rng, labeled=False)
        uidc = [0]
        _submit(state, uidc, 30, rng, [(300, 1 << 19)])
        model = get_cost_model("cpu_mem")
        cache = CostPlaneCache(model)
        view = state.build_round_view()
        cache.build(0, view.ecs, view.machines)
        # Usage update on EVERY machine: all columns dirty.
        for u in list(state.machines):
            state.add_node_stats(u, {"cpu_utilization": 0.9})
        view2 = state.build_round_view()
        got = cache.build(0, view2.ecs, view2.machines)
        want = model.build(view2.ecs, view2.machines)
        assert (got.costs == want.costs).all()
        assert cache.last_stats["path"] == "gate", cache.last_stats


class TestPlaneLedger:
    def _tables(self, rng, E, M, seed_used=0):
        state = _cluster(M, rng, labeled=False)
        uidc = [seed_used]
        _submit(state, uidc, E, rng, [(300 + seed_used, 1 << 19)])
        return state

    def test_ledger_accumulates_across_builds(self, delta_env):
        """Two delta builds between takes (the pipeline's speculative +
        authoritative pair): take_ledger returns the UNION of their
        dirty sets — a column only the first build patched must not
        vanish from the certificate's fold feed."""
        rng = np.random.default_rng(5)
        state = _cluster(20, rng, labeled=False)
        uidc = [0]
        _submit(state, uidc, 30, rng, [(300, 1 << 19)])
        model = get_cost_model("cpu_mem")
        cache = CostPlaneCache(model)
        view = state.build_round_view()
        cache.build(0, view.ecs, view.machines)
        cache.take_ledger(0)  # anchor point

        state.add_node_stats(list(state.machines)[2],
                             {"cpu_utilization": 0.8})
        v1 = state.build_round_view()
        cache.build(0, v1.ecs, v1.machines)  # build 1 dirties col 2
        assert cache.last_stats["path"] == "delta"
        d1 = set(v1.machines.uuids[int(j)]
                 for j in cache.last_stats["dirty_cols"])

        state.add_node_stats(list(state.machines)[7],
                             {"cpu_utilization": 0.6})
        v2 = state.build_round_view()
        cache.build(0, v2.ecs, v2.machines)  # build 2 dirties col 7
        assert cache.last_stats["path"] == "delta"
        d2 = set(v2.machines.uuids[int(j)]
                 for j in cache.last_stats["dirty_cols"])

        led = cache.take_ledger(0)
        assert led is not None and not led.broken
        assert d1 | d2 <= led.cols, (
            "ledger lost a build's dirty columns — the certificate "
            "would fold against a stale floor"
        )
        assert cache.take_ledger(0) is None  # consumed

    def test_full_rebuild_breaks_ledger(self, delta_env):
        rng = np.random.default_rng(6)
        state = _cluster(16, rng, labeled=False)
        uidc = [0]
        _submit(state, uidc, 20, rng, [(300, 1 << 19)])
        model = get_cost_model("cpu_mem")
        cache = CostPlaneCache(model)
        view = state.build_round_view()
        cache.build(0, view.ecs, view.machines)
        cache.take_ledger(0)
        # All-new EC population: dirty gate -> full rebuild.
        for uid in list(state.tasks):
            state.task_removed(uid)
        _submit(state, uidc, 20, rng, [(999, 1 << 20)])
        v2 = state.build_round_view()
        cache.build(0, v2.ecs, v2.machines)
        led = cache.take_ledger(0)
        assert led is not None and led.broken


class TestExcludedColumnCert:
    """Unit tests against a hand-built plane: the cert must reproduce
    the classic full-plane accept boundary, and a cost drop on a dirty
    excluded column must surface as a violation, never a blind accept."""

    def _setup(self, E=12, M=40, scale=64, seed=0):
        from poseidon_tpu.costmodel.delta import PlaneLedger
        from poseidon_tpu.ops import transport_pruned as tp

        rng = np.random.default_rng(seed)
        costs = rng.integers(10, 400, size=(E, M)).astype(np.int32)
        pe = rng.integers(-2000, 2000, size=E).astype(np.int64)
        supply = np.full(E, 2, dtype=np.int32)
        capacity = np.full(M, 4, dtype=np.int32)
        cert = tp.ExcludedColumnCert()
        ec_ids = np.arange(E, dtype=np.uint64)
        uuids = [f"u{j}" for j in range(M)]
        led = PlaneLedger()
        led.present = set(range(E))
        cert.note_build(ec_ids, uuids, led)
        min_e = (costs.astype(np.int64) * scale + pe[:, None]).min(axis=0)
        cert.refresh(scale=scale, pe=pe, min_e=min_e)
        return tp, cert, costs, pe, supply, capacity, ec_ids, uuids, scale

    @staticmethod
    def _oracle_viol(costs, pe, pt, supply, capacity, scale, mask):
        """The lift's exact accept boundary for excluded columns."""
        excluded = np.nonzero(~mask)[0]
        viol = []
        for m in excluded:
            if capacity[m] <= 0:
                continue
            vals = [
                int(costs[e, m]) * scale + int(pe[e])
                for e in range(costs.shape[0])
                if costs[e, m] < np.iinfo(np.int32).max // 2
                and supply[e] > 0
            ]
            if vals and min(vals) < pt - 2:
                viol.append(int(m))
        return viol

    def test_unchanged_plane_certifies(self):
        (tp, cert, costs, pe, supply, capacity,
         ec_ids, uuids, scale) = self._setup()
        mask = np.zeros(costs.shape[1], dtype=bool)
        mask[:8] = True
        # pt low enough that every excluded column prices out clean.
        pt = int((costs.astype(np.int64) * scale
                  + pe[:, None]).min()) - 10
        status, viol, worst, pm = cert.check(
            eff_costs=costs, pe=pe, pt=pt, supply=supply,
            capacity=capacity, arc_capacity=None, scale=scale, mask=mask,
        )
        assert status == "certified", (status, viol)
        assert self._oracle_viol(
            costs, pe, pt, supply, capacity, scale, mask) == []

    def test_dirty_column_cost_drop_is_caught(self):
        """A dirty excluded column whose cost collapsed must come back
        as a violation (soundness: the fold sees the CURRENT cells)."""
        from poseidon_tpu.costmodel.delta import PlaneLedger

        (tp, cert, costs, pe, supply, capacity,
         ec_ids, uuids, scale) = self._setup()
        mask = np.zeros(costs.shape[1], dtype=bool)
        mask[:8] = True
        base = costs.astype(np.int64) * scale + pe[:, None]
        pt = int(base[:, mask].min())  # boundary near the included plane
        # Collapse excluded column 20 far below the accept boundary and
        # report it dirty.
        costs2 = costs.copy()
        costs2[:, 20] = 0
        led = PlaneLedger()
        led.present = set(int(e) for e in ec_ids.tolist())
        led.cols = {uuids[20]}
        cert.note_build(ec_ids, uuids, led)
        assert cert.begin_attempt(costs2, scale)
        status, viol, worst, pm = cert.check(
            eff_costs=costs2, pe=pe, pt=pt, supply=supply,
            capacity=capacity, arc_capacity=None, scale=scale, mask=mask,
        )
        oracle = self._oracle_viol(
            costs2, pe, pt, supply, capacity, scale, mask)
        if 20 in oracle:
            assert status == "violations" and 20 in viol.tolist(), (
                status, viol, oracle)

    def test_unreported_build_never_certifies(self):
        """A build the ledger never saw (None) breaks the chain: the
        cert must refuse to certify what it cannot prove."""
        (tp, cert, costs, pe, supply, capacity,
         ec_ids, uuids, scale) = self._setup()
        cert.note_build(ec_ids, uuids, None)
        assert not cert.begin_attempt(costs, scale)
        status, *_ = cert.check(
            eff_costs=costs, pe=pe, pt=0, supply=supply,
            capacity=capacity, arc_capacity=None, scale=scale,
            mask=np.zeros(costs.shape[1], dtype=bool),
        )
        assert status == "inconclusive"

    def test_heavy_drift_rows_demoted_not_inconclusive(self):
        """A few rows with collapsed prices (the gang-repair shape) go
        to the exact path; the bound stays tight for the rest and the
        check still reaches a verdict instead of giving up."""
        (tp, cert, costs, pe, supply, capacity,
         ec_ids, uuids, scale) = self._setup(E=32, M=64, seed=2)
        mask = np.zeros(costs.shape[1], dtype=bool)
        mask[:16] = True
        pt = int((costs.astype(np.int64) * scale
                  + pe[:, None]).min()) - 10
        pe2 = pe.copy()
        pe2[:3] -= 500_000  # three heavy drifters...
        from poseidon_tpu.ops.transport import INF_COST

        eff = costs.copy()
        eff[:3] = INF_COST  # ...whose rows the repair FORBADE (the
        # real gang shape: collapsed pe, inadmissible arcs)
        status, viol, worst, pm = cert.check(
            eff_costs=eff, pe=pe2, pt=pt, supply=supply,
            capacity=capacity, arc_capacity=None, scale=scale, mask=mask,
        )
        assert status == "certified", (status, viol)
        assert self._oracle_viol(
            eff, pe2, pt, supply, capacity, scale, mask) == []


class TestShortlistRevival:
    def test_second_round_revives_accepted_union(self, monkeypatch):
        """Two warm rounds of the same pruned band: round 2 must revive
        round 1's accepted union instead of re-running the planner."""
        monkeypatch.setenv("POSEIDON_PRUNE_MIN_ROWS", "8")
        monkeypatch.setenv("POSEIDON_PRUNE_MIN_COLS", "32")
        for k, v in DELTA_ENV.items():
            monkeypatch.setenv(k, v)
        from poseidon_tpu.ops import transport_pruned as tp

        calls = []
        real_plan = tp.plan_shortlist

        def counting_plan(*a, **kw):
            calls.append(1)
            return real_plan(*a, **kw)

        monkeypatch.setattr(tp, "plan_shortlist", counting_plan)
        rng = np.random.default_rng(9)
        state = _cluster(80, rng, labeled=False)
        uidc = [0]
        # Many distinct shapes -> enough EC rows for the pruned gate.
        shapes = [(100 + 13 * i, 1 << 19) for i in range(24)]
        _submit(state, uidc, 200, rng, shapes)
        model = get_cost_model("cpu_mem")
        planner = RoundPlanner(state, model)
        planner.schedule_round()
        if planner.last_metrics.pruned_bands == 0:
            pytest.skip("pruned gate declined at this scale")
        n_round1 = len(calls)
        assert n_round1 >= 1
        # Steady-state churn: remove a few, resubmit same shapes.
        live = [t for t in state.tasks.values() if t.scheduled_to]
        for t in live[:5]:
            state.task_removed(t.uid)
        _submit(state, uidc, 5, rng, shapes)
        planner.schedule_round()
        m2 = planner.last_metrics
        if m2.pruned_bands and m2.cost_delta_hits:
            assert len(calls) == n_round1, (
                "round 2 re-ran plan_shortlist despite a revivable "
                "accepted union"
            )

    def test_revive_declines_on_machine_churn(self, monkeypatch):
        """>3% of the saved union's machines gone -> replan."""
        planner = RoundPlanner.__new__(RoundPlanner)
        planner._shortlist_bands = {
            5: ([f"u{j}" for j in range(100)], 7)}

        class _E:
            supply = np.full(200, 2, dtype=np.int32)
        monkeypatch.setenv("POSEIDON_PRUNE_MIN_ROWS", "1")
        monkeypatch.setenv("POSEIDON_PRUNE_MIN_COLS", "1")
        col_cap = np.full(500, 8, dtype=np.int32)
        # All saved machines present: revives.
        uuids = [f"u{j}" for j in range(500)]
        plan = planner._revive_shortlist(5, _E, col_cap, None, uuids,
                                         fresh_ok=True)
        assert plan is not None
        saved_cols = set(range(100))
        assert saved_cols <= set(plan.sel.tolist())
        # 10 of the 100 saved machines gone: declines.
        uuids2 = [f"u{j}" for j in range(10, 510)]
        assert planner._revive_shortlist(
            5, _E, col_cap, None, uuids2, fresh_ok=True) is None
        # Not fresh: declines outright.
        assert planner._revive_shortlist(
            5, _E, col_cap, None, uuids, fresh_ok=False) is None


class TestCostPipeline:
    class _SlowModel:
        """Cost-model stand-in whose build sleeps, so the overlap
        window is deterministic."""
        delta_plane = False

        def __init__(self, dt=0.05):
            self.dt = dt
            self.builds = []
            self.fail_next = False

        def build(self, ecs, machines):
            if self.fail_next:
                self.fail_next = False
                raise RuntimeError("speculative boom")
            time.sleep(self.dt)
            self.builds.append(threading.current_thread().name)
            from poseidon_tpu.costmodel.base import CostMatrices
            E, M = ecs.num_ecs, machines.num_machines
            return CostMatrices(
                costs=np.zeros((E, M), dtype=np.int32),
                unsched_cost=np.zeros(E, dtype=np.int32),
                capacity=machines.slots_free.astype(np.int32),
                arc_capacity=None,
            )

    def _tables(self):
        rng = np.random.default_rng(1)
        state = _cluster(12, rng, labeled=False)
        uidc = [0]
        _submit(state, uidc, 10, rng, [(300, 1 << 19)])
        v = state.build_round_view()
        return v.ecs, v.machines

    def test_overlap_window_math(self):
        from poseidon_tpu.graph.pipeline import CostPipeline

        model = self._SlowModel(dt=0.08)
        pipe = CostPipeline(CostPlaneCache(model))
        ecs, mt = self._tables()
        pipe.speculate(1, ecs, mt)
        t0 = time.perf_counter()
        time.sleep(0.02)  # "solving" while the worker builds
        cm, stats = pipe.build(1, ecs, mt)
        overlap = pipe.overlap_with(t0, time.perf_counter())
        assert overlap > 0.0
        assert cm.costs.shape == (ecs.num_ecs, mt.num_machines)

    def test_speculative_error_is_swallowed_authoritative_raises(self):
        from poseidon_tpu.graph.pipeline import CostPipeline

        model = self._SlowModel(dt=0.0)
        cache = CostPlaneCache(model)
        pipe = CostPipeline(cache)
        ecs, mt = self._tables()
        model.fail_next = True
        pipe.speculate(1, ecs, mt)   # worker raises; round must survive
        cm, stats = pipe.build(1, ecs, mt)  # authoritative recomputes
        assert cm is not None
        model.fail_next = True
        with pytest.raises(RuntimeError):
            pipe.build(1, ecs, mt)   # the REAL build's errors propagate
        pipe.drain()

    def test_planner_parity_pipeline_on_off(self, monkeypatch):
        """Multi-band rounds with the pipeline on vs off place
        identically (speculation is never wrong-RESULT)."""
        for k, v in DELTA_ENV.items():
            monkeypatch.setenv(k, v)

        def run(pipeline_on):
            monkeypatch.setenv("POSEIDON_PIPELINE_BANDS",
                               "1" if pipeline_on else "0")
            rng = np.random.default_rng(4)
            state = _cluster(30, rng, labeled=False)
            uidc = [0]
            # Two supply bands: singles and 8-task jobs.
            _submit(state, uidc, 40, rng, [(200, 1 << 19)])
            for g in range(10):
                for i in range(8):
                    state.task_submitted(TaskInfo(
                        uid=task_uid(f"cd-band2-{g}", i),
                        job_id=f"cd-b2-{g}",
                        cpu_request=900 + g, ram_request=1 << 20,
                    ))
            model = get_cost_model("cpu_mem")
            planner = RoundPlanner(state, model)
            digests = []
            for r in range(4):
                planner.schedule_round()
                digests.append(sorted(
                    (t.uid, t.scheduled_to)
                    for t in state.tasks.values() if t.scheduled_to
                ))
                live = [t for t in state.tasks.values()
                        if t.scheduled_to]
                for t in live[:4]:
                    state.task_removed(t.uid)
                _submit(state, uidc, 4, rng, [(200, 1 << 19)])
            return digests

        assert run(False) == run(True)
