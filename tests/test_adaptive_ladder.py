"""Adaptive epsilon-ladder entry + adaptive global-update cadence +
pinned-scale coarse: the round-9 device-wave paths, parity-pinned.

The contract (ISSUE 8 acceptance): with the escape hatches OFF
(``POSEIDON_ADAPTIVE_LADDER=0``, ``POSEIDON_ADAPTIVE_BF=0``,
``POSEIDON_COARSE_PINNED=0``) the solver arithmetic is bit-identical to
the pre-round-9 code; with them ON every accepted solution still carries
the same certificate (gap_bound == 0 on solvable instances) and the
objective is IDENTICAL to the fixed-ladder path — entry-phase selection
and update cadence may change the iterate path, never the optimum.
"""

import numpy as np
import pytest

from poseidon_tpu.ops import transport
from poseidon_tpu.ops.transport import (
    INF_COST,
    NUM_PHASES,
    derive_scale,
    padded_shape,
    solve_transport,
)


def _instance(E, M, seed, contended=False, inf_frac=0.1):
    rng = np.random.default_rng(seed)
    costs = rng.integers(0, 1000, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < inf_frac] = INF_COST
    supply = rng.integers(1, 9, size=E).astype(np.int32)
    cap = (
        np.full(M, max(1, int(supply.sum()) // (2 * M) + 1), np.int32)
        if contended
        else rng.integers(1, 12, size=M).astype(np.int32)
    )
    unsched = rng.integers(1000, 2000, size=E).astype(np.int32)
    arc = rng.integers(1, 6, size=(E, M)).astype(np.int32)
    return costs, supply, cap, unsched, arc


def _drift(costs, rng, mag=40):
    d = rng.integers(-mag, mag + 1, size=costs.shape).astype(np.int32)
    out = np.where(costs < INF_COST, np.clip(costs + d, 0, 4000), costs)
    return out.astype(np.int32)


def _off(monkeypatch):
    monkeypatch.setenv("POSEIDON_ADAPTIVE_LADDER", "0")
    monkeypatch.setenv("POSEIDON_ADAPTIVE_BF", "0")


def _on(monkeypatch):
    monkeypatch.setenv("POSEIDON_ADAPTIVE_LADDER", "1")
    monkeypatch.setenv("POSEIDON_ADAPTIVE_BF", "1")


def _certified_equal(a, b):
    """Both certified exactly optimal, identical objectives: the adaptive
    paths may walk a different iterate sequence but never a different
    optimum (placements equal or cost-equal)."""
    assert a.gap_bound == 0.0, a.gap_bound
    assert b.gap_bound == 0.0, b.gap_bound
    assert a.objective == b.objective


# ------------------------------------------------------- warm-frame entry


@pytest.mark.parametrize("seed", range(4))
def test_adaptive_warm_entry_parity(monkeypatch, seed):
    """Warm drift re-solves: the adaptive ladder enters at the start's
    CERTIFIED eps (host-checked from the duals) instead of the drift
    bound — same certificate, same objective as the fixed entry."""
    rng = np.random.default_rng(100 + seed)
    costs, supply, cap, unsched, arc = _instance(16, 96, seed)
    _off(monkeypatch)
    first = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    assert first.gap_bound == 0.0
    costs2 = _drift(costs, rng)
    # Drift-bound epsilon, exactly as the planner's incremental path
    # derives it (drift * scale + 1).
    e_pad, m_pad = padded_shape(*costs.shape)
    scale, _ = derive_scale(costs2, unsched, None, e_pad, m_pad)
    eps_drift = 40 * scale + 1
    kw = dict(
        arc_capacity=arc, init_flows=first.flows,
        init_unsched=first.unsched, eps_start=eps_drift,
    )
    _off(monkeypatch)
    fixed = solve_transport(costs2, supply, cap, unsched, first.prices, **kw)
    _on(monkeypatch)
    adapt = solve_transport(costs2, supply, cap, unsched, first.prices, **kw)
    _certified_equal(fixed, adapt)
    # The adaptive entry can only lower (or keep) the entry epsilon,
    # never raise it — iteration counts may wiggle either way (a lower
    # entry walks a different, equally-certified path).
    assert adapt.entry_phase >= fixed.entry_phase


@pytest.mark.parametrize("contended", [False, True])
def test_adaptive_cold_parity(monkeypatch, contended):
    """Cold solves (greedy/coarse-free small instances): adaptive paths
    on vs off certify the identical optimum."""
    costs, supply, cap, unsched, arc = _instance(
        20, 128, 7, contended=contended
    )
    _off(monkeypatch)
    fixed = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    _on(monkeypatch)
    adapt = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    _certified_equal(fixed, adapt)


def test_adaptive_off_is_bit_identical(monkeypatch):
    """The escape hatch: with both knobs off, repeated solves of the same
    instance are bit-for-bit reproducible (the hatches select the
    pre-round-9 arithmetic exactly — the fused-kernel parity suite pins
    the same property across implementations)."""
    costs, supply, cap, unsched, arc = _instance(16, 64, 3, contended=True)
    _off(monkeypatch)
    a = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    b = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    np.testing.assert_array_equal(a.flows, b.flows)
    np.testing.assert_array_equal(a.prices, b.prices)
    assert a.iterations == b.iterations
    assert a.bf_sweeps == b.bf_sweeps


def test_adaptive_bf_changes_schedule_not_optimum(monkeypatch):
    """The adaptive cadence is live (wiring test): on a contended
    instance with a long ladder it must produce a valid certified solve;
    sweeps may differ from the fixed cadence, the optimum must not."""
    costs, supply, cap, unsched, arc = _instance(24, 96, 11, contended=True)
    _off(monkeypatch)
    fixed = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    monkeypatch.setenv("POSEIDON_ADAPTIVE_BF", "1")
    adapt = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    _certified_equal(fixed, adapt)


# ----------------------------------------------- fused/tiled kernel parity


@pytest.mark.parametrize("impl_env", ["POSEIDON_FUSED", "POSEIDON_TILED"])
def test_kernel_parity_holds_under_adaptive_bf(monkeypatch, impl_env):
    """The Pallas twins implement the SAME adaptive schedule (shared
    scalar helpers): bit-parity with the lax path must hold with the
    adaptive cadence enabled, exactly as the fixed-cadence suites pin."""
    if impl_env == "POSEIDON_TILED":
        import poseidon_tpu.ops.transport_fused as TF
        import poseidon_tpu.ops.transport_tiled as TT

        # Route through the tiled gate: needs fits_tile true and
        # fits_vmem false at this shape.
        monkeypatch.setattr(TF, "fits_vmem", lambda e, m: False)
        monkeypatch.setattr(TT, "fits_tile", lambda e: True)
    costs, supply, cap, unsched, arc = _instance(16, 64, 5, contended=True)
    monkeypatch.setenv("POSEIDON_ADAPTIVE_BF", "1")
    monkeypatch.setenv(impl_env, "0")
    lax_sol = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    monkeypatch.setenv(impl_env, "1")
    pallas_sol = solve_transport(
        costs, supply, cap, unsched, arc_capacity=arc
    )
    np.testing.assert_array_equal(lax_sol.flows, pallas_sol.flows)
    np.testing.assert_array_equal(lax_sol.prices, pallas_sol.prices)
    assert lax_sol.iterations == pallas_sol.iterations
    assert lax_sol.bf_sweeps == pallas_sol.bf_sweeps
    assert lax_sol.phase_iters == pallas_sol.phase_iters


# ------------------------------------------------------ pinned-scale coarse


def _coarse_instance(seed):
    """Big enough for the coarse gates (M >= COARSE_MIN_MACHINES,
    supply >= 4K) yet cheap on CPU."""
    rng = np.random.default_rng(seed)
    E, M = 12, 1024
    # Load-shaped columns (distinct column means) so grouping has
    # structure and the greedy start does NOT certify (the coarse solve
    # actually runs).
    base = rng.integers(0, 800, size=M)
    costs = (base[None, :] + rng.integers(0, 64, size=(E, M))).astype(
        np.int32
    )
    supply = np.full(E, 96, dtype=np.int32)
    cap = np.full(M, 6, dtype=np.int32)
    unsched = np.full(E, 2000, dtype=np.int32)
    return costs, supply, cap, unsched


@pytest.mark.parametrize("seed", range(2))
def test_coarse_warm_start_pinned_scale_bit_identical(monkeypatch, seed):
    """Where the pinned scale EQUALS the derived one (the full-plane
    case), the pinned-scale coarse path must be bit-identical to the
    unpinned path — the satellite-4 pin for the reduced-plane road."""
    from poseidon_tpu.ops.transport import coarse_warm_start

    costs, supply, cap, unsched = _coarse_instance(seed)
    e_pad, m_pad = padded_shape(*costs.shape)
    scale, _ = derive_scale(costs, unsched, None, e_pad, m_pad)

    def solve(c, s, k, u, **kw):
        return solve_transport(c, s, k, u, **kw)

    from poseidon_tpu.ops.transport import coarse_precheck

    pre_unpinned = coarse_precheck(
        costs, supply, cap, None, unsched, None
    )
    pre_pinned = coarse_precheck(
        costs, supply, cap, None, unsched, None, scale=scale
    )
    assert pre_unpinned is not None and pre_pinned is not None
    assert pre_pinned["scale"] == pre_unpinned["scale"] == scale
    a = coarse_warm_start(
        costs, supply, cap, unsched, None, solve, pre=pre_unpinned
    )
    b = coarse_warm_start(
        costs, supply, cap, unsched, None, solve, pre=pre_pinned
    )
    assert (a is None) == (b is None)
    if a is not None:
        pa, fa, ua, ea = a
        pb, fb, ub, eb = b
        np.testing.assert_array_equal(pa, pb)
        np.testing.assert_array_equal(fa, fb)
        np.testing.assert_array_equal(ua, ub)
        assert ea == eb


def test_solve_plane_pinned_coarse_runs_and_certifies(monkeypatch):
    """The planner's pinned-scale plane solve (the pruned path's shape):
    _solve_plane with an explicit scale must still run the coarse warm
    start (POSEIDON_COARSE_PINNED default-on) and certify the same
    objective as the dense unpinned solve."""
    from poseidon_tpu.ops.transport import _certified_eps

    costs, supply, cap, unsched = _coarse_instance(5)
    e_pad, m_pad = padded_shape(*costs.shape)
    scale, _ = derive_scale(costs, unsched, None, e_pad, m_pad)
    _off(monkeypatch)
    ref = solve_transport(costs, supply, cap, unsched)
    _on(monkeypatch)
    pinned = solve_transport(costs, supply, cap, unsched, scale=scale)
    _certified_equal(ref, pinned)
    eps = _certified_eps(
        pinned.flows, pinned.unsched, pinned.prices, costs=costs,
        supply=supply, capacity=cap, unsched_cost=unsched, scale=scale,
    )
    assert eps <= 1


# ----------------------------------------------- randomized mixed regimes


@pytest.mark.parametrize("seed", range(6))
def test_randomized_regimes_parity(monkeypatch, seed):
    """Fuzzed cold/warm/repair starts: adaptive on vs off always lands
    on a certified-equal optimum.  Repair shape: warm frame stranded on
    freshly forbidden rows (the gang-repair start)."""
    rng = np.random.default_rng(7000 + seed)
    E, M = int(rng.integers(8, 28)), int(rng.integers(48, 160))
    contended = bool(rng.integers(0, 2))
    costs, supply, cap, unsched, arc = _instance(
        E, M, seed + 50, contended=contended
    )
    _off(monkeypatch)
    base = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    # Repair-shaped drift: forbid a loaded row outright + drift the rest.
    costs2 = _drift(costs, rng)
    loaded_rows = np.nonzero(base.flows.sum(axis=1) > 0)[0]
    if loaded_rows.size:
        costs2[loaded_rows[int(rng.integers(0, loaded_rows.size))]] = (
            INF_COST
        )
    kw = dict(
        arc_capacity=arc, init_flows=base.flows,
        init_unsched=base.unsched, eps_start=1,
    )
    _off(monkeypatch)
    fixed = solve_transport(costs2, supply, cap, unsched, base.prices, **kw)
    _on(monkeypatch)
    adapt = solve_transport(costs2, supply, cap, unsched, base.prices, **kw)
    _certified_equal(fixed, adapt)


# ------------------------------------------------------- entry telemetry


def test_entry_phase_telemetry(monkeypatch):
    """TransportSolution.entry_phase: 0 on cold full-ladder solves,
    positive when a certified start entered the ladder below the cold
    eps0 (the round-metrics/bench 'ladder entry phase' series)."""
    costs, supply, cap, unsched, arc = _instance(16, 96, 9, contended=True)
    _on(monkeypatch)
    cold = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    assert cold.entry_phase == 0
    drifted = _drift(np.asarray(costs), np.random.default_rng(1), mag=2)
    warm = solve_transport(
        drifted, supply, cap, unsched, cold.prices,
        arc_capacity=arc, init_flows=cold.flows,
        init_unsched=cold.unsched, eps_start=3,
    )
    assert warm.gap_bound == 0.0
    assert 0 < warm.entry_phase <= NUM_PHASES


# ------------------------------------------------- escalation warm-carry


def test_escalation_carry_is_sound_warm_start():
    """A pruned-path escalation's ``stats['carry']`` (the last lifted
    full-plane state + its exact eps) must be a certified-sound warm
    start for the dense fallback: solving from it lands on the dense
    optimum with an exact certificate — the de-double-pay road for
    price-out re-solves."""
    from poseidon_tpu.ops import transport_pruned as tp

    # The engineered price-out shape from test_transport_pruned: the
    # shortlist's cheapest columns are arc-blocked for every row, so the
    # reduced optimum strands supply on the fallback while cheaper open
    # columns sit outside the union.
    E, M = 4, 128
    costs = np.broadcast_to(np.arange(M, dtype=np.int32), (E, M)).copy()
    supply = np.full(E, 8, dtype=np.int32)
    capacity = np.full(M, 2, dtype=np.int32)
    unsched = np.full(E, 500, dtype=np.int32)
    arc = np.full((E, M), 8, dtype=np.int32)
    arc[:, :64] = 0
    scale, _ = derive_scale(costs, unsched, None, *padded_shape(E, M))

    def solve_on(sel, warm):
        p = f = u = eps = None
        if warm is not None and warm[0] is not None:
            p, f, u, eps = warm
        sol = solve_transport(
            costs[:, sel], supply, capacity[sel], unsched, p,
            arc_capacity=arc[:, sel], init_flows=f, init_unsched=u,
            eps_start=eps, scale=scale,
        )
        return sol, costs[:, sel]

    sol, eff, stats = tp.solve_pruned(
        costs, supply, capacity, unsched, arc_capacity=arc, scale=scale,
        solve_on=solve_on, plan_kw=dict(min_rows=2, min_cols=16),
        max_rounds=0,
    )
    assert sol is None and stats["escalated"]
    carry = stats["carry"]
    assert carry is not None
    p, f, u, eps = carry
    assert p.dtype == np.int32 and eps > 1
    dense = solve_transport(costs, supply, capacity, unsched,
                            arc_capacity=arc)
    warmed = solve_transport(
        costs, supply, capacity, unsched, p, arc_capacity=arc,
        init_flows=f, init_unsched=u, eps_start=eps, eps_exact=True,
    )
    assert warmed.gap_bound == 0.0 == dense.gap_bound
    assert warmed.objective == dense.objective


def test_wave_shaped_row_gate():
    """The wave-shaped secondary row gate: few-row/very-wide planes
    qualify, POSEIDON_PRUNE_WAVE=0 restores the classic gate exactly."""
    import os

    from poseidon_tpu.ops import transport_pruned as tp

    assert tp.row_gate_ok(400, 4096, 192)          # classic
    assert not tp.row_gate_ok(100, 4096, 192)      # too narrow for wave
    assert tp.row_gate_ok(100, 10240, 192)         # the 10k wave shape
    assert not tp.row_gate_ok(8, 10240, 192)       # too few rows even so
    os.environ["POSEIDON_PRUNE_WAVE"] = "0"
    try:
        assert not tp.row_gate_ok(100, 10240, 192)
    finally:
        os.environ.pop("POSEIDON_PRUNE_WAVE")
