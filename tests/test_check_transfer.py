"""Runtime tests for the transfer side of the check suite.

The static rule (``transfer-discipline``) names the *patterns*; the
``TransferLedger`` catches the *events*.  The seeded test here proves
the pairing end to end: one deliberate hot-path ``.item()`` trips the
static rule on the source AND the runtime ledger on execution —
the contract ``tests/test_check_ledger.py`` established for the
retrace-guard/CompileLedger pair.

Also here: the randomized use-after-donation parity suite (donating
kernels must be bit-identical to their non-donating oracles, and the
rebind idiom must keep warm state correct across calls), and the
``RoundMetrics.implicit_transfers`` wire-format/exporter ride.
"""

from __future__ import annotations

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.check import check_file
from poseidon_tpu.check.ledger import (
    TransferBudgetExceeded,
    TransferLedger,
    implicit_transfer_count,
)
from poseidon_tpu.check.transfer_discipline import TransferDisciplineRule

SEEDED_HOT_PATH = textwrap.dedent(
    """
    import jax
    import numpy as np


    @jax.jit
    def _step(x):
        return x * 2, x.sum()


    def hot_round(x):
        F, s = _step(x)
        total = s.item()  # the deliberate implicit sync
        return F, total
    """
)


def test_seeded_item_trips_static_rule_and_ledger(tmp_path):
    """The same deliberate ``.item()`` on a jitted result fails BOTH
    gates: the static scan flags the source line, and executing it
    under ``TransferLedger(budget=0)`` raises with the call site."""
    mod = tmp_path / "seeded_hot_path.py"
    mod.write_text(SEEDED_HOT_PATH)
    rule = TransferDisciplineRule()
    pre = check_file(mod, [rule], forced=True, root=tmp_path)
    found = pre + rule.finalize()
    assert len(found) == 1
    assert "item" in found[0].message
    assert "implicit device->host sync" in found[0].message

    # Runtime half: execute the very same module under budget 0.
    ns: dict = {}
    exec(compile(SEEDED_HOT_PATH, str(mod), "exec"), ns)
    x = jnp.arange(8)
    ns["hot_round"](x)  # warm (compile outside the window)
    with pytest.raises(TransferBudgetExceeded) as e:
        with TransferLedger(budget=0, label="seeded hot round"):
            ns["hot_round"](x)
    assert "item()" in str(e.value)
    assert "seeded hot round" in str(e.value)


def test_ledger_telemetry_and_budget_modes():
    x = jnp.arange(6)
    x.sum().block_until_ready()
    c0 = implicit_transfer_count()
    with TransferLedger(budget=None, label="telemetry") as tl:
        float(x.sum())
        int(x.max())
        bool(x.sum() > 0)
    assert tl.implicit_transfers == 3
    assert implicit_transfer_count() - c0 == 3
    # Offenders carry method + call-site attribution.
    assert any("__float__" in o for o in tl.offenders)
    assert all("test_check_transfer.py" in o for o in tl.offenders)

    # Explicit fetches are the sanctioned boundary: never counted.
    with TransferLedger(budget=0, label="clean") as tl2:
        host = jax.device_get(x)
        _ = float(host.sum())  # numpy scalar: host data, no sync
    assert tl2.implicit_transfers == 0

    # A body exception is never masked by the budget report.
    with pytest.raises(ValueError):
        with TransferLedger(budget=0, label="masking"):
            float(x.sum())
            raise ValueError("real failure")


def test_ledger_nests_with_compile_ledger():
    from poseidon_tpu.check.ledger import CompileLedger

    x = jnp.arange(4)
    x.sum().block_until_ready()
    with CompileLedger(budget=0, label="warm"), \
            TransferLedger(budget=0, label="warm"):
        y = jax.device_get(x.sum())
    assert int(y) == 6


def test_host_fetch_is_ledger_clean():
    """transport.host_fetch — the declared boundary — fetches arrays
    AND scalars in one explicit transfer that budget-0 windows admit."""
    from poseidon_tpu.ops.transport import host_fetch

    F = jnp.arange(12).reshape(3, 4)
    s = F.sum()
    with TransferLedger(budget=0, label="boundary fetch") as tl:
        F_h, s_h = host_fetch(F, s)
        total = int(s_h)  # numpy now: free
    assert tl.implicit_transfers == 0
    assert total == 66
    assert isinstance(F_h, np.ndarray)
    # Single-argument form returns the bare value.
    assert host_fetch(s).item() == 66


# ----------------------------------------------------------- donation


def test_use_after_donation_parity_randomized():
    """The resident-cache donating kernels against numpy oracles, with
    randomized shapes/payloads: results bit-identical, and the rebind
    idiom (never touching the donated handle again) keeps the device
    state correct across a chain of donating calls."""
    from poseidon_tpu.ops.transport import (
        _resident_scatter_cols,
        _resident_set_flows,
    )

    rng = np.random.default_rng(11)
    for _ in range(8):
        E = int(rng.integers(2, 9))
        M = int(rng.integers(4, 17))
        k = int(rng.integers(1, M + 1))
        big = rng.integers(-1000, 1000, size=(3, E, M)).astype(np.int32)
        idx = rng.choice(M, size=k, replace=False).astype(np.int32)
        payload = rng.integers(-1000, 1000, size=(3, E, k)).astype(
            np.int32
        )
        flows = rng.integers(0, 50, size=(E, M)).astype(np.int32)

        # Oracle: plain numpy column scatter, then plane-2 overwrite.
        oracle = big.copy()
        oracle[:, :, idx] = payload
        oracle2 = oracle.copy()
        oracle2[2] = flows

        dev = jnp.asarray(big)
        dev = _resident_scatter_cols(
            dev, jnp.asarray(idx), jnp.asarray(payload)
        )
        np.testing.assert_array_equal(np.asarray(dev), oracle)
        dev = _resident_set_flows(dev, jnp.asarray(flows))
        np.testing.assert_array_equal(np.asarray(dev), oracle2)


def test_donated_buffer_is_consumed():
    """After a donating call, the donated handle is dead where the
    backend supports donation; the rebind idiom the static rule's
    use-after-donation check enforces is what makes this safe."""
    from poseidon_tpu.ops.transport import _resident_set_flows

    big = jnp.zeros((3, 2, 4), jnp.int32)
    flows = jnp.ones((2, 4), jnp.int32)
    out = _resident_set_flows(big, flows)
    assert np.asarray(out)[2].sum() == 8
    if big.is_deleted():
        # Donation honored (accelerators; some CPU jaxlibs too): any
        # read of the donated operand must now fail loudly.
        with pytest.raises(RuntimeError):
            np.asarray(big)


# ----------------------------------------------------- metrics plumbing


def test_implicit_transfers_rides_wire_format_and_metrics():
    from poseidon_tpu.graph.instance import RoundMetrics
    from poseidon_tpu.obs.metrics import Registry, observe_round

    m = RoundMetrics(round_index=3, implicit_transfers=2)
    d = m.to_dict()
    assert d["implicit_transfers"] == 2
    back = RoundMetrics.from_dict(d)
    assert back.implicit_transfers == 2

    reg = Registry()
    observe_round(m, reg)
    text = reg.expose()
    assert "poseidon_round_implicit_transfers 2" in text
