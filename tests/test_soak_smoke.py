"""Chaos soak smoke (``make soak-smoke``): the acceptance gate.

A seeded fault plan injecting at least one instance of every fault
family runs N rounds of the full stack at ~200 machines, asserting all
pods place, scheduler/fake-kube state stays byte-identical after every
round, warm rounds compile nothing fresh, and a re-run with the same
seed places identically.  Then the flight-recorder path: killing the
Firmament stub mid-soak must produce a trace that the replay package
loads and re-drives to the identical failing round.

Slow-marked: excluded from the tier-1 gate, run via ``make soak-smoke``
(wired into ``make verify``) or ``pytest -m slow``.
"""

import pytest

from poseidon_tpu.chaos import run_soak
from poseidon_tpu.chaos.plan import KINDS, named_plan
from poseidon_tpu.replay import (
    ReplayDriver,
    flight_trace_events,
    load_flight,
    redrive_flight,
)

pytestmark = pytest.mark.slow

MACHINES = 200
ROUNDS = 10
SEED = 0


def test_soak_smoke_full_plan(tmp_path):
    out = run_soak(
        machines=MACHINES, rounds=ROUNDS, plan="smoke", seed=SEED,
        out_dir=str(tmp_path),
    )
    assert out["ok"], out.get("failure")
    # Every fault family actually FIRED (scheduled is not enough).
    fired_families = {KINDS[e["kind"]] for e in out["fired"]}
    assert fired_families == {"watch", "events", "rpc", "binding", "solver"}
    # Zero divergence on every round and zero warm fresh compiles are
    # enforced inside run_soak (they fail the soak); restate the
    # artifact contract here.
    assert out["divergent_rounds"] == 0
    assert out["warm_fresh_compiles"] == 0
    assert out["rounds_run"] == ROUNDS + 2  # settle rounds included
    # The degraded ladder served at least one faulted round, and the
    # fault plan covered the whole taxonomy.
    assert "host_greedy" in out["tiers"]
    assert named_plan("smoke", ROUNDS, SEED).families_covered() == (
        "binding", "events", "rpc", "solver", "watch"
    )

    # Determinism: same seed, same placements, round for round.
    rerun = run_soak(
        machines=MACHINES, rounds=ROUNDS, plan="smoke", seed=SEED,
        out_dir=str(tmp_path),
    )
    assert rerun["ok"], rerun.get("failure")
    assert rerun["digests"] == out["digests"]


def test_soak_smoke_incremental_forced(tmp_path, monkeypatch):
    """The incremental round engine under faults: the delta-maintained
    cost planes forced to serve at soak scale (gate floors dropped), the
    same kube-truth byte-identity and budget-0 warm-compile gates must
    hold, and the delta path must have actually fired — a soak that
    silently fell back to full rebuilds proves nothing."""
    monkeypatch.setenv("POSEIDON_COST_DELTA_MIN_CELLS", "1")
    monkeypatch.setenv("POSEIDON_COST_DELTA_MIN_ROWS", "1")
    out = run_soak(
        machines=MACHINES, rounds=ROUNDS, plan="smoke", seed=SEED,
        out_dir=str(tmp_path),
    )
    assert out["ok"], out.get("failure")
    assert out["divergent_rounds"] == 0
    assert out["warm_fresh_compiles"] == 0
    assert out["cost_delta_hits"] > 0, (
        "incremental cost path never served during the forced soak"
    )

    rerun = run_soak(
        machines=MACHINES, rounds=ROUNDS, plan="smoke", seed=SEED,
        out_dir=str(tmp_path),
    )
    assert rerun["ok"], rerun.get("failure")
    assert rerun["digests"] == out["digests"]


def test_flight_recorder_kill_and_redrive(tmp_path):
    """Kill the Firmament stub mid-soak: the crash-loop budget stops the
    loop fatally, the flight recorder writes a trace, and the replay
    package re-drives it to the identical failing round."""
    kill_round = 4

    def kill(r, ctx):
        if r == kill_round:
            ctx["server"].stop(grace=0.1)

    out = run_soak(
        machines=48, rounds=8, plan="smoke", seed=1,
        out_dir=str(tmp_path), on_round=kill,
    )
    assert not out["ok"]
    assert out["failure"]["kind"] == "fatal"
    assert out["failing_round"] == kill_round

    trace = load_flight(out["trace_path"])
    assert len(trace.rounds) == kill_round
    assert trace.failure["round"] == kill_round

    # replay/ loads the trace's workload directly...
    events = flight_trace_events(out["trace_path"])
    report = ReplayDriver(events, precompile=False).run(max_rounds=3)
    assert report.placed > 0

    # ...and the re-drive lands on the identical failing round with
    # byte-identical per-round placements.
    redriven = redrive_flight(out["trace_path"])
    assert redriven["reproduced"], redriven.get("digest_mismatches")
    assert redriven["rounds_run"] == kill_round
