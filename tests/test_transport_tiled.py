"""Tiled per-iteration Pallas kernel: BIT-parity with the lax path.

Same contract as test_transport_fused: identical int32 update sequence,
so flows/prices/iterations/bf/phase splits must be EQUAL, not merely
cost-equal.  Interpret mode (no TPU in CI) via POSEIDON_TILED=1; shapes
chosen to span multiple column tiles (M > TILE_W).
"""

import numpy as np
import pytest

from poseidon_tpu.ops import transport
from poseidon_tpu.ops.transport import solve_transport
from poseidon_tpu.ops import transport_fused
from poseidon_tpu.ops import transport_tiled

# Production constants captured at import time, BEFORE the autouse
# fixture shrinks them — the gate test below must exercise the real
# fused/tiled routing boundary, not a stale hardcoded copy.
PROD_VMEM_BUDGET = transport_fused.VMEM_ELEM_BUDGET
PROD_TILE_W = transport_tiled.TILE_W


@pytest.fixture(autouse=True)
def small_tiles(monkeypatch):
    # Multi-tile coverage at test-friendly sizes: 3 tiles of 128 lanes
    # instead of 512-wide production tiles, and a tiny VMEM budget so
    # these instances land ABOVE it (the tiled tier's precondition —
    # without this the gate routes them to the lax/fused tiers and the
    # parity assertions are vacuous).
    monkeypatch.setattr(transport_tiled, "TILE_W", 128)
    # Kernel-parity tests: a trivially-certifiable instance (e.g. the
    # all-inadmissible case) would be answered by the host certificate
    # before the kernel ever runs — force the dispatch path.
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    monkeypatch.setattr(transport_fused, "VMEM_ELEM_BUDGET", 1024)
    # Prove the kernel actually ran on the POSEIDON_TILED=1 leg.
    calls = {"n": 0}
    real = transport_tiled.solve_device_tiled

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(
        transport_tiled, "solve_device_tiled", counting
    )
    # The packed dispatch wrapper caches executables per shape; a
    # cached impl="tiled" entry from another test would bypass both the
    # counting spy and the TILE_W/VMEM overrides above.
    transport._solve_device_packed.clear_cache()
    yield calls
    transport._solve_device_packed.clear_cache()


def _instance(E, M, seed, contended=False):
    rng = np.random.default_rng(seed)
    costs = rng.integers(0, 1000, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < 0.1] = transport.INF_COST
    supply = rng.integers(1, 9, size=E).astype(np.int32)
    cap = (
        np.full(M, max(1, int(supply.sum()) // (2 * M) + 1), np.int32)
        if contended
        else rng.integers(1, 8, size=M).astype(np.int32)
    )
    unsched = rng.integers(1000, 2000, size=E).astype(np.int32)
    arc = rng.integers(1, 6, size=(E, M)).astype(np.int32)
    return costs, supply, cap, unsched, arc


def _solve_both(monkeypatch, small_tiles, *args, **kw):
    monkeypatch.setenv("POSEIDON_TILED", "0")
    monkeypatch.setenv("POSEIDON_FUSED", "0")
    lax_sol = solve_transport(*args, **kw)
    monkeypatch.setenv("POSEIDON_TILED", "1")
    before = small_tiles["n"]
    tiled_sol = solve_transport(*args, **kw)
    assert small_tiles["n"] == before + 1, "tiled kernel did not run"
    assert not transport._TILED_BROKEN
    return lax_sol, tiled_sol


def _assert_bit_equal(a, b):
    np.testing.assert_array_equal(a.flows, b.flows)
    np.testing.assert_array_equal(a.unsched, b.unsched)
    np.testing.assert_array_equal(a.prices, b.prices)
    assert a.objective == b.objective
    assert a.gap_bound == b.gap_bound
    assert a.iterations == b.iterations
    assert a.bf_sweeps == b.bf_sweeps
    assert a.phase_iters == b.phase_iters


@pytest.mark.parametrize("seed", range(2))
def test_tiled_bit_parity_cold(monkeypatch, small_tiles, seed):
    # M=300 pads to 320-bucket then 384 kernel lanes = 3 tiles of 128.
    costs, supply, cap, unsched, arc = _instance(12, 300, seed)
    a, b = _solve_both(
        monkeypatch, small_tiles, costs, supply, cap, unsched,
        arc_capacity=arc,
    )
    _assert_bit_equal(a, b)
    assert a.gap_bound == 0.0


def test_tiled_bit_parity_contended(monkeypatch, small_tiles):
    # Contention: multi-phase ladders, global updates, sink push-back.
    costs, supply, cap, unsched, arc = _instance(
        10, 260, 7, contended=True
    )
    a, b = _solve_both(
        monkeypatch, small_tiles, costs, supply, cap, unsched,
        arc_capacity=arc,
    )
    _assert_bit_equal(a, b)
    assert a.iterations > 0


def test_tiled_bit_parity_warm_start(monkeypatch, small_tiles):
    costs, supply, cap, unsched, arc = _instance(10, 260, 11)
    monkeypatch.setenv("POSEIDON_TILED", "0")
    monkeypatch.setenv("POSEIDON_FUSED", "0")
    first = solve_transport(costs, supply, cap, unsched, arc_capacity=arc)
    costs2 = np.where(
        costs < transport.INF_COST, costs + 3, costs
    ).astype(np.int32)
    kw = dict(
        arc_capacity=arc, init_flows=first.flows,
        init_unsched=first.unsched, eps_start=4 * 97,
    )
    a, b = _solve_both(
        monkeypatch, small_tiles, costs2, supply, cap, unsched,
        first.prices, **kw
    )
    _assert_bit_equal(a, b)


def test_use_tiled_gate(monkeypatch):
    # The autouse fixture shrinks the VMEM budget / tile width for the
    # parity tests; the gate semantics are defined against production —
    # restore the import-time constants rather than hardcoding copies.
    monkeypatch.setattr(transport_fused, "VMEM_ELEM_BUDGET",
                        PROD_VMEM_BUDGET)
    monkeypatch.setattr(transport_tiled, "TILE_W", PROD_TILE_W)
    monkeypatch.delenv("POSEIDON_TILED", raising=False)
    monkeypatch.setattr(transport, "_TILED_BROKEN", set())
    # CPU backend: off by default.
    assert not transport._use_tiled(256, 10240)
    monkeypatch.setenv("POSEIDON_TILED", "1")
    assert transport._use_tiled(256, 10240)
    # VMEM-sized instances belong to the fused kernel, not this one.
    assert not transport._use_tiled(128, 1024)
    # Shapes in the 160k-262k elem gap moved tiers when the live v5e
    # OOM calibrated the budget down: they are tiled-tier now.
    assert transport._use_tiled(128, 2048)
    # Row-bound: a column tile's working set must fit.
    assert not transport._use_tiled(1024, 10240)
    # The broken latch wins over the force flag.
    monkeypatch.setattr(transport, "_TILED_BROKEN", {(256, 10240)})
    assert not transport._use_tiled(256, 10240)


def test_tiled_bit_parity_all_inadmissible(monkeypatch, small_tiles):
    E, M = 8, 260  # 3 tiles at the test tile width
    costs = np.full((E, M), transport.INF_COST, dtype=np.int32)
    supply = np.arange(1, E + 1, dtype=np.int32)
    cap = np.full(M, 4, np.int32)
    unsched = np.full(E, 1500, np.int32)
    a, b = _solve_both(monkeypatch, small_tiles, costs, supply, cap,
                       unsched)
    _assert_bit_equal(a, b)
    assert (a.unsched == supply).all()


def test_tiled_bit_parity_zero_supply_rows(monkeypatch, small_tiles):
    costs, supply, cap, unsched, arc = _instance(8, 260, 23)
    supply[::2] = 0
    a, b = _solve_both(
        monkeypatch, small_tiles, costs, supply, cap, unsched,
        arc_capacity=arc,
    )
    _assert_bit_equal(a, b)
