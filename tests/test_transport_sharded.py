"""Mesh-sharded solver: parity with the single-chip path and the oracle.

Runs on the 8-device virtual CPU mesh from conftest.py (the driver
separately dry-runs the multichip path; tests never need TPU hardware).
"""

import jax
import numpy as np
import pytest

from poseidon_tpu.ops.transport import INF_COST, solve_transport
from poseidon_tpu.ops.transport_sharded import (
    make_solver_mesh,
    solve_transport_sharded,
)
from poseidon_tpu.solver.oracle import transport_objective


def random_instance(rng, E, M, max_cost=1000):
    costs = rng.integers(0, max_cost, size=(E, M)).astype(np.int32)
    # ~10% inadmissible arcs.
    costs[rng.random((E, M)) < 0.1] = INF_COST
    supply = rng.integers(1, 8, size=E).astype(np.int32)
    capacity = rng.integers(1, 10, size=M).astype(np.int32)
    unsched = rng.integers(max_cost, 2 * max_cost, size=E).astype(np.int32)
    return costs, supply, capacity, unsched


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_solver_mesh(8)


def test_sharded_matches_oracle(mesh):
    rng = np.random.default_rng(7)
    for E, M in [(5, 12), (9, 30), (16, 64)]:
        costs, supply, capacity, unsched = random_instance(rng, E, M)
        sol = solve_transport_sharded(
            costs, supply, capacity, unsched, mesh=mesh
        )
        want = transport_objective(costs, supply, capacity, unsched)
        assert sol.gap_bound == 0.0
        assert sol.objective == want, (E, M)


def test_sharded_matches_single_chip(mesh):
    rng = np.random.default_rng(11)
    costs, supply, capacity, unsched = random_instance(rng, 12, 40)
    single = solve_transport(costs, supply, capacity, unsched)
    sharded = solve_transport_sharded(
        costs, supply, capacity, unsched, mesh=mesh
    )
    assert sharded.objective == single.objective
    # Feasibility of the sharded assignment.
    assert (sharded.flows.sum(axis=0) <= capacity).all()
    np.testing.assert_array_equal(
        sharded.flows.sum(axis=1) + sharded.unsched, supply
    )


def test_sharded_respects_arc_capacity(mesh):
    rng = np.random.default_rng(13)
    costs, supply, capacity, unsched = random_instance(rng, 6, 16)
    arc_cap = rng.integers(0, 3, size=costs.shape).astype(np.int32)
    sol = solve_transport_sharded(
        costs, supply, capacity, unsched, mesh=mesh, arc_capacity=arc_cap
    )
    assert (sol.flows <= arc_cap).all()
    want = transport_objective(
        costs, supply, capacity, unsched, arc_capacity=arc_cap
    )
    assert sol.objective == want


def test_sharded_warm_start(mesh):
    rng = np.random.default_rng(17)
    costs, supply, capacity, unsched = random_instance(rng, 10, 24)
    cold = solve_transport_sharded(costs, supply, capacity, unsched, mesh=mesh)
    # Perturb a few costs and re-solve warm from the previous solution.
    costs2 = costs.copy()
    mask = (costs2 < INF_COST) & (rng.random(costs2.shape) < 0.05)
    costs2[mask] = np.minimum(costs2[mask] + 50, 1000)
    warm = solve_transport_sharded(
        costs2, supply, capacity, unsched, cold.prices, mesh=mesh,
        init_flows=cold.flows, init_unsched=cold.unsched,
    )
    want = transport_objective(costs2, supply, capacity, unsched)
    assert warm.objective == want


def test_single_device_mesh_falls_back():
    mesh1 = make_solver_mesh(1)
    rng = np.random.default_rng(19)
    costs, supply, capacity, unsched = random_instance(rng, 4, 6)
    sol = solve_transport_sharded(
        costs, supply, capacity, unsched, mesh=mesh1
    )
    want = transport_objective(costs, supply, capacity, unsched)
    assert sol.objective == want


def test_sharded_solver_through_service():
    """VERDICT round-2 Missing #3: solver_devices>1 must be a capability
    of the PRODUCT — NodeAdded/TaskSubmitted/Schedule over gRPC, with the
    planner routing every band through the mesh-sharded solver."""
    from poseidon_tpu.protos import firmament_pb2 as fpb
    from poseidon_tpu.service import FirmamentClient, FirmamentTPUServer
    from poseidon_tpu.utils.config import FirmamentTPUConfig
    from poseidon_tpu.utils.ids import generate_uuid, hash_combine

    cfg = FirmamentTPUConfig(
        listen_address="127.0.0.1:0", solver_devices=8
    )
    with FirmamentTPUServer(config=cfg) as server, \
            FirmamentClient(server.address) as client:
        for i in range(16):
            rtnd = fpb.ResourceTopologyNodeDescriptor()
            rd = rtnd.resource_desc
            rd.uuid = generate_uuid(f"svc-shard-m{i}")
            rd.type = fpb.ResourceDescriptor.RESOURCE_MACHINE
            rd.resource_capacity.cpu_cores = 4000
            rd.resource_capacity.ram_cap = 1 << 24
            rd.task_capacity = 100
            assert client.node_added(rtnd) == fpb.NODE_ADDED_OK
        for i in range(24):
            td = fpb.TaskDescriptor(
                uid=hash_combine(99, i), job_id="shard-job",
            )
            td.resource_request.cpu_cores = 100 * (1 + i % 3)
            td.resource_request.ram_cap = 1 << 20
            jd = fpb.JobDescriptor(uuid="shard-job", name="shard-job")
            assert client.task_submitted(td, jd) == fpb.TASK_SUBMITTED_OK
        deltas = client.schedule()
        placed = sum(
            1 for d in deltas if d.type == fpb.SchedulingDelta.PLACE
        )
        assert placed == 24
        mesh = server.servicer.planner._mesh
        assert mesh is not None and mesh.size == 8


def test_sharded_coarse_start_objective_parity(monkeypatch):
    """The coarse wave warm start routes its aggregated solve through
    the SAME dispatch as the full solve, so solver_devices=8 and =1 must
    land on identical objectives with the coarse lift firing (gates
    patched down to test scale; a disaggregation spy proves it ran on
    both legs)."""
    import poseidon_tpu.ops.transport as T
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    monkeypatch.setattr(T, "COARSE_MIN_MACHINES", 32)
    monkeypatch.setattr(T, "COARSE_GROUPS", 8)
    lifted = {"n": 0}
    orig = T._coarse_disaggregate

    def spy(*a, **k):
        lifted["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(T, "_coarse_disaggregate", spy)

    def run(devices):
        state = ClusterState()
        rng = np.random.default_rng(5)
        for i in range(64):
            state.node_added(MachineInfo(
                uuid=f"sc-m{i}", cpu_capacity=int(rng.integers(4000, 16000)),
                ram_capacity=1 << 24, task_slots=6,
            ))
        for i in range(600):
            state.task_submitted(TaskInfo(
                uid=task_uid("sc", i), job_id=f"j{i % 8}",
                cpu_request=int(rng.integers(400, 2000)),
                ram_request=1 << 18,
            ))
        planner = RoundPlanner(
            state, get_cost_model("cpu_mem"), solver_devices=devices
        )
        _, m = planner.schedule_round()
        assert m.converged and m.gap_bound == 0.0
        return m.objective, m.placed

    before = lifted["n"]
    single = run(1)
    assert lifted["n"] > before, "coarse lift did not fire on 1-device"
    mid = lifted["n"]
    sharded = run(8)
    assert lifted["n"] > mid, "coarse lift did not fire on 8-device"
    assert single == sharded, (single, sharded)
