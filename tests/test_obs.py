"""obs/ telemetry plane: tracer, Perfetto export, Prometheus metrics,
stagetimer shim parity, flight-timeline re-render, and the perf gate.

The satellite contracts pinned here:

- span/stagetimer total-time parity under CONCURRENT rounds (the
  original stagetimer raced `_totals[name] += dt` and lost time);
- Prometheus exposition conformance: label escaping, histogram bucket
  monotonicity, TYPE/HELP discipline;
- Perfetto export is valid trace-event JSON with properly nested
  round -> stage spans;
- `RoundMetrics.to_dict()` is THE round wire format and round-trips;
- `tools/bench_compare.py` fails on a synthetically slowed stage and
  never compares apples to oranges.
"""

from __future__ import annotations

import ast
import copy
import json
import re
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from poseidon_tpu.obs import metrics as obs_metrics
from poseidon_tpu.obs import trace as obs_trace

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))

import bench_compare  # noqa: E402  (tools/ is not a package)


@pytest.fixture(autouse=True)
def _clean_tracer(monkeypatch):
    """Every test starts with a quiet, env-ungated process tracer."""
    monkeypatch.delenv(obs_trace.TRACE_ENV, raising=False)
    monkeypatch.delenv(obs_trace.STAGE_ENV, raising=False)
    tracer = obs_trace.tracer()
    prev_force = tracer.force
    tracer.force = None
    tracer.reset()
    yield
    tracer.force = prev_force
    tracer.reset()


# ------------------------------------------------------------------ tracer


def test_disabled_path_is_shared_noop_singleton():
    s1 = obs_trace.span("round", attr=1)
    s2 = obs_trace.span("other")
    assert s1 is s2 is obs_trace.NULL_SPAN
    with s1 as sp:
        assert sp.set(more=2) is sp  # set() is safe when disabled
    assert obs_trace.spans() == []
    assert obs_trace.snapshot_totals() == {}


def test_stage_timers_mode_accumulates_without_recording(monkeypatch):
    monkeypatch.setenv(obs_trace.STAGE_ENV, "1")
    for _ in range(3):
        with obs_trace.span("round.cost_build"):
            pass
    totals = obs_trace.snapshot_totals()
    assert totals["round.cost_build"][1] == 3
    assert obs_trace.spans() == []  # aggregation only: no span objects


def test_tracing_records_nested_spans(monkeypatch):
    monkeypatch.setenv(obs_trace.TRACE_ENV, "1")
    with obs_trace.span("round", solve_tier="dense") as outer:
        with obs_trace.span("round.solve_band") as inner:
            inner.set(band=0)
    spans = obs_trace.spans()
    by_name = {s["name"]: s for s in spans}
    assert by_name["round.solve_band"]["parent"] == by_name["round"]["id"]
    assert by_name["round"]["parent"] is None
    assert by_name["round"]["attrs"]["solve_tier"] == "dense"
    assert by_name["round.solve_band"]["attrs"]["band"] == 0
    # exceptions annotate the span
    with pytest.raises(ValueError):
        with obs_trace.span("glue.try_round"):
            raise ValueError("boom")
    failed = [s for s in obs_trace.spans() if s["name"] == "glue.try_round"]
    assert failed[0]["attrs"]["error"] == "ValueError"


def test_current_span_attribution(monkeypatch):
    monkeypatch.setenv(obs_trace.TRACE_ENV, "1")
    assert obs_trace.current() is obs_trace.NULL_SPAN
    with obs_trace.span("round"):
        obs_trace.current().set(fresh_compiles=2)
    rec = obs_trace.spans()[-1]
    assert rec["attrs"]["fresh_compiles"] == 2


def test_span_buffer_cap_counts_drops():
    tracer = obs_trace.Tracer(max_spans=2)
    tracer.force = True
    for i in range(5):
        with tracer.span(f"s{i}"):
            pass
    assert len(tracer.spans()) == 2
    assert tracer.dropped == 3
    # aggregates stay honest past the cap
    assert len(tracer.snapshot_totals()) == 5


def test_span_stagetimer_parity_under_concurrent_rounds():
    """Total-time parity: spans and stagetimer totals are two views of
    the same records, and concurrent rounds must not lose time (the
    process-global-dict race this shim replaced)."""
    from poseidon_tpu.utils import stagetimer

    tracer = obs_trace.tracer()
    tracer.force = True
    n_threads, n_rounds = 4, 25

    def one_thread(k: int) -> None:
        for _ in range(n_rounds):
            with stagetimer.stage("round"):
                with stagetimer.stage("round.solve_band"):
                    time.sleep(0.0002)

    threads = [
        threading.Thread(target=one_thread, args=(k,), name=f"w{k}")
        for k in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    snap = stagetimer.snapshot()
    span_view = obs_trace.span_totals(obs_trace.spans())
    expect = n_threads * n_rounds
    for name in ("round", "round.solve_band"):
        assert snap[name][1] == expect, f"{name}: lost stagetimer calls"
        assert span_view[name][1] == expect, f"{name}: lost spans"
        # 5%: the acceptance band for the two views of the same rounds
        assert span_view[name][0] == pytest.approx(
            snap[name][0], rel=0.05
        )


def test_stagetimer_shim_api_preserved(monkeypatch):
    from poseidon_tpu.utils import stagetimer

    assert not stagetimer.enabled()
    monkeypatch.setenv("POSEIDON_STAGE_TIMERS", "1")
    assert stagetimer.enabled()
    with stagetimer.stage("round.mask_build"):
        pass
    assert "round.mask_build" in stagetimer.snapshot()
    assert "round.mask_build" in stagetimer.report()
    stagetimer.reset()
    assert stagetimer.snapshot() == {}


# ------------------------------------------------------------ chrome trace


def test_chrome_trace_export_is_valid_and_nested(monkeypatch, tmp_path):
    monkeypatch.setenv(obs_trace.TRACE_ENV, "1")
    for r in range(2):
        with obs_trace.span("round", round=r):
            with obs_trace.span("round.cost_build"):
                pass
            with obs_trace.span("round.solve_band"):
                with obs_trace.span("solve.device_wait"):
                    pass
    path = tmp_path / "trace.json"
    obj = obs_trace.export_chrome_trace(str(path))
    assert obs_trace.validate_chrome_trace(obj) == []
    on_disk = json.loads(path.read_text())
    assert on_disk["traceEvents"]  # serialized artifact parses back
    events = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    rounds = [e for e in events if e["name"] == "round"]
    assert len(rounds) == 2
    stages = [e for e in events if e["name"].startswith("round.")]
    round_ids = {e["args"]["span_id"] for e in rounds}
    assert all(e["args"]["parent_id"] in round_ids for e in stages)
    # thread metadata lane exists
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in obj["traceEvents"])


def test_chrome_trace_validator_catches_partial_overlap():
    bad = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 0, "dur": 100, "pid": 1, "tid": 1},
        {"name": "b", "ph": "X", "ts": 50, "dur": 100, "pid": 1, "tid": 1},
    ]}
    problems = obs_trace.validate_chrome_trace(bad)
    assert any("partially overlaps" in p for p in problems)
    assert obs_trace.validate_chrome_trace(
        {"traceEvents": [{"name": "a", "ph": "X", "ts": 0, "dur": 1,
                          "pid": 1}]}
    )  # missing tid flags


def test_chrome_trace_validator_cross_lane_overlap_is_legal():
    """The pipelined round's shape: band k's solve on the planner lane
    overlapping band k+1's cost build on the worker lane must validate
    — different lanes, and the worker span's explicit parent (the
    round) contains it in time."""
    good = {"traceEvents": [
        {"name": "round", "ph": "X", "ts": 0, "dur": 1000, "pid": 1,
         "tid": 1, "args": {"span_id": 1}},
        {"name": "round.solve_band", "ph": "X", "ts": 100, "dur": 500,
         "pid": 1, "tid": 1, "args": {"span_id": 2, "parent_id": 1}},
        {"name": "round.cost_build_spec", "ph": "X", "ts": 150,
         "dur": 500, "pid": 1, "tid": 2,
         "args": {"span_id": 3, "parent_id": 1}},
    ]}
    assert obs_trace.validate_chrome_trace(good) == []


def test_chrome_trace_validator_same_lane_overlap_still_fails():
    bad = {"traceEvents": [
        {"name": "round", "ph": "X", "ts": 0, "dur": 1000, "pid": 1,
         "tid": 1, "args": {"span_id": 1}},
        {"name": "round.solve_band", "ph": "X", "ts": 100, "dur": 500,
         "pid": 1, "tid": 1, "args": {"span_id": 2, "parent_id": 1}},
        # Same lane as the solve, partially overlapping: bookkeeping
        # bug, not concurrency.
        {"name": "round.cost_build", "ph": "X", "ts": 400, "dur": 500,
         "pid": 1, "tid": 1, "args": {"span_id": 3, "parent_id": 1}},
    ]}
    problems = obs_trace.validate_chrome_trace(bad)
    assert any("partially overlaps" in p for p in problems)


def test_chrome_trace_validator_child_escaping_parent_fails():
    """A cross-thread child outside its explicit parent's interval is a
    parenting bug even though the lanes differ."""
    bad = {"traceEvents": [
        {"name": "round", "ph": "X", "ts": 0, "dur": 100, "pid": 1,
         "tid": 1, "args": {"span_id": 1}},
        {"name": "round.cost_build_spec", "ph": "X", "ts": 90,
         "dur": 500, "pid": 1, "tid": 2,
         "args": {"span_id": 2, "parent_id": 1}},
    ]}
    problems = obs_trace.validate_chrome_trace(bad)
    assert any("escapes its parent" in p for p in problems)
    # Unknown parent ids are flagged too.
    bad2 = {"traceEvents": [
        {"name": "x", "ph": "X", "ts": 0, "dur": 10, "pid": 1, "tid": 1,
         "args": {"span_id": 7, "parent_id": 99}},
    ]}
    assert any("unknown parent" in p
               for p in obs_trace.validate_chrome_trace(bad2))


def test_chrome_trace_attrs_are_json_safe(monkeypatch):
    monkeypatch.setenv(obs_trace.TRACE_ENV, "1")
    with obs_trace.span("round", obj=object(), ok=True, n=3):
        pass
    obj = obs_trace.chrome_trace(obs_trace.spans())
    json.dumps(obj)  # must not raise
    args = [e for e in obj["traceEvents"] if e["ph"] == "X"][0]["args"]
    assert isinstance(args["obj"], str) and args["n"] == 3


# -------------------------------------------------------------- prometheus


def _lines(text: str):
    return [ln for ln in text.splitlines() if ln]


def test_exposition_format_conformance():
    reg = obs_metrics.Registry()
    c = reg.counter("poseidon_test_total", "helpful\ntext", ("rpc",))
    c.inc(2.5, 'we"ird\\lab\nel')
    g = reg.gauge("poseidon_gauge", "a gauge")
    g.set(-1.5)
    text = reg.expose()
    # HELP newline escaping
    assert '# HELP poseidon_test_total helpful\\ntext' in text
    # label value escaping: backslash, quote, newline
    assert 'rpc="we\\"ird\\\\lab\\nel"' in text
    assert "poseidon_gauge -1.5" in text
    # every sample line parses as <name>{labels}? <value>
    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
        r"(-?[0-9.e+-]+|\+Inf|-Inf|NaN)$"
    )
    for ln in _lines(text):
        if ln.startswith("#"):
            assert ln.startswith("# HELP") or ln.startswith("# TYPE")
        else:
            assert sample_re.match(ln), f"malformed sample line: {ln!r}"


def test_histogram_bucket_monotonicity_and_sum():
    reg = obs_metrics.Registry()
    h = reg.histogram("poseidon_lat_seconds", "latency",
                      buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0, 0.05):
        h.observe(v)
    text = reg.expose()
    buckets = []
    for ln in _lines(text):
        m = re.match(r'poseidon_lat_seconds_bucket\{le="([^"]+)"\} (\d+)',
                     ln)
        if m:
            buckets.append((m.group(1), int(m.group(2))))
    assert [b[0] for b in buckets] == ["0.01", "0.1", "1", "+Inf"]
    counts = [b[1] for b in buckets]
    assert counts == sorted(counts), "buckets must be cumulative"
    assert counts[-1] == 5
    assert "poseidon_lat_seconds_count 5" in text
    m = re.search(r"poseidon_lat_seconds_sum (\S+)", text)
    assert float(m.group(1)) == pytest.approx(5.605)


def test_counter_discipline():
    reg = obs_metrics.Registry()
    c = reg.counter("poseidon_x_total")
    with pytest.raises(ValueError):
        c.inc(-1)
    c.set_total(10.0)
    c.set_total(4.0)  # external regression clamps, never goes back
    assert c.value() == 10.0
    with pytest.raises(ValueError):
        reg.gauge("poseidon_x_total")  # type change is an error
    with pytest.raises(ValueError):
        reg.counter("bad-name")
    with pytest.raises(ValueError):
        reg.counter("ok_total", "", ("bad-label",))


def test_metrics_server_serves_exposition():
    reg = obs_metrics.Registry()
    reg.counter("poseidon_up_total", "updates").inc()
    server = obs_metrics.MetricsServer("127.0.0.1:0", registry=reg).start()
    try:
        base = f"http://{server.address}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            body = resp.read().decode()
            assert resp.headers["Content-Type"] == obs_metrics.CONTENT_TYPE
        assert "poseidon_up_total 1" in body
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            report = json.loads(resp.read())
            assert report["ok"] is True
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        server.stop()


def test_poseidon_serves_metrics_end_to_end():
    """Full wiring: Poseidon(metrics_address=...) starts the exporter,
    a scheduled round feeds the default registry from every layer
    (server-side RoundMetrics, glue LoopStats, client RPC counters),
    and one scrape sees them all."""
    from poseidon_tpu.glue import FakeKube, Node, Pod, Poseidon
    from poseidon_tpu.service.server import FirmamentTPUServer
    from poseidon_tpu.utils.config import PoseidonConfig

    with FirmamentTPUServer(address="127.0.0.1:0") as server:
        kube = FakeKube()
        cfg = PoseidonConfig(
            firmament_address=server.address, scheduling_interval=3600,
            metrics_address="127.0.0.1:0",
        )
        poseidon = Poseidon(kube, config=cfg, run_loop=False)
        poseidon.start(health_timeout=10)
        try:
            assert poseidon.metrics_server is not None
            kube.add_node(
                Node(name="n1", cpu_capacity=4000, ram_capacity=1 << 24)
            )
            kube.create_pod(
                Pod(name="p1", cpu_request=100, ram_request=1 << 20)
            )
            assert poseidon.drain_watchers()
            assert poseidon.try_round() == cfg.scheduling_interval
            url = f"http://{poseidon.metrics_server.address}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                body = resp.read().decode()
        finally:
            poseidon.stop()
    # Series from all three layers land in one exposition (the default
    # registry is process-global, so assert presence, not exact values).
    assert "poseidon_rounds_observed_total" in body       # server feed
    assert "poseidon_loop_rounds_total" in body           # glue feed
    assert 'poseidon_client_rpc_attempts_total{rpc="Schedule"}' in body
    assert 'poseidon_round_solve_tier{tier=' in body


def test_firmament_server_serves_metrics():
    """The SERVICE process exports too: the round metrics and compile
    ledger live server-side, so the deployed two-pod topology scrapes
    both pods (deploy/firmament-tpu-deployment.yaml annotations)."""
    from poseidon_tpu.service.server import FirmamentTPUServer
    from poseidon_tpu.utils.config import FirmamentTPUConfig

    cfg = FirmamentTPUConfig(metrics_address="127.0.0.1:0")
    with FirmamentTPUServer(address="127.0.0.1:0", config=cfg) as server:
        assert server.metrics_server is not None
        base = f"http://{server.metrics_server.address}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            assert json.loads(resp.read())["ok"] is True
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            assert resp.status == 200
    # Context exit stopped the exporter with the gRPC server.
    with pytest.raises(OSError):
        urllib.request.urlopen(f"{base}/healthz", timeout=2)


def test_observe_round_schema_driven():
    from poseidon_tpu.graph.instance import RoundMetrics

    reg = obs_metrics.Registry()
    m = RoundMetrics(round_index=7, solve_seconds=0.25, total_seconds=0.5,
                     placed=42, solve_tier="pruned",
                     gap_bound=float("inf"))
    obs_metrics.observe_round(m, registry=reg)
    obs_metrics.observe_round(m.to_dict(), registry=reg)  # dict feed too
    text = reg.expose()
    assert "poseidon_round_placed 42" in text
    assert "poseidon_round_gap_bound +Inf" in text
    assert 'poseidon_round_solve_tier{tier="pruned"} 1' in text
    assert 'poseidon_round_solve_tier{tier="dense"} 0' in text
    assert "poseidon_rounds_observed_total 2" in text
    assert "poseidon_rounds_placed_total 84" in text
    assert "poseidon_round_duration_seconds_count 2" in text
    # solve_seconds is BOTH a schema gauge and a histogram basis; the
    # names must not collide (the gauge keeps the field name).
    assert "poseidon_round_solve_seconds 0.25" in text
    assert "poseidon_round_solve_duration_seconds_count 2" in text


def test_solve_tier_one_hot_clears_unknown_tiers():
    """A tier name outside SOLVE_TIERS (added to instance.py before the
    exporter's list) must not stay pinned at 1 after later rounds."""
    from poseidon_tpu.graph.instance import RoundMetrics

    reg = obs_metrics.Registry()
    obs_metrics.observe_round(
        RoundMetrics(round_index=0, solve_tier="experimental"), registry=reg
    )
    assert ('poseidon_round_solve_tier{tier="experimental"} 1'
            in reg.expose())
    obs_metrics.observe_round(
        RoundMetrics(round_index=1, solve_tier="dense"), registry=reg
    )
    text = reg.expose()
    assert 'poseidon_round_solve_tier{tier="experimental"} 0' in text
    assert 'poseidon_round_solve_tier{tier="dense"} 1' in text
    ones = re.findall(r'poseidon_round_solve_tier\{[^}]*\} 1\b', text)
    assert len(ones) == 1  # one-hot


def test_observe_loop_and_rpc_counters():
    from poseidon_tpu.glue.poseidon import LoopStats

    reg = obs_metrics.Registry()
    stats = LoopStats()
    stats.rounds, stats.failed_rounds = 5, 2
    stats.consecutive_failures = 2
    obs_metrics.observe_loop(stats, resyncs=3, crash_loop_budget=8,
                             fatal=False, registry=reg)
    obs_metrics.rpc_attempt("Schedule", registry=reg)
    obs_metrics.rpc_error("Schedule", "UNAVAILABLE", retried=True,
                          registry=reg)
    obs_metrics.rpc_error("Schedule", "DEADLINE_EXCEEDED", retried=False,
                          registry=reg)
    obs_metrics.watch_event("pod", "added", registry=reg)
    text = reg.expose()
    assert "poseidon_loop_rounds_total 5" in text
    assert "poseidon_loop_failed_rounds_total 2" in text
    assert "poseidon_watch_resyncs_total 3" in text
    assert "poseidon_loop_consecutive_failures 2" in text
    assert 'poseidon_client_rpc_attempts_total{rpc="Schedule"} 1' in text
    assert ('poseidon_client_rpc_errors_total'
            '{rpc="Schedule",code="UNAVAILABLE"} 1') in text
    assert 'poseidon_client_rpc_retries_total{rpc="Schedule"} 1' in text
    assert 'poseidon_client_rpc_deadline_total{rpc="Schedule"} 1' in text
    assert 'poseidon_watch_events_total{watcher="pod",kind="added"} 1' \
        in text


# ------------------------------------------------------ RoundMetrics wire


def test_round_metrics_round_trip():
    from poseidon_tpu.graph.instance import RoundMetrics

    m = RoundMetrics(round_index=3, num_tasks=10, solve_seconds=1.5,
                     gap_bound=float("inf"), solve_tier="host_greedy",
                     converged=False,
                     overlap_fraction=0.25, admission_deferred=3,
                     admission_staleness_s=0.125,
                     placements_per_sec=42.5)
    d = m.to_dict()
    assert d["schema"] == RoundMetrics.SCHEMA
    assert d["gap_bound"] == "inf"  # JSON-safe
    # The streaming-engine series ride the same wire format.
    assert d["overlap_fraction"] == 0.25
    assert d["admission_deferred"] == 3
    assert d["admission_staleness_s"] == 0.125
    assert d["placements_per_sec"] == 42.5
    wire = json.loads(json.dumps(d))  # survives a real serialization
    m2 = RoundMetrics.from_dict(wire)
    assert m2 == m
    # forward compat: unknown keys drop, missing keys default
    m3 = RoundMetrics.from_dict({"round_index": 9, "future_field": 1})
    assert m3.round_index == 9 and m3.solve_tier == "none"
    with pytest.raises(ValueError):
        RoundMetrics.from_dict({"schema": RoundMetrics.SCHEMA + 1})


def test_soak_metrics_dict_uses_wire_format():
    from poseidon_tpu.chaos.soak import _metrics_dict
    from poseidon_tpu.graph.instance import RoundMetrics

    m = RoundMetrics(round_index=1, gap_bound=float("inf"))
    assert _metrics_dict(m) == m.to_dict()


# --------------------------------------------------------- flight timeline


def test_flight_timeline_rerenders_recorded_round(tmp_path):
    from poseidon_tpu.chaos.plan import named_plan
    from poseidon_tpu.chaos.recorder import FlightRecorder
    from poseidon_tpu.replay.flight import flight_timeline

    plan = named_plan("smoke", 2, seed=0)
    recorder = FlightRecorder({"name": "smoke", "seed": 0},
                              plan, out_dir=str(tmp_path))
    spans = [
        {"name": "round", "ts": 0.0, "dur": 0.5, "tid": 1,
         "tname": "MainThread", "id": 1, "parent": None,
         "attrs": {"solve_tier": "dense"}},
        {"name": "round.solve_band", "ts": 0.1, "dur": 0.3, "tid": 1,
         "tname": "MainThread", "id": 2, "parent": 1, "attrs": {}},
    ]
    recorder.record_round(0, faults=[], deltas=[], metrics={},
                          digest="d0", placements=1, spans=spans)
    path = recorder.record_failure(0, "divergence", "boom")
    out = tmp_path / "timeline.json"
    obj = flight_timeline(path, out_path=str(out))
    assert obs_trace.validate_chrome_trace(obj) == []
    assert obj["flightMeta"]["round"] == 0
    events = [e for e in obj["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in events} == {"round", "round.solve_band"}
    assert json.loads(out.read_text())["flightMeta"]["spans"] == 2
    # An EXPLICITLY requested round that was never recorded raises (the
    # last-completed-round fallback is for the default path only —
    # silently rendering a different round would have the caller
    # debugging the wrong timeline).
    with pytest.raises(ValueError, match="round 5"):
        flight_timeline(path, round_index=5)


# --------------------------------------------------- determinism confinement


def test_obs_clock_reads_confined_to_tracer():
    from poseidon_tpu.check.determinism import DeterminismRule

    rule = DeterminismRule()
    src = "import time\n\ndef f():\n    return time.perf_counter()\n"
    tree = ast.parse(src)
    found = rule.check(tree, src, "poseidon_tpu/obs/metrics.py")
    assert any("clock read" in f.message for f in found)
    assert rule.check(tree, src, "poseidon_tpu/obs/trace.py") == []
    # The exemption is the tracer EXACTLY — a module whose filename
    # merely ends in "trace.py" is still confined.
    found = rule.check(tree, src, "poseidon_tpu/obs/xtrace.py")
    assert any("clock read" in f.message for f in found)
    # outside obs/ the confinement does not apply (perf_counter is the
    # sanctioned telemetry clock there)
    assert rule.check(tree, src, "poseidon_tpu/graph/instance.py") == []
    assert rule.applies_to("poseidon_tpu/obs/metrics.py")


# ---------------------------------------------------------------- perf gate


def _artifact(**over):
    art = {
        "metric": "schedule_round_s", "backend": "cpu",
        "machines": 10_000, "tasks": 100_000,
        "wave_p50_s": 4.0, "churn_p50_s": 0.2, "restart_s": 0.3,
        "cold_s": 7.0,
        "features": {
            "backend": "cpu",
            "selectors": {"round_p50_s": 0.06},
            "pod_affinity": {"round_s": 2.2, "mask_build_s": 0.3,
                             "cost_build_s": 0.4, "solve_s": 1.2,
                             "view_build_s": 0.1},
            "gang": {"round_s": 4.5, "mask_build_s": 0.001,
                     "cost_build_s": 0.5, "solve_s": 3.8,
                     "view_build_s": 0.1},
        },
    }
    art.update(over)
    return art


def test_perf_gate_passes_identical_artifacts():
    res = bench_compare.compare(_artifact(), _artifact())
    assert res["comparable"] and res["regressions"] == []
    names = {r["name"] for r in res["rows"]}
    assert "features.gang.solve_s" in names
    assert "wave_p50_s" in names
    assert "cold_s" not in names  # cache-warmth-dependent; excluded


def test_perf_gate_fails_on_synthetically_slowed_stage():
    slowed = copy.deepcopy(_artifact())
    slowed["features"]["gang"]["solve_s"] *= 2.0
    res = bench_compare.compare(_artifact(), slowed)
    assert res["regressions"] == ["features.gang.solve_s"]
    # ... but a tiny stage doubling under the absolute floor is noise
    noisy = copy.deepcopy(_artifact())
    noisy["features"]["gang"]["mask_build_s"] *= 2.0
    assert bench_compare.compare(_artifact(), noisy)["regressions"] == []


def test_perf_gate_never_compares_apples_to_oranges():
    res = bench_compare.compare(_artifact(), _artifact(backend="tpu"))
    assert not res["comparable"] and "mismatch" in res["reason"]
    res = bench_compare.compare(_artifact(), _artifact(machines=200))
    assert not res["comparable"]
    missing = _artifact()
    del missing["features"]["gang"]
    res = bench_compare.compare(_artifact(), missing)
    assert res["comparable"]
    assert "features.gang.solve_s" in res["skipped"]


def test_perf_gate_refuses_streaming_vs_synchronous():
    """A streaming-engine artifact's throughput series measure a
    continuously-overlapped loop — never diffable against a round-
    synchronous baseline's numbers (mirrors the solver-tier guard)."""
    stream = _artifact(
        mode="streaming",
        throughput={"mode": "streaming", "placements_per_sec": 300.0},
    )
    res = bench_compare.compare(_artifact(), stream)
    assert not res["comparable"]
    assert "mode mismatch" in res["reason"]
    # Artifacts predating the marker default to synchronous.
    res = bench_compare.compare(stream, _artifact())
    assert not res["comparable"]


def test_perf_gate_throughput_series_direction():
    """placements_per_sec gates INVERTED relative to the timing rows:
    regression when the current run places SLOWER than baseline."""
    base = _artifact(
        mode="streaming",
        throughput={"mode": "streaming", "placements_per_sec": 300.0},
    )
    same = copy.deepcopy(base)
    res = bench_compare.compare(base, same)
    assert res["comparable"] and res["regressions"] == []
    assert "throughput.placements_per_sec" in {r["name"] for r in res["rows"]}

    slower = copy.deepcopy(base)
    slower["throughput"]["placements_per_sec"] = 100.0
    res = bench_compare.compare(base, slower)
    assert res["regressions"] == ["throughput.placements_per_sec"]

    faster = copy.deepcopy(base)
    faster["throughput"]["placements_per_sec"] = 900.0
    res = bench_compare.compare(base, faster)
    assert res["regressions"] == []


def test_perf_gate_cli_exit_codes(tmp_path, capsys):
    base, cur = tmp_path / "base.json", tmp_path / "cur.json"
    base.write_text(json.dumps(_artifact()))
    slowed = copy.deepcopy(_artifact())
    slowed["features"]["gang"]["solve_s"] *= 2.0
    cur.write_text(json.dumps(slowed))
    argv = ["--baseline", str(base), "--current", str(cur)]
    assert bench_compare.main(argv) == 1
    assert bench_compare.main(argv + ["--warn-only"]) == 0
    assert "regression" in capsys.readouterr().out
    # missing current artifact: 2 strict, 0 warn-only
    gone = ["--baseline", str(base), "--current", str(tmp_path / "nope")]
    assert bench_compare.main(gone) == 2
    assert bench_compare.main(gone + ["--warn-only"]) == 0
    # jsonl stream: the LAST parseable line wins
    stream = tmp_path / "cur.jsonl"
    stream.write_text(
        json.dumps(_artifact(wave_p50_s=99.0)) + "\n"
        + "not json\n" + json.dumps(_artifact()) + "\n"
    )
    assert bench_compare.main(
        ["--baseline", str(base), "--current", str(stream)]) == 0


def test_perf_gate_reads_committed_baselines():
    """The default baseline chain (the Makefile's PERF_BASELINES) must
    yield a parseable artifact from the repo as committed, and the
    winning baseline must carry the per-stage features series — without
    them every stage comparison lands in 'skipped' and the per-stage
    gate is vacuous."""
    art, path = bench_compare.first_artifact(
        [str(REPO / "docs" / "bench_r06_baseline.json"),
         str(REPO / "docs" / "bench_r05_final.json")]
    )
    assert art is not None and "features" in art, path
    timings = bench_compare.collect_timings(art)
    for stage in ("mask_build_s", "cost_build_s", "solve_s",
                  "view_build_s"):
        assert f"features.pod_affinity.{stage}" in timings
        assert f"features.gang.{stage}" in timings


# ------------------------------------------------------- trace smoke logic


def test_trace_smoke_validators():
    import trace_smoke

    spans = [
        {"name": "round", "ts": 0.0, "dur": 1.0, "tid": 1, "tname": "t",
         "id": 1, "parent": None, "attrs": {}},
    ]
    for i, stage in enumerate(trace_smoke.STAGES):
        spans.append({"name": stage, "ts": 0.1 * (i + 1), "dur": 0.05,
                      "tid": 1, "tname": "t", "id": i + 2, "parent": 1,
                      "attrs": {}})
    problems = []
    trace_smoke.validate_round_decomposition(spans, problems)
    assert problems == []
    snapshot = {s["name"]: (s["dur"], 1) for s in spans}
    trace_smoke.validate_stagetimer_parity(spans, snapshot, problems)
    assert problems == []
    # drifted totals are caught
    bad_snapshot = dict(snapshot)
    bad_snapshot["round.solve_band"] = (0.5, 1)
    trace_smoke.validate_stagetimer_parity(spans, bad_snapshot, problems)
    assert problems
    # a stage outside its round flags
    orphan = [dict(spans[0]), dict(spans[1])]
    orphan[1]["parent"] = None
    probs2 = []
    trace_smoke.validate_round_decomposition(orphan, probs2)
    assert probs2


# ------------------------------------------------- counter tracks (PR 13)


def test_counter_series_exports_and_validates(monkeypatch, tmp_path):
    monkeypatch.setenv(obs_trace.TRACE_ENV, "1")
    tracer = obs_trace.tracer()
    t0 = tracer._epoch + 1.0
    with obs_trace.span("round"):
        obs_trace.counter_series(
            "conv.active_excess", t0, t0 + 0.5, [100, 50, 25, 0]
        )
        obs_trace.counter("conv.active_rows", 7, ts=t0 + 0.1)
    obj = obs_trace.export_chrome_trace(str(tmp_path / "t.json"))
    assert obs_trace.validate_chrome_trace(obj) == []
    tracks = obs_trace.counter_tracks(obj)
    assert tracks["conv.active_excess"] == 4
    assert tracks["conv.active_rows"] == 1
    # samples land inside the window, evenly spread, values intact
    c_events = [e for e in obj["traceEvents"]
                if e.get("ph") == "C" and e["name"] == "conv.active_excess"]
    assert [e["args"]["value"] for e in c_events] == [100.0, 50.0, 25.0, 0.0]
    ts = [e["ts"] for e in c_events]
    assert ts == sorted(ts) and ts[-1] - ts[0] == pytest.approx(5e5, rel=0.01)


def test_counter_recording_gated_on_tracing():
    obs_trace.counter("conv.x", 1.0)
    obs_trace.counter_series("conv.y", 0.0, 1.0, [1, 2])
    assert obs_trace.counter_samples() == []


def test_counter_validator_catches_malformed_events():
    obj = {"traceEvents": [
        {"name": "c", "ph": "C", "ts": 1, "pid": 1, "args": {"value": 1}},
        {"ph": "C", "ts": 1, "pid": 1, "args": {"value": 1}},        # no name
        {"name": "c", "ph": "C", "ts": 0.5, "pid": 1,
         "args": {"value": 1}},                                      # float ts
        {"name": "c", "ph": "C", "ts": 1, "pid": 1, "args": {}},     # empty
        {"name": "c", "ph": "C", "ts": 1, "pid": 1,
         "args": {"value": "hi"}},                                   # non-num
    ]}
    problems = obs_trace.validate_chrome_trace(obj)
    assert len(problems) == 4


def test_drain_counter_samples_clears_buffer(monkeypatch):
    monkeypatch.setenv(obs_trace.TRACE_ENV, "1")
    obs_trace.counter("conv.z", 3.0)
    drained = obs_trace.drain_counter_samples()
    assert [d["value"] for d in drained] == [3.0]
    assert obs_trace.counter_samples() == []


def test_flight_timeline_carries_counters(tmp_path):
    from poseidon_tpu.chaos.plan import named_plan
    from poseidon_tpu.chaos.recorder import FlightRecorder
    from poseidon_tpu.replay.flight import flight_timeline

    plan = named_plan("smoke", 2, seed=0)
    recorder = FlightRecorder({"name": "smoke", "seed": 0},
                              plan, out_dir=str(tmp_path))
    spans = [{"name": "round", "ts": 0.0, "dur": 0.5, "tid": 1,
              "tname": "MainThread", "id": 1, "parent": None, "attrs": {}}]
    counters = [{"name": "conv.active_excess", "ts": 0.1, "value": 42.0}]
    recorder.record_round(0, faults=[], deltas=[], metrics={},
                          digest="d0", placements=1, spans=spans,
                          counters=counters)
    path = recorder.record_failure(0, "divergence", "boom")
    obj = flight_timeline(path)
    assert obs_trace.validate_chrome_trace(obj) == []
    assert obs_trace.counter_tracks(obj) == {"conv.active_excess": 1}
    assert obj["flightMeta"]["counters"] == 1


# ------------------------------------------- healthz + /debug introspection


def test_healthz_liveness_report(monkeypatch):
    from poseidon_tpu.glue.poseidon import LoopStats
    from poseidon_tpu.obs.history import default_history

    obs_metrics._reset_health()
    # The idle report must not fall back to rounds an earlier test's
    # planner recorded into the process-global history ring.
    default_history().clear()
    reg = obs_metrics.Registry()
    server = obs_metrics.MetricsServer("127.0.0.1:0", registry=reg).start()
    try:
        base = f"http://{server.address}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            idle = json.loads(resp.read())
        assert idle["ok"] is True and idle["last_round_age_s"] is None

        from poseidon_tpu.graph.instance import RoundMetrics

        obs_metrics.observe_round(RoundMetrics(round_index=5), registry=reg)
        stats = LoopStats(rounds=2, consecutive_failures=1)
        obs_metrics.observe_loop(stats, resyncs=3, crash_loop_budget=4,
                                 registry=reg)
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            live = json.loads(resp.read())
        assert live["last_round_index"] == 5
        assert live["last_round_age_s"] is not None
        assert live["consecutive_failures"] == 1
        assert live["crash_loop_budget"] == 4
        assert live["resyncs"] == 3

        # Watcher ingest liveness: before any watch event the age is
        # null (the wedge gate is unarmed — a cluster with no churn is
        # healthy); after one it is a real age.
        assert live["last_ingest_age_s"] is None
        obs_metrics.watch_event("pod", "ADDED", registry=reg)
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            ingested = json.loads(resp.read())
        assert ingested["ok"] is True
        assert ingested["last_ingest_age_s"] is not None

        # Streaming mode + stalled ingest -> 503 with the stall marker
        # (the loop itself is fine — speculative rounds still complete —
        # but a wedged watcher thread means the world is going stale).
        monkeypatch.setenv("POSEIDON_STREAMING", "1")
        monkeypatch.setenv("POSEIDON_INGEST_STALL_S", "0.000001")
        time.sleep(0.01)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/healthz", timeout=5)
        assert exc.value.code == 503
        stalled = json.loads(exc.value.read())
        assert stalled["ingest_stalled"] is True
        assert stalled["loop_fatal"] is False
        # Synchronous mode never trips the gate: the round loop's own
        # drain_watchers barrier bounds staleness there.
        monkeypatch.delenv("POSEIDON_STREAMING")
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            assert json.loads(resp.read())["ok"] is True
        monkeypatch.delenv("POSEIDON_INGEST_STALL_S")

        # A fatal loop stop fails liveness with 503.
        obs_metrics.observe_loop(stats, resyncs=3, crash_loop_budget=4,
                                 fatal=True, registry=reg)
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/healthz", timeout=5)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["loop_fatal"] is True
    finally:
        server.stop()
        obs_metrics._reset_health()


def test_debug_round_history_endpoints():
    from poseidon_tpu.obs.history import RoundHistory

    hist = RoundHistory(capacity=2)
    hist.record({"round_index": 0, "solve_tier": "dense", "placed": 3},
                curves=[{"band": 0, "samples": 10}])
    hist.record({"round_index": 1, "solve_tier": "quiet"})
    hist.record({"round_index": 2, "solve_tier": "pruned"})  # evicts 0
    server = obs_metrics.MetricsServer(
        "127.0.0.1:0", registry=obs_metrics.Registry(), history=hist,
    ).start()
    try:
        base = f"http://{server.address}"
        with urllib.request.urlopen(f"{base}/debug/rounds", timeout=5) as r:
            listing = json.loads(r.read())
        assert listing["capacity"] == 2 and listing["retained"] == 2
        assert [s["round"] for s in listing["rounds"]] == [1, 2]
        with urllib.request.urlopen(f"{base}/debug/round/2", timeout=5) as r:
            rec = json.loads(r.read())
        assert rec["metrics"]["solve_tier"] == "pruned"
        # Evicted and never-recorded rounds 404 with the retained range.
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/debug/round/0", timeout=5)
        assert exc.value.code == 404
        assert json.loads(exc.value.read())["retained_range"] == [1, 2]
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/debug/round/xyz", timeout=5)
        assert exc.value.code == 400
        # /healthz on the SAME server consults the SAME history ring
        # (its idle fallback must not read the process-global default —
        # the two endpoints would disagree about liveness).
        obs_metrics._reset_health()
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            health = json.loads(r.read())
        assert health["last_round_index"] == 2
        assert health["last_round_age_s"] is not None
    finally:
        server.stop()
        obs_metrics._reset_health()


def test_round_history_ring_and_summaries():
    from poseidon_tpu.obs.history import RoundHistory

    hist = RoundHistory(capacity=3)
    for i in range(5):
        hist.record({"round_index": i, "placed": i * 10,
                     "telem_samples": i})
    assert len(hist) == 3
    assert hist.retained_range() == (2, 4)
    assert hist.get(0) is None
    rec = hist.get(4)
    assert rec["metrics"]["placed"] == 40 and rec["age_s"] >= 0
    tops = hist.summaries()
    assert [s["round"] for s in tops] == [2, 3, 4]
    assert all("age_s" in s for s in tops)
    # capacity 0 disables recording entirely
    off = RoundHistory(capacity=0)
    off.record({"round_index": 1})
    assert len(off) == 0


# ------------------------------------- telemetry fields on the wire format


def test_observe_round_tolerates_schema_unknown_keys():
    reg = obs_metrics.Registry()
    d = {
        "round_index": 1, "solve_tier": "dense", "placed": 2,
        "schema": 1,
        "future_numeric": 17,          # unknown numeric -> gauge anyway
        "future_text": "whatever",     # unknown non-numeric -> skipped
        "future_list": [1, 2, 3],      # lists never become gauges
    }
    obs_metrics.observe_round(d, registry=reg)
    text = reg.expose()
    assert "poseidon_round_future_numeric 17" in text
    assert "future_text" not in text
    assert "future_list" not in text
    assert "poseidon_round_placed 2" in text


def test_telemetry_fields_ride_wire_exporter_and_recorder(tmp_path):
    from poseidon_tpu.chaos.plan import named_plan
    from poseidon_tpu.chaos.recorder import FlightRecorder
    from poseidon_tpu.graph.instance import RoundMetrics

    m = RoundMetrics(round_index=4, telem_samples=120, telem_gu_firings=30,
                     telem_decay_half_life=12.5, telem_iters_to_90=88)
    d = m.to_dict()
    # wire round-trip
    m2 = RoundMetrics.from_dict(json.loads(json.dumps(d)))
    assert m2 == m
    # exporter: the schema walk turns every telem scalar into a gauge
    reg = obs_metrics.Registry()
    obs_metrics.observe_round(m, registry=reg)
    text = reg.expose()
    assert "poseidon_round_telem_samples 120" in text
    assert "poseidon_round_telem_gu_firings 30" in text
    assert "poseidon_round_telem_decay_half_life 12.5" in text
    assert "poseidon_round_telem_iters_to_90 88" in text
    # flight recorder: the dict lands verbatim in the round record
    plan = named_plan("smoke", 1, seed=0)
    recorder = FlightRecorder({"name": "smoke", "seed": 0},
                              plan, out_dir=str(tmp_path))
    recorder.record_round(4, faults=[], deltas=[], metrics=d,
                          digest="dd", placements=0)
    path = recorder.record_failure(4, "kind", "detail")
    from poseidon_tpu.replay.flight import load_flight

    trace = load_flight(path)
    got = trace.rounds[-1]["metrics"]
    assert got["telem_samples"] == 120
    assert got["telem_iters_to_90"] == 88
    assert RoundMetrics.from_dict(got) == m
