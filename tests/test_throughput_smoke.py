"""Streaming-throughput smoke (``make throughput-smoke``).

A tiny fixed-duration run of ``bench.run_throughput`` — the sustained-
throughput rung's child — through the FULL stack (fake kube, watchers,
gRPC service, streaming glue loop): placements/sec must be positive in
both modes, the fixed-round identity legs must produce byte-identical
placement digests streaming-vs-synchronous, and the warm windows of
both duration legs must compile nothing fresh.

Slow-marked: excluded from the tier-1 gate, run via
``make throughput-smoke`` (wired into ``make verify``) or
``pytest -m slow``.
"""

import pytest

pytestmark = pytest.mark.slow


def test_throughput_rung_smoke():
    import bench

    out = bench.run_throughput(machines=48, seconds=3.0, seed=0)
    assert out["ok"], out.get("error", out)

    # The identity legs: 6 per-round-drained rounds, streaming and
    # synchronous kube truth byte-identical round for round.
    assert out["identity_ok"], out.get("error")
    assert out["identity_rounds"] == 6

    # The duration legs actually moved work in both modes.
    assert out["placements_per_sec"] > 0
    assert out["placements_per_sec_sync"] > 0
    assert out["streaming"]["rounds"] > 0
    assert out["synchronous"]["rounds"] > 0

    # Warm overlapped rounds stay inside the compile discipline: the
    # session marks warm at round 2 and counts fresh compiles after.
    assert out["streaming"]["warm_fresh_compiles"] == 0
    assert out["synchronous"]["warm_fresh_compiles"] == 0

    # The artifact self-identifies as a streaming-mode measurement so
    # tools/bench_compare.py can refuse apples-to-oranges diffs.
    assert out["mode"] == "streaming"

    # Overlap is only ever realized by the streaming engine — the
    # synchronous legs must report none (the fraction itself is
    # hardware-dependent, so no floor is asserted here; PERF.md carries
    # the honest measured numbers).
    assert out["synchronous"]["overlap_fraction_mean"] == 0.0
