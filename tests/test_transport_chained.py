"""Chained single-dispatch two-band wave vs the per-band host path.

Integer surfaces (placements, feasibility, convergence) must agree
exactly; objectives may differ by at most one normalized-cost unit per
placed task (band 2's costs are built in float32 on device vs float64
on host — see costmodel/device_build.py)."""

import numpy as np

from poseidon_tpu.costmodel.cpu_mem import CpuMemCostModel
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import generate_uuid, task_uid


def _mixed_state(machines=260, big=20, small=500, cpu_cap=64000):
    st = ClusterState()
    for i in range(machines):
        st.node_added(MachineInfo(
            uuid=generate_uuid(f"ch{i}"), cpu_capacity=cpu_cap,
            ram_capacity=1 << 26, task_slots=48,
        ))
    for i in range(big):
        st.task_submitted(TaskInfo(
            uid=task_uid("big", i), job_id="big",
            cpu_request=8000, ram_request=1 << 22,
        ))
    for i in range(small):
        st.task_submitted(TaskInfo(
            uid=task_uid("small", i), job_id="small",
            cpu_request=150 + 10 * (i % 7), ram_request=1 << 18,
        ))
    return st


def _round(monkeypatch, chained):
    monkeypatch.setenv("POSEIDON_CHAINED", "1" if chained else "0")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    return st, planner, deltas, m


def test_chained_matches_per_band(monkeypatch):
    st_a, _, deltas_a, m_a = _round(monkeypatch, chained=False)
    st_b, _, deltas_b, m_b = _round(monkeypatch, chained=True)

    assert m_b.converged and m_a.converged
    assert m_b.gap_bound == 0.0
    assert m_b.placed == m_a.placed == 520
    assert m_b.unscheduled == m_a.unscheduled == 0
    # One dispatch for the whole round (the chained program), vs >= 2.
    assert m_b.device_calls == 1
    assert m_a.device_calls >= 2
    # Objective: within one cost unit per placed task (float32 band-2
    # cost build), and typically equal.
    assert abs(m_b.objective - m_a.objective) <= m_b.placed


def test_chained_declines_with_gangs(monkeypatch):
    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    st = _mixed_state(big=6, small=300)
    for i in range(4):
        st.task_submitted(TaskInfo(
            uid=task_uid("gang", i), job_id="gangjob",
            cpu_request=2000, ram_request=1 << 20, gang=True,
            labels={"gangScheduling": "true"},
        ))
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    # Gated off: the per-band path runs (>= 2 dispatches) and the gang
    # places atomically.
    assert m.device_calls >= 2
    assert m.converged


def test_chained_warm_frames_route_next_round(monkeypatch):
    """After a chained round, the saved warm frames must be usable by
    the NORMAL path on the next (churn-free) round: same placements,
    zero additional iterations."""
    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    _, m1 = planner.schedule_round()
    assert m1.converged and m1.device_calls == 1
    # Quiet round: nothing changed.
    _, m2 = planner.schedule_round()
    assert m2.iterations == 0


def test_chained_dispatch_failure_declines(monkeypatch):
    """A backend failure inside the chained dispatch (tunnel flake,
    remote-compile restart) must DECLINE to the per-band path — never
    fail the scheduling round."""
    import poseidon_tpu.ops.transport_chained as TC

    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")

    def boom(*a, **k):
        raise RuntimeError("UNAVAILABLE: remote_compile: Connection refused")

    monkeypatch.setattr(TC, "_chained_wave_device", boom)
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    # The per-band path completed the round.
    assert m.converged and m.placed == 520
    assert m.device_calls >= 3  # chained counter + per-band dispatches


def test_chained_late_decline_discards_speculative_assignment(monkeypatch):
    """A decline AFTER the early band-1 assignment fired (non-converged
    band, failed costs2 fetch) must discard the speculative chunk: the
    per-band re-solve owns the round, with no duplicated deltas or
    double-counted metrics."""
    import poseidon_tpu.ops.transport_chained as TC

    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")

    def fake_solve(costs1, supply1, col_cap1, unsched1, arc1, rc, rr,
                   ops2, supply2, *, early=None, **kw):
        if early is not None:
            early(np.zeros_like(costs1))  # speculative, then decline
        return None

    monkeypatch.setattr(TC, "solve_wave_chained", fake_solve)
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    assert m.converged
    assert m.placed == 520  # not 520 + the discarded chunk's count
    placed_uids = [d.task_id for d in deltas
                   if d.type == d.type.__class__.PLACE]
    assert len(placed_uids) == len(set(placed_uids)) == 520
