"""Chained single-dispatch two-band wave vs the per-band host path.

Integer surfaces (placements, feasibility, convergence) must agree
exactly; objectives may differ by at most one normalized-cost unit per
placed task (band 2's costs are built in float32 on device vs float64
on host — see costmodel/device_build.py)."""

import numpy as np

from poseidon_tpu.costmodel.cpu_mem import CpuMemCostModel
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.utils.ids import generate_uuid, task_uid


def _mixed_state(machines=260, big=20, small=500, cpu_cap=64000):
    st = ClusterState()
    for i in range(machines):
        st.node_added(MachineInfo(
            uuid=generate_uuid(f"ch{i}"), cpu_capacity=cpu_cap,
            ram_capacity=1 << 26, task_slots=48,
        ))
    for i in range(big):
        st.task_submitted(TaskInfo(
            uid=task_uid("big", i), job_id="big",
            cpu_request=8000, ram_request=1 << 22,
        ))
    for i in range(small):
        st.task_submitted(TaskInfo(
            uid=task_uid("small", i), job_id="small",
            cpu_request=150 + 10 * (i % 7), ram_request=1 << 18,
        ))
    return st


def _round(monkeypatch, chained):
    monkeypatch.setenv("POSEIDON_CHAINED", "1" if chained else "0")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    return st, planner, deltas, m


def test_chained_matches_per_band(monkeypatch):
    st_a, _, deltas_a, m_a = _round(monkeypatch, chained=False)
    st_b, _, deltas_b, m_b = _round(monkeypatch, chained=True)

    assert m_b.converged and m_a.converged
    assert m_b.gap_bound == 0.0
    assert m_b.placed == m_a.placed == 520
    assert m_b.unscheduled == m_a.unscheduled == 0
    # One dispatch for the whole round (the chained program), vs >= 2.
    assert m_b.device_calls == 1
    assert m_a.device_calls >= 2
    # Objective: within one cost unit per placed task (float32 band-2
    # cost build), and typically equal.
    assert abs(m_b.objective - m_a.objective) <= m_b.placed


def test_chained_declines_with_gangs(monkeypatch):
    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    st = _mixed_state(big=6, small=300)
    for i in range(4):
        st.task_submitted(TaskInfo(
            uid=task_uid("gang", i), job_id="gangjob",
            cpu_request=2000, ram_request=1 << 20, gang=True,
            labels={"gangScheduling": "true"},
        ))
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    # Gated off: the per-band path runs (>= 2 dispatches) and the gang
    # places atomically.
    assert m.device_calls >= 2
    assert m.converged


def test_chained_warm_frames_route_next_round(monkeypatch):
    """After a chained round, the saved warm frames must be usable by
    the NORMAL path on the next (churn-free) round: same placements,
    zero additional iterations."""
    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    _, m1 = planner.schedule_round()
    assert m1.converged and m1.device_calls == 1
    # Quiet round: nothing changed.
    _, m2 = planner.schedule_round()
    assert m2.iterations == 0


def test_chained_dispatch_failure_declines(monkeypatch):
    """A backend failure inside the chained dispatch (tunnel flake,
    remote-compile restart) must DECLINE to the per-band path — never
    fail the scheduling round."""
    import poseidon_tpu.ops.transport_chained as TC

    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")

    def boom(*a, **k):
        raise RuntimeError("UNAVAILABLE: remote_compile: Connection refused")

    monkeypatch.setattr(TC, "_chained_wave_device", boom)
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    # The per-band path completed the round.
    assert m.converged and m.placed == 520
    assert m.device_calls >= 3  # chained counter + per-band dispatches


def test_chained_late_decline_discards_speculative_assignment(monkeypatch):
    """A decline AFTER the early band-1 assignment fired (non-converged
    band, failed costs2 fetch) must discard the speculative chunk: the
    per-band re-solve owns the round, with no duplicated deltas or
    double-counted metrics."""
    import poseidon_tpu.ops.transport_chained as TC

    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")

    def fake_solve(costs1, supply1, col_cap1, unsched1, arc1, rc, rr,
                   ops2, supply2, *, early=None, **kw):
        if early is not None:
            early(np.zeros_like(costs1))  # speculative, then decline
        return None

    monkeypatch.setattr(TC, "solve_wave_chained", fake_solve)
    st = _mixed_state()
    planner = RoundPlanner(st, CpuMemCostModel())
    deltas, m = planner.schedule_round()
    assert m.converged
    assert m.placed == 520  # not 520 + the discarded chunk's count
    placed_uids = [d.task_id for d in deltas
                   if d.type == d.type.__class__.PLACE]
    assert len(placed_uids) == len(set(placed_uids)) == 520


def test_chained_scale_covers_band2_heavy_waves(monkeypatch):
    """Regression (ADVICE r05): the shared scale must derive from the
    LARGER band's row padding.  E2 >> E1 at an exact padding-bucket M
    (320 = 256 * 1.25, so m_pad == M): with the old e1_pad-only
    derivation, scale = 332 < E2 + M + 3 = 371 and band 2's exactness
    certificate could never reach gap_bound == 0 — the chain paid its
    dispatch and then silently declined every fresh wave."""
    monkeypatch.setenv("POSEIDON_CHAINED", "1")
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    st = ClusterState()
    for i in range(320):
        st.node_added(MachineInfo(
            uuid=generate_uuid(f"sc{i}"), cpu_capacity=64000,
            ram_capacity=1 << 26, task_slots=48,
        ))
    # Band 1: two EC rows.  Band 2: 48 distinct rows (e2_pad = 64).
    for e in range(2):
        for i in range(3):
            st.task_submitted(TaskInfo(
                uid=task_uid(f"big{e}", i), job_id=f"big{e}",
                cpu_request=6000 + 1000 * e, ram_request=1 << 22,
            ))
    for e in range(48):
        for i in range(4):
            st.task_submitted(TaskInfo(
                uid=task_uid(f"small{e}", i), job_id=f"small{e}",
                cpu_request=150 + 10 * e, ram_request=1 << 18,
            ))
    planner = RoundPlanner(st, CpuMemCostModel())
    _, m = planner.schedule_round()
    # The chained program owned the round: ONE dispatch, certified.
    assert m.device_calls == 1
    assert m.converged and m.gap_bound == 0.0
    assert m.placed == 2 * 3 + 48 * 4
    assert m.unscheduled == 0


def test_chained_declines_on_band2_flow_mass_overflow():
    """Regression (ADVICE r05): band-2 validation must use the REAL
    (unclipped) slot capacities — an instance whose slot sum breaks
    int32 flow arithmetic declines loudly BEFORE any dispatch instead
    of validating a silently clipped bound and wasting the dispatch."""
    import poseidon_tpu.ops.transport_chained as TC
    from poseidon_tpu.costmodel.base import ECTable, MachineTable
    from poseidon_tpu.costmodel.device_build import extract_band_operands
    from poseidon_tpu.ops.transport import _Telemetry

    M = 600
    mt = MachineTable(
        uuids=[f"fm{i}" for i in range(M)],
        cpu_capacity=np.full(M, 64000, dtype=np.int64),
        ram_capacity=np.full(M, 1 << 26, dtype=np.int64),
        cpu_used=np.zeros(M, dtype=np.int64),
        ram_used=np.zeros(M, dtype=np.int64),
        cpu_util=np.zeros(M, dtype=np.float32),
        mem_util=np.zeros(M, dtype=np.float32),
        # 600 x 2^22 slots: sum ~2.5e9 >= 2^31.
        slots_free=np.full(M, 1 << 22, dtype=np.int32),
        labels=[{} for _ in range(M)],
    )
    ecs2 = ECTable(
        ec_ids=np.array([1], dtype=np.uint64),
        cpu_request=np.array([100], dtype=np.int64),
        ram_request=np.array([1 << 18], dtype=np.int64),
        supply=np.array([2], dtype=np.int32),
        priority=np.zeros(1, dtype=np.int32),
        task_type=np.zeros(1, dtype=np.int32),
        max_wait_rounds=np.zeros(1, dtype=np.int32),
        selectors=[()],
    )
    model = CpuMemCostModel()
    ops2 = extract_band_operands(ecs2, mt, model)
    calls0 = _Telemetry.device_calls
    out = TC.solve_wave_chained(
        np.ones((1, M), dtype=np.int32),
        np.array([2], dtype=np.int32),
        np.ones(M, dtype=np.int32),
        np.array([100], dtype=np.int32),
        None,
        np.array([6000], dtype=np.int32),
        np.array([1 << 12], dtype=np.int32),
        ops2, np.asarray(ecs2.supply),
        max_cost_hint=model.max_cost(),
    )
    assert out is None
    assert _Telemetry.device_calls == calls0  # declined pre-dispatch
