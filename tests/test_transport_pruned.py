"""Pruned-plane solver path (ops/transport_pruned).

Randomized parity of the shortlist + price-out driver against the dense
solve and the exact host oracle, the engineered price-out escalation, the
gate's decline conditions, and end-to-end planner parity with the pruned
path forced on vs off.
"""

import numpy as np
import pytest

from poseidon_tpu.ops import transport_pruned as tp
from poseidon_tpu.ops.transport import (
    INF_COST,
    derive_scale,
    padded_shape,
    solve_transport,
)
from poseidon_tpu.solver.oracle import transport_objective


def run_pruned(costs, supply, capacity, unsched_cost, arc_capacity=None,
               plan_kw=None, **driver_kw):
    """Drive solve_pruned with a plain solve_transport closure.

    The planner drives the same loop with its full per-band pipeline
    (coarse start, gang repair); the certificate contract is identical,
    so solver-level parity transfers.
    """
    costs = np.asarray(costs, dtype=np.int32)
    E, M = costs.shape
    scale, _ = derive_scale(costs, unsched_cost, None, *padded_shape(E, M))

    def solve_on(sel, warm):
        p = f = u = eps = None
        if warm is not None and warm[0] is not None:
            p, f, u, eps = warm
        sol = solve_transport(
            costs[:, sel], supply, capacity[sel], unsched_cost, p,
            arc_capacity=(
                arc_capacity[:, sel] if arc_capacity is not None else None
            ),
            init_flows=f, init_unsched=u, eps_start=eps, scale=scale,
        )
        return sol, costs[:, sel]

    kw = dict(min_rows=2, min_cols=16)
    kw.update(plan_kw or {})
    return tp.solve_pruned(
        costs, supply, capacity, unsched_cost, arc_capacity=arc_capacity,
        scale=scale, solve_on=solve_on, plan_kw=kw, **driver_kw,
    )


def assert_feasible(sol, costs, supply, capacity, arc_capacity=None):
    assert (sol.flows >= 0).all()
    assert (sol.flows.sum(axis=1) + sol.unsched == supply).all()
    assert (sol.flows.sum(axis=0) <= capacity).all()
    assert not sol.flows[costs >= INF_COST].any()
    if arc_capacity is not None:
        assert (sol.flows <= arc_capacity).all()


def fuzz_instance(rng):
    E = int(rng.integers(4, 11))
    M = int(rng.integers(192, 320))
    costs = rng.integers(1, 400, size=(E, M)).astype(np.int32)
    density = float(rng.choice([1.0, 0.9, 0.7]))
    if density < 1.0:
        knock = rng.random((E, M)) > density
        costs = np.where(knock, INF_COST, costs).astype(np.int32)
    supply = rng.integers(1, 9, size=E).astype(np.int32)
    capacity = rng.integers(1, 5, size=M).astype(np.int32)
    # Generous slack so the shortlist gate fires and certificates
    # typically accept (contention-driven escalations are exercised
    # separately below).
    while int(capacity.sum()) < 6 * int(supply.sum()):
        capacity = (capacity * 2).astype(np.int32)
    arc = None
    if rng.random() < 0.5:
        arc = rng.integers(1, 6, size=(E, M)).astype(np.int32)
    unsched = np.full(E, 600, dtype=np.int32)
    return costs, supply, capacity, unsched, arc


def test_pruned_parity_fuzz_vs_dense_and_oracle():
    accepted = 0
    for seed in range(8):
        rng = np.random.default_rng(seed)
        costs, supply, capacity, unsched, arc = fuzz_instance(rng)
        sol, eff, stats = run_pruned(
            costs, supply, capacity, unsched, arc_capacity=arc,
            plan_kw=dict(dense_factor=100),
        )
        dense = solve_transport(costs, supply, capacity, unsched,
                                arc_capacity=arc)
        oracle = transport_objective(costs, supply, capacity, unsched,
                                     arc_capacity=arc)
        assert dense.objective == oracle, f"seed {seed}: dense vs oracle"
        if sol is None:
            # The driver may legitimately decline (union too wide for
            # the plane) or escalate; the planner then solves dense.
            # Either way it must say so.
            assert stats["escalated"] or stats["declined"], (
                f"seed {seed}: None without a reason"
            )
            continue
        accepted += 1
        assert_feasible(sol, costs, supply, capacity, arc)
        assert sol.objective == oracle, (
            f"seed {seed}: pruned {sol.objective} != oracle {oracle} "
            f"(stats {stats})"
        )
        assert sol.gap_bound == 0.0
    # The accept path must be the norm on slack-rich fuzz, or the suite
    # is only testing the escalation fallback.
    assert accepted >= 5, f"only {accepted}/8 fuzz instances accepted"


def _escalation_instance():
    """Engineered to force a price-out round: the shortlist sizes itself
    on COLUMN capacity, but every column it selects is arc-blocked for
    every row, so the reduced optimum strands all supply on the fallback
    while cheaper open columns sit just outside the union."""
    E, M = 4, 128
    costs = np.broadcast_to(
        np.arange(M, dtype=np.int32), (E, M)
    ).copy()
    supply = np.full(E, 8, dtype=np.int32)
    capacity = np.full(M, 2, dtype=np.int32)
    unsched = np.full(E, 500, dtype=np.int32)
    arc = np.full((E, M), 8, dtype=np.int32)
    arc[:, :64] = 0  # the 64 cheapest columns: selected, unusable
    return costs, supply, capacity, unsched, arc


def test_price_out_adds_violating_columns_and_matches_oracle():
    costs, supply, capacity, unsched, arc = _escalation_instance()
    sol, eff, stats = run_pruned(costs, supply, capacity, unsched,
                                 arc_capacity=arc)
    assert sol is not None, stats
    assert stats["rounds"] >= 1, f"no price-out round fired: {stats}"
    oracle = transport_objective(costs, supply, capacity, unsched,
                                 arc_capacity=arc)
    assert sol.objective == oracle
    assert_feasible(sol, costs, supply, capacity, arc)
    # The optimum uses only columns the initial shortlist excluded.
    assert not sol.flows[:, :64].any()
    assert sol.unsched.sum() == 0


def test_price_out_budget_exhaustion_escalates():
    costs, supply, capacity, unsched, arc = _escalation_instance()
    sol, eff, stats = run_pruned(costs, supply, capacity, unsched,
                                 arc_capacity=arc, max_rounds=0)
    assert sol is None and eff is None
    assert stats["escalated"]


def test_plan_gate_declines():
    rng = np.random.default_rng(0)
    costs = rng.integers(1, 100, size=(8, 256)).astype(np.int32)
    supply = np.full(8, 4, dtype=np.int32)
    capacity = np.full(256, 2, dtype=np.int32)
    # Default thresholds: plane far too small.
    assert tp.plan_shortlist(costs, supply, capacity) is None
    # Capacity slack gate: demand beyond capacity / slack.
    big_supply = np.full(8, 256, dtype=np.int32)
    assert tp.plan_shortlist(costs, big_supply, capacity,
                             min_rows=2, min_cols=16) is None
    # Sparse plane: the density gate declines.
    sparse = np.full((8, 256), INF_COST, dtype=np.int32)
    sparse[:, :4] = 1
    assert tp.plan_shortlist(sparse, supply, capacity,
                             min_rows=2, min_cols=16) is None
    # A qualifying plane fires and honors must_include.
    must = np.zeros(256, dtype=bool)
    must[255] = True
    plan = tp.plan_shortlist(costs, supply, capacity, must_include=must,
                             min_rows=2, min_cols=16)
    assert plan is not None and 255 in plan.sel
    assert plan.sel.size <= 128


def test_planner_parity_pruned_vs_dense(monkeypatch):
    """End-to-end: the same gang-mix cluster scheduled with the pruned
    path forced on (tiny gate) vs off must produce identical objectives,
    placement counts, and per-gang outcomes."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    def build():
        st = ClusterState()
        for i in range(128):
            st.node_added(MachineInfo(
                uuid=generate_uuid(f"pp{i}"), cpu_capacity=32000,
                ram_capacity=128 << 20, task_slots=4,
            ))
        for g in range(6):
            for i in range(8):
                st.task_submitted(TaskInfo(
                    uid=task_uid(f"ppg{g}", i), job_id=f"ppg-{g}",
                    cpu_request=1000 + 100 * g, ram_request=1 << 20,
                    gang=True,
                ))
        for i in range(20):
            st.task_submitted(TaskInfo(
                uid=task_uid("pps", i), job_id=f"pps-{i % 4}",
                cpu_request=1200, ram_request=1 << 20,
            ))
        return st

    def run(pruned: bool):
        if pruned:
            monkeypatch.setenv("POSEIDON_PRUNED", "1")
            monkeypatch.setenv("POSEIDON_PRUNE_MIN_ROWS", "2")
            monkeypatch.setenv("POSEIDON_PRUNE_MIN_COLS", "32")
        else:
            monkeypatch.setenv("POSEIDON_PRUNED", "0")
        st = build()
        planner = RoundPlanner(st, get_cost_model("cpu_mem"))
        _, m = planner.schedule_round()
        placements = {
            uid: t.scheduled_to for uid, t in sorted(st.tasks.items())
        }
        return m, placements

    m_dense, p_dense = run(False)
    m_pruned, p_pruned = run(True)
    assert m_pruned.pruned_bands >= 1, "pruned path never fired"
    assert m_dense.pruned_bands == 0
    assert m_pruned.objective == m_dense.objective
    assert m_pruned.placed == m_dense.placed
    assert m_pruned.unscheduled == m_dense.unscheduled
    # Per-gang outcome parity: the same gangs run whole / wait whole.
    for g in range(6):
        from poseidon_tpu.utils.ids import task_uid as tu
        placed_d = sum(
            1 for i in range(8) if p_dense[tu(f"ppg{g}", i)] is not None
        )
        placed_p = sum(
            1 for i in range(8) if p_pruned[tu(f"ppg{g}", i)] is not None
        )
        assert placed_d == placed_p, f"gang {g}: {placed_d} vs {placed_p}"
        assert placed_p in (0, 8)
