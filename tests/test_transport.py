"""Parity tests: the TPU auction solver vs the exact host oracle.

This is the core correctness gate of the whole framework (SURVEY.md section 7:
"parity oracle standing in for cs2").  Randomized instances across shapes,
cost ranges, scarcity regimes, and admissibility sparsity.
"""

import numpy as np
import pytest

from poseidon_tpu.ops.transport import (
    COST_CAP,
    INF_COST,
    choose_scale,
    solve_transport,
)
from poseidon_tpu.solver import oracle


def random_instance(rng, E, M, *, max_cost=200, scarcity=1.0, inadmissible=0.0):
    costs = rng.integers(0, max_cost + 1, size=(E, M)).astype(np.int32)
    if inadmissible > 0:
        mask = rng.random((E, M)) < inadmissible
        # Keep at least one admissible machine per EC so tests exercise both
        # placement and fallback paths.
        mask[np.arange(E), rng.integers(0, M, size=E)] = False
        costs[mask] = INF_COST
    supply = rng.integers(0, 8, size=E).astype(np.int32)
    total = max(int(supply.sum()), 1)
    cap = rng.integers(0, max(2, int(scarcity * total / max(M, 1)) * 2 + 1),
                       size=M).astype(np.int32)
    unsched = rng.integers(max_cost // 2, max_cost * 2 + 1, size=E).astype(np.int32)
    unsched = np.minimum(unsched, COST_CAP).astype(np.int32)
    return costs, supply, cap, unsched


def check_solution_feasible(sol, costs, supply, cap):
    assert (sol.flows >= 0).all() and (sol.unsched >= 0).all()
    placed = sol.flows.sum(axis=1)
    np.testing.assert_array_equal(placed + sol.unsched, supply)
    assert (sol.flows.sum(axis=0) <= cap).all()
    # No flow on inadmissible arcs.
    assert sol.flows[costs >= INF_COST].sum() == 0


@pytest.mark.parametrize("seed", range(12))
def test_parity_small_random(seed):
    rng = np.random.default_rng(seed)
    E = int(rng.integers(1, 10))
    M = int(rng.integers(1, 12))
    costs, supply, cap, unsched = random_instance(rng, E, M)
    sol = solve_transport(costs, supply, cap, unsched)
    check_solution_feasible(sol, costs, supply, cap)
    assert sol.gap_bound == 0.0  # small instance: exact scale chosen
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected, (sol.objective, expected, seed)


@pytest.mark.parametrize("seed", range(6))
def test_parity_scarce_capacity(seed):
    """Scarcity forces heavy fallback + eviction churn."""
    rng = np.random.default_rng(100 + seed)
    costs, supply, cap, unsched = random_instance(
        rng, 8, 6, max_cost=50, scarcity=0.3
    )
    sol = solve_transport(costs, supply, cap, unsched)
    check_solution_feasible(sol, costs, supply, cap)
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected


@pytest.mark.parametrize("seed", range(6))
def test_parity_with_inadmissible_arcs(seed):
    """Selector gating: most arcs masked out."""
    rng = np.random.default_rng(200 + seed)
    costs, supply, cap, unsched = random_instance(
        rng, 6, 8, max_cost=100, inadmissible=0.6
    )
    sol = solve_transport(costs, supply, cap, unsched)
    check_solution_feasible(sol, costs, supply, cap)
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected


def test_parity_medium():
    rng = np.random.default_rng(7)
    E, M = 24, 40
    costs, supply, cap, unsched = random_instance(rng, E, M, max_cost=500)
    sol = solve_transport(costs, supply, cap, unsched)
    check_solution_feasible(sol, costs, supply, cap)
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected


def test_zero_supply_and_padding():
    """Padded rows (supply 0) and padded machines (cap 0, INF cost) are inert."""
    costs = np.array([[5, INF_COST], [3, INF_COST]], dtype=np.int32)
    supply = np.array([2, 0], dtype=np.int32)
    cap = np.array([1, 0], dtype=np.int32)
    unsched = np.array([10, 10], dtype=np.int32)
    sol = solve_transport(costs, supply, cap, unsched)
    # One unit placed at cost 5, one falls back at 10.
    assert sol.objective == 15
    assert sol.flows[0, 0] == 1 and sol.unsched[0] == 1
    assert sol.flows[1].sum() == 0


def test_everything_unschedulable():
    costs = np.full((2, 3), INF_COST, dtype=np.int32)
    supply = np.array([3, 2], dtype=np.int32)
    cap = np.array([5, 5, 5], dtype=np.int32)
    unsched = np.array([7, 9], dtype=np.int32)
    sol = solve_transport(costs, supply, cap, unsched)
    assert sol.flows.sum() == 0
    assert sol.objective == 3 * 7 + 2 * 9


def test_prefers_cheap_machines():
    costs = np.array([[1, 100]], dtype=np.int32)
    sol = solve_transport(
        costs,
        np.array([5], dtype=np.int32),
        np.array([3, 10], dtype=np.int32),
        np.array([COST_CAP], dtype=np.int32),
    )
    assert sol.flows[0, 0] == 3 and sol.flows[0, 1] == 2
    assert sol.unsched[0] == 0


def test_warm_start_prices_preserve_parity():
    rng = np.random.default_rng(42)
    costs, supply, cap, unsched = random_instance(rng, 6, 8)
    sol1 = solve_transport(costs, supply, cap, unsched)
    # Re-solve with warm prices: same optimum.
    sol2 = solve_transport(costs, supply, cap, unsched, init_prices=sol1.prices)
    assert sol2.objective == sol1.objective


@pytest.mark.parametrize("seed", range(8))
def test_warm_incremental_resolve_parity(seed):
    """The incremental path: carry flows+prices into a perturbed instance
    (changed costs, changed supply, shrunken capacity) with eps_start=1 and
    still land exactly on the oracle optimum."""
    rng = np.random.default_rng(500 + seed)
    E, M = 8, 10
    costs, supply, cap, unsched = random_instance(rng, E, M)
    sol1 = solve_transport(costs, supply, cap, unsched)

    costs2 = np.clip(
        costs + rng.integers(-20, 20, size=costs.shape), 0, COST_CAP
    ).astype(np.int32)
    costs2[costs >= INF_COST] = INF_COST
    supply2 = np.clip(
        supply + rng.integers(-2, 3, size=E), 0, None
    ).astype(np.int32)
    cap2 = np.clip(cap + rng.integers(-2, 2, size=M), 0, None).astype(np.int32)

    sol2 = solve_transport(
        costs2, supply2, cap2, unsched,
        init_prices=sol1.prices, init_flows=sol1.flows,
        init_unsched=sol1.unsched, eps_start=1,
    )
    check_solution_feasible(sol2, costs2, supply2, cap2)
    expected = oracle.transport_objective(costs2, supply2, cap2, unsched)
    assert sol2.objective == expected, (seed, sol2.objective, expected)


@pytest.mark.parametrize("seed", range(8))
def test_parity_with_arc_capacity(seed):
    """Per-arc fit bounds (the cpu_mem multi-dimensional packing limit)
    must thread through the jitted Uem clamp and still match the oracle."""
    rng = np.random.default_rng(300 + seed)
    E = int(rng.integers(2, 8))
    M = int(rng.integers(2, 10))
    costs, supply, cap, unsched = random_instance(rng, E, M)
    arc_cap = rng.integers(0, 4, size=(E, M)).astype(np.int32)
    sol = solve_transport(costs, supply, cap, unsched, arc_capacity=arc_cap)
    check_solution_feasible(sol, costs, supply, cap)
    assert (sol.flows <= arc_cap).all()
    expected = oracle.transport_objective(
        costs, supply, cap, unsched, arc_capacity=arc_cap
    )
    assert sol.objective == expected, (seed, sol.objective, expected)


def test_negative_arc_capacity_rejected():
    with pytest.raises(ValueError):
        solve_transport(
            np.zeros((1, 1), np.int32), np.ones(1, np.int32),
            np.ones(1, np.int32), np.ones(1, np.int32),
            arc_capacity=np.array([[-1]], np.int32),
        )


def test_empty_instances():
    sol = solve_transport(
        np.zeros((0, 3), np.int32), np.zeros(0, np.int32),
        np.ones(3, np.int32), np.zeros(0, np.int32),
    )
    assert sol.objective == 0 and sol.flows.shape == (0, 3)
    sol = solve_transport(
        np.zeros((2, 0), np.int32), np.array([3, 1], np.int32),
        np.zeros(0, np.int32), np.array([5, 7], np.int32),
    )
    assert sol.objective == 3 * 5 + 1 * 7
    assert (sol.unsched == [3, 1]).all()


def test_general_mcmf_oracle_matches_transport_oracle():
    """The general-graph oracle agrees with the transportation oracle when
    fed the same network shape (source->EC->machine->sink + fallback)."""
    rng = np.random.default_rng(9)
    costs, supply, cap, unsched = random_instance(rng, 4, 5)
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    E, M = costs.shape
    # Node ids: 0 = source, 1..E = ECs, E+1..E+M = machines, E+M+1 = sink.
    src, sink = 0, E + M + 1
    arcs = []
    for e in range(E):
        arcs.append((src, 1 + e, int(supply[e]), 0))
        arcs.append((1 + e, sink, int(supply[e]), int(unsched[e])))
        for m in range(M):
            if costs[e, m] < INF_COST and cap[m] > 0:
                arcs.append((1 + e, E + 1 + m, int(supply[e]), int(costs[e, m])))
    for m in range(M):
        if cap[m] > 0:
            arcs.append((E + 1 + m, sink, int(cap[m]), 0))
    got = oracle.mcmf_objective(
        E + M + 2, arcs, {src: int(supply.sum()), sink: -int(supply.sum())}
    )
    assert got == expected


def test_choose_scale_bounds():
    assert choose_scale(4, 4) == 12
    big = choose_scale(256, 100_000)
    assert big * 4 * COST_CAP <= (1 << 30)


def test_normalize_prices_anchor_and_clamp():
    from poseidon_tpu.ops.transport import PRICE_SPREAD_CAP, normalize_prices

    p = np.array([-(1 << 30) // 2 - 100_000_000, -5, 7], dtype=np.int32)
    out = normalize_prices(p)
    assert out.max() == 0
    assert out.min() == -PRICE_SPREAD_CAP  # deep outlier floored
    # A healthy spread is only shifted, never distorted.
    q = np.array([-300, -200, -100], dtype=np.int32)
    np.testing.assert_array_equal(
        normalize_prices(q), np.array([-200, -100, 0], dtype=np.int32)
    )


@pytest.mark.parametrize("seed", range(4))
def test_poisoned_warm_prices_still_converge(seed):
    """Warm frames that pre-date the price-hygiene invariant can carry
    potentials at/below the relabel floor; such a node could never relabel
    again and the solve livelocked to the iteration budget (the round-2
    TPU-worker 'crash' at 10k/100k).  The entry normalization must make
    these solves terminate AND still land on the oracle optimum."""
    rng = np.random.default_rng(900 + seed)
    E, M = 6, 8
    costs, supply, cap, unsched = random_instance(rng, E, M)
    # Poisoned potentials: huge negative magnitudes straddling the floor.
    poisoned = (
        -rng.integers(1 << 28, 1 << 30, size=E + M + 1)
    ).astype(np.int64).astype(np.int32)
    sol = solve_transport(
        costs, supply, cap, unsched, init_prices=poisoned,
    )
    check_solution_feasible(sol, costs, supply, cap)
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected
    # Returned prices are re-anchored: bounded spread, max at 0.
    assert sol.prices.max() == 0
    assert sol.prices.min() >= -(1 << 28)


def test_returned_prices_are_anchored():
    rng = np.random.default_rng(77)
    costs, supply, cap, unsched = random_instance(rng, 5, 7)
    sol = solve_transport(costs, supply, cap, unsched)
    assert sol.prices.max() == 0


class TestSelectiveSolve:
    """Column-selected sparse-round solve: must be EXACT (certificate-
    backed) in every regime — reduction sound, reduction unsound
    (fallback), warm-started, arc-capped.  The reduced path only
    engages for M >= ~180 (minimum width 128 plus the 3/4 guard), so
    these instances are wide with sparse supply."""

    @staticmethod
    def _reduced_engaged(costs, supply, capacity=None, init_flows=None,
                         slack=2):
        """True iff this instance takes the reduced path (mirrors the
        wrapper's gating, contention pre-check included), so tests can
        assert they exercise it."""
        from poseidon_tpu.ops.transport import INF_COST

        E, M = costs.shape
        k = int(supply.max(initial=0)) + slack
        if k >= M:
            return False
        part = np.argpartition(costs, k - 1, axis=1)[:, :k]
        mask = np.zeros(M, dtype=bool)
        mask[part.ravel()] = True
        if init_flows is not None:
            mask |= init_flows.sum(axis=0) > 0
        target = 128
        while target < int(mask.sum()):
            target *= 4
        col_min = np.where(
            (costs < INF_COST).any(axis=0), costs.min(axis=0), INF_COST
        )
        order = np.argsort(col_min, kind="stable")
        if capacity is not None:
            need = 2 * int(supply.astype(np.int64).sum())
            while target * 4 < M * 3:
                if mask.sum() < target:
                    extra = order[~mask[order]][: target - int(mask.sum())]
                    mask[extra] = True
                if int(capacity.astype(np.int64)[mask].sum()) >= need:
                    break
                target *= 4
        return target * 4 < M * 3

    @pytest.mark.parametrize("seed", range(6))
    def test_matches_oracle(self, seed):
        from poseidon_tpu.ops.transport import solve_transport_selective

        rng = np.random.default_rng(700 + seed)
        E, M = int(rng.integers(2, 7)), int(rng.integers(200, 320))
        costs, supply, cap, unsched = random_instance(rng, E, M)
        assert self._reduced_engaged(costs, supply, cap)
        sol = solve_transport_selective(
            costs, supply, cap, unsched, slack=2
        )
        check_solution_feasible(sol, costs, supply, cap)
        expected = oracle.transport_objective(costs, supply, cap, unsched)
        assert sol.objective == expected, seed
        assert sol.gap_bound == 0.0

    @pytest.mark.parametrize("seed", range(3))
    def test_contested_cheap_columns_fall_back_exactly(self, seed):
        """Every row's cheapest-k union misses capacity the optimum
        needs (a contested cheap tier over tiny capacities).  The
        contention pre-check (union capacity < 2x supply) now skips the
        doomed reduction outright — still landing on the oracle."""
        from poseidon_tpu.ops.transport import solve_transport_selective

        rng = np.random.default_rng(800 + seed)
        E, M = 4, 300
        costs = np.full((E, M), 500, dtype=np.int32)
        cheap = rng.choice(M, size=30, replace=False)
        costs[:, cheap] = 1
        # Mid-priced tier the optimum needs once the cheap tier fills.
        mid = np.setdiff1d(np.arange(M), cheap)[:200]
        costs[:, mid[100:]] = 50
        supply = np.full(E, 60, dtype=np.int32)
        cap = np.ones(M, dtype=np.int32)
        unsched = np.full(E, 2000, dtype=np.int32)
        # The pre-check (not the certificate) rejects the reduction here.
        assert not self._reduced_engaged(costs, supply, cap, slack=0)
        sol = solve_transport_selective(
            costs, supply, cap, unsched, slack=0
        )
        check_solution_feasible(sol, costs, supply, cap)
        expected = oracle.transport_objective(costs, supply, cap, unsched)
        assert sol.objective == expected, seed

    def test_certificate_failure_falls_back_exactly(self):
        """The certificate-fallback path proper: union capacity is ample
        (pre-check passes) but one row's cheap-BY-COST columns are all
        arc-capped to zero, so its usable columns live OUTSIDE the
        cost-derived union — the lifted certificate must fail and force
        the full-solve fallback, landing on the oracle with out-of-union
        flow."""
        from poseidon_tpu.ops.transport import solve_transport_selective

        E, M = 3, 400
        costs = np.zeros((E, M), dtype=np.int32)
        costs[:, :94] = 10
        costs[:, 94:] = 100 + np.arange(M - 94, dtype=np.int32)
        supply = np.array([5, 30, 30], dtype=np.int32)
        cap = np.full(M, 10, dtype=np.int32)
        unsched = np.full(E, 2000, dtype=np.int32)
        # Row 0 cannot actually use any column the union will contain
        # (rows select 0..93 by cost; padding adds 94..127 by col_min),
        # but its columns from 166 on are open and far cheaper than
        # going unscheduled.
        arc_cap = np.full((E, M), 1 << 20, dtype=np.int32)
        arc_cap[0, :166] = 0
        # The selection gating itself passes (capacity is ample).
        assert self._reduced_engaged(costs, supply, cap, slack=0)
        sol = solve_transport_selective(
            costs, supply, cap, unsched, arc_capacity=arc_cap, slack=0
        )
        check_solution_feasible(sol, costs, supply, cap)
        expected = oracle.transport_objective(
            costs, supply, cap, unsched, arc_capacity=arc_cap
        )
        assert sol.objective == expected
        assert sol.gap_bound == 0.0
        # Row 0's flow really is outside the union — only the fallback
        # full solve can have produced it.
        assert sol.flows[0, 166:].sum() == 5
        assert sol.flows[0, :166].sum() == 0

    def test_warm_start_with_arc_caps(self):
        from poseidon_tpu.ops.transport import solve_transport_selective

        rng = np.random.default_rng(42)
        E, M = 5, 250
        costs, supply, cap, unsched = random_instance(rng, E, M)
        arc_cap = rng.integers(0, 4, size=(E, M)).astype(np.int32)
        assert self._reduced_engaged(costs, supply, cap, slack=4)
        sol1 = solve_transport_selective(
            costs, supply, cap, unsched, arc_capacity=arc_cap, slack=4
        )
        sol2 = solve_transport_selective(
            costs, supply, cap, unsched, sol1.prices,
            arc_capacity=arc_cap, init_flows=sol1.flows,
            init_unsched=sol1.unsched, slack=4,
        )
        expected = oracle.transport_objective(
            costs, supply, cap, unsched, arc_capacity=arc_cap
        )
        assert sol1.objective == expected
        assert sol2.objective == expected

    def test_dense_supply_falls_through(self):
        """Supply comparable to M: no reduction, plain full solve."""
        from poseidon_tpu.ops.transport import solve_transport_selective

        rng = np.random.default_rng(9)
        costs, supply, cap, unsched = random_instance(rng, 4, 12)
        assert not self._reduced_engaged(costs, supply, slack=64)
        sol = solve_transport_selective(
            costs, supply, cap, unsched, slack=64
        )
        expected = oracle.transport_objective(costs, supply, cap, unsched)
        assert sol.objective == expected


def test_auction_dual_start_certifies_uncontested():
    """On an uncontested instance (ample capacity, distinct cheap
    columns per row) the greedy cold start plus its auction duals is
    already 1-optimal: the solve must confirm in ZERO device iterations
    with an exact certificate."""
    from poseidon_tpu.ops.transport import solve_transport

    E, M = 6, 120
    costs = np.full((E, M), 3000, dtype=np.int32)
    for e in range(E):
        costs[e, e * 20 : e * 20 + 20] = 10 + e  # disjoint cheap tiers
    supply = np.full(E, 10, dtype=np.int32)
    cap = np.full(M, 4, dtype=np.int32)
    unsched = np.full(E, 6000, dtype=np.int32)
    sol = solve_transport(costs, supply, cap, unsched)
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected
    assert sol.gap_bound == 0.0
    assert sol.iterations == 0, sol.iterations


@pytest.mark.parametrize("seed", range(5))
def test_greedy_flows_always_feasible(seed):
    """The cold-start initializer must respect supply, column capacity,
    arc capacity, and admissibility for any instance shape."""
    from poseidon_tpu.ops.transport import INF_COST, greedy_flows

    rng = np.random.default_rng(4000 + seed)
    E, M = int(rng.integers(1, 12)), int(rng.integers(1, 60))
    costs = rng.integers(0, 500, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < 0.2] = INF_COST
    supply = rng.integers(0, 30, size=E).astype(np.int32)
    capacity = rng.integers(0, 8, size=M).astype(np.int32)
    arc_cap = rng.integers(0, 5, size=(E, M)).astype(np.int32)
    F = greedy_flows(costs, supply, capacity, arc_cap)
    assert (F >= 0).all()
    assert (F <= arc_cap).all()
    assert (F.sum(axis=1) <= supply).all()
    assert (F.sum(axis=0) <= capacity).all()
    assert (F[costs >= INF_COST] == 0).all()
    # Without arc caps the admissibility rule still holds.
    F2 = greedy_flows(costs, supply, capacity)
    assert (F2[costs >= INF_COST] == 0).all()
    assert (F2.sum(axis=0) <= capacity).all()
    assert (F2.sum(axis=1) <= supply).all()


def test_flow_mass_overflow_rejected():
    """Instances whose total slot capacity + supply would overflow the
    full-width push's int32 cumsum are rejected with a clear error (a
    cluster would need ~2 billion task slots to hit this)."""
    costs = np.zeros((1, 2), dtype=np.int32)
    supply = np.array([1], dtype=np.int32)
    cap = np.array([1 << 30, 1 << 30], dtype=np.int32)
    unsched = np.array([10], dtype=np.int32)
    with pytest.raises(ValueError, match="int32 flow arithmetic"):
        solve_transport(costs, supply, cap, unsched)


def test_bucket_size_ladder():
    from poseidon_tpu.ops.transport import bucket_size

    assert bucket_size(1) == 32
    assert bucket_size(32) == 32
    assert bucket_size(33) == 64
    assert bucket_size(256) == 256
    assert bucket_size(300) == 320        # 1.25 * 256
    assert bucket_size(4000) == 4096
    assert bucket_size(10_000) == 10_240  # 1.25 * 8192: 2.4% waste
    # Monotone and always >= n.
    prev = 0
    for n in range(1, 3000, 7):
        b = bucket_size(n)
        assert b >= n and b >= prev
        prev = b


def test_shape_churn_does_not_recompile():
    """EC/machine counts moving within a bucket, and cost maxima drifting
    under a max_cost_hint, must all reuse one compile key — per-round
    recompiles were the round-2 churn storm (27x wave latency)."""
    # The packed wrapper is the dispatch boundary — the inner solve
    # variants inline into its trace and mint no executables of their
    # own, so ITS cache is where a per-round recompile would show.
    from poseidon_tpu.ops.transport import _solve_device_packed

    rng = np.random.default_rng(5)

    def solve(E, M, max_cost):
        costs, supply, cap, unsched = random_instance(
            rng, E, M, max_cost=max_cost
        )
        return solve_transport(
            costs, supply, cap, unsched, max_cost_hint=500
        )

    solve(9, 33, 500)  # warm the cache at the (16, 64) bucket
    before = _solve_device_packed._cache_size()
    assert before > 0  # the boundary being measured is the live one
    solve(10, 40, 500)   # same buckets, different extents
    solve(12, 64, 500)   # M at the bucket edge
    solve(16, 50, 137)   # cost bound drifts under the hint
    solve(13, 48, 20)
    assert _solve_device_packed._cache_size() == before


def test_coarse_warm_start_exact_and_gated():
    """The coarse (machine-aggregated) wave warm start must (a) produce a
    feasible lift whose warmed solve reaches the exact oracle objective
    with a zero-gap certificate, and (b) decline instances below its
    size gates (small M, thin supply) so churn/selective rounds are
    untouched."""
    from poseidon_tpu.ops.transport import (
        COARSE_GROUPS,
        coarse_warm_start,
        solve_transport,
    )

    rng = np.random.default_rng(11)
    E, M = 24, max(2048, 4 * COARSE_GROUPS)
    # Load-shaped columns (a per-machine offset) + request-shaped rows:
    # the structure the grouping keys on.
    load = rng.integers(0, 400, size=M).astype(np.int32)
    base = rng.integers(50, 800, size=E).astype(np.int32)
    costs = (base[:, None] + load[None, :]).astype(np.int32)
    supply = rng.integers(40, 90, size=E).astype(np.int32)
    cap = rng.integers(1, 3, size=M).astype(np.int32)
    unsched = np.full(E, 5000, dtype=np.int32)

    calls = []

    def solve(*args, **kw):
        calls.append(args[0].shape)
        return solve_transport(*args, **kw)

    cs = coarse_warm_start(costs, supply, cap, unsched, None, solve)
    assert cs is not None and calls == [(E, COARSE_GROUPS)]
    prices, flows, left, eps = cs
    # Feasible lift: column capacity and supply conservation hold.
    assert (flows.sum(axis=0) <= cap).all()
    assert (flows.sum(axis=1) + left == supply).all()
    assert eps >= 1

    warmed = solve_transport(
        costs, supply, cap, unsched, prices, init_flows=flows,
        init_unsched=left, eps_start=eps, greedy_init=False,
    )
    cold = solve_transport(costs, supply, cap, unsched)
    assert warmed.gap_bound == 0.0
    assert warmed.objective == cold.objective
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert warmed.objective == expected

    # Gates: small machine axis / thin supply decline.
    assert coarse_warm_start(
        costs[:, :512], supply, cap[:512], unsched, None, solve
    ) is None
    assert coarse_warm_start(
        costs, np.ones(E, dtype=np.int32), cap, unsched, None, solve
    ) is None


def test_selective_honors_pinned_scale():
    """A caller-pinned scale (the coarse warm start pins the full
    instance's scale onto its aggregated solve, which may route through
    the selective wrapper) must be honored on BOTH selective branches —
    regression: the reduced branch forwarded **kw containing 'scale'
    into a call that also passed scale positionally (TypeError)."""
    from poseidon_tpu.ops.transport import (
        derive_scale,
        padded_shape,
        solve_transport_selective,
    )

    rng = np.random.default_rng(5)
    E, M = 8, 600
    costs = rng.integers(10, 2000, size=(E, M)).astype(np.int32)
    supply = np.full(E, 4, dtype=np.int32)   # sparse: reduction fires
    cap = np.full(M, 8, dtype=np.int32)
    unsched = np.full(E, 9000, dtype=np.int32)
    scale, _ = derive_scale(costs, unsched, None, *padded_shape(E, M))
    sol = solve_transport_selective(
        costs, supply, cap, unsched, scale=scale * 2,  # deliberately odd
    )
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected
    assert sol.gap_bound == 0.0


def test_selective_precheck_skips_reduction_when_duals_certify(monkeypatch):
    """Cold steady-state rounds whose FULL-instance greedy+auction-dual
    start is already near-optimal must go straight to the full-width
    solve — the column reduction makes the union columns everyone's
    cheapest and can be cost-contended where the full instance is not
    (measured at 10k/100k churn: 554 reduced iterations vs ZERO
    full-width, identical objectives)."""
    import poseidon_tpu.ops.transport as T

    rng = np.random.default_rng(17)
    E, M = 12, 800
    # Uncontested: ample capacity, per-row distinct cheap tiers.
    costs = rng.integers(500, 3000, size=(E, M)).astype(np.int32)
    for e in range(E):
        costs[e, e * 40:(e + 1) * 40] = 10 + e
    supply = np.full(E, 6, dtype=np.int32)
    cap = np.full(M, 4, dtype=np.int32)
    unsched = np.full(E, 9000, dtype=np.int32)

    widths = []
    inner = T.solve_transport

    def spy(costs_, *a, **k):
        widths.append(np.asarray(costs_).shape[1])
        return inner(costs_, *a, **k)

    monkeypatch.setattr(T, "solve_transport", spy)
    sol = T.solve_transport_selective(costs, supply, cap, unsched)
    assert widths == [M], widths  # one full-width solve, no reduction
    assert sol.iterations == 0
    assert sol.gap_bound == 0.0
    expected = oracle.transport_objective(costs, supply, cap, unsched)
    assert sol.objective == expected


def test_resident_operand_cache_parity(monkeypatch):
    """POSEIDON_RESIDENT=1: repeated solves at one padded shape ship
    only changed columns onto a device-resident operand buffer and fold
    flow results back into it.  Every round must stay oracle-exact
    through all cache paths (cold upload, column scatter, wholesale
    re-upload, warm-flows round with the fetch skip), and the resident
    host mirror must track exactly what a fresh upload would ship."""
    import poseidon_tpu.ops.transport as T

    monkeypatch.setenv("POSEIDON_RESIDENT", "1")
    # Cache-path test: warm rounds here certify exactly, and the host
    # certificate would answer them without ever touching the resident
    # buffer — force every round through the dispatch paths under test.
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    T._RESIDENT.clear()
    rng = np.random.default_rng(23)
    E, M = 10, 120
    costs = rng.integers(1, 400, size=(E, M)).astype(np.int32)
    supply = rng.integers(0, 8, size=E).astype(np.int32)
    cap = rng.integers(1, 6, size=M).astype(np.int32)
    unsched = np.full(E, 900, dtype=np.int32)

    sol = None
    for rnd in range(5):
        if rnd == 1:
            costs[:, 7] += 3            # few-column drift -> scatter
            costs[:, 40] -= 1
        elif rnd == 2:
            costs = rng.integers(       # wholesale change -> re-upload
                1, 400, size=(E, M)
            ).astype(np.int32)
        # rnd 3: identical instance, warm flows -> zero-upload round
        elif rnd == 4:
            costs[3, :] += 5            # row drift touches many columns
        warm = {} if sol is None else dict(
            init_prices=sol.prices, init_flows=sol.flows,
            init_unsched=sol.unsched, eps_start=8,
        )
        sol = T.solve_transport(costs, supply, cap, unsched, **warm)
        assert sol.gap_bound == 0.0
        expected = oracle.transport_objective(costs, supply, cap, unsched)
        assert sol.objective == expected, rnd
        # The resident mirror must equal what a fresh pack would ship.
        (key, entry), = T._RESIDENT.items()
        E_pad, M_pad = key
        want = np.full((E_pad, M_pad), T.INF_COST, dtype=np.int32)
        want[:E, :M] = costs
        np.testing.assert_array_equal(entry["host"][0], want)
        np.testing.assert_array_equal(
            np.asarray(entry["dev"])[0], want
        )
        # Plane 2 tracks the last RESULT flows (fold-back), so the next
        # warm round's init flows diff clean against it.
        np.testing.assert_array_equal(
            entry["host"][2][:E, :M], sol.flows
        )
        np.testing.assert_array_equal(
            np.asarray(entry["dev"])[2], entry["host"][2]
        )
    T._RESIDENT.clear()


def test_host_cert_skips_dispatch_bit_identical(monkeypatch):
    """A warm re-solve of an unchanged instance must be answered by the
    host certificate with ZERO device dispatches, and the answer must be
    bit-identical to what the dispatch path returns (the device would
    run 0 iterations and hand the start back unchanged).  Measured
    motivation: every live-TPU churn/restart round at 10k/100k was such
    a round paying ~0.5 s of tunnel transfers for a no-op dispatch."""
    import poseidon_tpu.ops.transport as T

    rng = np.random.default_rng(77)
    costs, supply, cap, unsched = random_instance(rng, 8, 12)
    sol1 = solve_transport(costs, supply, cap, unsched)
    warm = dict(init_prices=sol1.prices, init_flows=sol1.flows,
                init_unsched=sol1.unsched, eps_start=1)

    calls0, cert0 = T.device_call_count(), T.host_cert_count()
    sol2 = solve_transport(costs, supply, cap, unsched, **warm)
    assert T.host_cert_count() == cert0 + 1
    assert T.device_call_count() == calls0  # no dispatch
    assert sol2.gap_bound == 0.0 and sol2.iterations == 0

    # Force the dispatch path on the identical warm instance: the
    # short-circuit must be invisible in every returned field.
    monkeypatch.setenv("POSEIDON_HOST_CERT", "0")
    sol3 = solve_transport(costs, supply, cap, unsched, **warm)
    assert sol3.objective == sol2.objective == sol1.objective
    np.testing.assert_array_equal(sol2.flows, sol3.flows)
    np.testing.assert_array_equal(sol2.unsched, sol3.unsched)
    np.testing.assert_array_equal(sol2.prices, sol3.prices)

    # Caller ownership: mutating the returned arrays must not corrupt
    # the warm frame handed in.
    sol2.flows[0, 0] += 1
    assert not np.array_equal(sol2.flows, sol1.flows)


def test_host_cert_respects_tightened_arc_capacity():
    """A warm frame whose flows exceed a freshly TIGHTENED finite arc
    bound must DISPATCH (the device clamps the start to Uem and
    re-places the excess); the epsilon certificate's forward mask skips
    saturated arcs, so without the guard the host path would return an
    arc-infeasible placement as certified-optimal."""
    import poseidon_tpu.ops.transport as T

    costs = np.array([[1, 50]], dtype=np.int32)
    supply = np.array([5], dtype=np.int32)
    cap = np.array([8, 8], dtype=np.int32)
    unsched = np.array([500], dtype=np.int32)
    wide = np.array([[5, 5]], dtype=np.int32)
    sol1 = solve_transport(costs, supply, cap, unsched, arc_capacity=wide)
    assert sol1.flows[0, 0] == 5  # all on the cheap arc

    tight = np.array([[3, 5]], dtype=np.int32)  # cheap arc tightened
    cert0 = T.host_cert_count()
    sol2 = solve_transport(
        costs, supply, cap, unsched, arc_capacity=tight,
        init_prices=sol1.prices, init_flows=sol1.flows,
        init_unsched=sol1.unsched, eps_start=1,
    )
    assert T.host_cert_count() == cert0  # guard forced the dispatch
    assert (sol2.flows <= tight).all()
    assert sol2.flows[0, 0] == 3 and sol2.flows[0, 1] == 2
    expected = oracle.transport_objective(
        costs, supply, cap, unsched, arc_capacity=tight
    )
    assert sol2.objective == expected
