"""Unit tests for the K8s object conversion layer (no cluster, no
``kubernetes`` package): quantity parsing and V1Pod/V1Node mapping over
duck-typed stand-ins — the surface the reference covers in
pkg/k8sclient/nodewatcher_test.go:120-216 and podwatcher_test.go."""

from types import SimpleNamespace as NS

import pytest

from poseidon_tpu.glue.kube_convert import (
    node_from_v1,
    parse_cpu,
    parse_mem_kb,
    pod_from_v1,
)


@pytest.mark.parametrize("q,want", [
    ("", 0),
    ("100m", 100),
    ("1", 1000),
    ("2", 2000),
    ("0.5", 500),
    ("1.5", 1500),
    ("250m", 250),
])
def test_parse_cpu(q, want):
    assert parse_cpu(q) == want


@pytest.mark.parametrize("q,want", [
    ("", 0),
    ("1024", 1),            # plain bytes -> KB
    ("2048Ki", 2048),
    ("1Mi", 1 << 10),
    ("2Gi", 2 << 20),
    ("1Ti", 1 << 30),
    ("1000K", 1000),
    ("1M", 1000),
    ("2G", 2 * 10 ** 6),
    ("1.5Gi", int(1.5 * (1 << 20))),
])
def test_parse_mem_kb(q, want):
    assert parse_mem_kb(q) == want


def _v1_pod(**kw):
    containers = [
        NS(resources=NS(requests={"cpu": "250m", "memory": "512Mi"})),
        NS(resources=NS(requests={"cpu": "0.5", "memory": "1Gi"})),
    ]
    meta = NS(
        name=kw.get("name", "p1"),
        namespace="default",
        owner_references=kw.get("owners"),
        labels=kw.get("labels"),
        deletion_timestamp=kw.get("deletion_timestamp"),
    )
    spec = NS(
        containers=containers,
        scheduler_name="poseidon",
        node_name=kw.get("node_name", ""),
        node_selector=kw.get("node_selector"),
        affinity=kw.get("affinity"),
    )
    status = NS(phase=kw.get("phase", "Pending"))
    return NS(metadata=meta, spec=spec, status=status)


def test_pod_requests_summed_across_containers():
    pod = pod_from_v1(_v1_pod())
    assert pod.cpu_request == 250 + 500
    assert pod.ram_request == (512 << 10) + (1 << 20)
    assert pod.scheduler_name == "poseidon"
    assert pod.phase == "Pending"
    assert not pod.deleted


def test_pod_owner_and_deletion():
    pod = pod_from_v1(_v1_pod(
        owners=[NS(uid="rs-123")], deletion_timestamp="2026-01-01",
    ))
    assert pod.owner_uid == "rs-123"
    assert pod.deleted


def test_pod_affinity_terms_extracted():
    term = NS(label_selector=NS(match_labels={"app": "db"}))
    anti = NS(label_selector=NS(match_labels={"app": "web"}))
    affinity = NS(
        pod_affinity=NS(
            required_during_scheduling_ignored_during_execution=[term]
        ),
        pod_anti_affinity=NS(
            required_during_scheduling_ignored_during_execution=[anti]
        ),
    )
    pod = pod_from_v1(_v1_pod(affinity=affinity))
    assert pod.pod_affinity == {"app": "db"}
    assert pod.pod_anti_affinity == {"app": "web"}


def _v1_node(conditions=(), unschedulable=False, cpu="4", mem="16Gi"):
    return NS(
        metadata=NS(name="n1", labels={"zone": "a"}),
        spec=NS(unschedulable=unschedulable),
        status=NS(
            capacity={"cpu": cpu, "memory": mem},
            conditions=list(conditions),
        ),
    )


def test_node_capacity_and_labels():
    node = node_from_v1(_v1_node())
    assert node.cpu_capacity == 4000
    assert node.ram_capacity == 16 << 20
    assert node.labels == {"zone": "a"}
    assert node.ready and not node.out_of_disk and not node.unschedulable


@pytest.mark.parametrize("ctype,status,field,want", [
    ("Ready", "False", "ready", False),
    ("Ready", "True", "ready", True),
    ("OutOfDisk", "True", "out_of_disk", True),
    ("OutOfDisk", "False", "out_of_disk", False),
])
def test_node_condition_mapping(ctype, status, field, want):
    node = node_from_v1(_v1_node(conditions=[NS(type=ctype, status=status)]))
    assert getattr(node, field) is want


def test_node_unschedulable_gate():
    assert node_from_v1(_v1_node(unschedulable=True)).unschedulable
