"""Pod-level affinity/anti-affinity with multi-round resolution
(BASELINE config 3).

Selectors are evaluated against the labels of tasks *running* on each
machine, so affinity to a not-yet-placed pod resolves on a later round —
the reference's roadmap semantics built on the contract extension
(TaskDescriptor.pod_affinity/pod_anti_affinity).
"""

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.glue import FakeKube, Node, Pod, Poseidon
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.service import FirmamentTPUServer
from poseidon_tpu.utils.config import PoseidonConfig
from poseidon_tpu.utils.ids import generate_uuid

IN_SET = 0


def cluster(n=3, cpu=4000):
    st = ClusterState()
    for i in range(n):
        st.node_added(
            MachineInfo(uuid=generate_uuid(f"pa{i}"), cpu_capacity=cpu,
                        ram_capacity=1 << 24)
        )
    return st


def test_affinity_follows_target_across_rounds():
    st = cluster()
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    # Round 1: place the database pod.
    st.task_submitted(
        TaskInfo(uid=1, job_id="db", cpu_request=100, ram_request=1 << 18,
                 labels={"app": "db"})
    )
    planner.schedule_round()
    db_machine = st.tasks[1].scheduled_to
    assert db_machine is not None

    # Round 2: a web pod with affinity to app=db must land next to it.
    st.task_submitted(
        TaskInfo(uid=2, job_id="web", cpu_request=100, ram_request=1 << 18,
                 labels={"app": "web"},
                 pod_affinity=((IN_SET, "app", ("db",)),))
    )
    planner.schedule_round()
    assert st.tasks[2].scheduled_to == db_machine


def test_affinity_waits_until_target_runs():
    st = cluster()
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    # The dependent pod arrives FIRST: no machine hosts app=db yet, so it
    # waits (multi-round resolution), then follows once the target runs.
    st.task_submitted(
        TaskInfo(uid=2, job_id="web", cpu_request=100, ram_request=1 << 18,
                 pod_affinity=((IN_SET, "app", ("db",)),))
    )
    _, m1 = planner.schedule_round()
    assert m1.placed == 0 and m1.unscheduled == 1

    st.task_submitted(
        TaskInfo(uid=1, job_id="db", cpu_request=100, ram_request=1 << 18,
                 labels={"app": "db"})
    )
    planner.schedule_round()
    _, m3 = planner.schedule_round()
    assert st.tasks[2].scheduled_to == st.tasks[1].scheduled_to


def test_anti_affinity_avoids_target():
    st = cluster(n=2)
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    st.task_submitted(
        TaskInfo(uid=1, job_id="noisy", cpu_request=100,
                 ram_request=1 << 18, labels={"class": "noisy"})
    )
    planner.schedule_round()
    noisy_machine = st.tasks[1].scheduled_to

    st.task_submitted(
        TaskInfo(uid=2, job_id="quiet", cpu_request=100,
                 ram_request=1 << 18,
                 pod_anti_affinity=((IN_SET, "class", ("noisy",)),))
    )
    planner.schedule_round()
    assert st.tasks[2].scheduled_to is not None
    assert st.tasks[2].scheduled_to != noisy_machine


def test_anti_self_spreads_one_per_machine():
    st = cluster(n=3)
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    # 4 replicas anti-affine to their own label on 3 machines: 3 spread
    # out, the 4th waits.
    for i in range(4):
        st.task_submitted(
            TaskInfo(uid=10 + i, job_id="spread", cpu_request=100,
                     ram_request=1 << 18, labels={"app": "spread"},
                     pod_anti_affinity=((IN_SET, "app", ("spread",)),))
        )
    _, m = planner.schedule_round()
    assert m.placed == 3 and m.unscheduled == 1
    machines = {
        t.scheduled_to for t in st.tasks.values() if t.scheduled_to
    }
    assert len(machines) == 3


def test_pod_affinity_over_the_wire():
    kube = FakeKube()
    for i in range(3):
        kube.add_node(Node(name=f"n{i}", cpu_capacity=4000,
                           ram_capacity=1 << 24))
    with FirmamentTPUServer(address="127.0.0.1:0") as server:
        cfg = PoseidonConfig(firmament_address=server.address,
                             scheduling_interval=3600)
        with Poseidon(kube, config=cfg, run_loop=False) as poseidon:
            kube.create_pod(
                Pod(name="db", cpu_request=100, ram_request=1 << 18,
                    labels={"app": "db"})
            )
            assert poseidon.drain_watchers()
            poseidon.schedule_once()
            db_node = kube.pods["default/db"].node_name

            kube.create_pod(
                Pod(name="web", cpu_request=100, ram_request=1 << 18,
                    pod_affinity={"app": "db"})
            )
            kube.create_pod(
                Pod(name="loner", cpu_request=100, ram_request=1 << 18,
                    pod_anti_affinity={"app": "db"})
            )
            assert poseidon.drain_watchers()
            poseidon.schedule_once()
            assert kube.pods["default/web"].node_name == db_node
            loner_node = kube.pods["default/loner"].node_name
            assert loner_node and loner_node != db_node
