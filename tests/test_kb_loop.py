"""Knowledge-base loop: AddTaskStats history must change placements.

The reference feeds task and node usage history into the scheduler's
cost models via the stats path (reference pkg/stats/stats.go:77-159);
round-2 review flagged that TaskStats were stored but never read.  These
tests pin the loop end to end: stats in -> observed machine load /
observed interference class -> different placement out.
"""


from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo


def mk_machine(uuid, cpu=10_000, ram=1 << 24):
    return MachineInfo(uuid=uuid, cpu_capacity=cpu, ram_capacity=ram)


def _place_one_each(st, planner):
    """Two resident tasks, one per machine (placed over two rounds: the
    load term prices machines by committed state, so round two spreads);
    returns {machine_uuid: uid}."""
    st.task_submitted(TaskInfo(uid=1, job_id="res-a", cpu_request=100,
                               ram_request=1 << 10))
    _, m = planner.schedule_round()
    assert m.placed == 1
    st.task_submitted(TaskInfo(uid=2, job_id="res-b", cpu_request=101,
                               ram_request=1 << 10))
    _, m = planner.schedule_round()
    assert m.placed == 1
    out = {st.tasks[uid].scheduled_to: uid for uid in (1, 2)}
    assert len(out) == 2, "residents did not spread"
    return out


def test_task_stats_shift_placement_cpu_mem():
    """Identical reservations on both machines, but the KB says machine
    A's resident is a CPU hog: the next task must land on machine B."""
    st = ClusterState()
    st.node_added(mk_machine("m-a"))
    st.node_added(mk_machine("m-b"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    by_machine = _place_one_each(st, planner)

    hog_machine = "m-a"
    hog_uid = by_machine[hog_machine]
    other_machine = next(u for u in by_machine if u != hog_machine)
    # Observed usage 50x the reservation.
    assert st.add_task_stats(hog_uid, {"cpu_usage": 5000, "mem_usage": 1 << 10})

    st.task_submitted(TaskInfo(uid=3, job_id="new", cpu_request=100,
                               ram_request=1 << 10))
    deltas, m = planner.schedule_round()
    assert m.placed == 1
    assert st.tasks[3].scheduled_to == other_machine


def test_task_stats_can_attract_placement_too():
    """Symmetric: the KB showing a resident chronically idle makes its
    machine CHEAPER than the reservation picture suggests."""
    st = ClusterState()
    st.node_added(mk_machine("m-a"))
    st.node_added(mk_machine("m-b"))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))

    # Heavy reservation on one machine, light on the other.
    st.task_submitted(TaskInfo(uid=1, job_id="res-a", cpu_request=4000,
                               ram_request=1 << 10))
    _, m = planner.schedule_round()
    assert m.placed == 1
    heavy_machine = st.tasks[1].scheduled_to
    st.task_submitted(TaskInfo(uid=2, job_id="res-b", cpu_request=500,
                               ram_request=1 << 10))
    planner.schedule_round()
    assert st.tasks[2].scheduled_to != heavy_machine

    # Without stats a new task avoids the big reservation...
    st.task_submitted(TaskInfo(uid=4, job_id="probe", cpu_request=100,
                               ram_request=1 << 10))
    planner.schedule_round()
    assert st.tasks[4].scheduled_to != heavy_machine
    st.task_removed(4)

    # ...but history shows the big reservation actually uses ~nothing,
    # while the other machine's picture is unchanged.
    st.add_task_stats(1, {"cpu_usage": 10, "mem_usage": 1 << 10})
    st.task_submitted(TaskInfo(uid=3, job_id="new", cpu_request=100,
                               ram_request=1 << 10))
    planner.schedule_round()
    assert st.tasks[3].scheduled_to == heavy_machine


def test_observed_class_refines_whare_census():
    """A resident labeled SHEEP whose usage history screams DEVIL must
    repel an incoming TURTLE under the Whare-Map model."""
    st = ClusterState()
    st.node_added(mk_machine("m-a"))
    st.node_added(mk_machine("m-b"))
    planner = RoundPlanner(st, get_cost_model("whare"))
    by_machine = _place_one_each(st, planner)  # both residents type SHEEP

    wolf_machine = "m-a"
    wolf_uid = by_machine[wolf_machine]
    other_machine = next(u for u in by_machine if u != wolf_machine)
    # Usage 30x request: observed class flips SHEEP -> DEVIL.  Memory is
    # kept at the reservation so cpu_mem's base load term stays balanced
    # against the small cpu delta; the census flip dominates.
    view = st.build_round_view()
    st.add_task_stats(wolf_uid, {"cpu_usage": 3000, "mem_usage": 1 << 10})
    view2 = st.build_round_view()
    col_a = view2.machines.uuids.index(wolf_machine)
    assert view2.machines.type_census[col_a, 2] == 1  # now a DEVIL
    assert view.machines.type_census[col_a, 2] == 0

    # TURTLE pays 100/resident next to a DEVIL vs 5 next to a SHEEP.
    st.task_submitted(TaskInfo(uid=9, job_id="turtle", cpu_request=100,
                               ram_request=1 << 10, task_type=3))
    planner.schedule_round()
    assert st.tasks[9].scheduled_to == other_machine


def test_kb_absent_means_no_obs_arrays():
    st = ClusterState()
    st.node_added(mk_machine("m-a"))
    st.task_submitted(TaskInfo(uid=1, job_id="j", cpu_request=10,
                               ram_request=1 << 10))
    view = st.build_round_view()
    assert view.machines.cpu_obs_used is None
    assert view.machines.ram_obs_used is None
