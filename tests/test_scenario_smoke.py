"""Scenario-harness smoke (``make scenario-smoke``): the acceptance
gate for the trace-driven scenario subsystem.

Every registered production-shaped scenario drives through the FULL
glue stack (FakeKube + watchers + gRPC service + the production round
loop, via the shared chaos/harness.py stack) with every gate armed —
kube-truth byte-identity per round, the budget-0 warm ledger quartet,
tier vocabulary, and the end-of-drive "everything placed" check.  Then
the drain-equivalence leg (synchronous vs streaming drives of one plan
must produce identical placement AND delta-stream digests), seeded
determinism across re-runs, robustness scoring under chaos-seeded cost
perturbation, and the flight-recorder path: a deliberately killed
drive must write a trace that re-drives offline to the identical
failing round.

Slow-marked: excluded from the tier-1 gate, run via
``make scenario-smoke`` (wired into ``make verify``) or
``pytest -m slow``.
"""

import pytest

from poseidon_tpu.chaos.harness import KNOWN_TIERS
from poseidon_tpu.replay import (
    ReplayDriver,
    flight_trace_events,
    load_flight,
    redrive_flight,
)
from poseidon_tpu.scenario import (
    SCENARIOS,
    SETTLE_ROUNDS,
    drive_scenario,
    named_scenario,
    score_scenario,
)

pytestmark = pytest.mark.slow

MACHINES = 8
ROUNDS = 5
SEED = 3


def _plan(name):
    return named_scenario(name, machines=MACHINES, rounds=ROUNDS, seed=SEED)


def test_scenario_registry_full_stack_sync(tmp_path):
    """Every named scenario drives clean through the full stack in the
    synchronous loop with all gates armed."""
    for name in SCENARIOS:
        out = drive_scenario(_plan(name), out_dir=str(tmp_path))
        assert out["ok"], (name, out.get("failure"))
        assert out["rounds_run"] == ROUNDS + SETTLE_ROUNDS, name
        # The per-round gates are enforced inside the drive (they fail
        # it); restate the artifact contract here.
        assert out["divergent_rounds"] == 0, name
        assert out["warm_fresh_compiles"] == 0, name
        assert out["warm_implicit_transfers"] == 0, name
        assert set(out["tiers"]) <= set(KNOWN_TIERS), name
        # Satellite pin: the planner stamps throughput in the sync loop
        # too — the scenario artifact must carry a real figure.
        assert out["placements_per_sec"] > 0, name
        assert len(out["digests"]) == out["rounds_run"], name
        assert len(out["delta_digests"]) == out["rounds_run"], name


def test_scenario_sync_streaming_drain_equivalence(tmp_path):
    """Synchronous and streaming drives of the same plan are
    drain-equivalent: identical per-round placement digests AND
    identical enacted delta streams — and a same-seed re-run is
    bit-identical (seeded determinism through the whole stack)."""
    for name in ("diurnal", "node_churn"):
        plan = _plan(name)
        sync = drive_scenario(plan, out_dir=str(tmp_path))
        assert sync["ok"], (name, sync.get("failure"))
        stream = drive_scenario(
            plan, streaming=True, out_dir=str(tmp_path)
        )
        assert stream["ok"], (name, stream.get("failure"))
        assert stream["mode"] == "streaming"
        assert stream["digests"] == sync["digests"], name
        assert stream["delta_digests"] == sync["delta_digests"], name
        assert stream["scenario_digest"] == sync["scenario_digest"], name

    rerun = drive_scenario(_plan("diurnal"), out_dir=str(tmp_path))
    base = drive_scenario(_plan("diurnal"), out_dir=str(tmp_path))
    assert rerun["digests"] == base["digests"]
    assert rerun["delta_digests"] == base["delta_digests"]
    assert rerun["scenario_digest"] == base["scenario_digest"]


def test_scenario_robustness_score(tmp_path):
    """Robustness under chaos-seeded cost perturbation: three perturbed
    re-drives, every correctness gate still armed (a perturbed run that
    diverges or recompiles zeroes the score), and the regression
    quantiles fold into a (0, 1] score."""
    out = score_scenario(
        _plan("diurnal"), perturb_seeds=(1, 2, 3),
    )
    assert out["gates_ok"], out.get("failures")
    assert out["perturb_seeds"] == [1, 2, 3]
    assert len(out["objectives"]) == 3
    assert len(out["regressions"]) == 3
    assert 0.0 < out["robustness_score"] <= 1.0
    assert out["regression_p50"] <= out["regression_p90"] <= (
        out["regression_max"]
    )
    assert 0.0 <= out["placement_divergence"] <= 1.0


def test_scenario_kill_and_redrive(tmp_path):
    """Kill the Firmament stub mid-scenario: the crash-loop budget stops
    the loop fatally, the flight recorder writes a scenario trace (full
    materialized plan embedded), and the replay package re-drives it
    offline to the identical failing round."""
    kill_round = 3

    def kill(r, ctx):
        if r == kill_round:
            ctx["server"].stop(grace=0.1)

    out = drive_scenario(
        _plan("diurnal"), out_dir=str(tmp_path), on_round=kill,
    )
    assert not out["ok"]
    assert out["failure"]["kind"] == "fatal"
    assert out["failing_round"] == kill_round

    trace = load_flight(out["trace_path"])
    assert len(trace.rounds) == kill_round
    assert trace.failure["round"] == kill_round
    assert trace.spec["kind"] == "scenario"
    assert trace.spec["plan"]["name"] == "diurnal"

    # replay/ lowers the embedded plan to trace events directly...
    events = flight_trace_events(out["trace_path"])
    report = ReplayDriver(events, precompile=False).run(max_rounds=2)
    assert report.placed > 0

    # ...and the re-drive lands on the identical failing round with
    # byte-identical per-round placements.
    redriven = redrive_flight(out["trace_path"])
    assert redriven["reproduced"], redriven.get("digest_mismatches")
    assert redriven["rounds_run"] == kill_round
