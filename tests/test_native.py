"""Native C++ graph core: availability, parity, and differential fuzzing.

The native core mirrors every ClusterState mutation; any divergence
between its round view and the pure-Python builder is a bug in one of
them.  The fuzz drives a long random mutation sequence through two states
(one native, one pure-Python) and compares the views field by field.
"""

import numpy as np
import pytest

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.native import native_available
from poseidon_tpu.utils.ids import generate_uuid, task_uid

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


def make_machine(i, **kw):
    defaults = dict(cpu_capacity=8000, ram_capacity=1 << 24,
                    net_rx_capacity=1000)
    defaults.update(kw)
    return MachineInfo(uuid=generate_uuid(f"nm{i}"), **defaults)


def make_task(i, **kw):
    defaults = dict(cpu_request=100 * (1 + i % 5), ram_request=1 << 18)
    defaults.update(kw)
    return TaskInfo(uid=task_uid("njob", i), job_id=f"njob-{i % 3}",
                    **defaults)


def assert_views_equal(va, vb):
    np.testing.assert_array_equal(va.ecs.ec_ids, vb.ecs.ec_ids)
    np.testing.assert_array_equal(va.ecs.supply, vb.ecs.supply)
    np.testing.assert_array_equal(va.ecs.cpu_request, vb.ecs.cpu_request)
    np.testing.assert_array_equal(va.ecs.max_wait_rounds,
                                  vb.ecs.max_wait_rounds)
    np.testing.assert_array_equal(va.ecs.is_gang, vb.ecs.is_gang)
    np.testing.assert_array_equal(va.ecs.running_by_machine,
                                  vb.ecs.running_by_machine)
    assert va.machines.uuids == vb.machines.uuids
    np.testing.assert_array_equal(va.machines.cpu_used, vb.machines.cpu_used)
    np.testing.assert_array_equal(va.machines.ram_used, vb.machines.ram_used)
    np.testing.assert_array_equal(va.machines.net_rx_used,
                                  vb.machines.net_rx_used)
    np.testing.assert_array_equal(va.machines.slots_free,
                                  vb.machines.slots_free)
    np.testing.assert_array_equal(va.machines.type_census,
                                  vb.machines.type_census)
    for i in range(len(va.member_uids)):
        np.testing.assert_array_equal(va.member_uids[i], vb.member_uids[i])
        np.testing.assert_array_equal(va.member_cur[i], vb.member_cur[i])
        np.testing.assert_array_equal(va.member_wait[i], vb.member_wait[i])


def test_native_is_active_by_default():
    st = ClusterState()
    assert st._native is not None


def test_differential_fuzz():
    rng = np.random.default_rng(5)
    st_n = ClusterState(use_native=True)
    st_p = ClusterState(use_native=False)
    assert st_n._native is not None and st_p._native is None

    live_machines = []
    live_tasks = []
    for step in range(400):
        op = rng.random()
        if op < 0.15 or not live_machines:
            i = len(live_machines)
            for st in (st_n, st_p):
                st.node_added(make_machine(i))
            live_machines.append(generate_uuid(f"nm{i}"))
        elif op < 0.55:
            i = int(rng.integers(0, 10_000))
            t = make_task(i, task_type=int(rng.integers(0, 4)))
            for st in (st_n, st_p):
                st.task_submitted(
                    TaskInfo(uid=t.uid, job_id=t.job_id,
                             cpu_request=t.cpu_request,
                             ram_request=t.ram_request,
                             task_type=t.task_type)
                )
            if t.uid not in live_tasks:
                live_tasks.append(t.uid)
        elif op < 0.7 and live_tasks:
            uid = live_tasks[int(rng.integers(0, len(live_tasks)))]
            target = (
                live_machines[int(rng.integers(0, len(live_machines)))]
                if rng.random() < 0.8 else None
            )
            for st in (st_n, st_p):
                st.apply_placements([(uid, target)])
        elif op < 0.8 and live_tasks:
            uid = live_tasks.pop(int(rng.integers(0, len(live_tasks))))
            for st in (st_n, st_p):
                st.task_removed(uid)
        elif op < 0.9 and live_tasks:
            uid = live_tasks[int(rng.integers(0, len(live_tasks)))]
            for st in (st_n, st_p):
                st.task_completed(uid)
        elif live_machines and rng.random() < 0.5:
            uuid = live_machines[int(rng.integers(0, len(live_machines)))]
            for st in (st_n, st_p):
                st.node_failed(uuid)
        elif live_machines:
            uuid = live_machines.pop(
                int(rng.integers(0, len(live_machines)))
            )
            for st in (st_n, st_p):
                st.node_removed(uuid)

        if step % 40 == 0 or step == 399:
            for include_running in (False, True):
                assert_views_equal(
                    st_n.build_round_view(include_running),
                    st_p.build_round_view(include_running),
                )


def test_planner_native_matches_python():
    """Same workload through two planners (native vs pure state): same
    objective and same placements."""
    results = []
    for use_native in (True, False):
        st = ClusterState(use_native=use_native)
        for i in range(6):
            st.node_added(make_machine(i))
        for i in range(30):
            st.task_submitted(make_task(i))
        planner = RoundPlanner(st, get_cost_model("cpu_mem"))
        _, m = planner.schedule_round()
        placements = sorted(
            (uid, t.scheduled_to) for uid, t in st.tasks.items()
        )
        results.append((m.objective, m.placed, placements))
    assert results[0] == results[1]
