"""The bench artifact's scoring contract (round-4 review, Weak #1).

The scored ``value``/``vs_baseline`` must be pinned to the TARGET config:
a bench that loses rungs to a timeout posts a worse artifact, never a
better-looking one, and uncertified (unconverged) rungs never score.
``build_artifact`` is pure, so these run without any child process.
"""

from bench import build_artifact

TARGET = (10_000, 100_000)
OK_PARITY = {"parity_ok": True, "ok": True}
NONE_RUN = {"ok": False, "error": "not run"}


def rung(machines, tasks, wave, *, ok=True, converged=True):
    return {
        "machines": machines, "tasks": tasks, "ok": ok,
        "converged": converged, "wave_p50_s": wave, "cold_s": 10.0,
        "churn_p50_s": 0.1, "restart_round_s": 0.5, "backend": "cpu",
    }


def test_scores_only_the_target_config():
    # A completed SMALLER rung must not set the score (the round-4
    # flattery: 4k completed, 10k absent, score posted anyway).
    out = build_artifact(
        [rung(4_000, 40_000, 1.9)], TARGET, OK_PARITY, NONE_RUN, NONE_RUN,
    )
    assert out["value"] is None
    assert out["vs_baseline"] == 0.0
    assert "not completed" in out["error"]


def test_target_rung_scores_with_restart():
    out = build_artifact(
        [rung(10_000, 100_000, 5.0), rung(1_000, 10_000, 0.3)],
        TARGET, OK_PARITY, NONE_RUN, NONE_RUN,
    )
    assert out["value"] == 5.0
    assert out["vs_baseline"] == 0.2
    assert out["restart_s"] == 0.5
    assert out["machines"] == 10_000


def test_unconverged_target_posts_no_score():
    out = build_artifact(
        [rung(10_000, 100_000, 0.5, converged=False)],
        TARGET, OK_PARITY, NONE_RUN, NONE_RUN,
    )
    # A fast-but-uncertified wave would otherwise look like a 2x win.
    assert out["value"] == 0.5
    assert out["vs_baseline"] == 0.0
    assert out["converged"] is False


def test_failed_target_rung_does_not_score():
    out = build_artifact(
        [{"machines": 10_000, "tasks": 100_000, "ok": False,
          "error": "timeout", "wave_p50_s": 3.0}],
        TARGET, OK_PARITY, NONE_RUN, NONE_RUN,
    )
    assert out["value"] is None and out["vs_baseline"] == 0.0


def test_single_config_mode_scores_requested_config():
    target = (200, 2_000)
    out = build_artifact(
        [rung(200, 2_000, 0.2)], target, OK_PARITY, NONE_RUN, NONE_RUN,
    )
    assert out["value"] == 0.2
    assert out["vs_baseline"] == 5.0
    assert out["target_machines"] == 200


def test_last_live_tpu_loader(tmp_path, monkeypatch):
    """The evidence loader returns the newest COMPLETED live-TPU rung at
    the target config, skipping corrupt lines and later partial
    captures, and never raises."""
    import json as _json

    import bench

    out = tmp_path / "out"
    out.mkdir()
    monkeypatch.setattr(
        bench.os.path, "dirname", lambda p: str(tmp_path)
    )
    rung = {"machines": 10, "tasks": 100, "backend": "tpu", "ok": True,
            "wave_p50_s": 1.5}
    lines = [
        _json.dumps({"ladder": [rung]}),
        "{not json",
        _json.dumps({"ladder": []}),  # later partial capture
    ]
    (out / "tpu_bench.jsonl").write_text("\n".join(lines))
    got = bench._load_last_live_tpu((10, 100))
    assert got is not None and got["wave_p50_s"] == 1.5
    assert bench._load_last_live_tpu((99, 999)) is None
