"""Compile-ledger tests: the runtime half of the retrace-guard story.

The static rule (``retrace-guard``) flags the *patterns* that mint
compile keys; ``CompileLedger`` catches the *events*.  The seeded-
retrace tests here drive the same hazard through both layers — the AST
rule flags the test-copy source, and the runtime ledger trips on the
actual recompiles — so a regression in either detector fails tier-1.

Planner integration (warm wave / gang rounds at toy scale) lives here
too: an identical re-built instance scheduled by a fresh planner must
compile nothing (the jit cache is process-wide), which is exactly the
restart-warm production story.
"""

from __future__ import annotations

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.check import check_file, rules_by_name
from poseidon_tpu.check.ledger import (
    CompileBudgetExceeded,
    CompileLedger,
    fresh_compile_count,
    retrace_count,
)

REPO = Path(__file__).parent.parent


# ------------------------------------------------------------------ ledger


@jax.jit
def _toy_kernel(x):
    return x * 2 + 1


def test_counter_is_monotonic_and_counts_fresh_compiles():
    base = fresh_compile_count()
    _toy_kernel(jnp.arange(7, dtype=jnp.int32))  # cold at this shape
    after_cold = fresh_compile_count()
    assert after_cold >= base + 1
    _toy_kernel(jnp.arange(7, dtype=jnp.int32))  # cache hit
    assert fresh_compile_count() == after_cold


def test_warm_window_passes_budget_zero():
    _toy_kernel(jnp.arange(5, dtype=jnp.int32))
    with CompileLedger(budget=0, label="warm toy") as led:
        _toy_kernel(jnp.arange(5, dtype=jnp.int32))
    assert led.fresh_compiles == 0


def test_shape_drift_trips_budget_and_names_the_program():
    _toy_kernel(jnp.arange(3, dtype=jnp.int32))
    with pytest.raises(CompileBudgetExceeded, match="_toy_kernel"):
        with CompileLedger(budget=0, label="drift"):
            _toy_kernel(jnp.arange(11, dtype=jnp.int32))


def test_telemetry_mode_records_without_asserting():
    with CompileLedger(budget=None, label="telemetry") as led:
        _toy_kernel(jnp.arange(13, dtype=jnp.int32))
    assert led.fresh_compiles >= 1
    assert "_toy_kernel" in led.compiled_names


def test_body_exception_is_not_masked_by_budget_report():
    with pytest.raises(ValueError, match="body failure"):
        with CompileLedger(budget=0, label="masking"):
            _toy_kernel(jnp.arange(17, dtype=jnp.int32))  # over budget
            raise ValueError("body failure")


def test_retrace_counter_moves_on_fresh_trace():
    base = retrace_count()
    _toy_kernel(jnp.arange(19, dtype=jnp.int32))
    assert retrace_count() > base


# --------------------------------------------- seeded retrace, both layers

# A "test copy" of a production jit signature with the static_argnames
# entry for `mode` DROPPED: the call site that used to be sanctioned
# (str bound to a static parameter) is now a str at a traced position.
_DROPPED_STATIC_SRC = '''
import functools

import jax


@functools.partial(jax.jit, static_argnames=("max_iter",))
def solve(x, *, max_iter, mode):
    return x * max_iter


def round_path(x):
    return solve(x, max_iter=64, mode="dense")
'''


def test_dropped_static_argname_trips_the_static_rule(tmp_path):
    f = tmp_path / "dropped_static.py"
    f.write_text(_DROPPED_STATIC_SRC)
    found = check_file(
        f, rules_by_name(["retrace-guard"]), forced=True, root=tmp_path
    )
    assert len(found) == 1
    assert found[0].rule == "retrace-guard"
    assert "str constant at traced position" in found[0].message
    assert "static_argnames" in found[0].message


def test_jit_in_loop_trips_the_static_rule(tmp_path):
    f = tmp_path / "jit_in_loop.py"
    f.write_text(
        "import jax\n\n\n"
        "def _kern(x):\n    return x + 1\n\n\n"
        "def round_path(xs):\n"
        "    return [jax.jit(_kern)(x) for x in xs]\n"
    )
    found = check_file(
        f, rules_by_name(["retrace-guard"]), forced=True, root=tmp_path
    )
    assert len(found) == 1
    assert "fresh compile cache per call" in found[0].message


def test_seeded_retrace_trips_the_runtime_ledger():
    """The runtime twin of the static findings above: a per-value
    static argument retraces each round; a per-call jit wrapper
    recompiles each round.  Both blow a zero budget on the WARM call."""

    @jax.jit
    def step(x, n):  # pretend n was meant to be static_argnames
        return x + n

    import functools

    @functools.partial(jax.jit, static_argnames=("n",))
    def step_static(x, *, n):
        return x + n

    x = jnp.arange(8, dtype=jnp.int32)
    step_static(x, n=1)  # cold: compile paid outside the window
    with pytest.raises(CompileBudgetExceeded, match="step_static"):
        with CompileLedger(budget=0, label="per-value static"):
            # The retrace: a new static value mints a new executable on
            # what the caller believes is a warm path.
            step_static(x, n=2)

    def round_path(v):
        # The jit-in-function hazard: the wrapped callable is a fresh
        # closure object per round, so the process-wide cache never
        # hits — every round retraces AND recompiles.  (Re-wrapping
        # the SAME function object would cache by identity; that is
        # precisely why the static rule flags construction site, not
        # call site.)
        def _kern(u):
            return u - 1

        return jax.jit(_kern)(v)

    round_path(x)  # a previous "round" already compiled this program
    with pytest.raises(CompileBudgetExceeded):
        with CompileLedger(budget=0, label="per-call jit wrapper"):
            round_path(x)

    # Sanity: the correctly-warm variants stay inside the budget.
    step(x, jnp.int32(0))  # cold compile paid outside the window
    with CompileLedger(budget=0, label="actually warm"):
        step_static(x, n=1)
        step(x, jnp.int32(3))
        step(x, jnp.int32(4))  # traced operand: value churn is free


# ------------------------------------------------- planner warm rounds


def _toy_cluster(num_machines=12, num_tasks=48, gang=False):
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    st = ClusterState()
    for i in range(num_machines):
        st.node_added(MachineInfo(
            uuid=generate_uuid(f"ldg-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=16,
        ))
    for i in range(num_tasks):
        st.task_submitted(TaskInfo(
            uid=task_uid("ldg", i),
            job_id=f"ldg-gang-{i % 4}" if gang else f"ldg-{i % 4}",
            cpu_request=500, ram_request=1 << 19, gang=gang,
        ))
    return st


@pytest.mark.parametrize("gang", [False, True], ids=["wave", "gang"])
def test_identical_rebuilt_round_is_compile_free(gang):
    """Restart-warm contract: a fresh planner over an identically
    rebuilt instance compiles nothing (process-wide jit cache), so a
    warm wave/gang round is bit-for-bit budget-zero.  This is the test
    harness twin of the bench's in-band gang/warm-round ledgers."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    st1 = _toy_cluster(gang=gang)
    p1 = RoundPlanner(st1, get_cost_model("cpu_mem"))
    _, m1 = p1.schedule_round()  # cold: pays whatever compiles exist
    assert m1.placed > 0

    st2 = _toy_cluster(gang=gang)
    p2 = RoundPlanner(st2, get_cost_model("cpu_mem"))
    with CompileLedger(budget=0, label="rebuilt warm round") as led:
        _, m2 = p2.schedule_round()
    assert m2.placed == m1.placed
    assert m2.objective == m1.objective
    assert led.fresh_compiles == 0
    # The RoundMetrics surface agrees with the ledger.
    assert m2.fresh_compiles == 0


def test_round_metrics_fresh_compiles_counts_cold_round():
    """A planner solving a NEVER-SEEN padded shape must report its
    fresh compiles in RoundMetrics — the per-round observability the
    bench artifact columns ride on.  Machine count 97 pads to a 128
    bucket no other test in this module uses at this EC bucket; numpy
    churn in task count keeps the EC axis on a distinct bucket too."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    rng = np.random.default_rng(7)
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    st = ClusterState()
    for i in range(97):
        st.node_added(MachineInfo(
            uuid=generate_uuid(f"cold-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=16,
        ))
    for i in range(120):
        st.task_submitted(TaskInfo(
            uid=task_uid("cold", i), job_id=f"cold-{i % 24}",
            cpu_request=int(rng.integers(100, 900)),
            ram_request=1 << 19,
        ))
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    base = fresh_compile_count()
    _, m = planner.schedule_round()
    # Whatever compiled during the round is attributed to the round.
    assert m.fresh_compiles == fresh_compile_count() - base
    assert m.fresh_compiles >= 0
