"""Seeded interleaving suites (the preemption-point race harness,
chaos/preempt.py) over the three thread boundaries the concurrency PR
hardened:

- CostPipeline speculate/build racing from two threads: cache builds
  must stay strictly serialized (the pipelining contract);
- MetricsServer scrapes racing ``observe_round``: the solve-tier
  one-hot must never read all-zero — including the REGRESSION test that
  re-creates the pre-fix zero-then-set write order and shows the
  harness catches the tear the fixed order can't produce;
- watcher-resync-style SharedState churn racing enactment-style
  readers: the id maps must stay mutually consistent.

Every TrackedLock acquire/release is a preemption point; the same seed
replays the same schedule pressure (chaos/preempt.race_seeds sweeps
POSEIDON_RACE_SWEEP of them from POSEIDON_RACE_SEED).
"""

from __future__ import annotations

import re
import threading
import urllib.request

import pytest

from poseidon_tpu.chaos.preempt import (
    InvariantTracker,
    PreemptPoints,
    race_seeds,
)
from poseidon_tpu.obs import metrics as obs_metrics
from poseidon_tpu.utils import locks as L

SEEDS = list(race_seeds())


@pytest.fixture(autouse=True)
def _fresh_edge_graph():
    L._reset_edges_for_tests()
    yield
    L._reset_edges_for_tests()


# ------------------------------------------- CostPipeline speculate/build


class _SerialCache:
    """Cache stub: records build sections; any overlap is a violation."""

    def __init__(self, tracker: InvariantTracker) -> None:
        self.tracker = tracker
        self.builds = 0
        self.last_stats = {"stub": True}

    def build(self, key, ecs_b, mt_b):
        me = threading.current_thread().name
        self.tracker.enter("cache", me)
        self.builds += 1
        self.tracker.exit("cache", me)
        return {"key": key}


@pytest.mark.parametrize("seed", SEEDS)
def test_pipeline_speculate_build_stays_serialized(seed):
    from poseidon_tpu.graph.pipeline import CostPipeline

    tracker = InvariantTracker()
    cache = _SerialCache(tracker)
    pipe = CostPipeline(cache)
    errors = []

    def speculator():
        try:
            for k in range(20):
                pipe.speculate(k, None, None)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def builder():
        try:
            for k in range(20):
                cm, _stats = pipe.build(k, None, None)
                assert cm == {"key": k}
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    with PreemptPoints(seed=seed):
        threads = [
            threading.Thread(target=speculator, name="spec"),
            threading.Thread(target=builder, name="auth"),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        pipe.drain()
    assert errors == []
    assert tracker.violations == [], tracker.violations
    # Every authoritative build ran; speculative ones may be superseded.
    assert cache.builds >= 20


# -------------------------------- MetricsServer scrape vs observe_round


def _tier_values(text: str):
    """tier -> value from a /metrics exposition."""
    return {
        m.group(1): float(m.group(2))
        for m in re.finditer(
            r'poseidon_round_solve_tier\{tier="([^"]+)"\}\s+([0-9.e+-]+)',
            text,
        )
    }


def _old_zero_then_set(tier_g, tier):
    """The PRE-FIX observe_round write order: zero every labelset, THEN
    mark the serving tier — leaving an all-zero window a concurrent
    scrape can land in."""
    for key in tier_g.labelsets():
        tier_g.set(0.0, *key)
    for t in obs_metrics.SOLVE_TIERS:
        if t != tier:
            tier_g.set(0.0, t)
    tier_g.set(1.0, tier)


def _tier_storm(write_one, reg, rounds):
    """Drive tier writes against a scraping reader; returns the number
    of all-zero scrapes observed."""
    tears = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            vals = _tier_values(reg.expose())
            if vals and all(v == 0.0 for v in vals.values()):
                tears.append(dict(vals))

    t = threading.Thread(target=reader, name="scraper")
    t.start()
    tiers = obs_metrics.SOLVE_TIERS
    for i in range(rounds):
        write_one(tiers[i % len(tiers)])
        if tears:
            break
    stop.set()
    t.join(timeout=30)
    assert not t.is_alive()
    return len(tears)


def test_tier_onehot_tear_reproduced_with_prefix_order():
    """REGRESSION: the pre-fix zero-then-set order tears under the
    harness — the reader catches an all-zero one-hot.  This is the
    interleaving failure the PR fixed in observe_round (set the serving
    tier first); the companion test below holds the fixed order to
    zero tears under the same storm."""
    found = 0
    for seed in race_seeds(sweep=6):
        reg = obs_metrics.Registry()
        tier_g = reg.gauge(
            "poseidon_round_solve_tier", "one-hot", ("tier",)
        )
        tier_g.set(1.0, "none")
        with PreemptPoints(seed=seed, p_park=0.3, p_yield=0.4):
            found += _tier_storm(
                lambda t: _old_zero_then_set(tier_g, t), reg, 400
            )
        if found:
            break
    assert found > 0, (
        "pre-fix write order never tore; the harness lost its "
        "regression sensitivity"
    )


@pytest.mark.parametrize("seed", SEEDS)
def test_observe_round_keeps_onehot_under_scrape(seed):
    reg = obs_metrics.Registry()
    tiers = obs_metrics.SOLVE_TIERS

    def write_one(tier):
        obs_metrics.observe_round(
            {"round_index": 1, "solve_tier": tier}, reg
        )

    with PreemptPoints(seed=seed, p_park=0.3, p_yield=0.4):
        tears = _tier_storm(write_one, reg, 120)
    assert tears == 0
    # Steady state: exactly one tier serving.
    vals = _tier_values(reg.expose())
    assert sum(1 for v in vals.values() if v == 1.0) == 1
    assert set(vals) >= set(tiers)


@pytest.mark.parametrize("seed", SEEDS[:2])
def test_http_scrape_during_observe_round(seed):
    """End-to-end: a real MetricsServer thread answering GETs while
    observe_round feeds — every HTTP scrape sees a serving tier."""
    reg = obs_metrics.Registry()
    server = obs_metrics.MetricsServer("127.0.0.1:0", registry=reg).start()
    try:
        obs_metrics.observe_round(
            {"round_index": 0, "solve_tier": "none"}, reg
        )
        stop = threading.Event()
        bad = []

        def scraper():
            url = f"http://{server.address}/metrics"
            while not stop.is_set():
                with urllib.request.urlopen(url, timeout=5) as resp:
                    vals = _tier_values(resp.read().decode())
                if vals and not any(v == 1.0 for v in vals.values()):
                    bad.append(vals)

        t = threading.Thread(target=scraper)
        t.start()
        tiers = obs_metrics.SOLVE_TIERS
        with PreemptPoints(seed=seed):
            for i in range(60):
                obs_metrics.observe_round(
                    {"round_index": i, "solve_tier": tiers[i % len(tiers)]},
                    reg,
                )
        stop.set()
        t.join(timeout=30)
        assert not t.is_alive()
        assert bad == [], f"scrapes saw no serving tier: {bad[:3]}"
    finally:
        server.stop()


# ------------------------------ watcher resync racing enactment readers


@pytest.mark.parametrize("seed", SEEDS)
def test_shared_state_resync_vs_enactment(seed):
    """A resync-style writer re-registers/removes tasks (what the pod
    watcher does after a dropped watch) while enactment-style readers
    walk the id maps (what ``_reconcile_after_failure`` and the stats
    path do).  The maps must stay mutually consistent: a uid the
    reader got from ``uid_for_pod`` must resolve back to the same pod,
    and ``live_uids`` must never contain a finished/removed task."""
    from poseidon_tpu.glue.fake_kube import Pod
    from poseidon_tpu.glue.types import SharedState
    from poseidon_tpu.protos import firmament_pb2 as fpb

    shared = SharedState()
    n = 24
    pods = [Pod(name=f"p{i}") for i in range(n)]
    errors = []
    stop = threading.Event()

    def resyncer():
        # Churn: re-register (MODIFIED after resync), finish, remove,
        # re-add — the full lifecycle the watcher drives.
        try:
            for cycle in range(15):
                for i, pod in enumerate(pods):
                    uid = 1000 + i
                    shared.put_task(uid, pod, fpb.TaskDescriptor(uid=uid))
                for i in range(0, n, 3):
                    shared.mark_finished(1000 + i)
                for i in range(0, n, 6):
                    shared.pop_task(1000 + i)
        except Exception as e:  # noqa: BLE001
            errors.append(e)
        finally:
            stop.set()

    def enactor():
        try:
            while not stop.is_set():
                for uid, pod in shared.live_uids().items():
                    entry = shared.get_task(uid)
                    if entry is not None and entry.pod.key != pod.key:
                        errors.append(
                            AssertionError(f"uid {uid} pod mismatch")
                        )
                for pod in pods:
                    uid = shared.uid_for_pod(pod.key)
                    if uid is None:
                        continue
                    back = shared.task_for_uid(uid)
                    if back is not None and back.key != pod.key:
                        errors.append(
                            AssertionError(f"{pod.key} -> {uid} -> "
                                           f"{back.key}")
                        )
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    with PreemptPoints(seed=seed):
        threads = [
            threading.Thread(target=resyncer, name="resync"),
            threading.Thread(target=enactor, name="enact-a"),
            threading.Thread(target=enactor, name="enact-b"),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
    assert errors == [], errors[:3]


@pytest.mark.parametrize("seed", SEEDS[:2])
def test_keyed_queue_under_preemption(seed):
    """PR 1's KeyedQueue storm, re-driven through the seeded harness:
    the tracked Condition turns every queue operation into a preemption
    point, widening the park/hand-off windows the original test relied
    on thread-count brute force to hit."""
    from poseidon_tpu.glue.keyed_queue import KeyedQueue

    q = KeyedQueue()
    tracker = InvariantTracker()
    done = []

    def producer():
        for i in range(40):
            for k in range(4):
                q.add(f"k{k}", i)

    def worker(name):
        while True:
            batch = q.get()
            if batch is None:
                return
            key, items = batch
            tracker.enter(key, name)
            tracker.exit(key, name)
            done.extend(items)
            q.done(key)

    with PreemptPoints(seed=seed):
        threads = [threading.Thread(target=producer)] + [
            threading.Thread(target=worker, args=(f"w{i}",))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        threads[0].join(timeout=60)
        assert not threads[0].is_alive()
        deadline = threading.Event()
        for _ in range(30_000):
            if len(q) == 0:
                break
            deadline.wait(0.001)
        q.shut_down()
        for t in threads[1:]:
            t.join(timeout=60)
            assert not t.is_alive()
    assert tracker.violations == []
    assert len(done) == 160
