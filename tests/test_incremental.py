"""Incremental re-solve: quiet-round fast path + warm epsilon ladder.

Correctness bar: an incremental planner must produce the same objective as
a cold planner on every round of a churn sequence (the incremental path is
an accelerator, never an approximation).
"""

import numpy as np
import pytest

from poseidon_tpu.costmodel import get_cost_model
from poseidon_tpu.graph.instance import RoundPlanner
from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
from poseidon_tpu.solver.oracle import transport_objective
from poseidon_tpu.utils.ids import generate_uuid, task_uid


def make_state(num_machines=12, num_tasks=60, seed=0):
    # Machines large enough that per-machine capacity never binds: the
    # incremental paths are then exercised against the pure transportation
    # relaxation (which the exact oracle also solves), without the
    # planner's joint-capacity cuts entering the comparison.
    rng = np.random.default_rng(seed)
    st = ClusterState()
    for i in range(num_machines):
        st.node_added(
            MachineInfo(
                uuid=generate_uuid(f"im{i}"),
                cpu_capacity=int(rng.integers(32000, 64000)),
                ram_capacity=int(rng.integers(1 << 26, 1 << 27)),
            )
        )
    shapes = [(100, 1 << 18), (500, 1 << 19), (1500, 1 << 20), (250, 1 << 18)]
    for i in range(num_tasks):
        cpu, ram = shapes[i % len(shapes)]
        st.task_submitted(
            TaskInfo(
                uid=task_uid("ijob", i), job_id=f"ijob-{i % 4}",
                cpu_request=cpu, ram_request=ram,
            )
        )
    return st


def test_quiet_round_fast_path():
    st = make_state()
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    deltas, m1 = planner.schedule_round()
    assert m1.placed == 60 and m1.unscheduled == 0
    # Nothing changed: the next round must skip the solve entirely.
    deltas2, m2 = planner.schedule_round()
    assert deltas2 == []
    assert m2.solve_seconds == 0.0 and m2.iterations == 0
    assert m2.objective == m1.objective
    # A mutation re-arms the solve.
    st.task_submitted(
        TaskInfo(uid=task_uid("ijob", 999), job_id="ijob-x",
                 cpu_request=100, ram_request=1 << 18)
    )
    from poseidon_tpu.ops.transport import host_cert_count

    cert0 = host_cert_count()
    deltas3, m3 = planner.schedule_round()
    # The greedy+auction-dual cold start can solve a one-task instance
    # in ZERO device iterations — and the host certificate may then
    # answer it without any dispatch at all.  The solve re-arming is
    # proven by a dispatch OR a host-certified return, plus the
    # placement itself.
    assert (m3.device_calls > 0 or host_cert_count() > cert0)
    assert m3.placed == 1
    # The re-solve may migrate toward a cheaper optimum; it must then
    # settle: the following round is quiet again.
    deltas4, m4 = planner.schedule_round()
    assert deltas4 == [] and m4.iterations == 0


def test_incremental_matches_cold_over_churn():
    st_inc = make_state(seed=3)
    st_cold = make_state(seed=3)
    inc = RoundPlanner(st_inc, get_cost_model("cpu_mem"), incremental=True)
    cold = RoundPlanner(st_cold, get_cost_model("cpu_mem"), incremental=False)

    rng = np.random.default_rng(42)
    for r in range(6):
        # Churn drawn once, applied identically to both states: remove a
        # few tasks, add a few new ones.
        live = sorted(
            uid for uid, t in st_inc.tasks.items() if t.state in (2, 4)
        )
        doomed = [live[int(k)] for k in
                  rng.choice(len(live), size=3, replace=False)]
        fresh = [
            (task_uid(f"churn-{r}", j), int(rng.integers(1, 20)) * 100)
            for j in range(3)
        ]
        for st in (st_inc, st_cold):
            for uid in doomed:
                st.task_removed(uid)
            for uid, cpu in fresh:
                st.task_submitted(
                    TaskInfo(
                        uid=uid, job_id=f"churn-{r}",
                        cpu_request=cpu, ram_request=1 << 19,
                    )
                )
        d_inc, m_inc = inc.schedule_round()
        d_cold, m_cold = cold.schedule_round()
        assert m_inc.gap_bound == 0.0
        assert m_inc.objective == m_cold.objective, f"round {r}"


def test_incremental_solve_parity_with_oracle():
    # Global-rescheduling mode: every round re-solves the whole workload,
    # so a stats drift re-prices running tasks too.  The epsilon-start
    # (incremental) path must land exactly where a cold solve of the same
    # pipeline lands; the banded total is additionally sandwiched against
    # the full-instance exact optimum (bands are individually certified
    # optimal, but largest-first commitment can cost a small premium when
    # an earlier band has ties — so >=, not ==).
    def drifted_round(incremental):
        st = make_state(num_machines=8, num_tasks=40, seed=9)
        planner = RoundPlanner(
            st, get_cost_model("cpu_mem"), reschedule_running=True,
            incremental=incremental,
        )
        planner.schedule_round()
        for uuid in list(st.machines)[:4]:
            st.add_node_stats(
                uuid, {"cpu_utilization": 0.9, "mem_utilization": 0.8}
            )
        view = st.build_round_view(include_running=True)
        cm = planner.cost_model.build(view.ecs, view.machines)
        _, metrics = planner.schedule_round()
        return st, view, cm, metrics

    st, view, cm, m_inc = drifted_round(incremental=True)
    _, _, _, m_cold = drifted_round(incremental=False)
    assert m_inc.objective == m_cold.objective
    assert m_inc.gap_bound == 0.0 and m_inc.converged

    want = transport_objective(
        cm.costs, view.ecs.supply, cm.capacity, cm.unsched_cost,
        arc_capacity=cm.arc_capacity,
    )
    assert want <= m_inc.objective <= want + 2 * len(st.machines)


def test_ssp_flow_solver_matches_auction():
    """flow_solver="ssp" (host network-simplex verification solver) must
    produce the same certified objective as the TPU auction kernel
    through the full banded pipeline."""
    def run(flow_solver):
        st = make_state(num_machines=6, num_tasks=30, seed=21)
        p = RoundPlanner(
            st, get_cost_model("cpu_mem"), flow_solver=flow_solver
        )
        _, m = p.schedule_round()
        return m

    m_ssp = run("ssp")
    m_auction = run("auction")
    assert m_ssp.objective == m_auction.objective
    assert m_ssp.placed == m_auction.placed
    assert m_ssp.gap_bound == 0.0


def test_unknown_flow_solver_rejected():
    import pytest

    st = make_state(num_machines=2, num_tasks=2, seed=1)
    with pytest.raises(ValueError):
        RoundPlanner(st, get_cost_model("cpu_mem"), flow_solver="cs2")


def test_precompile_covers_round_shapes():
    """After precompile(), a first scheduling round must not add compile
    keys (the server's precompile flag, FirmamentTPUConfig.precompile)."""
    # The packed wrapper is the dispatch boundary (the inner solve
    # variants inline into its trace and mint no executables of their
    # own), so its cache is where a missed precompile key would show.
    from poseidon_tpu.ops.transport import _solve_device_packed

    st = make_state(num_machines=40, num_tasks=60, seed=13)
    planner = RoundPlanner(st, get_cost_model("cpu_mem"))
    shapes = planner.precompile(max_ecs=64)
    assert shapes >= 3
    before = _solve_device_packed._cache_size()
    _, metrics = planner.schedule_round()
    assert metrics.placed > 0
    assert _solve_device_packed._cache_size() == before


def test_resubmission_affinity_returns_tasks_to_prior_machines():
    """A removed-and-resubmitted task goes back to the machine it ran on
    whenever the solver's flow still covers it (assignment-level
    affinity: image/data locality at zero solver cost).  Solver seeding
    from priors was measured net-harmful and is intentionally absent
    (docs/PERF.md round-4 negative results)."""
    import numpy as np

    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    state = ClusterState()
    # Uniformly loaded at steady state (5 slots x 8 machines = exactly
    # the workload): vacated slots are then the only free capacity, the
    # cost-optimal flow returns to them, and the affinity pass decides
    # WHICH member takes which vacated slot — its own.
    for i in range(8):
        state.node_added(MachineInfo(
            uuid=f"ra-m{i}", cpu_capacity=8000, ram_capacity=1 << 24,
            task_slots=5,
        ))
    for i in range(40):
        state.task_submitted(TaskInfo(
            uid=task_uid("ra", i), job_id=f"j{i % 4}",
            cpu_request=500, ram_request=1 << 18,
        ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    planner.schedule_round()
    placed = {u: t.scheduled_to for u, t in state.tasks.items()}
    assert all(placed.values())

    # Churn 25%: remove + resubmit identical tasks.
    rng = np.random.default_rng(0)
    churned = [list(placed)[k] for k in
               rng.choice(len(placed), size=10, replace=False)]
    for uid in churned:
        t = state.tasks[uid]
        state.task_removed(uid)
        assert state.prior_machine[uid] == placed[uid]
        state.task_submitted(TaskInfo(
            uid=uid, job_id=t.job_id, cpu_request=t.cpu_request,
            ram_request=t.ram_request,
        ))
    _, m = planner.schedule_round()
    assert m.converged and m.placed == 10
    back = sum(
        1 for uid in churned if state.tasks[uid].scheduled_to == placed[uid]
    )
    # All capacity they vacated is still free, so everyone goes home.
    assert back == 10, back
    # Consumed: the hint dict does not accumulate.
    assert not any(uid in state.prior_machine for uid in churned)


def test_affinity_never_starves_longest_waiter():
    """Affinity is a WHERE tie-break, not a WHO override: when an EC has
    more pending members than flow, the longest-waiting member places
    first even if a freshly resubmitted member carries a prior-machine
    hint (the starvation escalator's bounded-unfairness guarantee)."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    state = ClusterState()
    # One slot total: exactly one member can place per round.
    state.node_added(MachineInfo(
        uuid="st-m0", cpu_capacity=8000, ram_capacity=1 << 24, task_slots=1,
    ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))

    # Occupant runs; a waiter accumulates wait rounds behind it.
    state.task_submitted(TaskInfo(uid=task_uid("st", 0), job_id="j",
                                  cpu_request=100, ram_request=1 << 18))
    planner.schedule_round()
    waiter = task_uid("st", 1)
    state.task_submitted(TaskInfo(uid=waiter, job_id="j",
                                  cpu_request=100, ram_request=1 << 18))
    for _ in range(3):
        _, m = planner.schedule_round()
        assert state.tasks[waiter].scheduled_to is None  # still waiting
    assert state.tasks[waiter].wait_rounds >= 2

    # The occupant churns: removed (recording its prior machine) and
    # resubmitted with wait 0.  The freed slot must go to the WAITER,
    # not back to the resubmission via its affinity hint.
    occ = task_uid("st", 0)
    state.task_removed(occ)
    state.task_submitted(TaskInfo(uid=occ, job_id="j",
                                  cpu_request=100, ram_request=1 << 18))
    _, m = planner.schedule_round()
    assert state.tasks[waiter].scheduled_to == "st-m0"
    assert state.tasks[occ].scheduled_to is None
    # The consumed-but-unapplied hint went BACK into the dict (losing
    # one round's tie-break must not permanently lose locality), and it
    # still works: when the waiter departs, the occupant goes home.
    assert state.prior_machine.get(occ) == "st-m0"
    state.task_removed(waiter)
    planner.schedule_round()
    assert state.tasks[occ].scheduled_to == "st-m0"
    assert occ not in state.prior_machine  # applied -> consumed


def test_affinity_hint_not_consumed_when_machine_absent():
    """A hint whose prior machine is missing from the round view stays in
    the dict (the FIFO cap bounds growth) instead of being popped
    uselessly — it becomes usable again if the machine returns."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    state = ClusterState()
    for name in ("ab-m0", "ab-m1"):
        state.node_added(MachineInfo(
            uuid=name, cpu_capacity=8000, ram_capacity=1 << 24,
            task_slots=4,
        ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    uid = task_uid("ab", 0)
    state.task_submitted(TaskInfo(uid=uid, job_id="j", cpu_request=100,
                                  ram_request=1 << 18))
    planner.schedule_round()
    home = state.tasks[uid].scheduled_to
    state.task_removed(uid)
    assert state.prior_machine[uid] == home
    state.node_removed(home)
    state.task_submitted(TaskInfo(uid=uid, job_id="j", cpu_request=100,
                                  ram_request=1 << 18))
    planner.schedule_round()
    # Placed on the surviving machine; the unusable hint was NOT popped.
    assert state.tasks[uid].scheduled_to is not None
    assert state.tasks[uid].scheduled_to != home
    assert state.prior_machine.get(uid) == home


def test_coarse_start_preserves_round_objective(monkeypatch):
    """The coarse warm start is a pure accelerant: with the size gates
    patched down so it fires at test scale, a CONTENDED fresh-wave round
    must produce the same objective and placement count as with the path
    disabled — and the coarse LIFT leg (not just the greedy pre-check)
    must actually run, asserted via a disaggregation spy."""
    import numpy as np

    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.ops import transport
    from poseidon_tpu.utils.ids import task_uid

    def build():
        # Contended on purpose (demand ~ 1.5x slot capacity with load-
        # shaped costs): an uncontested instance would satisfy the
        # greedy pre-check and never reach the coarse lift.
        state = ClusterState()
        rng = np.random.default_rng(3)
        for i in range(64):
            state.node_added(MachineInfo(
                uuid=f"cw-m{i}", cpu_capacity=int(rng.integers(4000, 16000)),
                ram_capacity=1 << 24, task_slots=6,
            ))
        for i in range(600):
            state.task_submitted(TaskInfo(
                uid=task_uid("cw", i), job_id=f"j{i % 8}",
                cpu_request=int(rng.integers(400, 2000)),
                ram_request=1 << 18,
            ))
        return state

    lifted = {"n": 0}
    orig_disagg = transport._coarse_disaggregate

    def spy(*a, **k):
        lifted["n"] += 1
        return orig_disagg(*a, **k)

    results = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("POSEIDON_COARSE", flag)
        if flag == "1":
            monkeypatch.setattr(transport, "COARSE_MIN_MACHINES", 32)
            monkeypatch.setattr(transport, "COARSE_GROUPS", 8)
            monkeypatch.setattr(transport, "_coarse_disaggregate", spy)
        state = build()
        planner = RoundPlanner(state, get_cost_model("cpu_mem"))
        _, m = planner.schedule_round()
        assert m.converged and m.gap_bound == 0.0
        results[flag] = (m.objective, m.placed, m.unscheduled)
    assert lifted["n"] > 0, "coarse lift leg never ran; test is vacuous"
    assert results["0"] == results["1"], results
