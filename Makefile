# poseidon_tpu build/test plumbing (the analog of the reference's
# K8s-forked Makefile + hack/ verify scripts, reduced to what this
# framework actually needs).

PY ?= python

.PHONY: all test test-fast bench protos native verify lint lint-fast \
  bench-smoke soak-smoke trace-smoke profile-smoke throughput-smoke \
  scenario-smoke perf-gate demo demo-stop clean

all: protos native lint test

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -x -q -p no:cacheprovider

bench:
	$(PY) bench.py

bench-small:
	$(PY) bench.py --machines 500 --tasks 5000 --ecs 50 --rounds 3 --verbose

# Tiny-scale (~200-machine) features-config run on CPU: a fast
# regression gate for the selector/affinity/gang paths' latency AND
# semantics (zero violations, whole gangs), without the full bench.
bench-smoke:
	$(PY) -m pytest tests/test_bench_smoke.py -q -m slow -p no:cacheprovider

# Chaos soak smoke (docs/CHAOS.md): the full glue+service stack at ~200
# machines under a seeded fault plan covering every fault family —
# gates zero state divergence per round, zero warm fresh compiles,
# seed-reproducible placements, and the flight-recorder redrive path.
# The recorder writes failure traces under out/soak/ (cleaned by
# `make clean`).
soak-smoke:
	$(PY) -m pytest tests/test_soak_smoke.py -q -m slow -p no:cacheprovider

# Observability smoke (docs/OBSERVABILITY.md): one features-config
# round with POSEIDON_TRACE=1, exported to out/trace_smoke.json and
# validated — Perfetto-loadable format, round->stage span nesting,
# span/stagetimer parity within 5%, and at least one conv.* counter
# track rendered from the solver convergence telemetry.
trace-smoke:
	$(PY) tools/trace_smoke.py

# Solver-introspection smoke (docs/OBSERVABILITY.md): a CPU-pinned
# telemetry-on contended round — convergence-curve artifact validated
# (out/profile_smoke.json), jax profiler capture window exercised,
# /debug/rounds + /debug/round/<n> + /healthz probed on a live
# exporter, and a warm instrumented round held under BOTH
# CompileLedger(budget=0) and TransferLedger(budget=0).
profile-smoke:
	$(PY) tools/profile_smoke.py

# Streaming-throughput smoke (docs/PERF.md round 11): a tiny fixed-
# duration run of the sustained-throughput rung through the full stack
# — placements/sec > 0 in both modes, fixed-round streaming-vs-
# synchronous kube truth byte-identical, warm windows compile-free.
throughput-smoke:
	$(PY) -m pytest tests/test_throughput_smoke.py -q -m slow -p no:cacheprovider

# Scenario-harness smoke (docs/SCENARIOS.md): a tiny two-scenario plan
# through the full glue+service stack in BOTH loop modes with every
# gate armed — sync/streaming drain-equivalence (identical placement
# and delta digests), seeded determinism, robustness scoring under
# chaos-seeded cost perturbation, and the flight-recorder redrive of a
# deliberately failed round.  Failure traces land under out/scenario/
# (cleaned by `make clean`).
scenario-smoke:
	$(PY) -m pytest tests/test_scenario_smoke.py -q -m slow -p no:cacheprovider

# Perf-regression gate (tools/bench_compare.py): diff a fresh bench
# artifact's timing series (headline p50s + per-stage features timings)
# against the committed round baseline; fail past the tolerance band.
# Point PERF_BENCH at the fresh artifact (bench.py writes superset
# JSON lines; the last parseable one wins):
#   python bench.py > out/bench_gate.jsonl && make perf-gate
# Without a fresh artifact, the NEWEST committed baseline stands in as
# the current side — judged against the OLDER chain only (never against
# itself, which would make the gate vacuous): machines that never ran
# the bench stay green, while a PR that commits a regressed baseline
# fails against its predecessors.
# First parseable baseline wins.  bench_r08_baseline.json adds the
# per-round device-work series (wave/churn solve_iters, bf_sweeps,
# device_calls — gated as counts, machine-independently);
# bench_r07_baseline.json carries the incremental-round-engine stage
# series (PR 7); r06 is the first artifact with the per-stage features
# series (mask/cost/solve/view) — without one of them those rows fall
# in "skipped" and only headline round timings are gated.
PERF_FRESH := $(wildcard out/bench_gate.jsonl)
ifeq ($(PERF_FRESH),)
PERF_BENCH ?= docs/bench_r08_baseline.json
PERF_BASELINES = --baseline docs/bench_r07_baseline.json \
  --baseline docs/bench_r06_baseline.json \
  --baseline docs/bench_r05_final.json
else
PERF_BENCH ?= $(PERF_FRESH)
PERF_BASELINES = --baseline docs/bench_r08_baseline.json \
  --baseline docs/bench_r07_baseline.json \
  --baseline docs/bench_r06_baseline.json \
  --baseline docs/bench_r05_final.json
endif
# ENFORCING since PR 7 (this PR's stage wins must not be silently
# regressable); POSEIDON_PERF_GATE=warn is the escape hatch for known-
# noisy machines.
PERF_GATE_FLAGS = $(if $(filter warn,$(POSEIDON_PERF_GATE)),--warn-only,)
perf-gate:
	$(PY) tools/bench_compare.py $(PERF_BASELINES) --current $(PERF_BENCH) \
	  $(PERF_GATE_FLAGS)

protos:
	$(PY) -m poseidon_tpu.protos.gen

native:
	$(PY) -c "from poseidon_tpu.native import native_available; \
	  assert native_available(), 'native build failed'; print('native ok')"

# Static analysis: the posecheck suite (docs/CHECKS.md), ruff when
# installed (the container may not ship it; config in pyproject.toml),
# and the generated-proto staleness gate — one target gates all
# mechanical hygiene (the analog of the reference's hack/verify-*).
# The scan set covers bench.py/tools/ and the driver entry too: the
# hatch-registry rule's dead-flag sub-check needs every POSEIDON_*
# reader walked, and the bench knobs live outside the package.  A
# machine-readable finding artifact (one JSON object per line; empty
# when clean) lands in out/posecheck.json for CI annotators — also how
# `make verify` publishes the lint verdict.
LINT_PATHS = poseidon_tpu/ bench.py tools/ __graft_entry__.py
lint:
	@mkdir -p out
	$(PY) -m poseidon_tpu.check --format=json $(LINT_PATHS) \
	  > out/posecheck.json; \
	  rc=$$?; \
	  if [ $$rc -ne 0 ]; then cat out/posecheck.json; fi; \
	  exit $$rc
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check .; \
	else \
	  echo "lint: ruff not installed; skipping (configs in pyproject.toml)"; \
	fi
	@if command -v protoc >/dev/null 2>&1; then \
	  $(PY) -m poseidon_tpu.protos.gen && \
	  git diff --exit-code --stat -- 'poseidon_tpu/protos/*_pb2.py'; \
	else \
	  echo "lint: protoc not installed; skipping proto drift gate" \
	    "(gen.py did not regenerate, so a diff would not prove drift)"; \
	fi

# Pre-commit speed path: posecheck over git-changed files only.
lint-fast:
	$(PY) -m poseidon_tpu.check --changed $(LINT_PATHS)

# Entry-point smoke: compile check + multichip dryrun + demo loop, with
# the behavior smokes (feature semantics + chaos robustness + traced
# round) gating alongside static analysis.  The perf gate is ENFORCING
# (PR 7): a fresh out/bench_gate.jsonl is judged against the committed
# baseline chain, and with no fresh artifact the newest committed
# baseline is judged against its predecessors — either way a regression
# past the band fails verify.  POSEIDON_PERF_GATE=warn downgrades to
# warn-only on known-noisy machines.
verify: lint bench-smoke soak-smoke trace-smoke profile-smoke \
  throughput-smoke scenario-smoke perf-gate
	$(PY) __graft_entry__.py

# Backgrounded demo loop with its PID on record (out/demo.pid), so the
# process no longer leaks: `make demo-stop` (or `make clean`) kills it.
demo:
	@mkdir -p out
	@$(PY) -m poseidon_tpu.glue.main --demo --scheduling-interval=2 \
	  --firmament-address=127.0.0.1:19090 & \
	  echo $$! > out/demo.pid; \
	  echo "demo running (pid $$(cat out/demo.pid)); make demo-stop ends it"

demo-stop:
	@if [ -f out/demo.pid ]; then \
	  kill "$$(cat out/demo.pid)" 2>/dev/null \
	    && echo "demo stopped (pid $$(cat out/demo.pid))" \
	    || echo "demo pid $$(cat out/demo.pid) was not running"; \
	  rm -f out/demo.pid; \
	else \
	  echo "no demo running (out/demo.pid absent)"; \
	fi

clean: demo-stop
	rm -f poseidon_tpu/native/_graphcore.so
	rm -rf out/soak out/scenario
	rm -f out/trace_smoke.json out/trace_smoke_conv.json
	rm -f out/trace_features.json out/bench_gate.jsonl
	rm -f out/posecheck.json out/profile_smoke.json
	rm -rf out/profile_smoke_jax
	find . -name __pycache__ -type d -exec rm -rf {} +
