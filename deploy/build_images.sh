#!/usr/bin/env bash
# Build the three poseidon-tpu images (the analog of the reference's
# deploy/build_docker_image.sh).  Run from the repo root.
set -euo pipefail
cd "$(dirname "$0")/.."

TAG="${TAG:-latest}"
for target in firmament-tpu poseidon metrics-agent; do
  docker build -f deploy/Dockerfile --target "$target" \
    -t "poseidon-tpu/${target}:${TAG}" .
done
echo "built: poseidon-tpu/{firmament-tpu,poseidon,metrics-agent}:${TAG}"
