"""Benchmark: one Schedule() round at cluster scale on real hardware.

North-star target (BASELINE.md): 10k machines / 100k pending pods per
round in < 1 s with placement-cost parity vs the exact oracle.  The
reference publishes no numbers of its own (its default round *interval* is
10 s, pkg/config/config.go:120); the 1 s round target is the baseline this
prints ``vs_baseline`` against (>1.0 = beating it).

Prints ONE JSON line:
  {"metric": "schedule_round_s", "value": <p50 seconds>, "unit": "s",
   "vs_baseline": <1.0 / value>}
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def build_cluster(num_machines: int, num_tasks: int, num_ecs: int, seed=0):
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    rng = np.random.default_rng(seed)
    state = ClusterState()
    # Machine fleet: 3 hardware shapes (the trace-like heterogeneity).
    shapes = [(16000, 64 << 20), (32000, 128 << 20), (64000, 256 << 20)]
    for i in range(num_machines):
        cpu, ram = shapes[i % len(shapes)]
        state.node_added(
            MachineInfo(
                uuid=generate_uuid(f"bench-m{i}"),
                cpu_capacity=cpu,
                ram_capacity=ram,
                task_slots=64,
            )
        )
    # Task population: num_ecs distinct shapes, Zipf-ish multiplicity.
    ec_cpu = rng.integers(100, 4000, size=num_ecs)
    ec_ram = rng.integers(1 << 18, 1 << 22, size=num_ecs)
    ec_of_task = rng.integers(0, num_ecs, size=num_tasks)
    for i in range(num_tasks):
        e = int(ec_of_task[i])
        state.task_submitted(
            TaskInfo(
                uid=task_uid("bench-job", i),
                job_id=f"bench-job-{e}",
                cpu_request=int(ec_cpu[e]),
                ram_request=int(ec_ram[e]),
            )
        )
    return state


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--machines", type=int, default=10_000)
    p.add_argument("--tasks", type=int, default=100_000)
    p.add_argument("--ecs", type=int, default=100)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)

    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import TaskState

    state = build_cluster(args.machines, args.tasks, args.ecs)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))

    # Warm-up round: triggers XLA compilation (cached afterwards) and
    # places the initial wave.
    t0 = time.perf_counter()
    deltas, metrics = planner.schedule_round()
    warm_s = time.perf_counter() - t0
    if args.verbose:
        print(
            f"# warmup: {warm_s:.3f}s placed={metrics.placed} "
            f"unsched={metrics.unscheduled} solve={metrics.solve_seconds:.3f}s",
            file=sys.stderr,
        )

    # Steady-state rounds: churn 1% of tasks (complete + resubmit) between
    # rounds so the incremental path does real work each time.
    from poseidon_tpu.graph.state import TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    rng = np.random.default_rng(1)
    lat = []
    uids = list(state.tasks.keys())
    for r in range(args.rounds):
        churn = rng.choice(len(uids), size=max(1, len(uids) // 100),
                           replace=False)
        for k in churn:
            uid = uids[k]
            t = state.tasks.get(uid)
            if t is None:
                continue
            state.task_removed(uid)
            fresh = TaskInfo(
                uid=uid, job_id=t.job_id, cpu_request=t.cpu_request,
                ram_request=t.ram_request,
            )
            state.task_submitted(fresh)
        t0 = time.perf_counter()
        deltas, metrics = planner.schedule_round()
        dt = time.perf_counter() - t0
        lat.append(dt)
        if args.verbose:
            print(
                f"# round {r}: {dt:.3f}s solve={metrics.solve_seconds:.3f}s "
                f"deltas={len(deltas)} obj={metrics.objective} "
                f"gap={metrics.gap_bound}",
                file=sys.stderr,
            )

    p50 = float(np.percentile(lat, 50))
    print(
        json.dumps(
            {
                "metric": "schedule_round_s",
                "value": round(p50, 4),
                "unit": "s",
                "vs_baseline": round(1.0 / p50, 3) if p50 > 0 else 0.0,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
