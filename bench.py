"""Benchmark: one Schedule() round at cluster scale on real hardware.

North-star target (BASELINE.md): 10k machines / 100k pending pods per
round in < 1 s with placement-cost parity vs the exact oracle.  The
reference publishes no numbers of its own (its default round *interval* is
10 s, pkg/config/config.go:120); the 1 s round target is the baseline this
prints ``vs_baseline`` against (>1.0 = beating it).

Prints ONE JSON line:
  {"metric": "schedule_round_s", "value": <p50 seconds>, "unit": "s",
   "vs_baseline": <1.0 / value>}
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def _ensure_live_backend() -> None:
    """Probe the accelerator in a subprocess; fall back to CPU if dead.

    The TPU tunnel can wedge (worker crash leaves every op hanging
    forever).  A 120s subprocess probe detects that without hanging this
    process; the fallback re-execs with the accelerator plugin stripped
    so the benchmark still reports a number (tagged via stderr).
    """
    if os.environ.get("POSEIDON_BENCH_NO_PROBE"):
        return
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax,jax.numpy as jnp;"
             "print(float((jnp.ones((64,64))@jnp.ones((64,64))).sum()))"],
            capture_output=True, text=True, timeout=150,
        )
        # ones(64,64) @ ones(64,64) sums to 64**3 = 262144.
        ok = probe.returncode == 0 and "262144" in probe.stdout
    except subprocess.TimeoutExpired:
        ok = False
    if ok:
        return
    from poseidon_tpu.utils.envutil import clean_cpu_env

    env = clean_cpu_env(os.path.dirname(os.path.abspath(__file__)))
    env["POSEIDON_BENCH_NO_PROBE"] = "1"
    print("# accelerator unreachable; falling back to CPU", file=sys.stderr)
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def build_cluster(num_machines: int, num_tasks: int, num_ecs: int, seed=0):
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    rng = np.random.default_rng(seed)
    state = ClusterState()
    # Machine fleet: 3 hardware shapes (the trace-like heterogeneity).
    shapes = [(16000, 64 << 20), (32000, 128 << 20), (64000, 256 << 20)]
    for i in range(num_machines):
        cpu, ram = shapes[i % len(shapes)]
        state.node_added(
            MachineInfo(
                uuid=generate_uuid(f"bench-m{i}"),
                cpu_capacity=cpu,
                ram_capacity=ram,
                task_slots=64,
            )
        )
    # Task population: num_ecs distinct shapes, Zipf-ish multiplicity.
    ec_cpu = rng.integers(100, 4000, size=num_ecs)
    ec_ram = rng.integers(1 << 18, 1 << 22, size=num_ecs)
    ec_of_task = rng.integers(0, num_ecs, size=num_tasks)
    for i in range(num_tasks):
        e = int(ec_of_task[i])
        state.task_submitted(
            TaskInfo(
                uid=task_uid("bench-job", i),
                job_id=f"bench-job-{e}",
                cpu_request=int(ec_cpu[e]),
                ram_request=int(ec_ram[e]),
            )
        )
    return state


def main(argv=None) -> int:
    _ensure_live_backend()
    p = argparse.ArgumentParser()
    p.add_argument("--machines", type=int, default=10_000)
    p.add_argument("--tasks", type=int, default=100_000)
    p.add_argument("--ecs", type=int, default=100)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)

    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import TaskState

    state = build_cluster(args.machines, args.tasks, args.ecs)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))

    # Warm-up round: triggers XLA compilation (cached afterwards) and
    # places the initial wave.
    t0 = time.perf_counter()
    deltas, metrics = planner.schedule_round()
    warm_s = time.perf_counter() - t0
    if args.verbose:
        print(
            f"# warmup: {warm_s:.3f}s placed={metrics.placed} "
            f"unsched={metrics.unscheduled} solve={metrics.solve_seconds:.3f}s",
            file=sys.stderr,
        )

    # Headline metric (the north-star config): a full wave — every task
    # pending at once — scheduled in one round, 10k machines x 100k pods.
    # Between measured rounds the whole workload is drained and
    # resubmitted fresh; compilation is cached from the warm-up.
    uids = list(state.tasks.keys())
    lat = []
    for r in range(args.rounds):
        shapes = {
            uid: (t.job_id, t.cpu_request, t.ram_request)
            for uid, t in state.tasks.items()
        }
        for uid in uids:
            state.task_removed(uid)
        from poseidon_tpu.graph.state import TaskInfo

        for uid, (job, cpu, ram) in shapes.items():
            state.task_submitted(
                TaskInfo(uid=uid, job_id=job, cpu_request=cpu,
                         ram_request=ram)
            )
        t0 = time.perf_counter()
        deltas, metrics = planner.schedule_round()
        dt = time.perf_counter() - t0
        lat.append(dt)
        if args.verbose:
            print(
                f"# wave {r}: {dt:.3f}s solve={metrics.solve_seconds:.3f}s "
                f"placed={metrics.placed} unsched={metrics.unscheduled} "
                f"obj={metrics.objective} gap={metrics.gap_bound}",
                file=sys.stderr,
            )

    # Secondary: steady-state churn rounds (1% of tasks replaced).
    rng = np.random.default_rng(1)
    churn_lat = []
    for r in range(args.rounds):
        churn = rng.choice(len(uids), size=max(1, len(uids) // 100),
                           replace=False)
        for k in churn:
            uid = uids[k]
            t = state.tasks.get(uid)
            if t is None:
                continue
            state.task_removed(uid)
            state.task_submitted(
                TaskInfo(uid=uid, job_id=t.job_id,
                         cpu_request=t.cpu_request,
                         ram_request=t.ram_request)
            )
        t0 = time.perf_counter()
        deltas, metrics = planner.schedule_round()
        dt = time.perf_counter() - t0
        churn_lat.append(dt)
        if args.verbose:
            print(
                f"# churn round {r}: {dt:.3f}s "
                f"solve={metrics.solve_seconds:.3f}s deltas={len(deltas)}",
                file=sys.stderr,
            )
    if args.verbose:
        print(
            f"# churn p50: {float(np.percentile(churn_lat, 50)):.4f}s",
            file=sys.stderr,
        )

    p50 = float(np.percentile(lat, 50))
    print(
        json.dumps(
            {
                "metric": "schedule_round_s",
                "value": round(p50, 4),
                "unit": "s",
                "vs_baseline": round(1.0 / p50, 3) if p50 > 0 else 0.0,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
